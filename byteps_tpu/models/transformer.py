"""Transformer encoder/decoder core, TPU-first.

The reference has no model code at all (it wraps torch/tf/mxnet models);
its benchmark configs are BERT-large / GPT-2 style transformers
(reference: README.md:37-44, example/pytorch/benchmark_byteps.py). Here
the model zoo is part of the framework, built for the MXU:

  - matmul-heavy blocks in bfloat16, fp32 accumulation for softmax/LN
  - optional **tensor parallelism** over the ``model`` mesh axis,
    Megatron-style: QKV and MLP-in are column-parallel (no comm), attn-out
    and MLP-out are row-parallel (one psum each); heads divide across TP
    ranks
  - optional **sequence parallelism** over the ``seq`` axis via ring
    attention (byteps_tpu.parallel.ring)
  - ``param_specs`` returns the PartitionSpec tree so pjit/shard_map can
    lay the weights out without a wrapper class
  - ``jax.checkpoint`` on each block to trade FLOPs for HBM when training
    deep configs
"""

from __future__ import annotations

import dataclasses
from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from ..parallel.ring import ring_attention


@dataclasses.dataclass(frozen=True)
class TransformerConfig:
    vocab_size: int = 32000
    hidden: int = 1024
    layers: int = 24
    heads: int = 16
    mlp_dim: int = 4096
    max_seq: int = 512
    causal: bool = False          # False: BERT-style encoder; True: GPT
    dtype: str = "bfloat16"       # compute dtype (params stay fp32)
    remat: bool = True            # checkpoint each block
    remat_policy: Optional[str] = None
    # None: checkpoint the whole block, save only its input (min memory).
    # "dots": save MXU outputs, recompute elementwise (measured slower —
    #   the saved activations' HBM traffic beats the recompute).
    # "mlp_only": checkpoint only the MLP half; attention residuals
    #   (qkv, flash out+lse) are kept so the backward never re-runs the
    #   attention forward. ~300MB/layer at batch 64 seq 512.
    remat_layers: int = -1        # how many of the layers to checkpoint
    # (-1 = all). Layers beyond the first ``remat_layers`` keep their
    # activations resident and skip the backward's forward-recompute —
    # full remat executes ~4/3× the model FLOPs, so un-rematting the k
    # layers that fit in leftover HBM buys back k/L of that 33% overhead
    # (the single biggest MFU lever on one chip; see docs/performance.md).
    attn_impl: str = "auto"       # auto | flash (Pallas) | naive
    tp_axis: Optional[str] = None # mesh axis for tensor parallelism
    sp_axis: Optional[str] = None # mesh axis for ring-attention seq shards
    pp_axis: Optional[str] = None # mesh axis for pipeline (layer) stages
    pp_microbatches: int = 0      # GPipe microbatches (0 → pipeline size)
    pp_interleave: int = 1        # virtual chunks per pipeline rank (>1 =
    # interleaved/circular schedule: bubble shrinks interleave-fold; the
    # stacked layer params must be laid out with
    # parallel.pipeline.interleave_permutation)
    pp_remat_chunk: bool = True   # interleaved PP: checkpoint each tick's
    # chunk (10× less scan-residual memory, ~1/3 extra compute; overrides
    # remat_policy inside the chunk). False keeps per-tick residuals and
    # honors remat_policy (e.g. "mlp_only") at full memory cost.
    scan_unroll: int = 1          # lax.scan unroll factor over layers
    lm_head_chunk: int = 0        # >0: chunked cross-entropy — the LM
    # head + softmax run per sequence chunk under jax.checkpoint, so the
    # [s, vocab] logits never materialize (13 GB at GPT-2 seq 64k; the
    # enabler for very long contexts on one chip). 0 = full head.

    def __post_init__(self):
        if self.remat_policy not in (None, "dots", "mlp_only", "save_attn"):
            raise ValueError(f"remat_policy must be None|'dots'|'mlp_only'|"
                             f"'save_attn', got {self.remat_policy!r}")
        if self.remat_policy is not None and not self.remat:
            raise ValueError("remat_policy set but remat=False — the policy "
                             "would be silently ignored")
        if self.remat_layers != -1 and not (0 <= self.remat_layers
                                            <= self.layers):
            raise ValueError(f"remat_layers must be -1 or 0..{self.layers}, "
                             f"got {self.remat_layers}")
        if self.remat_layers != -1 and not self.remat:
            raise ValueError("remat_layers set but remat=False — the knob "
                             "would be silently ignored")
        if self.pp_interleave < 1:
            raise ValueError(f"pp_interleave must be >= 1, "
                             f"got {self.pp_interleave}")
        if self.pp_interleave > 1 and self.pp_axis is None:
            raise ValueError("pp_interleave > 1 needs pp_axis")

    @property
    def head_dim(self) -> int:
        return self.hidden // self.heads


# ----------------------------------------------------------------- params

def init_params(rng, cfg: TransformerConfig):
    """Full (unsharded) parameter pytree; shard with param_specs."""
    keys = jax.random.split(rng, cfg.layers + 3)
    h, m = cfg.hidden, cfg.mlp_dim
    sd = 0.02

    def norm(key, shape):
        return jax.random.normal(key, shape, dtype=jnp.float32) * sd

    def one_block(key):
        k1, k2, k3, k4 = jax.random.split(key, 4)
        return {
            "ln1": {"scale": jnp.ones((h,)), "bias": jnp.zeros((h,))},
            # [h, 3, heads, head_dim] so TP shards whole heads, not a
            # contiguous slice of the fused [q|k|v] columns
            "qkv": norm(k1, (h, 3, cfg.heads, cfg.head_dim)),
            "attn_out": norm(k2, (h, h)),
            "ln2": {"scale": jnp.ones((h,)), "bias": jnp.zeros((h,))},
            "mlp_in": norm(k3, (h, m)),
            "mlp_in_b": jnp.zeros((m,)),
            "mlp_out": norm(k4, (m, h)),
            "mlp_out_b": jnp.zeros((h,)),
        }

    blocks = [one_block(keys[i + 2]) for i in range(cfg.layers)]
    # stack per-layer params on a leading layer axis: the whole depth runs
    # as one lax.scan, so compile time is O(1) in layer count
    stacked = jax.tree_util.tree_map(lambda *xs: jnp.stack(xs), *blocks)
    return {
        "embed": {
            "tok": norm(keys[0], (cfg.vocab_size, h)),
            "pos": norm(keys[1], (cfg.max_seq, h)),
        },
        "blocks": stacked,
        "final_ln": {"scale": jnp.ones((h,)), "bias": jnp.zeros((h,))},
    }


def param_specs(cfg: TransformerConfig):
    """PartitionSpec tree matching init_params: column-parallel weights
    shard their output dim on tp_axis, row-parallel their input dim."""
    tp = cfg.tp_axis
    pp = cfg.pp_axis  # stacked layer axis shards across pipeline stages
    rep = P()
    lead = P(pp)
    block = {
        "ln1": {"scale": lead, "bias": lead},
        "qkv": P(pp, None, None, tp, None),    # column parallel over heads
        "attn_out": P(pp, tp, None),           # row parallel
        "ln2": {"scale": lead, "bias": lead},
        "mlp_in": P(pp, None, tp),
        "mlp_in_b": P(pp, tp),
        "mlp_out": P(pp, tp, None),
        "mlp_out_b": lead,
    }
    return {
        "embed": {"tok": rep, "pos": rep},
        "blocks": block,
        "final_ln": {"scale": rep, "bias": rep},
    }


# ----------------------------------------------------------------- layers

def embed_lookup(table, tokens):
    """Token-embedding lookup with an MXU backward.

    Forward is the plain gather. The default backward — scatter-add of
    [b·s, hid] rows into the [vocab, hid] table — serializes badly on
    TPU: measured 115 ms/step for BERT-large (batch 64, seq 512) vs
    29 ms when the same contraction runs as a one-hot matmul on the MXU
    (~10% of the whole train step). The one-hot never materializes: XLA
    fuses it into the dot."""
    return _embed_lookup(table.shape[0], str(table.dtype), table, tokens)


@partial(jax.custom_vjp, nondiff_argnums=(0, 1))
def _embed_lookup(vocab: int, dt: str, table, tokens):
    return table[tokens]


def _embed_lookup_fwd(vocab, dt, table, tokens):
    return table[tokens], tokens


def _embed_lookup_bwd(vocab, dt, tokens, ct):
    flat_t = tokens.reshape(-1)
    flat_ct = ct.reshape(-1, ct.shape[-1])
    onehot = jax.nn.one_hot(flat_t, vocab, dtype=flat_ct.dtype)
    # fp32 cotangents keep scatter-add exactness (TPU fp32 dots default
    # to bf16 MXU passes); bf16 cotangents take the fast default
    prec = (jax.lax.Precision.HIGHEST
            if flat_ct.dtype == jnp.float32 else None)
    grad = jax.lax.dot_general(onehot, flat_ct, (((0,), (0,)), ((), ())),
                               precision=prec,
                               preferred_element_type=jnp.float32)
    return grad.astype(dt), None


_embed_lookup.defvjp(_embed_lookup_fwd, _embed_lookup_bwd)


def _layernorm(x, scale, bias, eps=1e-5):
    x32 = x.astype(jnp.float32)
    mu = x32.mean(-1, keepdims=True)
    var = ((x32 - mu) ** 2).mean(-1, keepdims=True)
    out = (x32 - mu) * jax.lax.rsqrt(var + eps) * scale + bias
    return out.astype(x.dtype)


def _attention(x, blk, cfg: TransformerConfig, tp_size: int):
    b, s, _ = x.shape
    local_heads = cfg.heads // tp_size
    qkv = jnp.einsum("bsh,hcnd->bscnd", x, blk["qkv"].astype(x.dtype))
    q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]  # [b, s, lh, hd]
    if cfg.sp_axis is not None:
        out = ring_attention(q, k, v, cfg.sp_axis, causal=cfg.causal,
                             impl=cfg.attn_impl)
    else:
        from ..ops.flash_attention import attention
        out = attention(q, k, v, causal=cfg.causal, impl=cfg.attn_impl)
    out = out.reshape(b, s, local_heads * cfg.head_dim)
    out = out @ blk["attn_out"].astype(x.dtype)   # row-parallel: partial sum
    if cfg.tp_axis is not None:
        out = jax.lax.psum(out, cfg.tp_axis)
    return out


def _mlp(x, blk, cfg: TransformerConfig):
    hdt = x.dtype
    h = x @ blk["mlp_in"].astype(hdt) + blk["mlp_in_b"].astype(hdt)
    h = jax.nn.gelu(h)
    out = h @ blk["mlp_out"].astype(hdt)          # row-parallel: partial sum
    if cfg.tp_axis is not None:
        out = jax.lax.psum(out, cfg.tp_axis)
    return out + blk["mlp_out_b"].astype(hdt)


def _block(x, blk, cfg: TransformerConfig, tp_size: int,
           remat_mlp: bool = False):
    """Transformer block; remat_mlp checkpoints only the MLP half
    (remat_policy="mlp_only": attention residuals kept, MLP recomputed)."""
    x = x + _attention(_layernorm(x, blk["ln1"]["scale"], blk["ln1"]["bias"]),
                       blk, cfg, tp_size)

    def mlp_half(y, b):
        return _mlp(_layernorm(y, b["ln2"]["scale"], b["ln2"]["bias"]),
                    b, cfg)

    if remat_mlp:
        mlp_half = jax.checkpoint(mlp_half)
    return x + mlp_half(x, blk)


def apply(params, cfg: TransformerConfig, tokens: jnp.ndarray,
          positions: Optional[jnp.ndarray] = None) -> jnp.ndarray:
    """Forward to final hidden states [b, s_local, hidden].

    Call inside shard_map when tp/sp/pp axes are set. With sp_axis,
    ``tokens`` is the local sequence shard and ``positions`` must be the
    global positions of that shard (defaults assume shard-contiguous
    layout). With pp_axis, the returned hidden states are only valid on
    the LAST pipeline stage — finite zeros-fed garbage elsewhere; mask
    any derived quantity with ``parallel.pipeline.last_stage_value`` (as
    ``lm_loss`` does) before use.
    """
    dt = jnp.dtype(cfg.dtype)
    b, s = tokens.shape
    if positions is None:
        if cfg.sp_axis is not None:
            offset = jax.lax.axis_index(cfg.sp_axis) * s
        else:
            offset = 0
        positions = offset + jnp.arange(s)
    tp_size = jax.lax.axis_size(cfg.tp_axis) if cfg.tp_axis else 1
    x = embed_lookup(params["embed"]["tok"], tokens).astype(dt)
    x = x + params["embed"]["pos"][positions].astype(dt)

    plain_fn = partial(_block, cfg=cfg, tp_size=tp_size)
    if cfg.remat and cfg.remat_policy == "mlp_only":
        blk_fn = partial(_block, cfg=cfg, tp_size=tp_size, remat_mlp=True)
    else:
        blk_fn = plain_fn
        if cfg.remat:
            if cfg.remat_policy == "dots":
                policy = jax.checkpoint_policies.dots_with_no_batch_dims_saveable
            elif cfg.remat_policy == "save_attn":
                # pin ONLY the flash kernel's residuals (out + squeezed
                # lse, named in ops/flash_attention._fwd_rule); everything
                # else recomputes
                policy = jax.checkpoint_policies.save_only_these_names(
                    "flash_out", "flash_lse")
            else:
                policy = None
            blk_fn = jax.checkpoint(blk_fn, policy=policy)

    def body(carry, blk):
        return blk_fn(carry, blk), None

    def plain_body(carry, blk):
        return plain_fn(carry, blk), None

    def stack_fn(blocks, h):
        k = cfg.remat_layers
        if not cfg.remat or k == -1 or k >= cfg.layers or cfg.pp_axis:
            # uniform policy across the stack (pp stages keep it uniform
            # too: their layer shard sizes vary with the stage count)
            out, _ = jax.lax.scan(body, h, blocks, unroll=cfg.scan_unroll)
            return out
        # partial remat: first k layers checkpointed, the rest keep
        # activations resident (two scans; compile time stays O(1))
        rem = jax.tree_util.tree_map(lambda x: x[:k], blocks)
        res = jax.tree_util.tree_map(lambda x: x[k:], blocks)
        if k:
            h, _ = jax.lax.scan(body, h, rem, unroll=cfg.scan_unroll)
        out, _ = jax.lax.scan(plain_body, h, res, unroll=cfg.scan_unroll)
        return out

    if cfg.pp_axis is not None:
        # Pipeline over the pipe axis: params["blocks"] arrives as this
        # stage's layer shard; microbatch the batch dim and stream.
        from ..parallel.pipeline import pipeline, pipeline_interleaved
        pn = jax.lax.axis_size(cfg.pp_axis)
        V = cfg.pp_interleave
        if cfg.layers % (pn * V):
            raise ValueError(f"{cfg.layers} layers not divisible by "
                             f"{pn} stages x {V} chunks")
        n_micro = cfg.pp_microbatches or pn
        if b % n_micro:
            raise ValueError(f"batch {b} not divisible by {n_micro} microbatches")
        xm = x.reshape(n_micro, b // n_micro, *x.shape[1:])
        if V > 1:
            # interleaved layout contract: the caller permuted the stacked
            # layers with interleave_permutation, so this rank's [L/pn]
            # shard reshapes to [V, Lc] chunks in ring order
            chunked = jax.tree_util.tree_map(
                lambda p: p.reshape(V, p.shape[0] // V, *p.shape[1:]),
                params["blocks"])
            xm = pipeline_interleaved(stack_fn, chunked, xm, cfg.pp_axis,
                                      remat_chunk=cfg.pp_remat_chunk)
        else:
            xm = pipeline(stack_fn, params["blocks"], xm, cfg.pp_axis)
        x = xm.reshape(b, *x.shape[1:])   # valid on the last stage only
    else:
        x = stack_fn(params["blocks"], x)
    x = _layernorm(x, params["final_ln"]["scale"], params["final_ln"]["bias"])
    return x


def logits(params, cfg: TransformerConfig, hidden: jnp.ndarray) -> jnp.ndarray:
    """Tied-embedding LM head → [b, s, vocab] in fp32.

    The matmul runs at the compute dtype (bf16 on the MXU — at fp32 this
    one op dominates the step) with fp32 accumulation."""
    dt = jnp.dtype(cfg.dtype)
    return jnp.einsum("bsh,vh->bsv", hidden.astype(dt),
                      params["embed"]["tok"].astype(dt),
                      preferred_element_type=jnp.float32)


_warned_chunk: set = set()


def _chunked_nll_sum(h, emb, targets, mask, chunk: int, dt) -> jnp.ndarray:
    """Masked NLL sum with the LM head applied per sequence chunk.

    Each chunk's logits/log-softmax live only inside a jax.checkpoint
    region of a lax.scan: the forward keeps no [s, vocab] tensor and the
    backward recomputes one [chunk, vocab] block at a time — O(chunk·V)
    memory instead of O(s·V)."""
    b, s, hid = h.shape
    n = s // chunk
    hc = jnp.moveaxis(h.reshape(b, n, chunk, hid), 1, 0)
    tc = jnp.moveaxis(targets.reshape(b, n, chunk), 1, 0)
    mc = jnp.moveaxis(mask.reshape(b, n, chunk), 1, 0)

    @jax.checkpoint
    def one(hb, tb, mb):
        lg = jnp.einsum("bch,vh->bcv", hb.astype(dt), emb.astype(dt),
                        preferred_element_type=jnp.float32)
        logp = jax.nn.log_softmax(lg, axis=-1)
        nll = -jnp.take_along_axis(
            logp, jnp.where(mb, tb, 0)[..., None], axis=-1)[..., 0]
        return (nll * mb).sum()

    def body(acc, xs):
        return acc + one(*xs), None

    total, _ = jax.lax.scan(body, jnp.float32(0.0), (hc, tc, mc))
    return total


def lm_loss(params, cfg: TransformerConfig, batch) -> jnp.ndarray:
    """Cross-entropy LM loss. batch = (tokens, targets); targets < 0 are
    ignored (the MLM mask convention).

    Under sequence parallelism the nll-sum and mask-count are psum'd over
    the sp axis *before* dividing, so every rank holds the true global
    loss — local-mean losses would weight shards with different mask
    counts unevenly and bias the gradient."""
    tokens, targets = batch
    h = apply(params, cfg, tokens)
    mask = (targets >= 0)
    s = h.shape[1]
    chunk = cfg.lm_head_chunk
    if chunk and s > chunk and s % chunk:
        # silent fallback would materialize the [s, vocab] logits the
        # user configured the chunking to avoid — warn once per shape
        if (s, chunk) not in _warned_chunk:
            _warned_chunk.add((s, chunk))
            from ..common.logging import get_logger
            get_logger().warning(
                "lm_head_chunk=%d does not divide seq %d — falling back "
                "to the FULL [s, vocab] head (O(s·vocab) memory); pick a "
                "divisor of the sequence length", chunk, s)
    if chunk and s > chunk and s % chunk == 0:
        nll_sum = _chunked_nll_sum(h, params["embed"]["tok"], targets,
                                   mask, chunk, jnp.dtype(cfg.dtype))
    else:
        lg = logits(params, cfg, h)
        logp = jax.nn.log_softmax(lg, axis=-1)
        tgt = jnp.where(mask, targets, 0)
        nll = -jnp.take_along_axis(logp, tgt[..., None], axis=-1)[..., 0]
        nll_sum = (nll * mask).sum()
    cnt = mask.sum().astype(jnp.float32)
    if cfg.sp_axis is not None:
        nll_sum = jax.lax.psum(nll_sum, cfg.sp_axis)
        cnt = jax.lax.psum(cnt, cfg.sp_axis)
    if cfg.pp_axis is not None:
        # Only the last pipeline stage holds real hidden states; mask the
        # other ranks' (finite, zero-init) dummy outputs and replicate —
        # the psum's n× grad factor matches the trainer's uniform rescale
        # convention (see ShardedTrainer.step).
        from ..parallel.pipeline import last_stage_value
        nll_sum = last_stage_value(nll_sum, cfg.pp_axis)
        cnt = last_stage_value(cnt, cfg.pp_axis)
    return nll_sum / jnp.maximum(cnt, 1.0)

"""GPT-2 model family (reference benchmark config: GPT-2 medium with
DistributedDataParallel + cross-barrier, BASELINE.json configs)."""

from __future__ import annotations

import numpy as np

from .transformer import TransformerConfig, lm_loss


def gpt2_config(hidden=1024, layers=24, heads=16, vocab_size=50257,
                max_seq=1024, dtype="bfloat16", **kw) -> TransformerConfig:
    return TransformerConfig(vocab_size=vocab_size, hidden=hidden,
                             layers=layers, heads=heads, mlp_dim=4 * hidden,
                             max_seq=max_seq, causal=True, dtype=dtype, **kw)


def gpt2_medium(**kw) -> TransformerConfig:
    return gpt2_config(hidden=1024, layers=24, heads=16, **kw)


def gpt2_small(**kw) -> TransformerConfig:
    return gpt2_config(hidden=768, layers=12, heads=12, **kw)


def gpt2_tiny(**kw) -> TransformerConfig:
    return gpt2_config(hidden=64, layers=2, heads=4, vocab_size=128,
                       max_seq=64, dtype="float32", remat=False, **kw)


def causal_lm_loss(params, cfg: TransformerConfig, batch):
    """batch = tokens [b, s]; next-token prediction.

    Under sequence parallelism the local shard must NOT be shifted in
    isolation (that would drop one target per shard boundary and misalign
    global positions). Instead each shard keeps its full token block as
    input and borrows the next shard's first token as its final target via
    ppermute; the globally-last position is masked out.
    """
    import jax

    tokens = batch
    if cfg.sp_axis is None:
        # Keep the FULL sequence as input and mask the last target instead
        # of shifting to s-1: identical loss (positions < s-1 attend only
        # backwards, position s-1's prediction is ignored either way), but
        # s stays a multiple of 128 so the flash-attention kernels stay
        # eligible — a s-1 shift silently fell back to the O(s²) naive
        # path (28x slower at seq 8k, OOM at 16k).
        import jax.numpy as jnp
        targets = jnp.concatenate(
            [tokens[:, 1:],
             jnp.full((tokens.shape[0], 1), -1, tokens.dtype)], axis=1)
        return lm_loss(params, cfg, (tokens, targets))

    sp = jax.lax.axis_size(cfg.sp_axis)
    idx = jax.lax.axis_index(cfg.sp_axis)
    # first token of the *next* shard arrives from rank r+1
    perm = [(i, (i - 1) % sp) for i in range(sp)]
    next_first = jax.lax.ppermute(tokens[:, :1], cfg.sp_axis, perm)
    targets = jax.numpy.concatenate([tokens[:, 1:], next_first], axis=1)
    # globally-last position has no next token: mask it on the last rank
    is_last = (idx == sp - 1)
    last_col_masked = jax.numpy.where(is_last, -1, targets[:, -1:])
    targets = jax.numpy.concatenate([targets[:, :-1], last_col_masked], axis=1)
    return lm_loss(params, cfg, (tokens, targets))


def synth_lm_batch(rng: np.random.RandomState, batch: int, seq: int, vocab: int):
    return rng.randint(1, vocab, size=(batch, seq)).astype(np.int32)

"""Minimal MLP model — the round-1 flagship placeholder and the
synthetic-benchmark workhorse (reference analogue: the synthetic benchmark
models in example/pytorch/benchmark_byteps.py)."""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np


def mlp_init(rng, dim: int, depth: int, out_dim: int | None = None):
    out_dim = out_dim or dim
    params = {}
    keys = jax.random.split(rng, depth)
    for i in range(depth):
        d_out = out_dim if i == depth - 1 else dim
        params[f"w{i}"] = jax.random.normal(keys[i], (dim, d_out)) / np.sqrt(dim)
        params[f"b{i}"] = jnp.zeros((d_out,))
    return params


def mlp_apply(params, x):
    depth = len(params) // 2
    for i in range(depth):
        x = x @ params[f"w{i}"] + params[f"b{i}"]
        if i < depth - 1:
            x = jax.nn.gelu(x)
    return x


def mlp_loss(params, batch):
    x, y = batch
    pred = mlp_apply(params, x)
    return jnp.mean((pred - y) ** 2)

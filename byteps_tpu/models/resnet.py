"""ResNet family (reference benchmark config: ResNet-50 ImageNet,
docs/performance.md:3-12). Pure-JAX functional implementation; convs lower
straight onto the MXU via XLA. BatchNorm uses batch statistics (training
mode); gradients for the affine params flow normally.
"""

from __future__ import annotations

from functools import partial
from typing import List, Tuple

import jax
import jax.numpy as jnp
import numpy as np

# stage plan: (blocks, channels) per stage; ResNet-50 uses bottleneck blocks
RESNET50_STAGES = [(3, 256), (4, 512), (6, 1024), (3, 2048)]
RESNET18_STAGES = [(2, 64), (2, 128), (2, 256), (2, 512)]


def _conv_init(key, kh, kw, cin, cout):
    fan_in = kh * kw * cin
    return jax.random.normal(key, (kh, kw, cin, cout)) * np.sqrt(2.0 / fan_in)


def _conv(x, w, stride=1, padding="SAME"):
    """Conv at the activation dtype (weights cast to match — bf16 feeds
    the MXU, which accumulates fp32 internally; fp32 convs take the slow
    multi-pass path on TPU). Output stays at the activation dtype (a
    fp32 preferred_element_type would break the conv's vjp rule)."""
    return jax.lax.conv_general_dilated(
        x, w.astype(x.dtype), (stride, stride), padding,
        dimension_numbers=("NHWC", "HWIO", "NHWC"))


def _bn(x, p, eps=1e-5):
    """BatchNorm with fp32 statistics; returns the input's dtype."""
    x32 = x.astype(jnp.float32)
    mu = x32.mean(axis=(0, 1, 2), keepdims=True)
    var = x32.var(axis=(0, 1, 2), keepdims=True)
    xn = (x32 - mu) * jax.lax.rsqrt(var + eps)
    return (xn * p["scale"] + p["bias"]).astype(x.dtype)


def _net_dtype(dtype):
    """None → bf16 on TPU (mixed precision), fp32 elsewhere."""
    if dtype is not None:
        return jnp.dtype(dtype)
    return jnp.dtype(jnp.bfloat16 if jax.default_backend() == "tpu"
                     else jnp.float32)


def _bn_init(c):
    return {"scale": jnp.ones((c,)), "bias": jnp.zeros((c,))}


def init_resnet50(rng, num_classes: int = 1000, stages=None):
    stages = stages or RESNET50_STAGES
    keys = iter(jax.random.split(rng, 200))
    params = {"stem": {"conv": _conv_init(next(keys), 7, 7, 3, 64),
                       "bn": _bn_init(64)},
              "stages": [], "fc_w": None, "fc_b": None}
    cin = 64
    for si, (blocks, cout) in enumerate(stages):
        stage = []
        for bi in range(blocks):
            stride = 2 if (bi == 0 and si > 0) else 1
            mid = cout // 4
            blk = {
                "conv1": _conv_init(next(keys), 1, 1, cin, mid), "bn1": _bn_init(mid),
                "conv2": _conv_init(next(keys), 3, 3, mid, mid), "bn2": _bn_init(mid),
                "conv3": _conv_init(next(keys), 1, 1, mid, cout), "bn3": _bn_init(cout),
            }
            if cin != cout or stride != 1:
                blk["proj"] = _conv_init(next(keys), 1, 1, cin, cout)
                blk["proj_bn"] = _bn_init(cout)
            stage.append(blk)
            cin = cout
        params["stages"].append(stage)
    params["fc_w"] = jax.random.normal(next(keys), (cin, num_classes)) * 0.01
    params["fc_b"] = jnp.zeros((num_classes,))
    return params


def _bottleneck(x, blk, stride):
    out = jax.nn.relu(_bn(_conv(x, blk["conv1"]), blk["bn1"]))
    out = jax.nn.relu(_bn(_conv(out, blk["conv2"], stride=stride),
                          blk["bn2"]))
    out = _bn(_conv(out, blk["conv3"]), blk["bn3"])
    if "proj" in blk:
        x = _bn(_conv(x, blk["proj"], stride=stride), blk["proj_bn"])
    return jax.nn.relu(out + x)


def resnet50_apply(params, x, dtype=None):
    """x: [n, h, w, 3] → logits [n, classes] fp32.

    dtype: activation/compute dtype; None → bf16 on TPU, fp32 elsewhere
    (params stay fp32; convs accumulate fp32; BN statistics fp32)."""
    dt = _net_dtype(dtype)
    x = x.astype(dt)
    x = _conv(x, params["stem"]["conv"], stride=2)
    x = jax.nn.relu(_bn(x, params["stem"]["bn"]))
    x = jax.lax.reduce_window(x, -jnp.inf, jax.lax.max,
                              (1, 3, 3, 1), (1, 2, 2, 1), "SAME")
    for si, stage in enumerate(params["stages"]):
        for bi, blk in enumerate(stage):
            # stride 2 on the first block of stages 1+ (standard ResNet)
            x = _bottleneck(x, blk, 2 if (bi == 0 and si > 0) else 1)
    x = x.astype(jnp.float32).mean(axis=(1, 2))
    return x @ params["fc_w"] + params["fc_b"]


def resnet_loss(params, batch, dtype=None):
    x, y = batch
    lg = resnet50_apply(params, x, dtype=dtype)
    logp = jax.nn.log_softmax(lg)
    return -jnp.take_along_axis(logp, y[:, None], axis=-1).mean()


def synth_imagenet_batch(rng: np.random.RandomState, n: int, size: int = 224,
                         classes: int = 1000):
    """Synthetic ImageNet-like data (reference: tests/utils.py fake_data)."""
    x = rng.randn(n, size, size, 3).astype(np.float32)
    y = rng.randint(0, classes, size=(n,)).astype(np.int32)
    return x, y

"""Encoder-decoder (T5-style) transformer — seq2seq model family.

Additive beyond the reference's zoo (its examples cover CV + BERT/GPT;
no seq2seq anywhere in `/root/reference/example/`): a full
encoder-decoder with causal decoder self-attention plus cross-attention
over the encoder's memory, reusing this framework's building blocks —
`transformer`'s layernorm/MLP/embedding (MXU-backward embed), the flash
kernels for self-attention, and the same Megatron-style tensor-parallel
sharding (column-parallel QKV over heads, row-parallel projections with
one psum per sublayer).

Round 4 fidelity upgrades (the two signature T5 mechanisms):

- **Relative position bias** (`pos_encoding="relative"`, the default):
  no absolute position embedding; each stack owns ONE learned
  [num_buckets, heads] table (shared across its layers, exactly T5's
  weight sharing) — bidirectional buckets in the encoder, causal in
  the decoder. The table rides the flash kernels' IN-KERNEL rel-bias
  input: each (q-block, kv-block) derives its bucket map from block
  offsets and folds the table into the scores inside VMEM, dtable
  accumulated in kernel scratch — no [heads, s, s] bias ever
  materializes in HBM, so relative-bias self-attention stays O(s)
  memory at ANY length (a materialized bias is 34 GB at s=32k, h=8;
  the in-kernel form runs it in ~0.85 s fwd+bwd on one chip). T5's
  no-1/√d-scaling convention applies in this mode.
  ``pos_encoding="absolute"`` keeps the learned-positions variant.
- **Flash cross-attention**: the kernels' tiling contract is per-axis
  (q and kv lengths independent), so decoder-over-encoder attention
  runs the same Pallas path as self-attention — the O(sq·sk) score
  matrix never leaves VMEM, which is what makes LONG-encoder seq2seq
  (e.g. summarization at 8k+ source tokens) feasible.
"""

from __future__ import annotations

import dataclasses
from functools import partial
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from .transformer import _layernorm, _mlp, embed_lookup

__all__ = ["T5Config", "t5_tiny", "t5_small", "init_t5_params",
           "t5_param_specs", "encode", "decode", "seq2seq_loss",
           "synth_seq2seq_batch", "relative_position_bucket",
           "relative_bias"]


@dataclasses.dataclass(frozen=True)
class T5Config:
    vocab_size: int = 32000
    hidden: int = 512
    enc_layers: int = 6
    dec_layers: int = 6
    heads: int = 8
    mlp_dim: int = 2048
    max_seq: int = 512
    dtype: str = "bfloat16"
    remat: bool = True
    attn_impl: str = "auto"
    tp_axis: Optional[str] = None
    # T5's signature position scheme (see module docstring); "absolute"
    # restores the learned position table
    pos_encoding: str = "relative"
    rel_buckets: int = 32
    rel_max_distance: int = 128

    @property
    def head_dim(self) -> int:
        return self.hidden // self.heads

    @property
    def relative(self) -> bool:
        return self.pos_encoding == "relative"


def t5_tiny(**kw) -> T5Config:
    return T5Config(vocab_size=128, hidden=64, enc_layers=2, dec_layers=2,
                    heads=4, mlp_dim=128, max_seq=64, **kw)


def t5_small(**kw) -> T5Config:
    return T5Config(**kw)


# ------------------------------------------------------------------ params

def _enc_block_init(key, h, m, heads, hd, sd=0.02):
    k = jax.random.split(key, 4)
    n = lambda kk, shape: jax.random.normal(kk, shape, jnp.float32) * sd
    return {
        "ln1": {"scale": jnp.ones((h,)), "bias": jnp.zeros((h,))},
        "qkv": n(k[0], (h, 3, heads, hd)),
        "attn_out": n(k[1], (h, h)),
        "ln2": {"scale": jnp.ones((h,)), "bias": jnp.zeros((h,))},
        "mlp_in": n(k[2], (h, m)), "mlp_in_b": jnp.zeros((m,)),
        "mlp_out": n(k[3], (m, h)), "mlp_out_b": jnp.zeros((h,)),
    }


def _dec_block_init(key, h, m, heads, hd, sd=0.02):
    k = jax.random.split(key, 7)
    n = lambda kk, shape: jax.random.normal(kk, shape, jnp.float32) * sd
    blk = _enc_block_init(key, h, m, heads, hd, sd)
    blk.update({
        # cross-attention: q from the decoder stream, k/v from memory
        "lnx": {"scale": jnp.ones((h,)), "bias": jnp.zeros((h,))},
        "xq": n(k[4], (h, heads, hd)),
        "xkv": n(k[5], (h, 2, heads, hd)),
        "x_out": n(k[6], (h, h)),
    })
    return blk


def init_t5_params(rng, cfg: T5Config):
    h, m, hd = cfg.hidden, cfg.mlp_dim, cfg.head_dim
    keys = jax.random.split(rng, cfg.enc_layers + cfg.dec_layers + 3)
    enc = [_enc_block_init(keys[i + 2], h, m, cfg.heads, hd)
           for i in range(cfg.enc_layers)]
    dec = [_dec_block_init(keys[cfg.enc_layers + i + 2], h, m, cfg.heads,
                           hd)
           for i in range(cfg.dec_layers)]
    stack = lambda blocks: jax.tree_util.tree_map(
        lambda *xs: jnp.stack(xs), *blocks)
    sd = 0.02
    if cfg.relative:
        # one bucket table PER STACK, shared by its layers (T5's
        # weight sharing; reference T5 holds it in layer 0)
        k1, k2 = jax.random.split(keys[1])
        embed = {"tok": jax.random.normal(keys[0], (cfg.vocab_size, h),
                                          jnp.float32) * sd}
        rel = {
            "enc_rel_bias": jax.random.normal(
                k1, (cfg.rel_buckets, cfg.heads), jnp.float32) * sd,
            "dec_rel_bias": jax.random.normal(
                k2, (cfg.rel_buckets, cfg.heads), jnp.float32) * sd,
        }
    else:
        embed = {
            "tok": jax.random.normal(keys[0], (cfg.vocab_size, h),
                                     jnp.float32) * sd,
            "pos": jax.random.normal(keys[1], (cfg.max_seq, h),
                                     jnp.float32) * sd,
        }
        rel = {}
    return {
        "embed": embed,
        **rel,
        "enc_blocks": stack(enc),
        "dec_blocks": stack(dec),
        "enc_final_ln": {"scale": jnp.ones((h,)), "bias": jnp.zeros((h,))},
        "dec_final_ln": {"scale": jnp.ones((h,)), "bias": jnp.zeros((h,))},
    }


def t5_param_specs(cfg: T5Config):
    """Megatron TP layout (column-parallel over heads / mlp columns,
    row-parallel back): same convention as transformer.param_specs."""
    tp = cfg.tp_axis
    rep = P()
    lead = P(None)
    enc = {
        "ln1": {"scale": lead, "bias": lead},
        "qkv": P(None, None, None, tp, None),
        "attn_out": P(None, tp, None),
        "ln2": {"scale": lead, "bias": lead},
        "mlp_in": P(None, None, tp), "mlp_in_b": P(None, tp),
        "mlp_out": P(None, tp, None), "mlp_out_b": lead,
    }
    dec = dict(enc)
    dec.update({
        "lnx": {"scale": lead, "bias": lead},
        "xq": P(None, None, tp, None),
        "xkv": P(None, None, None, tp, None),
        "x_out": P(None, tp, None),
    })
    specs = {
        "embed": ({"tok": rep} if cfg.relative
                  else {"tok": rep, "pos": rep}),
        "enc_blocks": enc,
        "dec_blocks": dec,
        "enc_final_ln": {"scale": rep, "bias": rep},
        "dec_final_ln": {"scale": rep, "bias": rep},
    }
    if cfg.relative:
        # bucket tables shard over HEADS like qkv's head axis, so each
        # TP rank computes the bias for exactly its local heads
        specs["enc_rel_bias"] = P(None, tp)
        specs["dec_rel_bias"] = P(None, tp)
    return specs


# ------------------------------------------------------ relative positions
# (shared with the Pallas kernels — byteps_tpu/ops/relpos.py; re-exported
# here for the model-facing API and backward compatibility)

from ..ops.relpos import relative_bias, relative_position_bucket  # noqa: E402,F401


# ------------------------------------------------------------------ layers

def _self_attention(x, blk, cfg: T5Config, causal: bool, rel_table=None):
    # local sibling of transformer._attention rather than a reuse: the
    # encoder/decoder pair varies ``causal`` per stack (the shared fn
    # reads it from its config) and T5 has no sp_axis/ring branch
    b, s, _ = x.shape
    qkv = jnp.einsum("bsh,hcnd->bscnd", x, blk["qkv"].astype(x.dtype))
    q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]
    from ..ops.flash_attention import attention
    # T5's convention: no 1/sqrt(d) score scaling in relative mode;
    # the [nb, heads] stack table rides the flash kernels' in-kernel
    # rel-bias input ([heads, nb] layout) — no [h, s, s] bias in HBM,
    # so relative-bias self-attention stays O(s) memory at any length
    scale = 1.0 if cfg.relative else None
    out = attention(q, k, v, causal=causal, impl=cfg.attn_impl,
                    scale=scale,
                    rel_table=None if rel_table is None else rel_table.T,
                    rel_bidirectional=not causal,
                    rel_max_distance=cfg.rel_max_distance)
    out = out.reshape(b, s, -1)
    out = out @ blk["attn_out"].astype(x.dtype)
    if cfg.tp_axis is not None:
        out = jax.lax.psum(out, cfg.tp_axis)
    return out


def _cross_attention(x, memory, blk, cfg: T5Config):
    """q from the decoder stream [b, sq, h]; k/v from the encoder
    memory [b, sk, h] — MISMATCHED lengths on the flash path (the
    kernels' tiling contract is per-axis), so a long encoder never
    materializes the O(sq·sk) score matrix in HBM. T5 applies no
    position bias to cross-attention."""
    dt = x.dtype
    q = jnp.einsum("bsh,hnd->bsnd", x, blk["xq"].astype(dt))
    kv = jnp.einsum("bth,hcnd->btcnd", memory.astype(dt),
                    blk["xkv"].astype(dt))
    k, v = kv[:, :, 0], kv[:, :, 1]
    from ..ops.flash_attention import attention
    out = attention(q, k, v, causal=False, impl=cfg.attn_impl,
                    scale=(1.0 if cfg.relative else None))
    out = out.reshape(*x.shape[:2], -1) @ blk["x_out"].astype(dt)
    if cfg.tp_axis is not None:
        out = jax.lax.psum(out, cfg.tp_axis)
    return out


def _enc_block(x, blk, cfg: T5Config, rel_table=None):
    x = x + _self_attention(
        _layernorm(x, blk["ln1"]["scale"], blk["ln1"]["bias"]),
        blk, cfg, False, rel_table=rel_table)
    # transformer._mlp reads only cfg.tp_axis, which T5Config has
    return x + _mlp(_layernorm(x, blk["ln2"]["scale"], blk["ln2"]["bias"]),
                    blk, cfg)


def _dec_block(x, memory, blk, cfg: T5Config, rel_table=None):
    x = x + _self_attention(
        _layernorm(x, blk["ln1"]["scale"], blk["ln1"]["bias"]),
        blk, cfg, True, rel_table=rel_table)
    x = x + _cross_attention(
        _layernorm(x, blk["lnx"]["scale"], blk["lnx"]["bias"]),
        memory, blk, cfg)
    return x + _mlp(_layernorm(x, blk["ln2"]["scale"], blk["ln2"]["bias"]),
                    blk, cfg)


# ------------------------------------------------------------------ model

def _embed(params, cfg: T5Config, tokens):
    dt = jnp.dtype(cfg.dtype)
    s = tokens.shape[1]
    x = embed_lookup(params["embed"]["tok"], tokens).astype(dt)
    if not cfg.relative:
        x = x + params["embed"]["pos"][:s].astype(dt)
    return x


def encode(params, cfg: T5Config, src_tokens: jnp.ndarray) -> jnp.ndarray:
    """Encoder memory [b, s_src, hidden]."""
    x = _embed(params, cfg, src_tokens)
    # the [nb, heads] table is closed over by every scan step — T5's
    # shared-across-layers bias; the kernels expand it per block
    rel = params["enc_rel_bias"] if cfg.relative else None
    fn = partial(_enc_block, cfg=cfg, rel_table=rel)
    if cfg.remat:
        fn = jax.checkpoint(fn)

    def body(carry, blk):
        return fn(carry, blk), None

    x, _ = jax.lax.scan(body, x, params["enc_blocks"])
    return _layernorm(x, params["enc_final_ln"]["scale"],
                      params["enc_final_ln"]["bias"])


def decode(params, cfg: T5Config, tgt_tokens: jnp.ndarray,
           memory: jnp.ndarray) -> jnp.ndarray:
    """Decoder hidden states [b, s_tgt, hidden] (teacher forcing)."""
    x = _embed(params, cfg, tgt_tokens)
    rel = params["dec_rel_bias"] if cfg.relative else None
    fn = partial(_dec_block, cfg=cfg, rel_table=rel)
    if cfg.remat:
        fn = jax.checkpoint(fn)
    x, _ = jax.lax.scan(lambda c, b: (fn(c, memory, b), None), x,
                        params["dec_blocks"])
    return _layernorm(x, params["dec_final_ln"]["scale"],
                      params["dec_final_ln"]["bias"])


def seq2seq_loss(params, cfg: T5Config, batch: Tuple) -> jnp.ndarray:
    """Teacher-forced next-token CE: ``batch = (src, tgt)``; the decoder
    sees tgt[:-1] and predicts tgt[1:] (position 0 acts as BOS).
    Tied-embedding head, fp32 log-softmax."""
    src, tgt = batch
    memory = encode(params, cfg, src)
    hidden = decode(params, cfg, tgt[:, :-1], memory)
    dt = jnp.dtype(cfg.dtype)
    logits = jnp.einsum("bsh,vh->bsv", hidden.astype(dt),
                        params["embed"]["tok"].astype(dt),
                        preferred_element_type=jnp.float32)
    labels = tgt[:, 1:]
    logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
    nll = -jnp.take_along_axis(logp, labels[..., None], axis=-1)
    return nll.mean()


def synth_seq2seq_batch(rng: np.random.RandomState, batch: int,
                        src_len: int, tgt_len: int, vocab: int):
    """Synthetic copy-task data: target = source prefix (learnable
    structure, so convergence tests mean something)."""
    src = rng.randint(1, vocab, size=(batch, src_len)).astype(np.int32)
    tgt = np.concatenate(
        [np.zeros((batch, 1), np.int32),                 # BOS
         src[:, : tgt_len - 1]], axis=1).astype(np.int32)
    return src, tgt

"""Mixture-of-Experts transformer with expert parallelism.

Additive scope vs the reference (SURVEY §2.5: "Expert parallelism (EP/MoE):
Absent"). TPU-first design:

  - GShard/Switch-style top-k routing with **static-shape capacity
    buffers**: dispatch/combine are one-hot einsums, so everything stays
    MXU-shaped and jit-compatible (no dynamic token counts).
  - Experts shard over the ``expert`` mesh axis; tokens travel to their
    experts via ``lax.all_to_all`` over ICI and back — the canonical EP
    exchange.
  - The ``expert`` axis doubles as a batch axis (batch sharded over
    data × expert), so every rank routes its own token shard: EP adds no
    idle ranks, and gradient rescale in ShardedTrainer treats ``expert``
    exactly like a data axis (per-leaf psum + uniform 1/n).
  - Load-balance auxiliary loss (Switch: E · Σ_e f_e·p_e) accumulated
    through the block scan carry.

References (public techniques): GShard (Lepikhin et al. 2020), Switch
Transformer (Fedus et al. 2021).
"""

from __future__ import annotations

import dataclasses
from functools import partial
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from .transformer import (TransformerConfig, _attention, _layernorm,
                          embed_lookup)


@dataclasses.dataclass(frozen=True)
class MoEConfig(TransformerConfig):
    num_experts: int = 8
    top_k: int = 2
    capacity_factor: float = 1.25   # per-expert buffer = cf·k·T/E tokens
    ep_axis: Optional[str] = None   # mesh axis holding expert shards
    aux_weight: float = 1e-2        # load-balance loss coefficient


# ----------------------------------------------------------------- params

def init_moe_params(rng, cfg: MoEConfig):
    """Parameter pytree: transformer attention + per-expert FFN weights,
    per-layer leaves stacked on a leading layer axis (lax.scan depth)."""
    keys = jax.random.split(rng, cfg.layers + 3)
    h, m, e = cfg.hidden, cfg.mlp_dim, cfg.num_experts
    sd = 0.02

    def norm(key, shape):
        return jax.random.normal(key, shape, dtype=jnp.float32) * sd

    def one_block(key):
        k1, k2, k3, k4, k5 = jax.random.split(key, 5)
        return {
            "ln1": {"scale": jnp.ones((h,)), "bias": jnp.zeros((h,))},
            "qkv": norm(k1, (h, 3, cfg.heads, cfg.head_dim)),
            "attn_out": norm(k2, (h, h)),
            "ln2": {"scale": jnp.ones((h,)), "bias": jnp.zeros((h,))},
            "router": norm(k3, (h, e)),
            "w_in": norm(k4, (e, h, m)),
            "w_in_b": jnp.zeros((e, m)),
            "w_out": norm(k5, (e, m, h)),
            "w_out_b": jnp.zeros((e, h)),
        }

    blocks = [one_block(keys[i + 2]) for i in range(cfg.layers)]
    stacked = jax.tree_util.tree_map(lambda *xs: jnp.stack(xs), *blocks)
    return {
        "embed": {
            "tok": norm(keys[0], (cfg.vocab_size, h)),
            "pos": norm(keys[1], (cfg.max_seq, h)),
        },
        "blocks": stacked,
        "final_ln": {"scale": jnp.ones((h,)), "bias": jnp.zeros((h,))},
    }


def moe_param_specs(cfg: MoEConfig):
    """PartitionSpec tree: expert-indexed weights shard on ep_axis; the
    router and attention stay replicated across it."""
    ep = cfg.ep_axis
    rep = P()
    lead = P(None)
    block = {
        "ln1": {"scale": lead, "bias": lead},
        "qkv": P(None, None, None, cfg.tp_axis, None),
        "attn_out": P(None, cfg.tp_axis, None),
        "ln2": {"scale": lead, "bias": lead},
        "router": P(None, None, None),
        "w_in": P(None, ep, None, None),
        "w_in_b": P(None, ep, None),
        "w_out": P(None, ep, None, None),
        "w_out_b": P(None, ep, None),
    }
    return {
        "embed": {"tok": rep, "pos": rep},
        "blocks": block,
        "final_ln": {"scale": rep, "bias": rep},
    }


# ------------------------------------------------------------------ layer

def _route(x, router_w, cfg: MoEConfig):
    """Top-k routing. x: [T, h] → (combine [T, E, C], dispatch [T, E, C],
    aux scalar). Static capacity C; overflow tokens are dropped (their
    residual path carries them through)."""
    tcount, e = x.shape[0], cfg.num_experts
    cap = max(1, int(cfg.capacity_factor * cfg.top_k * tcount / e))
    logits = x.astype(jnp.float32) @ router_w.astype(jnp.float32)
    probs = jax.nn.softmax(logits, axis=-1)                   # [T, E]

    # top-k expert choices per token; renormalize gate weights over the k
    topv, topi = jax.lax.top_k(probs, cfg.top_k)              # [T, k]
    gates_norm = topv / jnp.maximum(topv.sum(-1, keepdims=True), 1e-9)

    # one-hot per choice → position in each expert's capacity buffer.
    # Choices are flattened in (k, token) order so first choices win
    # buffer slots before any second choice competes.
    sel = jax.nn.one_hot(topi, e, dtype=jnp.float32)          # [T, k, E]
    sel_flat = sel.transpose(1, 0, 2).reshape(-1, e)          # [k*T, E]
    pos_flat = jnp.cumsum(sel_flat, axis=0) - sel_flat        # slot index
    keep_flat = sel_flat * (pos_flat < cap)
    dispatch_flat = keep_flat[..., None] * jax.nn.one_hot(
        pos_flat.astype(jnp.int32), cap, dtype=jnp.float32)   # [k*T, E, C]
    dispatch_k = dispatch_flat.reshape(cfg.top_k, tcount, e, cap)
    combine = jnp.einsum("ktec,tk->tec", dispatch_k, gates_norm)
    dispatch = dispatch_k.sum(0)                              # [T, E, C]

    # Switch aux loss: E · Σ_e (fraction routed to e)·(mean prob of e)
    frac = sel.sum(1).mean(0)                                 # [E]
    aux = e * jnp.sum(frac * probs.mean(0)) / cfg.top_k
    return combine, dispatch, aux


def _moe_ffn(x, blk, cfg: MoEConfig):
    """MoE FFN over flattened tokens x: [T, h] → ([T, h], aux)."""
    combine, dispatch, aux = _route(x, blk["router"], cfg)
    dt = x.dtype
    buf = jnp.einsum("tec,th->ech", dispatch.astype(dt), x)   # [E, C, h]

    if cfg.ep_axis is not None:
        n = jax.lax.axis_size(cfg.ep_axis)
        if cfg.num_experts % n:
            raise ValueError(
                f"{cfg.num_experts} experts not divisible by ep size {n}")
        # exchange: every rank keeps E/n experts, receives all ranks' slots
        buf = jax.lax.all_to_all(buf, cfg.ep_axis, split_axis=0,
                                 concat_axis=1, tiled=True)   # [E/n, n·C, h]

    h1 = jnp.einsum("ech,ehm->ecm", buf, blk["w_in"].astype(dt))
    h1 = jax.nn.gelu(h1 + blk["w_in_b"][:, None, :].astype(dt))
    out = jnp.einsum("ecm,emh->ech", h1, blk["w_out"].astype(dt))
    out = out + blk["w_out_b"][:, None, :].astype(dt)

    if cfg.ep_axis is not None:
        out = jax.lax.all_to_all(out, cfg.ep_axis, split_axis=1,
                                 concat_axis=0, tiled=True)   # [E, C, h]

    y = jnp.einsum("tec,ech->th", combine.astype(dt), out)
    return y, aux


def _moe_block(carry, blk, cfg: MoEConfig, tp_size: int):
    x, aux_acc = carry
    x = x + _attention(_layernorm(x, blk["ln1"]["scale"], blk["ln1"]["bias"]),
                       blk, cfg, tp_size)
    b, s, h = x.shape
    flat = _layernorm(x, blk["ln2"]["scale"], blk["ln2"]["bias"]).reshape(-1, h)
    y, aux = _moe_ffn(flat, blk, cfg)
    return (x + y.reshape(b, s, h), aux_acc + aux), None


# ---------------------------------------------------------------- forward

def moe_apply(params, cfg: MoEConfig, tokens: jnp.ndarray,
              positions: Optional[jnp.ndarray] = None
              ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Forward to (hidden [b, s, h], mean aux loss). Call inside shard_map
    when ep/tp/sp axes are set."""
    if cfg.pp_axis is not None:
        raise ValueError("MoE does not support pipeline parallelism yet; "
                         "unset pp_axis")
    dt = jnp.dtype(cfg.dtype)
    b, s = tokens.shape
    if positions is None:
        if cfg.sp_axis is not None:
            offset = jax.lax.axis_index(cfg.sp_axis) * s
        else:
            offset = 0
        positions = offset + jnp.arange(s)
    tp_size = jax.lax.axis_size(cfg.tp_axis) if cfg.tp_axis else 1
    x = embed_lookup(params["embed"]["tok"], tokens).astype(dt)
    x = x + params["embed"]["pos"][positions].astype(dt)

    blk_fn = partial(_moe_block, cfg=cfg, tp_size=tp_size)
    if cfg.remat:
        blk_fn = jax.checkpoint(blk_fn)

    (x, aux), _ = jax.lax.scan(blk_fn, (x, jnp.float32(0.0)),
                               params["blocks"])
    x = _layernorm(x, params["final_ln"]["scale"], params["final_ln"]["bias"])
    return x, aux / cfg.layers


def moe_lm_loss(params, cfg: MoEConfig, batch) -> jnp.ndarray:
    """Cross-entropy + load-balance aux. batch = (tokens, targets),
    targets < 0 ignored (same convention as transformer.lm_loss)."""
    tokens, targets = batch
    h, aux = moe_apply(params, cfg, tokens)
    lg = jnp.einsum("bsh,vh->bsv", h.astype(jnp.float32),
                    params["embed"]["tok"].astype(jnp.float32))
    logp = jax.nn.log_softmax(lg, axis=-1)
    mask = (targets >= 0)
    tgt = jnp.where(mask, targets, 0)
    nll = -jnp.take_along_axis(logp, tgt[..., None], axis=-1)[..., 0]
    nll_sum = (nll * mask).sum()
    cnt = mask.sum().astype(jnp.float32)
    if cfg.sp_axis is not None:
        nll_sum = jax.lax.psum(nll_sum, cfg.sp_axis)
        cnt = jax.lax.psum(cnt, cfg.sp_axis)
    return nll_sum / jnp.maximum(cnt, 1.0) + cfg.aux_weight * aux


def moe_tiny(**kw) -> MoEConfig:
    """Test-sized config."""
    return MoEConfig(vocab_size=128, hidden=64, layers=2, heads=4,
                     mlp_dim=128, max_seq=64, causal=False, dtype="float32",
                     remat=False, num_experts=4, top_k=2, **kw)

"""MirroredStrategy-style API over a device mesh.

The reference ships a forked ``tf.distribute.MirroredStrategy`` whose
cross-device ops route through BytePS push_pull instead of TF collectives
(reference: tensorflow/distribute/mirrored_strategy.py:349-430,
docs/MirroredStrategy.md). The TPU-native analogue keeps the strategy
surface — ``scope()``, ``run()``, ``reduce()``,
``experimental_distribute_dataset()``, ``num_replicas_in_sync`` — but a
"replica" is a slot on the mesh's data axes and ``run`` is a shard_map'd
call, so per-replica code compiles into one SPMD XLA program exactly like
the rest of the framework.
"""

from __future__ import annotations

import contextlib
import threading
from typing import Callable, Iterable, Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from .common.global_state import GlobalState
from .parallel.mesh import data_axes, make_mesh

_current = threading.local()


def current_strategy() -> Optional["MirroredStrategy"]:
    return getattr(_current, "strategy", None)


class MirroredStrategy:
    """Synchronous data-parallel strategy over the mesh's data axes.

    Example::

        strat = bps.MirroredStrategy()
        with strat.scope():
            step = strat.make_step(loss_fn, optax.adam(1e-3), params)
        loss = step(batch)          # batch split over replicas, grads synced
    """

    def __init__(self, mesh: Optional[Mesh] = None) -> None:
        if mesh is None:
            mesh = GlobalState.get().mesh if GlobalState.initialized() \
                else make_mesh()
        self.mesh = mesh
        self.axes = data_axes(mesh)
        self._run_cache = {}

    @property
    def num_replicas_in_sync(self) -> int:
        n = 1
        for ax in self.axes:
            n *= self.mesh.shape[ax]
        return n

    @contextlib.contextmanager
    def scope(self):
        """Make this the current strategy: trainers built inside the scope
        (DistributedTrainer / make_step) default to this strategy's mesh
        instead of the global one."""
        prev = current_strategy()
        _current.strategy = self
        try:
            yield self
        finally:
            _current.strategy = prev

    # ------------------------------------------------------------- running

    def run(self, fn: Callable, args=(), in_specs=None, out_specs=None):
        """Run ``fn`` once per replica under shard_map and return the
        global (mesh-stitched) result.

        By default every argument is split on its leading dimension over
        the data axes and outputs are likewise sharded; pass explicit
        PartitionSpecs to override (P() = replicated). The jitted wrapper
        is cached per (fn, specs), so calling run() in a loop does not
        retrace.
        """
        batch_spec = P(self.axes) if self.axes else P()
        if in_specs is None:
            in_specs = (batch_spec,) * len(args)
        if out_specs is None:
            out_specs = batch_spec
        key = (fn, tuple(in_specs) if isinstance(in_specs, (tuple, list))
               else in_specs, out_specs)
        jitted = self._run_cache.get(key)
        if jitted is None:
            shard_fn = jax.shard_map(fn, mesh=self.mesh, in_specs=in_specs,
                                     out_specs=out_specs, check_vma=False)
            jitted = self._run_cache[key] = jax.jit(shard_fn)
        return jitted(*args)

    def reduce(self, reduce_op: str, value, axis=0):
        """Merge a per-replica-stacked host/device value: "mean" | "sum"."""
        if reduce_op not in ("mean", "sum"):
            raise ValueError(f"reduce_op must be mean|sum, got {reduce_op!r}")
        fn = jnp.mean if reduce_op == "mean" else jnp.sum
        return jax.tree_util.tree_map(lambda x: fn(x, axis=axis), value)

    def experimental_distribute_dataset(self, dataset: Iterable):
        """Yield batches placed on the mesh, split over the data axes."""
        from .data import data_sharding, shard_batch
        sharding = data_sharding(self.mesh)
        for batch in dataset:
            yield shard_batch(batch, self.mesh, sharding=sharding)

    # ---------------------------------------------------------- train step

    def make_step(self, loss_fn: Callable, tx, params,
                  **trainer_kwargs) -> Callable:
        """Build a compiled distributed train step (the strategy-flavoured
        path into DistributedTrainer); returns ``step(batch) -> loss``."""
        from .training import DistributedTrainer
        trainer = DistributedTrainer(loss_fn, params, tx, mesh=self.mesh,
                                     **trainer_kwargs)

        def step(batch):
            return trainer.step(batch)

        step.trainer = trainer          # expose state for checkpointing
        return step

"""MirroredStrategy-style API over a device mesh.

The reference ships a forked ``tf.distribute.MirroredStrategy`` whose
cross-device ops route through BytePS push_pull instead of TF collectives
(reference: tensorflow/distribute/mirrored_strategy.py:349-430,
docs/MirroredStrategy.md). The TPU-native analogue keeps the strategy
surface — ``scope()``, ``run()``, ``reduce()``,
``experimental_distribute_dataset()``, ``num_replicas_in_sync`` — but a
"replica" is a slot on the mesh's data axes and ``run`` is a shard_map'd
call, so per-replica code compiles into one SPMD XLA program exactly like
the rest of the framework.
"""

from __future__ import annotations

import contextlib
import threading
from typing import Callable, Iterable, Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from .common.global_state import GlobalState
from .parallel.mesh import data_axes, make_mesh

_current = threading.local()


def current_strategy() -> Optional["MirroredStrategy"]:
    return getattr(_current, "strategy", None)


class MirroredStrategy:
    """Synchronous data-parallel strategy over the mesh's data axes.

    Example::

        strat = bps.MirroredStrategy()
        with strat.scope():
            step = strat.make_step(loss_fn, optax.adam(1e-3), params)
        loss = step(batch)          # batch split over replicas, grads synced
    """

    def __init__(self, mesh: Optional[Mesh] = None,
                 cross_device_ops=None) -> None:
        if mesh is None:
            mesh = GlobalState.get().mesh if GlobalState.initialized() \
                else make_mesh()
        self.mesh = mesh
        self.axes = data_axes(mesh)
        self._run_cache = {}
        # the reductions seam (reference: MirroredStrategy(devices,
        # cross_device_ops) wiring BytepsCrossDeviceOps in,
        # mirrored_strategy.py:365-372); default = the bucketed engine
        if cross_device_ops is None:
            from .cross_device_ops import BpsCrossDeviceOps
            cross_device_ops = BpsCrossDeviceOps(mesh=mesh)
        self.cross_device_ops = cross_device_ops

    @property
    def num_replicas_in_sync(self) -> int:
        n = 1
        for ax in self.axes:
            n *= self.mesh.shape[ax]
        return n

    @contextlib.contextmanager
    def scope(self):
        """Make this the current strategy: trainers built inside the scope
        (DistributedTrainer / make_step) default to this strategy's mesh
        instead of the global one."""
        prev = current_strategy()
        _current.strategy = self
        try:
            yield self
        finally:
            _current.strategy = prev

    # ------------------------------------------------------------- running

    def run(self, fn: Callable, args=(), in_specs=None, out_specs=None):
        """Run ``fn`` once per replica under shard_map and return the
        global (mesh-stitched) result.

        By default every argument is split on its leading dimension over
        the data axes and outputs are likewise sharded; pass explicit
        PartitionSpecs to override (P() = replicated). The jitted wrapper
        is cached per (fn, specs), so calling run() in a loop does not
        retrace.
        """
        batch_spec = P(self.axes) if self.axes else P()
        if in_specs is None:
            in_specs = (batch_spec,) * len(args)
        if out_specs is None:
            out_specs = batch_spec
        key = (fn, tuple(in_specs) if isinstance(in_specs, (tuple, list))
               else in_specs, out_specs)
        jitted = self._run_cache.get(key)
        if jitted is None:
            shard_fn = jax.shard_map(fn, mesh=self.mesh, in_specs=in_specs,
                                     out_specs=out_specs, check_vma=False)
            jitted = self._run_cache[key] = jax.jit(shard_fn)
        return jitted(*args)

    def reduce(self, reduce_op: str, value, axis=0):
        """Merge a per-replica-stacked host/device value: "mean" | "sum"
        (ReduceOp-style spellings like "MEAN"/ReduceOp.SUM accepted).
        ``axis=None`` keeps per-replica values and reduces ACROSS
        replicas through the cross-device ops instead (the reference's
        strategy.reduce semantics)."""
        from .cross_device_ops import ReduceOp
        op = ReduceOp.parse(reduce_op)
        if axis is None:
            return self.cross_device_ops.reduce(op, value)
        fn = jnp.mean if op == ReduceOp.MEAN else jnp.sum
        return jax.tree_util.tree_map(lambda x: fn(x, axis=axis), value)

    def batch_reduce(self, reduce_op: str, values):
        """Reduce several per-replica trees in ONE bucketed exchange
        (reference: batch_reduce_implementation +
        _make_gradient_chunks — small tensors share launches)."""
        return self.cross_device_ops.batch_reduce(reduce_op, values)

    def broadcast(self, value, root_replica: int = 0):
        """Every replica row := ``root_replica``'s row."""
        return self.cross_device_ops.broadcast(value,
                                               root_replica=root_replica)

    def experimental_distribute_dataset(self, dataset: Iterable,
                                        per_process: bool = False):
        """Yield batches placed on the mesh, split over the data axes.

        ``per_process=True``: each PROCESS's iterator yields only its
        local shard (multi-host input pipelines — the reference's
        per-worker dataset sharding in _experimental_distribute_dataset);
        batches are assembled into global arrays from the local data.
        Default: every process supplies the full global batch
        (single-controller convenience)."""
        from .data import data_sharding, shard_batch, shard_local_batch
        sharding = data_sharding(self.mesh)
        for batch in dataset:
            if per_process:
                yield shard_local_batch(batch, self.mesh, sharding=sharding)
            else:
                yield shard_batch(batch, self.mesh, sharding=sharding)

    # ---------------------------------------------------------- train step

    def make_step(self, loss_fn: Callable, tx, params,
                  **trainer_kwargs) -> Callable:
        """Build a compiled distributed train step (the strategy-flavoured
        path into DistributedTrainer); returns ``step(batch) -> loss``."""
        from .training import DistributedTrainer
        trainer = DistributedTrainer(loss_fn, params, tx, mesh=self.mesh,
                                     **trainer_kwargs)

        def step(batch):
            return trainer.step(batch)

        step.trainer = trainer          # expose state for checkpointing
        return step

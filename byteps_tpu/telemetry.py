"""Push-pull bandwidth telemetry (reference: PushPullSpeed,
global.cc:697-752 — a 10-second MB/s sliding window exposed to Python as
``bps.get_pushpull_speed()``, operations.cc:131-136)."""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Deque, Tuple

WINDOW_SEC = 10.0


class PushPullSpeed:
    def __init__(self, window_sec: float = WINDOW_SEC) -> None:
        self._lock = threading.Lock()
        self._window = window_sec
        self._events: Deque[Tuple[float, int]] = deque()  # (ts, nbytes)

    def record(self, nbytes: int, duration_s: float = 0.0) -> None:
        """Record a completed transfer. ``duration_s`` BACK-DATES the
        event to the transfer's start: booking all bytes at completion
        time made a long transfer look like an instantaneous burst and
        skewed ``mbps()`` for bursty windows (a 5 s push landing "now"
        doubled the apparent rate of the last instant). A duration
        longer than the window clamps to the window edge — the bytes
        then read as sustained window-rate instead of vanishing from
        the deque immediately."""
        now = time.time()
        # clamp inside the window (not exactly at its edge): an event
        # back-dated to precisely now-window would be evicted by the
        # first reader a microsecond later, vanishing the bytes of any
        # transfer longer than the window
        ts = now - min(max(float(duration_s), 0.0), self._window * 0.99)
        with self._lock:
            # back-dated events may land behind newer completions; keep
            # the deque ts-ordered so _evict's head-pop stays correct.
            # Scan from the TAIL — a transfer's start lies at most its
            # duration behind the newest event, so the insert point is
            # near the right end and the scan touches only the few
            # events that completed while this one was in flight (a
            # full-window list rebuild here would be O(n) per record
            # on the transfer hot path)
            if self._events and ts < self._events[-1][0]:
                idx = len(self._events)
                while idx > 0 and self._events[idx - 1][0] > ts:
                    idx -= 1
                self._events.insert(idx, (ts, nbytes))
            else:
                self._events.append((ts, nbytes))
            self._evict(now)

    def _evict(self, now: float) -> None:
        while self._events and now - self._events[0][0] > self._window:
            self._events.popleft()

    def mbps(self) -> float:
        """Mean MB/s over the sliding window."""
        now = time.time()
        with self._lock:
            self._evict(now)
            if not self._events:
                return 0.0
            total = sum(n for _, n in self._events)
            span = max(now - self._events[0][0], 1e-6)
            return total / span / 1e6


# ------------------------------------------------- stage aggregation
#
# Consumers of Timeline spans (bench.py's exchange-tail breakdown, the
# overlap regression test) need per-stage totals and the one question
# the streamed tail is judged on: did PS_H2D / PS_APPLY_CHUNK work
# actually START before the last PS_PULL FINISHED (real pipeline), or
# did the stages merely get renamed?

def summarize_stages(events) -> dict:
    """Aggregate Chrome-trace events (Timeline.snapshot()/comm.json
    ``traceEvents``) into ``{stage: {"count": n, "total_ms": ms}}``.

    Tolerates degenerate traces (hand-written fixtures, foreign
    producers, metadata events): entries without a ``name`` are
    skipped, a missing ``dur`` counts as 0 — previously a KeyError."""
    out: dict = {}
    for e in events:
        name = e.get("name")
        if name is None:
            continue
        s = out.setdefault(name, {"count": 0, "total_ms": 0.0})
        s["count"] += 1
        s["total_ms"] += e.get("dur", 0) / 1e3
    for s in out.values():
        s["total_ms"] = round(s["total_ms"], 3)
    return out


def _step_of(e: dict) -> int:
    """The event's step tag; 0 for events with missing/None ``args``
    (degenerate traces must group deterministically, not raise)."""
    return (e.get("args") or {}).get("step", 0)


def exchange_tail_overlap(events) -> dict:
    """Overlap stats for the streamed sync-PS tail.

    Computed PER STEP (events carry ``args.step``; comparing step 1's
    H2D against step N's pulls would overlap trivially): within a step,
    ``overlap_ms`` is how long before that step's LAST ``PS_PULL``
    finished its FIRST ``PS_H2D``/``PS_APPLY_CHUNK`` span started.
    Returns the max over steps and ``overlapped`` = any step's tail
    span started strictly before its last pull end. Empty/absent
    stages yield ``overlapped: False``."""
    pull_end: dict = {}
    tail_start: dict = {}
    for e in events:
        step = _step_of(e)
        if e.get("name") == "PS_PULL":
            pull_end[step] = max(pull_end.get(step, 0),
                                 e.get("ts", 0) + e.get("dur", 0))
        elif e.get("name") in ("PS_H2D", "PS_APPLY_CHUNK"):
            tail_start[step] = min(tail_start.get(step, 1 << 62),
                                   e.get("ts", 0))
    best = None
    for step, first_tail in tail_start.items():
        if step in pull_end:
            gap = pull_end[step] - first_tail
            best = gap if best is None else max(best, gap)
    if best is None:
        return {"overlapped": False, "overlap_ms": 0.0}
    return {"overlapped": best > 0,
            "overlap_ms": round(max(0.0, best) / 1e3, 3)}


def cross_step_overlap(events) -> dict:
    """Overlap stats for the CROSS-STEP pipeline (BPS_CROSS_STEP).

    The cross-barrier claim is inter-step: step k's straggler tail
    (``PS_APPLY_CHUNK``/``PS_PULL``/``PS_H2D`` spans tagged step k)
    must still be running when step k+1's first gated backward segment
    (``PS_BWD_SEG`` tagged step k+1) has already STARTED — a
    non-draining ``step()`` whose tail actually finished before the
    next step began would be a renamed barrier. Events must carry
    true-owner step tags (Timeline.record's explicit ``step``).
    Returns the max overlap across consecutive step pairs,
    ``overlapped`` = any pair overlapped, and ``gate_ms`` = total
    PS_XSTEP_GATE wait (what the gating cost, for the same trace)."""
    tail_end: dict = {}
    bwd_start: dict = {}
    gate_ms = 0.0
    for e in events:
        step = _step_of(e)
        if e.get("name") in ("PS_APPLY_CHUNK", "PS_PULL", "PS_H2D"):
            tail_end[step] = max(tail_end.get(step, 0),
                                 e.get("ts", 0) + e.get("dur", 0))
        elif e.get("name") == "PS_BWD_SEG":
            bwd_start[step] = min(bwd_start.get(step, 1 << 62),
                                  e.get("ts", 0))
        elif e.get("name") == "PS_XSTEP_GATE":
            gate_ms += e.get("dur", 0) / 1e3
    best = None
    for step, first_bwd in bwd_start.items():
        if step - 1 in tail_end:
            gap = tail_end[step - 1] - first_bwd
            best = gap if best is None else max(best, gap)
    if best is None:
        return {"overlapped": False, "overlap_ms": 0.0,
                "gate_ms": round(gate_ms, 3)}
    return {"overlapped": best > 0,
            "overlap_ms": round(max(0.0, best) / 1e3, 3),
            "gate_ms": round(gate_ms, 3)}


def exchange_head_overlap(events) -> dict:
    """Overlap stats for the staged sync-PS step HEAD.

    The head's pipeline claim is the mirror of the tail's: push-side
    work (``PS_D2H``/``PS_PACK``/``PS_PUSH``) for an early layer group
    must START before the backward's LAST ``PS_BWD_SEG`` span FINISHED
    — a staged backward whose pushes all fire after the final segment
    would be renamed stages, not a pipeline. Computed per step (see
    ``exchange_tail_overlap``); returns the max over steps and
    ``overlapped`` = any step's push-side span started strictly before
    its last backward segment ended."""
    bwd_end: dict = {}
    comm_start: dict = {}
    for e in events:
        step = _step_of(e)
        if e.get("name") == "PS_BWD_SEG":
            bwd_end[step] = max(bwd_end.get(step, 0),
                                e.get("ts", 0) + e.get("dur", 0))
        elif e.get("name") in ("PS_D2H", "PS_PACK", "PS_PUSH"):
            comm_start[step] = min(comm_start.get(step, 1 << 62),
                                   e.get("ts", 0))
    best = None
    for step, first_comm in comm_start.items():
        if step in bwd_end:
            gap = bwd_end[step] - first_comm
            best = gap if best is None else max(best, gap)
    if best is None:
        return {"overlapped": False, "overlap_ms": 0.0}
    return {"overlapped": best > 0,
            "overlap_ms": round(max(0.0, best) / 1e3, 3)}

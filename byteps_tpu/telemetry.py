"""Push-pull bandwidth telemetry (reference: PushPullSpeed,
global.cc:697-752 — a 10-second MB/s sliding window exposed to Python as
``bps.get_pushpull_speed()``, operations.cc:131-136)."""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Deque, Tuple

WINDOW_SEC = 10.0


class PushPullSpeed:
    def __init__(self, window_sec: float = WINDOW_SEC) -> None:
        self._lock = threading.Lock()
        self._window = window_sec
        self._events: Deque[Tuple[float, int]] = deque()  # (ts, nbytes)

    def record(self, nbytes: int, duration_s: float = 0.0) -> None:
        now = time.time()
        with self._lock:
            self._events.append((now, nbytes))
            self._evict(now)

    def _evict(self, now: float) -> None:
        while self._events and now - self._events[0][0] > self._window:
            self._events.popleft()

    def mbps(self) -> float:
        """Mean MB/s over the sliding window."""
        now = time.time()
        with self._lock:
            self._evict(now)
            if not self._events:
                return 0.0
            total = sum(n for _, n in self._events)
            span = max(now - self._events[0][0], 1e-6)
            return total / span / 1e6

"""Device-side PS_COMPRESS: encode the bucket BEFORE the D2H copy.

The host codec path (PR 7) compresses on the pack worker — after every
leaf already crossed PCIe dense, so only the WIRE shrank. This module
moves the whole encode onto the accelerator as one jitted pipeline per
bucket recipe:

    gather segments (device) -> fold EF residual (device) -> amax /
    scale -> Pallas quantize kernel -> D2H of the ENCODED bytes only

so the D2H copy, the host pack, and the wire shrink together (~4x for
int8/fp8). EF residuals become DEVICE-resident: the new residual is
computed on device (``x - dequant(q)``) and never crosses PCIe; the
plane's commit-on-pull protocol handles it unchanged (the pending slot
just holds a ``jax.Array``).

Byte-identity contract: the payload produced here is BYTE-IDENTICAL to
``wire.encode`` on the same dense input — same pure-f32 ``amax/denom``
scale rule (``wire.amax_scale``), the PR-7-proven int8 kernel, and the
fp8 kernel whose uint32 SR math is shared with the numpy reference.
``probe()`` verifies this end to end on an adversarial vector at
startup; any mismatch (or a backend whose Mosaic rejects the kernels)
falls back to the host codec with one INFO line — probe-or-fallback,
the staged-grad contract applied to the codec plane.

``BPS_COMPRESS_DEVICE``: ``auto`` (default — on when the default JAX
backend is an accelerator), ``1`` (force, e.g. CPU tests via Pallas
interpret mode), ``0`` (off).
"""

from __future__ import annotations

import functools
import os
import struct
import threading
from typing import List, Optional, Tuple

import numpy as np

from ..common.logging import get_logger
from . import wire

#: codecs the device pipeline can produce (topk's argsort has no
#: kernel; fp16 buckets gain nothing from a kernel — the cast IS the
#: D2H narrowing and jnp does it fine, but the astype path below
#: handles it anyway for uniform d2h accounting)
DEVICE_CODECS = (wire.CODEC_INT8, wire.CODEC_FP8_E4M3,
                 wire.CODEC_FP8_E5M2)

_log = get_logger()
_probe_lock = threading.Lock()
_probe_result: Optional[bool] = None


def _fp8_decode_device(q, kind):
    """fp8 byte encodings -> f32 on device, as pure uint32 math (no
    fp8 dtype needed — portable to Mosaics without float8 support);
    value-identical to ``fp8sr.decode_bits``."""
    import jax
    import jax.numpy as jnp

    from ..ops.compression import fp8sr
    _, mant, base, emin, e_sub, _ = fp8sr.fmt_params(kind)
    b = q.astype(jnp.uint32)
    sign = b >> jnp.uint32(7)
    mag8 = b & jnp.uint32(0x7F)
    e8 = mag8 >> jnp.uint32(mant)
    f8 = mag8 & jnp.uint32((1 << mant) - 1)
    norm_bits = (((e8 + jnp.uint32(emin - 1)) << jnp.uint32(23))
                 | (f8 << jnp.uint32(base)))
    norm = jax.lax.bitcast_convert_type(norm_bits, jnp.float32)
    sub = f8.astype(jnp.float32) * jnp.float32(2.0 ** (e_sub - 127))
    val = jnp.where(e8 > 0, norm, sub)
    return jnp.where(sign > 0, -val, val)


@functools.lru_cache(maxsize=256)
def _gather_amax(spec: Tuple[Tuple[int, int], ...], ef: bool):
    """Jitted stage 1 per (bucket segment recipe, EF): gather the
    bucket's flat f32 view on device, fold the residual, reduce amax.
    ``x`` stays device-resident for stage 2."""
    import jax
    import jax.numpy as jnp

    def fn(residual, *leaves):
        xs = [jnp.ravel(l)[off:off + ln].astype(jnp.float32)
              for l, (off, ln) in zip(leaves, spec)]
        x = xs[0] if len(xs) == 1 else jnp.concatenate(xs)
        if ef:
            x = x + residual
        return x, jnp.max(jnp.abs(x))

    return jax.jit(fn)


@functools.lru_cache(maxsize=32)
def _quantize(level: int, ef: bool):
    """Jitted stage 2 per (codec, EF): quantize at the HOST-computed
    scale (see ``wire.scale_from_amax`` — dividing on device is ~1 ulp
    off numpy and would break payload byte-identity), and compute the
    new device residual."""
    import jax
    import jax.numpy as jnp

    from ..ops.compression import fp8sr
    from ..ops.compression.pallas_kernels import (fp8_sr_quantize,
                                                 int8_quantize)
    kind = None if level == wire.CODEC_INT8 else (
        fp8sr.E4M3 if level == wire.CODEC_FP8_E4M3 else fp8sr.E5M2)

    def fn(x, scale, seed):
        if level == wire.CODEC_INT8:
            q = int8_quantize(x, scale)
            deq = q.astype(jnp.float32) * scale
        else:
            q = fp8_sr_quantize(x, scale, seed, kind)
            deq = _fp8_decode_device(q, kind) * scale
        new_r = (x - deq) if ef else None
        return q, new_r

    return jax.jit(fn)


def encode_bucket(parts: List[tuple], size: int, level: int, seed: int,
                  residual, ef: bool, div: int = wire.TOPK_DIV) -> tuple:
    """Encode one bucket on device. ``parts`` =
    ``[(device leaf, leaf_offset, length), ...]`` in bucket-segment
    order covering exactly ``size`` f32 elements. Returns
    ``(payload bytes, new device residual or None, d2h_bytes)``.

    Two jitted stages with a 4-byte amax sync between them: the sync is
    what lets the scale take the host division every other encode site
    uses (byte-identity), and it serializes nothing the pack worker
    wasn't already going to wait for — the payload D2H follows
    immediately."""
    import jax.numpy as jnp
    if level not in DEVICE_CODECS:
        raise ValueError(f"codec {wire.codec_name(level)} has no device "
                         f"encode")
    spec = tuple((int(off), int(ln)) for _, off, ln in parts)
    leaves = tuple(l for l, _, _ in parts)
    r = residual
    if ef and r is None:
        r = jnp.zeros(size, jnp.float32)
    x, amax = _gather_amax(spec, bool(ef))(r, *leaves)
    if level == wire.CODEC_INT8:
        denom = 127.0
    else:
        from ..ops.compression import fp8sr
        denom = fp8sr.fmt_max(fp8sr.E4M3 if level == wire.CODEC_FP8_E4M3
                              else fp8sr.E5M2)
    scale = wire.scale_from_amax(np.asarray(amax), denom)   # 4B sync
    q, new_r = _quantize(int(level), bool(ef))(
        x, jnp.float32(scale), jnp.uint32(seed & 0xFFFFFFFF))
    q_np = np.asarray(q)                      # the ONLY bulk D2H copy
    hdr = wire._HDR.pack(wire.MAGIC, wire.VERSION, level,
                         b"float32".ljust(8, b"\0"), size)
    body = (q_np.view(np.int8) if level == wire.CODEC_INT8
            else q_np.view(np.uint8)).tobytes()
    payload = hdr + struct.pack("<f", scale) + body
    return payload, new_r, len(body) + 4


def _probe() -> bool:
    """Bitwise probe: device payloads must equal the host codec's on an
    adversarial vector (ties, zeros, binade edges, denormal-range
    values). Any exception or byte mismatch -> fallback."""
    import jax.numpy as jnp
    rng = np.random.RandomState(0xB5C1)
    x = np.concatenate([
        rng.randn(3800).astype(np.float32),
        rng.randn(120).astype(np.float32) * 1e-4,
        rng.randn(120).astype(np.float32) * 1e3,
        np.array([0.0, -0.0, 0.5, -0.5, 1.0, 2.0 ** -10, -2.0 ** -10,
                  3.5, -3.5] * 6 + [1e-30, -1e-30], np.float32)])
    xd = jnp.asarray(x)
    n = x.size
    for cid in DEVICE_CODECS:
        host = wire.encode(cid, x, seed=1234)
        dev, _, _ = encode_bucket([(xd, 0, n)], n, cid, 1234,
                                  None, False)
        if dev != host:
            _log.info(
                "BPS_COMPRESS_DEVICE: device %s payload diverges from "
                "the host codec on this backend — falling back to host "
                "encode", wire.codec_name(cid))
            return False
    return True


def device_encode_enabled() -> bool:
    """Resolve BPS_COMPRESS_DEVICE (probe result cached per process;
    ``reset_probe`` for tests). ``auto`` keeps CPU rigs on the host
    codec — interpret-mode kernels are correct but not a speed-up."""
    global _probe_result
    v = (os.environ.get("BPS_COMPRESS_DEVICE", "auto") or "auto") \
        .strip().lower()
    if v in ("0", "off", "false", "none"):
        return False
    if v == "auto":
        import jax
        if jax.default_backend() == "cpu":
            return False
    with _probe_lock:
        if _probe_result is None:
            try:
                _probe_result = _probe()
            except Exception as e:   # noqa: BLE001 — probe-or-fallback
                _log.info(
                    "BPS_COMPRESS_DEVICE: device encode unavailable "
                    "(%s: %s) — falling back to host encode",
                    type(e).__name__, e)
                _probe_result = False
        return _probe_result


def reset_probe() -> None:
    """Forget the cached probe verdict (tests flip envs/backends)."""
    global _probe_result
    with _probe_lock:
        _probe_result = None
    _gather_amax.cache_clear()
    _quantize.cache_clear()

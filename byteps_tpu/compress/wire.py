"""Self-describing wire format for the fused compression plane.

The legacy compressed path (``server/compressed.py``) registers ONE
immutable codec per key at INIT time — the right shape for a static,
user-declared compression config, and the wrong one for the adaptive
controller, which re-decides each layer's codec at round boundaries
(arXiv 2105.07829). Here every compressed payload carries its own codec
HEADER, so a shard can decode any round's push without out-of-band
state, and two rounds of the same key in flight (cross-step) can carry
different codec decisions.

Header (little-endian, ``_HDR``)::

    magic:u16 | version:u8 | codec:u8 | dtype:char[8] | elems:u64

``magic``/``version`` are checked LOUDLY on decode: a torn frame, a
stale-version peer, or plain-dense bytes routed onto the fused path
raise :class:`CodecError` instead of scattering garbage into the store
— the codec analogue of the server plane's ``WrongEpoch`` refusal.

Codecs (the controller's ladder, cheapest first):

    ``none``      raw bytes (self-describing dense — used by replay paths)
    ``fp16``      float16 cast, 2x on fp32 buckets
    ``int8``      symmetric max-abs linear quantization, one fp32 scale
                  per bucket, round-half-even — deterministic, 4x
    ``fp8_e4m3``  max-abs-scaled fp8 (OCP e4m3fn) with DETERMINISTIC
                  counter-based stochastic rounding — same 4x as int8
                  but an unbiased quantizer with ~2^13 dynamic range
                  under the scale (EQuARX-style, arXiv 2506.17615);
                  sits ABOVE int8 in the ladder
    ``fp8_e5m2``  as above at e5m2 (range over mantissa) — the rung for
                  long-tailed gradient distributions
    ``topk``      largest-k magnitudes as (int32 idx | fp32 val), k =
                  elems/topk_div — sparse, ~4x over int8 at div=32

All codecs are DETERMINISTIC functions of the dense input — the fp8
rungs' stochastic rounding draws its noise from a counter-based hash of
``(element index, seed)`` with the seed derived from ``(key, round)``
(``sr_seed``) or supplied by the caller, never from a global RNG — so a
fixed codec decision trace makes compressed training reproducible
bit-for-bit, and a server re-encoding a merged round serves
byte-identical payloads to every puller without a cache being load-
bearing (the cache in :class:`FusedPullCache` is for throughput only).
"""

from __future__ import annotations

import struct
import threading
from typing import Dict, Optional

import numpy as np

MAGIC = 0xB5C1
#: v2 renumbered the codec ids to keep ladder order == codec id when
#: the fp8 rungs landed above int8 (topk moved 3 -> 5) — a v1 peer's
#: payloads are refused LOUDLY by the version check below, never
#: misdecoded through the shifted id space
VERSION = 2

(CODEC_NONE, CODEC_FP16, CODEC_INT8, CODEC_FP8_E4M3, CODEC_FP8_E5M2,
 CODEC_TOPK) = 0, 1, 2, 3, 4, 5

#: controller ladder order — index = aggressiveness level (the fp8
#: rungs ride above int8: same wire bytes, unbiased quantizer)
LEVELS = ("none", "fp16", "int8", "fp8_e4m3", "fp8_e5m2", "topk")
_NAME_TO_ID = {n: i for i, n in enumerate(LEVELS)}

FP8_CODECS = (CODEC_FP8_E4M3, CODEC_FP8_E5M2)


def _fp8_kind(cid: int) -> int:
    from ..ops.compression import fp8sr
    return fp8sr.E4M3 if cid == CODEC_FP8_E4M3 else fp8sr.E5M2


def sr_seed(key: int, rnd: int) -> int:
    """The one (key, round) -> stochastic-rounding seed derivation,
    shared by every server-side encode site (pull re-encode, the
    homogeneous merge renormalize) so divergent paths serve
    byte-identical fp8 payloads for the same round. splitmix64-style
    fold to 32 bits; pure, no state."""
    h = (int(key) * 0x9E3779B97F4A7C15 + int(rnd) * 0xBF58476D1CE4E5B9) \
        & 0xFFFFFFFFFFFFFFFF
    h ^= h >> 31
    h = (h * 0x94D049BB133111EB) & 0xFFFFFFFFFFFFFFFF
    h ^= h >> 29
    return h & 0xFFFFFFFF

_HDR = struct.Struct("<HBB8sQ")

#: default top-k keep fraction denominator (k = elems // TOPK_DIV)
TOPK_DIV = 32


class CodecError(RuntimeError):
    """A payload that cannot be decoded safely: bad magic (dense bytes
    or a torn frame on the fused path), codec-version mismatch between
    peers, an unknown codec id, or a body whose length disagrees with
    its header. Always LOUD — decoding a torn payload into plausible
    garbage and summing it would corrupt the round silently."""


def codec_id(name: str) -> int:
    try:
        return _NAME_TO_ID[name]
    except KeyError:
        raise ValueError(
            f"unknown fused codec {name!r}; expected one of {LEVELS}")


def codec_name(cid: int) -> str:
    if not 0 <= cid < len(LEVELS):
        raise ValueError(f"unknown fused codec id {cid}")
    return LEVELS[cid]


def lossy(cid: int) -> bool:
    return cid != CODEC_NONE


def topk_k(elems: int, div: int = TOPK_DIV) -> int:
    return max(1, int(elems) // int(div))


def wire_nbytes(cid: int, elems: int, dtype, div: int = TOPK_DIV) -> int:
    """Exact payload size (header included) for ``elems`` elements."""
    dt = np.dtype(dtype)
    if cid == CODEC_NONE:
        body = elems * dt.itemsize
    elif cid == CODEC_FP16:
        body = elems * 2
    elif cid in (CODEC_INT8, CODEC_FP8_E4M3, CODEC_FP8_E5M2):
        body = 4 + elems
    elif cid == CODEC_TOPK:
        body = 4 + topk_k(elems, div) * 8
    else:
        raise ValueError(f"unknown fused codec id {cid}")
    return _HDR.size + body


def scale_from_amax(amax, denom: float) -> np.float32:
    """``amax / denom`` in PURE f32 numpy ops — the one scale rule
    every encode site shares. The device pipeline feeds its (exact)
    device-computed amax through THIS host division rather than
    dividing on device: XLA's constant-divide strength reduction is ~1
    ulp off numpy's IEEE divide, which would break host<->device
    payload byte-identity."""
    amax = np.float32(amax)
    if not amax > 0:
        return np.float32(1.0)
    return np.float32(amax / np.float32(denom))


def amax_scale(x: np.ndarray, denom: float) -> np.float32:
    return scale_from_amax(
        np.max(np.abs(x)) if x.size else 0.0, denom)


def encode(cid: int, arr: np.ndarray, div: int = TOPK_DIV,
           seed: int = 0) -> bytes:
    """Compress a flat dense array into a self-describing payload.

    Lossy codecs run their math in fp32 regardless of the wire dtype
    recorded in the header (the decode target); ``none`` ships the raw
    bytes. Deterministic for every codec: the fp8 rungs' stochastic
    rounding is a pure function of ``(arr, seed)`` (see module
    docstring) — callers that need cross-site byte identity derive
    ``seed`` via :func:`sr_seed`."""
    arr = np.ascontiguousarray(np.asarray(arr).reshape(-1))
    dt = arr.dtype
    hdr = _HDR.pack(MAGIC, VERSION, cid,
                    dt.name.encode()[:8].ljust(8, b"\0"), arr.size)
    if cid == CODEC_NONE:
        return hdr + arr.tobytes()
    x = arr.astype(np.float32, copy=False)
    if cid == CODEC_FP16:
        return hdr + x.astype(np.float16).tobytes()
    if cid == CODEC_INT8:
        scale = amax_scale(x, 127.0)
        # rint = round-half-even, matching jnp.round → the Pallas
        # int8 kernel pair produces byte-identical q for the same scale
        q = np.clip(np.rint(x / scale), -127, 127).astype(np.int8)
        return hdr + struct.pack("<f", scale) + q.tobytes()
    if cid in FP8_CODECS:
        from ..ops.compression import fp8sr
        kind = _fp8_kind(cid)
        scale = amax_scale(x, fp8sr.fmt_max(kind))
        q = fp8sr.sr_quantize_bits(x, scale, kind, seed)
        return hdr + struct.pack("<f", scale) + q.tobytes()
    if cid == CODEC_TOPK:
        k = topk_k(x.size, div)
        # ties to the lower index (stable argsort of -|x|), matching
        # the legacy HostTopk selection rule
        idx = np.argsort(-np.abs(x), kind="stable")[:k].astype(np.int32)
        return (hdr + struct.pack("<I", k) + idx.tobytes()
                + x[idx].astype(np.float32).tobytes())
    raise ValueError(f"unknown fused codec id {cid}")


def decode_for_store(payload, meta) -> np.ndarray:
    """The one decode recipe the server-side push paths share
    (``HostPSBackend.push_fused`` and the transport's OP_PUSH_F
    handler): validate the payload against the key's registered store
    meta — ``(nbytes, dtype, ...)`` or None for an unregistered key —
    and return the dense array in store dtype, ready for the engine."""
    if meta is None:
        return decode(payload)
    nbytes, dtype = meta[0], meta[1]
    return decode(payload,
                  expect_elems=int(nbytes) // np.dtype(dtype).itemsize,
                  expect_dtype=dtype)


def peek(payload) -> tuple:
    """(codec_id, dtype_name, elems) of a payload's header, validated.
    Raises :class:`CodecError` on anything that is not a well-formed
    fused payload of THIS version."""
    buf = bytes(payload[:_HDR.size]) if len(payload) >= _HDR.size else \
        bytes(payload)
    if len(buf) < _HDR.size:
        raise CodecError(
            f"fused payload truncated: {len(payload)} bytes is shorter "
            f"than the {_HDR.size}-byte codec header")
    magic, ver, cid, dt, elems = _HDR.unpack(buf)
    if magic != MAGIC:
        raise CodecError(
            f"bad codec magic 0x{magic:04x} (expected 0x{MAGIC:04x}) — "
            f"not a fused compression payload; refusing a torn decode")
    if ver != VERSION:
        raise CodecError(
            f"codec-version mismatch: payload v{ver}, this build speaks "
            f"v{VERSION} — refusing to decode across codec versions")
    if cid >= len(LEVELS):
        raise CodecError(f"unknown codec id {cid} in payload header")
    return cid, dt.rstrip(b"\0").decode(), int(elems)


def validate(payload, expect_elems: int) -> int:
    """STRUCTURAL validation without materializing the dense array —
    every check :func:`decode` would fail on (header, element count,
    body length, topk k/index bounds), so a payload that passes here
    cannot make a later decode raise. The homogeneous sum store runs
    this at INGEST: a torn payload must refuse before it can count as
    a round arrival (refusing inside the merge would discard the other
    workers' buffered arrivals and poison the round). Returns the
    codec id."""
    payload = bytes(payload)
    cid, dt_name, elems = peek(payload)
    if elems != int(expect_elems):
        raise CodecError(
            f"fused payload declares {elems} elements, bucket plan "
            f"expects {expect_elems} — key/plan mismatch")
    body = len(payload) - _HDR.size
    dt = np.dtype(dt_name)
    if cid == CODEC_NONE:
        want = elems * dt.itemsize
    elif cid == CODEC_FP16:
        want = elems * 2
    elif cid in (CODEC_INT8, CODEC_FP8_E4M3, CODEC_FP8_E5M2):
        want = 4 + elems
    else:                                   # CODEC_TOPK
        if body < 4:
            raise CodecError("topk body missing its k prefix")
        (k,) = struct.unpack("<I", payload[_HDR.size:_HDR.size + 4])
        want = 4 + k * 8
        if body == want and k:
            idx = np.frombuffer(payload, np.int32,
                                count=k, offset=_HDR.size + 4)
            if idx.min() < 0 or idx.max() >= elems:
                raise CodecError(
                    f"topk index out of range 0..{elems} — torn payload")
    if body != want:
        raise CodecError(
            f"{codec_name(cid)} body is {body} bytes for {elems} "
            f"elements (expected {want})")
    return cid


def decode(payload, expect_elems: Optional[int] = None,
           expect_dtype=None) -> np.ndarray:
    """Decompress a payload to its dense flat array (header dtype, or
    ``expect_dtype`` when given). Every structural inconsistency —
    element-count mismatch with the caller's bucket plan, body length
    disagreeing with the header — is a :class:`CodecError`."""
    payload = bytes(payload)
    cid, dt_name, elems = peek(payload)
    if expect_elems is not None and elems != expect_elems:
        raise CodecError(
            f"fused payload declares {elems} elements, bucket plan "
            f"expects {expect_elems} — key/plan mismatch")
    dt = np.dtype(dt_name)
    body = payload[_HDR.size:]
    if cid == CODEC_NONE:
        if len(body) != elems * dt.itemsize:
            raise CodecError(
                f"dense body is {len(body)} bytes, header says "
                f"{elems}x{dt.itemsize}")
        out = np.frombuffer(body, dt).copy()
    elif cid == CODEC_FP16:
        if len(body) != elems * 2:
            raise CodecError(
                f"fp16 body is {len(body)} bytes for {elems} elements")
        out = np.frombuffer(body, np.float16).astype(np.float32)
    elif cid == CODEC_INT8:
        if len(body) != 4 + elems:
            raise CodecError(
                f"int8 body is {len(body)} bytes for {elems} elements")
        (scale,) = struct.unpack("<f", body[:4])
        out = np.frombuffer(body[4:], np.int8).astype(np.float32) * scale
    elif cid in FP8_CODECS:
        if len(body) != 4 + elems:
            raise CodecError(
                f"fp8 body is {len(body)} bytes for {elems} elements")
        (scale,) = struct.unpack("<f", body[:4])
        from ..ops.compression import fp8sr
        out = fp8sr.decode_bits(np.frombuffer(body[4:], np.uint8),
                                _fp8_kind(cid)) * np.float32(scale)
    elif cid == CODEC_TOPK:
        if len(body) < 4:
            raise CodecError("topk body missing its k prefix")
        (k,) = struct.unpack("<I", body[:4])
        if len(body) != 4 + k * 8:
            raise CodecError(
                f"topk body is {len(body)} bytes for k={k}")
        idx = np.frombuffer(body[4:4 + k * 4], np.int32)
        vals = np.frombuffer(body[4 + k * 4:], np.float32)
        if k and (idx.min() < 0 or idx.max() >= elems):
            raise CodecError(
                f"topk index out of range 0..{elems} — torn payload")
        out = np.zeros(elems, np.float32)
        out[idx] = vals
    else:  # pragma: no cover — peek() already refused
        raise CodecError(f"unknown codec id {cid}")
    want = np.dtype(expect_dtype) if expect_dtype is not None else dt
    return out.astype(want, copy=False)


# how many recompressed rounds each (key, codec) keeps: all workers pull
# round r before r+2 can complete (admission gate: they must pull r
# before pushing r+1), so 4 is comfortably past the in-flight window
_CACHE_ROUNDS = 4


class FusedPullCache:
    """Per-backend cache of encoded merged rounds for the fused pull
    path. Purely a THROUGHPUT cache — every fused codec is
    deterministic, so a miss re-encodes byte-identical payloads; what
    the cache buys is skipping the dense copy out of the engine and the
    encode for every puller after the first (the same lesson the
    native legacy path learned, server/compressed.py)."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        # key -> {(round, codec): payload}, insertion-ordered eviction
        self._cache: Dict[int, Dict[tuple, bytes]] = {}

    def get(self, key: int, rnd: int, cid: int,
            div: int = TOPK_DIV) -> Optional[bytes]:
        if rnd == 0:
            return None          # round 0 = "latest": mutates, never cache
        with self._lock:
            return self._cache.get(key, {}).get((rnd, cid, div))

    def put(self, key: int, rnd: int, cid: int, payload: bytes,
            div: int = TOPK_DIV) -> None:
        if rnd == 0:
            return
        with self._lock:
            rounds = self._cache.setdefault(key, {})
            rounds.setdefault((rnd, cid, div), payload)
            while len(rounds) > _CACHE_ROUNDS:
                rounds.pop(next(iter(rounds)))

    def drop(self, key: int) -> None:
        """Invalidate a key's cached rounds. Called on (re-)INIT: a
        re-initialized store restarts its shard-local rounds, so a key
        migrated away and later BACK to this shard would otherwise be
        served its first tenancy's cached payloads for the recurring
        round numbers — silently stale gradients."""
        with self._lock:
            self._cache.pop(key, None)


def pull_encoded(backend, cache: Optional[FusedPullCache], key: int,
                 nbytes: int, dtype: str, cid: int, rnd: int,
                 timeout_ms: int = 30000, div: int = TOPK_DIV) -> bytes:
    """The one fused-pull recipe shared by ``HostPSBackend`` and the
    transport server: cache hit, else round-blocked dense pull out of
    the engine → ``encode`` at the requested codec → cache → bytes.
    ``div`` rides in from the puller's request so the topk keep
    fraction honors the worker's BPS_COMPRESS_TOPK_DIV in BOTH wire
    directions (it is part of the cache key — two workers configured
    differently must not be served each other's k)."""
    if cache is not None:
        hit = cache.get(key, rnd, cid, div)
        if hit is not None:
            return hit
    dense = np.empty(int(nbytes) // np.dtype(dtype).itemsize,
                     dtype=np.dtype(dtype))
    backend.pull(key, dense, round=rnd, timeout_ms=timeout_ms)
    # fp8 SR seed pinned to (key, round): every serve site — this
    # re-encode, a replica's, the homogeneous merge's renormalize —
    # derives the same seed, so they stay byte-interchangeable
    payload = encode(cid, dense, div=div, seed=sr_seed(key, rnd))
    if cache is not None:
        cache.put(key, rnd, cid, payload, div)
    return payload

"""Adaptive per-layer codec controller for the fused compression plane.

Compression only pays when the WIRE, not compute, is the bottleneck
(arXiv 2103.00543) — on an idle link the extra quantize/dequantize work
is pure loss, and the right codec strength tracks how congested the
link actually is (arXiv 2105.07829's adaptive compressed communication).
This controller closes that loop against the live PR-4 metrics registry
instead of a static config:

  signals (``bps.get_metrics()``):
    ``nic/stalls``                token-bucket pacing stalls (counter;
                                  a delta > 0 means senders waited on
                                  the wire since the last decision)
    ``server/engine_queue_depth`` enqueued-but-unsummed pushes (gauge;
                                  the server-side backlog)
    ``transport/resends``         reconnect-and-resend events (counter;
                                  a flapping wire)
    per-layer ``ps/push_bytes/<layer>``  who is actually loading the
                                  wire: the three global signals set
                                  the DIRECTION, the per-layer byte
                                  deltas pick which layers an
                                  up-ratchet applies to (a layer that
                                  moved no bytes since the last
                                  decision holds its level)

  decision ladder (``wire.LEVELS``): none -> fp16 -> int8 -> topk

Decisions happen at ROUND boundaries (the exchange calls ``on_round``
when it opens a round) with HYSTERESIS: a level moves only after
``hold`` CONSECUTIVE congested (or idle) verdicts, and a mixed/boundary
verdict resets both streaks — so a signal sitting on the threshold can
never flap the codec every round (each flap would invalidate the
server's per-(round, codec) pull cache and wiggle convergence behavior
for nothing). The hard fallback is built into the verdict: an IDLE wire
(all three signals quiet) decays every layer back toward ``none``, so
compression auto-disables where it would lose.

Every decision is observable: ``compress/level/<layer>`` gauges hold
the current ladder index per layer and ``compress/decisions`` counts
level CHANGES — when the bench's byte counters move, the registry says
why.
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional

from ..obs.metrics import MetricsRegistry, get_registry
from . import wire


class CompressController:
    """Maps live congestion signals to a per-layer codec level."""

    def __init__(self, registry: Optional[MetricsRegistry] = None,
                 max_level: str = "int8",
                 hold: int = 2,
                 queue_depth_min: float = 2.0,
                 interval: int = 1, fleet=None) -> None:
        self.reg = registry if registry is not None else get_registry()
        # fleet telemetry view (obs.fleet): when a scraper is present
        # (explicit, or the process-current one), the backlog signal is
        # the SCRAPED max engine queue depth across fresh shards — on
        # a remote deployment the worker-local gauge is a proxy that
        # nobody even sets, so without the fleet the controller was
        # blind to server pressure across process boundaries
        self._fleet = fleet
        self.max_level = wire.codec_id(max_level)
        self.hold = max(1, int(hold))
        self.queue_depth_min = float(queue_depth_min)
        self.interval = max(1, int(interval))
        self._lock = threading.Lock()
        self._layers: Dict[str, int] = {}        # layer -> ladder index
        self._gauges: Dict[str, object] = {}
        self._bytes: Dict[str, object] = {}      # ps/push_bytes/<layer>
        self._bytes_snap: Dict[str, int] = {}    # value at last decision
        self._up = 0                              # consecutive verdicts
        self._down = 0
        self._last_stalls = self.reg.counter("nic/stalls").value
        self._last_resends = self.reg.counter("transport/resends").value
        self._rounds_seen = 0
        self._m_decisions = self.reg.counter("compress/decisions")

    # ------------------------------------------------------------ layers

    def register_layer(self, layer: str) -> None:
        with self._lock:
            if layer in self._layers:
                return
            self._layers[layer] = wire.CODEC_NONE
            g = self.reg.gauge(f"compress/level/{layer}")
            g.set(wire.CODEC_NONE)
            self._gauges[layer] = g
            # per-layer wire-load signal (the exchange incs it on every
            # push of the layer's bucket, dense or fused): who is
            # actually loading the wire — see _shift
            self._bytes[layer] = self.reg.counter(
                f"ps/push_bytes/{layer}")
            self._bytes_snap[layer] = self._bytes[layer].value

    def level_of(self, layer: str) -> int:
        return self._layers.get(layer, wire.CODEC_NONE)

    def levels(self) -> Dict[str, int]:
        with self._lock:
            return dict(self._layers)

    # ---------------------------------------------------------- decision

    def _verdict(self) -> Optional[bool]:
        """True = wire-bound, False = idle, None = boundary (no vote).

        Deltas of the two counters since the LAST decision plus the
        backlog gauge's current value. All-quiet is the idle verdict —
        the hard auto-disable path; any stall/resend or a real backlog
        is wire-bound; a backlog below the floor with no stalls is the
        boundary case that must not flap the ladder."""
        stalls = self.reg.counter("nic/stalls").value
        resends = self.reg.counter("transport/resends").value
        depth = self.reg.gauge("server/engine_queue_depth").value
        fl = self._fleet
        if fl is None:
            from ..obs import fleet as fleet_mod
            fl = fleet_mod.current()
        if fl is not None:
            d = fl.max_queue_depth()
            if d is not None:
                # shard-attributed server pressure (scraped) replaces
                # the worker-local proxy; a fully-stale fleet view
                # (d None) falls back rather than reading 0-as-idle
                depth = d
        d_stalls = stalls - self._last_stalls
        d_resends = resends - self._last_resends
        self._last_stalls, self._last_resends = stalls, resends
        if d_stalls > 0 or d_resends > 0 or depth >= self.queue_depth_min:
            return True
        if d_stalls == 0 and d_resends == 0 and depth <= 0:
            return False
        return None

    def on_round(self) -> None:
        """One round boundary passed; every ``interval`` rounds, read
        the signals and (maybe) move the ladder."""
        with self._lock:
            self._rounds_seen += 1
            if self._rounds_seen % self.interval:
                return
            self.decide_locked()

    def decide(self) -> Dict[str, int]:
        """Force one decision pass (tests, explicit callers); returns
        the post-decision per-layer levels."""
        with self._lock:
            self.decide_locked()
            return dict(self._layers)

    def decide_locked(self) -> None:
        v = self._verdict()
        try:
            if v is None:
                # boundary signal: reset both streaks — hysteresis
                # means a threshold-riding signal holds levels steady
                self._up = self._down = 0
                return
            if v:
                self._up += 1
                self._down = 0
                if self._up >= self.hold:
                    self._up = 0
                    self._shift(+1)
            else:
                self._down += 1
                self._up = 0
                if self._down >= self.hold:
                    self._down = 0
                    self._shift(-1)
        finally:
            # "bytes since the last decision" is the _shift signal:
            # re-snapshot every pass, verdict or not
            for l, c in self._bytes.items():
                self._bytes_snap[l] = c.value

    def _shift(self, direction: int) -> None:
        """Move layers one ladder step (clamped to [none, max_level]);
        record changed levels in the gauges/counter.

        The per-layer ``ps/push_bytes/<layer>`` counters pick WHICH
        layers an up-ratchet applies to: only layers that actually
        moved bytes since the last decision — an idle layer (a second
        trainer between steps, an accumulation window) has nothing on
        the wire to compress, so ratcheting it buys codec work for
        free. Cold start (no layer has recorded bytes yet) falls back
        to all layers.
        Decays apply to every layer — an idle layer should shed its
        level, not hold it. Size/dtype eligibility is enforced by the
        plane at encode time — the controller only expresses wire
        pressure."""
        targets = self._layers
        if direction > 0:
            deltas = {l: self._bytes[l].value - self._bytes_snap[l]
                      for l in self._layers}
            loaded = {l for l, d in deltas.items() if d > 0}
            if loaded:
                targets = loaded
        for layer in list(targets):
            lvl = self._layers[layer]
            new = min(max(lvl + direction, wire.CODEC_NONE),
                      self.max_level)
            if new != lvl:
                self._layers[layer] = new
                self._gauges[layer].set(new)
                self._m_decisions.inc()
                # key-less flight event: codec decisions are context
                # for EVERY key's postmortem (a pull refused two
                # rounds later traces back to this ladder move)
                from ..obs import flight
                flight.record("codec", stage=layer,
                              detail=f"level {lvl}->{new}")


class FixedController:
    """Pinned decision trace: every registered layer runs ONE codec,
    forever. ``BPS_COMPRESS=<codec>`` — the determinism contract's
    anchor (a fixed trace + deterministic codecs = bit-reproducible
    compressed training) and the bench's non-adaptive arm."""

    def __init__(self, level: str,
                 registry: Optional[MetricsRegistry] = None) -> None:
        self.reg = registry if registry is not None else get_registry()
        self.level = wire.codec_id(level)
        self._layers: List[str] = []
        self._m_decisions = self.reg.counter("compress/decisions")

    def register_layer(self, layer: str) -> None:
        if layer in self._layers:
            return
        self._layers.append(layer)
        self.reg.gauge(f"compress/level/{layer}").set(self.level)
        if self.level != wire.CODEC_NONE:
            self._m_decisions.inc()

    def level_of(self, layer: str) -> int:
        return self.level

    def levels(self) -> Dict[str, int]:
        return {l: self.level for l in self._layers}

    def on_round(self) -> None:
        pass

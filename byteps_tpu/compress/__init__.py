"""Fused adaptive gradient compression for the streamed PS pipeline.

The compression PLANE: per-bucket codecs composed into the pipeline
(compress on the pack worker right before PUSH, decompress on the
pull → H2D path feeding ``ChunkedApply``), self-describing wire
payloads any shard can decode without out-of-band codec registration,
and a runtime controller that reads the live congestion signals from
the metrics registry and assigns each layer a codec level — ratcheting
up when the wire is the bottleneck, decaying to ``none`` when it isn't
(arXiv 2105.07829, 2103.00543). ``BPS_COMPRESS=auto|none|<codec>``;
docs/gradient-compression.md.

Modules:
  ``wire``        codec header + deterministic host codecs (incl. the
                  counter-based-SR fp8 rungs) + pull cache
  ``controller``  the adaptive (and the pinned) decision logic
  ``plane``       per-exchange state: eligibility, EF residuals, levels
  ``device``      device-side PS_COMPRESS: Pallas encode before D2H,
                  bitwise probe-or-fallback

The legacy per-key server-codec path (``server/compressed.py``, the
reference's INIT_C/PUSH_C/PULL_C protocol) stays available behind its
explicit opt-in — declaring a tensor with ``compressor_type`` kwargs —
and takes precedence for keys that declare it.
"""

from .controller import CompressController, FixedController
from .plane import CompressionPlane
from .wire import (CODEC_FP16, CODEC_FP8_E4M3, CODEC_FP8_E5M2,
                   CODEC_INT8, CODEC_NONE, CODEC_TOPK, CodecError,
                   FusedPullCache, LEVELS, codec_id, codec_name,
                   decode, encode, peek, pull_encoded, sr_seed,
                   wire_nbytes)

__all__ = [
    "CompressController", "CompressionPlane", "CodecError",
    "FixedController", "FusedPullCache", "LEVELS",
    "CODEC_NONE", "CODEC_FP16", "CODEC_INT8", "CODEC_FP8_E4M3",
    "CODEC_FP8_E5M2", "CODEC_TOPK",
    "codec_id", "codec_name", "decode", "encode", "peek",
    "pull_encoded", "sr_seed", "wire_nbytes",
]

"""Worker-side state of the fused compression plane.

``CompressionPlane`` is what ``PSGradientExchange`` talks to: one per
exchange, holding

  - per-PS-key codec eligibility (size floor, fp32-only lossy math) and
    the LAYER identity the controller decides on (``<decl>.<bucket>``),
  - the controller (adaptive or pinned — ``BPS_COMPRESS=auto|<codec>``),
  - per-key ERROR-FEEDBACK residual state with a commit-on-pull
    protocol: ``encode`` stages the round's new residual as PENDING and
    ``commit`` (called when that round's pull lands) installs it. The
    per-key admission gate already serializes round k's pull before
    round k+1's push of the same key, so with two rounds in flight the
    residual each compress reads is exactly the previous committed
    round's — and a round that DIES between push and pull never
    commits, leaving the EF state consistent for the retry instead of
    double-counting the dead round's error. (Compress-active keys pin
    ``BPS_MAX_LAG=1``, so this two-round window holds even when the
    rest of the fleet runs bounded-stale — docs/admission.md.)

Levels are PINNED PER ROUND: the exchange snapshots ``level_of`` for
every bucket when the round opens, and both the push and the pull of
that round use the snapshot — the controller re-deciding mid-round can
never make a worker pull a codec the server didn't encode.
"""

from __future__ import annotations

import threading
from typing import Dict, Optional

import numpy as np

from ..obs.metrics import MetricsRegistry, get_registry, metrics_enabled
from . import wire
from .controller import CompressController, FixedController

#: BPS_COMPRESS values that mean "plane off" (dense path, bit-identical
#: to a build without the plane)
OFF_VALUES = ("", "0", "none", "off", "false")


class _KeyState:
    __slots__ = ("size", "dtype", "layer", "residual", "pending",
                 "m_bytes", "sr_seq")

    def __init__(self, size: int, dtype, layer: str, m_bytes) -> None:
        self.size = int(size)
        self.dtype = np.dtype(dtype)
        self.layer = layer
        # committed EF state — a numpy array on the host-codec path, a
        # DEVICE array while the bucket rides the Pallas device encode
        # (residuals then never cross PCIe); consumers coerce
        self.residual = None
        self.pending: Optional[tuple] = None         # (round, residual)
        self.m_bytes = m_bytes                       # per-layer counter
        # fp8 stochastic-rounding sequence: advances per fp8 encode of
        # this key (decorrelates SR noise across EF iterations beyond
        # what the round tag gives) and is RESET by the idle-decay
        # flush — a `none`-decayed layer re-entering the ladder starts
        # from a clean SR trace, reproducible from the decision trace
        # alone
        self.sr_seq = 0


class CompressionPlane:
    """Per-exchange fused-compression state + controller front."""

    def __init__(self, mode: str, min_bytes: int = 65536,
                 ef: bool = True, interval: int = 1,
                 max_level: str = "int8", topk_div: int = wire.TOPK_DIV,
                 registry: Optional[MetricsRegistry] = None) -> None:
        mode = (mode or "none").strip().lower()
        if mode in OFF_VALUES:
            raise ValueError("CompressionPlane constructed with mode off "
                             "— callers must skip construction instead")
        self.mode = mode
        self.min_bytes = int(min_bytes)
        self.ef = bool(ef)
        self.topk_div = int(topk_div)
        self.reg = registry if registry is not None else get_registry()
        if mode == "auto":
            self.controller = CompressController(
                registry=self.reg, max_level=max_level, interval=interval)
        else:
            self.controller = FixedController(mode, registry=self.reg)
        self._keys: Dict[int, _KeyState] = {}
        self._lock = threading.Lock()
        self._m_raw = self.reg.counter("compress/raw_bytes")
        self._m_wire = self.reg.counter("compress/wire_bytes")

    @staticmethod
    def from_config(mode: Optional[str], min_bytes: int,
                    registry: Optional[MetricsRegistry] = None
                    ) -> Optional["CompressionPlane"]:
        """The one construction recipe (exchange + tests): env-resolved
        knobs, None when the plane is off."""
        import os
        # the repo's ONE env-parsing rule (common/config.py): a user
        # writing BPS_COMPRESS_EF=off must not silently keep EF on
        from ..common.config import _env, _env_bool, _env_int
        mode = (mode if mode is not None
                else _env("BPS_COMPRESS", None, "none"))
        if (mode or "none").strip().lower() in OFF_VALUES:
            return None
        if mode.strip().lower() == "auto" and not metrics_enabled():
            # the controller's verdict signals are metrics-registry
            # counters, and BPS_STATS=0 freezes every one of them at
            # zero: auto would be a silent permanent no-op. Say so.
            from ..common.logging import get_logger
            get_logger().warning(
                "BPS_COMPRESS=auto with BPS_STATS=0: the congestion "
                "signals the controller reads are frozen, so every "
                "layer will stay at `none` — enable BPS_STATS or pin "
                "a codec (BPS_COMPRESS=int8)")
        ef = _env_bool("BPS_COMPRESS_EF", None, True)
        interval = _env_int("BPS_COMPRESS_INTERVAL", None, 1)
        max_level = _env("BPS_COMPRESS_MAX", None, "int8")
        topk_div = _env_int("BPS_COMPRESS_TOPK_DIV", None,
                            wire.TOPK_DIV)
        return CompressionPlane(mode, min_bytes=min_bytes, ef=ef,
                                interval=interval, max_level=max_level,
                                topk_div=topk_div, registry=registry)

    # ------------------------------------------------------ registration

    def register(self, pskey: int, size: int, dtype, layer: str) -> bool:
        """Declare a bucket to the plane; returns eligibility. Lossy
        codec math runs in fp32, so only fp32 buckets at or above the
        compression floor are eligible — everything else stays on the
        dense path (same floor rule as the legacy
        BYTEPS_MIN_COMPRESS_BYTES)."""
        dt = np.dtype(dtype)
        nbytes = int(size) * dt.itemsize
        if dt != np.float32 or nbytes < self.min_bytes:
            return False
        with self._lock:
            if pskey not in self._keys:
                self._keys[pskey] = _KeyState(
                    size, dt, layer,
                    self.reg.counter(f"ps/push_bytes/{layer}"))
            self.controller.register_layer(layer)
        return True

    def active(self, pskey: int) -> bool:
        return pskey in self._keys

    # --------------------------------------------------------- decisions

    def on_round(self) -> None:
        self.controller.on_round()

    def level_of(self, pskey: int) -> int:
        st = self._keys.get(pskey)
        if st is None:
            return wire.CODEC_NONE
        return self.controller.level_of(st.layer)

    # --------------------------------------------------------- data path

    def _sr_seed(self, pskey: int, st: "_KeyState",
                 round_tag: int, level: int) -> int:
        """Worker-side fp8 SR seed: (key, round) folded with the key's
        SR sequence. Only fp8 levels take noise. Does NOT advance the
        sequence — callers bump ``st.sr_seq`` only after the encode
        SUCCEEDS, so a device-encode failure falling back to the host
        codec consumes exactly one sequence value and the run stays
        bitwise-equal to a pure-host run."""
        if level not in wire.FP8_CODECS:
            return 0
        return wire.sr_seed(pskey, round_tag) \
            ^ ((st.sr_seq * 0x9E3779B9) & 0xFFFFFFFF)

    def encode(self, pskey: int, buf: np.ndarray, level: int,
               round_tag: int) -> bytes:
        """Compress ``buf`` for the wire at ``level`` (> none), with the
        committed EF residual folded in and the round's NEW residual
        staged as pending (installed by ``commit`` when the pull
        lands)."""
        st = self._keys[pskey]
        x = np.asarray(buf, np.float32).reshape(-1)
        if self.ef and st.residual is not None:
            # np.asarray: the residual may live on DEVICE (a previous
            # round rode the Pallas encode and the level has since
            # moved to a host-only codec)
            x = x + np.asarray(st.residual, np.float32)
        payload = wire.encode(level, x.astype(st.dtype, copy=False),
                              div=self.topk_div,
                              seed=self._sr_seed(pskey, st, round_tag,
                                                 level))
        if level in wire.FP8_CODECS:
            st.sr_seq += 1
        if self.ef:
            st.pending = (round_tag,
                          x - wire.decode(payload, st.size, np.float32))
        st.m_bytes.inc(len(payload))
        self._m_raw.inc(st.size * st.dtype.itemsize)
        self._m_wire.inc(len(payload))
        return payload

    def encode_on_device(self, pskey: int, parts, level: int,
                         round_tag: int) -> tuple:
        """Device-side sibling of ``encode``: the bucket is gathered,
        EF-folded, and quantized ON DEVICE (``compress/device.py``
        Pallas pipeline) and only the ENCODED payload crosses D2H.
        ``parts`` is the bucket's segment recipe
        ``[(device leaf, leaf_offset, length), ...]``. EF residuals
        stay device-resident (committed by the same ``commit`` the host
        path uses). Returns ``(payload, d2h_bytes)``; raises to signal
        the caller's probe-or-fallback."""
        from . import device as cdev
        st = self._keys[pskey]
        seed = self._sr_seed(pskey, st, round_tag, level)
        payload, new_resid, d2h = cdev.encode_bucket(
            parts, st.size, level, seed,
            st.residual if self.ef else None, self.ef,
            div=self.topk_div)
        # state mutations only AFTER the fallible device encode: a
        # kernel failure falls back to plane.encode with the SAME
        # sr_seq, keeping the run bitwise-equal to a pure-host one
        if level in wire.FP8_CODECS:
            st.sr_seq += 1
        if self.ef:
            st.pending = (round_tag, new_resid)
        st.m_bytes.inc(len(payload))
        self._m_raw.inc(st.size * st.dtype.itemsize)
        self._m_wire.inc(len(payload))
        return payload, d2h

    def note_dense_push(self, pskey: int, nbytes: int) -> None:
        """Account a DENSE push of a plane-managed key into its
        per-layer ``ps/push_bytes/<layer>`` counter — the controller's
        which-layers-are-loading-the-wire signal must see the layer's
        traffic even while its level sits at ``none`` (that is exactly
        when an up-ratchet decision needs it)."""
        st = self._keys.get(pskey)
        if st is not None:
            st.m_bytes.inc(nbytes)
            # a dense round means the level decayed to none: clear the
            # fp8 SR sequence with it, so the layer re-entering the
            # ladder starts from a clean, trace-reproducible state
            st.sr_seq = 0

    def fold_residual(self, pskey: int, buf: np.ndarray,
                      round_tag: int) -> np.ndarray:
        """Dense-path sibling of ``encode`` for a key whose level
        decayed back to ``none`` while it still carries a residual:
        flush the residual into this round's push ONCE (pending a zero
        state, committed like any round) so the accumulated error isn't
        silently dropped when the controller disables compression."""
        st = self._keys.get(pskey)
        if st is None or not self.ef or st.residual is None:
            return buf
        out = (np.asarray(buf, np.float32).reshape(-1)
               + np.asarray(st.residual, np.float32)) \
            .astype(np.dtype(buf.dtype), copy=False)
        st.pending = (round_tag, None)      # commit clears the residual
        st.sr_seq = 0                       # clean SR state on decay too
        return out

    def decode(self, pskey: int, payload, round_tag: int) -> np.ndarray:
        """Decompress a pulled merged payload to the key's dense dtype
        and COMMIT the round's pending residual (see class docstring)."""
        st = self._keys[pskey]
        out = wire.decode(payload, st.size, st.dtype)
        self.commit(pskey, round_tag)
        return out

    def commit(self, pskey: int, round_tag: int) -> None:
        st = self._keys.get(pskey)
        if st is None or st.pending is None:
            return
        tag, resid = st.pending
        if tag == round_tag:
            st.residual = resid
            st.pending = None

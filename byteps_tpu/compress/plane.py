"""Worker-side state of the fused compression plane.

``CompressionPlane`` is what ``PSGradientExchange`` talks to: one per
exchange, holding

  - per-PS-key codec eligibility (size floor, fp32-only lossy math) and
    the LAYER identity the controller decides on (``<decl>.<bucket>``),
  - the controller (adaptive or pinned — ``BPS_COMPRESS=auto|<codec>``),
  - per-key ERROR-FEEDBACK residual state with a commit-on-pull
    protocol: ``encode`` stages the round's new residual as PENDING and
    ``commit`` (called when that round's pull lands) installs it. The
    per-key admission gate already serializes round k's pull before
    round k+1's push of the same key, so with two rounds in flight the
    residual each compress reads is exactly the previous committed
    round's — and a round that DIES between push and pull never
    commits, leaving the EF state consistent for the retry instead of
    double-counting the dead round's error.

Levels are PINNED PER ROUND: the exchange snapshots ``level_of`` for
every bucket when the round opens, and both the push and the pull of
that round use the snapshot — the controller re-deciding mid-round can
never make a worker pull a codec the server didn't encode.
"""

from __future__ import annotations

import threading
from typing import Dict, Optional

import numpy as np

from ..obs.metrics import MetricsRegistry, get_registry, metrics_enabled
from . import wire
from .controller import CompressController, FixedController

#: BPS_COMPRESS values that mean "plane off" (dense path, bit-identical
#: to a build without the plane)
OFF_VALUES = ("", "0", "none", "off", "false")


class _KeyState:
    __slots__ = ("size", "dtype", "layer", "residual", "pending",
                 "m_bytes")

    def __init__(self, size: int, dtype, layer: str, m_bytes) -> None:
        self.size = int(size)
        self.dtype = np.dtype(dtype)
        self.layer = layer
        self.residual: Optional[np.ndarray] = None   # committed EF state
        self.pending: Optional[tuple] = None         # (round, residual)
        self.m_bytes = m_bytes                       # per-layer counter


class CompressionPlane:
    """Per-exchange fused-compression state + controller front."""

    def __init__(self, mode: str, min_bytes: int = 65536,
                 ef: bool = True, interval: int = 1,
                 max_level: str = "int8", topk_div: int = wire.TOPK_DIV,
                 registry: Optional[MetricsRegistry] = None) -> None:
        mode = (mode or "none").strip().lower()
        if mode in OFF_VALUES:
            raise ValueError("CompressionPlane constructed with mode off "
                             "— callers must skip construction instead")
        self.mode = mode
        self.min_bytes = int(min_bytes)
        self.ef = bool(ef)
        self.topk_div = int(topk_div)
        self.reg = registry if registry is not None else get_registry()
        if mode == "auto":
            self.controller = CompressController(
                registry=self.reg, max_level=max_level, interval=interval)
        else:
            self.controller = FixedController(mode, registry=self.reg)
        self._keys: Dict[int, _KeyState] = {}
        self._lock = threading.Lock()
        self._m_raw = self.reg.counter("compress/raw_bytes")
        self._m_wire = self.reg.counter("compress/wire_bytes")

    @staticmethod
    def from_config(mode: Optional[str], min_bytes: int,
                    registry: Optional[MetricsRegistry] = None
                    ) -> Optional["CompressionPlane"]:
        """The one construction recipe (exchange + tests): env-resolved
        knobs, None when the plane is off."""
        import os
        # the repo's ONE env-parsing rule (common/config.py): a user
        # writing BPS_COMPRESS_EF=off must not silently keep EF on
        from ..common.config import _env, _env_bool, _env_int
        mode = (mode if mode is not None
                else _env("BPS_COMPRESS", None, "none"))
        if (mode or "none").strip().lower() in OFF_VALUES:
            return None
        if mode.strip().lower() == "auto" and not metrics_enabled():
            # the controller's verdict signals are metrics-registry
            # counters, and BPS_STATS=0 freezes every one of them at
            # zero: auto would be a silent permanent no-op. Say so.
            from ..common.logging import get_logger
            get_logger().warning(
                "BPS_COMPRESS=auto with BPS_STATS=0: the congestion "
                "signals the controller reads are frozen, so every "
                "layer will stay at `none` — enable BPS_STATS or pin "
                "a codec (BPS_COMPRESS=int8)")
        ef = _env_bool("BPS_COMPRESS_EF", None, True)
        interval = _env_int("BPS_COMPRESS_INTERVAL", None, 1)
        max_level = _env("BPS_COMPRESS_MAX", None, "int8")
        topk_div = _env_int("BPS_COMPRESS_TOPK_DIV", None,
                            wire.TOPK_DIV)
        return CompressionPlane(mode, min_bytes=min_bytes, ef=ef,
                                interval=interval, max_level=max_level,
                                topk_div=topk_div, registry=registry)

    # ------------------------------------------------------ registration

    def register(self, pskey: int, size: int, dtype, layer: str) -> bool:
        """Declare a bucket to the plane; returns eligibility. Lossy
        codec math runs in fp32, so only fp32 buckets at or above the
        compression floor are eligible — everything else stays on the
        dense path (same floor rule as the legacy
        BYTEPS_MIN_COMPRESS_BYTES)."""
        dt = np.dtype(dtype)
        nbytes = int(size) * dt.itemsize
        if dt != np.float32 or nbytes < self.min_bytes:
            return False
        with self._lock:
            if pskey not in self._keys:
                self._keys[pskey] = _KeyState(
                    size, dt, layer,
                    self.reg.counter(f"ps/push_bytes/{layer}"))
            self.controller.register_layer(layer)
        return True

    def active(self, pskey: int) -> bool:
        return pskey in self._keys

    # --------------------------------------------------------- decisions

    def on_round(self) -> None:
        self.controller.on_round()

    def level_of(self, pskey: int) -> int:
        st = self._keys.get(pskey)
        if st is None:
            return wire.CODEC_NONE
        return self.controller.level_of(st.layer)

    # --------------------------------------------------------- data path

    def encode(self, pskey: int, buf: np.ndarray, level: int,
               round_tag: int) -> bytes:
        """Compress ``buf`` for the wire at ``level`` (> none), with the
        committed EF residual folded in and the round's NEW residual
        staged as pending (installed by ``commit`` when the pull
        lands)."""
        st = self._keys[pskey]
        x = np.asarray(buf, np.float32).reshape(-1)
        if self.ef and st.residual is not None:
            x = x + st.residual
        payload = wire.encode(level, x.astype(st.dtype, copy=False),
                              div=self.topk_div)
        if self.ef:
            st.pending = (round_tag,
                          x - wire.decode(payload, st.size, np.float32))
        st.m_bytes.inc(len(payload))
        self._m_raw.inc(st.size * st.dtype.itemsize)
        self._m_wire.inc(len(payload))
        return payload

    def note_dense_push(self, pskey: int, nbytes: int) -> None:
        """Account a DENSE push of a plane-managed key into its
        per-layer ``ps/push_bytes/<layer>`` counter — the controller's
        which-layers-are-loading-the-wire signal must see the layer's
        traffic even while its level sits at ``none`` (that is exactly
        when an up-ratchet decision needs it)."""
        st = self._keys.get(pskey)
        if st is not None:
            st.m_bytes.inc(nbytes)

    def fold_residual(self, pskey: int, buf: np.ndarray,
                      round_tag: int) -> np.ndarray:
        """Dense-path sibling of ``encode`` for a key whose level
        decayed back to ``none`` while it still carries a residual:
        flush the residual into this round's push ONCE (pending a zero
        state, committed like any round) so the accumulated error isn't
        silently dropped when the controller disables compression."""
        st = self._keys.get(pskey)
        if st is None or not self.ef or st.residual is None:
            return buf
        out = (np.asarray(buf, np.float32).reshape(-1)
               + st.residual).astype(np.dtype(buf.dtype), copy=False)
        st.pending = (round_tag, None)      # commit clears the residual
        return out

    def decode(self, pskey: int, payload, round_tag: int) -> np.ndarray:
        """Decompress a pulled merged payload to the key's dense dtype
        and COMMIT the round's pending residual (see class docstring)."""
        st = self._keys[pskey]
        out = wire.decode(payload, st.size, st.dtype)
        self.commit(pskey, round_tag)
        return out

    def commit(self, pskey: int, round_tag: int) -> None:
        st = self._keys.get(pskey)
        if st is None or st.pending is None:
            return
        tag, resid = st.pending
        if tag == round_tag:
            st.residual = resid
            st.pending = None

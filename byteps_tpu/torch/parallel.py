"""torch DistributedDataParallel over the PS runtime (reference:
torch/parallel/distributed.py:122-287 — a module wrapper with
group-sync counting: every parameter's grad hook dispatches an async
push_pull, and the LAST hook of the backward drains them all, so
gradients are already averaged when ``loss.backward()`` returns and any
plain torch optimizer can step).

Differences from wrapping the optimizer (``DistributedOptimizer``):
the model, not the optimizer, is wrapped; grads sync during backward
with no ``synchronize()`` call; ``no_sync()`` accumulates locally for
gradient-accumulation loops, syncing on the first backward after the
context exits (torch DDP semantics)."""

from __future__ import annotations

from contextlib import contextmanager

import torch

from .compression import Compression
from .ops import declare_model_keys, push_pull_async, size, synchronize
from .optimizer import broadcast_parameters


class DistributedDataParallel(torch.nn.Module):
    def __init__(self, module: torch.nn.Module, broadcast_buffers=True,
                 compression=Compression.none):
        super().__init__()
        self.module = module
        self.broadcast_buffers = broadcast_buffers
        self._compression = compression
        self._require_backward_grad_sync = True
        self._handles = {}
        self._hook_handles = []
        named = list(module.named_parameters())
        self._parameter_names = {p: n for n, p in named}
        self._num_grads = sum(p.requires_grad for _, p in named)
        self._fired = 0
        if size() > 1:
            for _, p in named:
                if p.requires_grad:
                    self._hook_handles.append(
                        p.register_post_accumulate_grad_hook(self._hook))
        declare_model_keys(self._parameter_names.values())
        if size() > 1:
            if broadcast_buffers:
                # rank 0's weights AND buffers (batchnorm stats etc.)
                broadcast_parameters(self.module.state_dict(),
                                     root_rank=0)
            else:
                broadcast_parameters(dict(self.module.named_parameters()),
                                     root_rank=0)

    def forward(self, *args, **kwargs):
        if (self.broadcast_buffers and size() > 1
                and any(True for _ in self.module.buffers())):
            # torch DDP semantics: buffers re-broadcast from rank 0
            # before every forward so running stats stay identical
            broadcast_parameters(dict(self.module.named_buffers()),
                                 root_rank=0, prefix="Buffer.")
        return self.module(*args, **kwargs)

    def _hook(self, p):
        if not self._require_backward_grad_sync:
            return                      # no_sync(): accumulate locally
        name = self._parameter_names[p]
        if p in self._handles:
            raise RuntimeError(
                f"gradient for {name!r} is already in flight — the "
                f"previous backward left {len(self._handles)} "
                f"reduction(s) unsynced (requires_grad parameters unused "
                f"in that graph?). Call model.synchronize() after any "
                f"backward that does not touch every parameter "
                f"(upstream torch DDP raises in this case too).")
        compressed, ctx = self._compression.compress(p.grad)
        self._handles[p] = (push_pull_async(
            compressed, average=True, name="Gradient." + name), ctx)
        self._fired += 1
        if self._fired >= self._num_grads:
            # group-sync: the LAST grad of the backward drains every
            # handle, so backward() returns with averaged grads
            # (reference: byteps_torch_set_num_grads counting)
            self._sync_all()

    def _sync_all(self):
        for p, (handle, ctx) in self._handles.items():
            out = synchronize(handle)
            with torch.no_grad():
                p.grad.copy_(self._compression.decompress(out, ctx))
        self._handles.clear()
        self._fired = 0

    def synchronize(self):
        """Drain any in-flight grad reductions manually. Needed only for
        models where some requires_grad parameters are UNUSED in a given
        backward (the group count never fills — the same counting
        contract as the reference's byteps_torch_set_num_grads); call it
        between backward() and optimizer.step() in that case."""
        self._sync_all()

    @contextmanager
    def no_sync(self):
        """Skip gradient sync inside the context (accumulation loops);
        the first backward AFTER it syncs the accumulated grads."""
        self._require_backward_grad_sync = False
        try:
            yield
        finally:
            self._require_backward_grad_sync = True

"""DistributedOptimizer + parameter/optimizer-state broadcast for torch
(reference: torch/__init__.py:35-409).

The wrapper subclasses the user's optimizer class dynamically (same
trick as the reference) and:

  - hooks every parameter's gradient accumulation
    (``register_post_accumulate_grad_hook`` — the modern form of the
    reference's ``grad_acc.register_hook`` trick) to dispatch an async
    push_pull the moment a grad is ready, overlapping communication
    with the rest of backward;
  - counts ``backward_passes_per_step`` backwards before communicating
    (local gradient accumulation, reference :83-113);
  - ``synchronize()`` drains handles and writes averaged grads back, so
    gradient clipping between backward and step works (reference
    docstring pattern);
  - async-PS mode (``BPS_ENABLE_ASYNC``): ``step()`` applies the local
    update, pushes the weight DELTA, and pulls fresh global weights
    (reference :186-214).
"""

from __future__ import annotations

import os
from contextlib import contextmanager

import torch

from .compression import Compression
from .ops import _Dispatcher, push_pull_async, rank, size, synchronize


class _DistributedOptimizer(torch.optim.Optimizer):
    def __init__(self, params, named_parameters, compression,
                 backward_passes_per_step=1):
        super(self.__class__, self).__init__(params)
        self._compression = compression
        self._enable_async = os.getenv(
            "BPS_ENABLE_ASYNC", os.getenv("BYTEPS_ENABLE_ASYNC", "0")) \
            not in ("0", "", "false")

        named_parameters = list(named_parameters or [])
        if any(not isinstance(p, tuple) for p in named_parameters):
            raise ValueError("named_parameters should be a sequence of "
                             "(name, parameter) tuples, usually "
                             "model.named_parameters()")
        names = [n for n, _ in named_parameters]
        if len(set(names)) != len(names):
            dups = sorted({n for n in names if names.count(n) > 1})
            raise ValueError(f"parameter names must be unique; "
                             f"duplicates: {', '.join(dups)}")
        if named_parameters:
            self._parameter_names = {p: n for n, p in named_parameters}
        else:
            # one running index across ALL groups — per-group enumerate
            # would alias the first param of every group onto the same
            # PS key (first-wins init + wrong-shape push rejections)
            allp = [p for group in self.param_groups
                    for p in group["params"]]
            self._parameter_names = {
                p: f"push_pull.noname.{i}" for i, p in enumerate(allp)}
        self.backward_passes_per_step = backward_passes_per_step
        # forward position of each param (named_parameters yields in
        # module order) — used as exchange priority
        self._param_index = {p: i for i, p in
                             enumerate(self._parameter_names)}
        self._push_pull_delay = {p: backward_passes_per_step
                                 for p in self._parameter_names}
        self._handles = {}
        self._hook_handles = []
        self._requires_update = set()
        self._should_sync = True
        if size() > 1:
            self._register_hooks()
        from .ops import declare_model_keys
        declare_model_keys(self._parameter_names.values())

    def _register_hooks(self):
        for group in self.param_groups:
            for p in group["params"]:
                if p.requires_grad:
                    self._requires_update.add(p)
                    self._hook_handles.append(
                        p.register_post_accumulate_grad_hook(
                            self._make_hook()))

    def _make_hook(self):
        def hook(p):
            if p in self._handles and self._handles[p][0] is not None:
                if self._push_pull_delay[p] <= 0:
                    raise AssertionError(
                        "Gradients were computed more than "
                        "backward_passes_per_step times before step(); "
                        "increase backward_passes_per_step to accumulate")
            assert self._push_pull_delay[p] > 0
            handle, ctx = None, None
            self._push_pull_delay[p] -= 1
            if self._push_pull_delay[p] == 0:
                handle, ctx = self._push_pull_grad_async(p)
            self._handles[p] = (handle, ctx)
        return hook

    def _push_pull_grad_async(self, p):
        name = self._parameter_names[p]
        if self._enable_async:
            return None, None        # real handle created in step()
        compressed, ctx = self._compression.compress(p.grad)
        # priority = forward position: when channels are busy, earlier
        # layers' exchanges jump the queue, so the NEXT forward (which
        # consumes layer 0 first) unblocks soonest — the reference's
        # priority scheduling, which is what makes CrossBarrier pay off
        handle = push_pull_async(compressed, average=True,
                                 name="Gradient." + name,
                                 priority=self._param_index.get(p, 0))
        return handle, ctx

    def set_backward_passes_per_step(self, passes):
        self.backward_passes_per_step = passes
        for p in self._push_pull_delay:
            self._push_pull_delay[p] = passes

    def synchronize(self):
        if size() <= 1:
            return
        # params whose hook never fired (unused in this forward) and
        # that have no grad contribute nothing — forcing a push of
        # p.grad=None would crash; peers must skip them identically
        # (torch autograd leaves unused params' grads None everywhere)
        missing = {p for p in self._requires_update - set(self._handles)
                   if p.grad is not None}
        for p in missing:
            self._handles[p] = self._push_pull_grad_async(p)
        for p, (handle, ctx) in list(self._handles.items()):
            if handle is None and not self._enable_async:
                self._handles[p] = self._push_pull_grad_async(p)
        for p, (handle, ctx) in self._handles.items():
            if handle is None:
                continue
            out = synchronize(handle)
            self._push_pull_delay[p] = self.backward_passes_per_step
            if not self._enable_async:
                with torch.no_grad():
                    p.grad.copy_(self._compression.decompress(out, ctx))
        self._handles.clear()

    @contextmanager
    def skip_synchronize(self):
        if self._enable_async:
            raise AssertionError(
                "skip_synchronize cannot be used in async training")
        self._should_sync = False
        try:
            yield
        finally:
            self._should_sync = True

    def step(self, closure=None):
        if self._enable_async and size() > 1:
            # async-PS: local update → push delta → pull fresh weights
            # (no inter-worker barrier; the server folds deltas into the
            # global weights as they arrive)
            import numpy as _np
            from .ops import async_param_exchange
            old = {p: p.data.clone().detach()
                   for p in self._parameter_names}
            loss = super(self.__class__, self).step(closure)
            # the STORE runs fp32 (seeded below, so half/double models
            # work); BPS_ASYNC_WIRE_DTYPE narrows just the delta wire —
            # bf16 deltas cross at half the bytes, the server upcasts
            wire = os.environ.get("BPS_ASYNC_WIRE_DTYPE") or None
            if wire:
                import ml_dtypes  # noqa: F401 — registers bf16 w/ numpy
            for p, name in self._parameter_names.items():
                delta = (p.data - old[p]).cpu().numpy().astype(
                    _np.float32, copy=False)
                if wire:
                    delta = delta.astype(wire)
                fresh = async_param_exchange(
                    "AsyncParam." + name, delta,
                    old[p].cpu().numpy().astype(_np.float32, copy=False))
                with torch.no_grad():
                    p.data.copy_(torch.from_numpy(
                        _np.ascontiguousarray(fresh)).to(p.dtype))
            self._handles.clear()
            for p in self._push_pull_delay:
                self._push_pull_delay[p] = self.backward_passes_per_step
            return loss
        if self._should_sync:
            self.synchronize()
        return super(self.__class__, self).step(closure)


def DistributedOptimizer(optimizer, named_parameters=None,
                         compression=Compression.none,
                         backward_passes_per_step=1):
    """Wrap a torch optimizer so gradients are push_pull-averaged across
    workers before each step (reference: torch/__init__.py:218-252 —
    dynamic subclass of the wrapped optimizer's class)."""
    cls = type(optimizer.__class__.__name__, (optimizer.__class__,),
               dict(_DistributedOptimizer.__dict__))
    return cls(optimizer.param_groups, named_parameters, compression,
               backward_passes_per_step)


def broadcast_parameters(params, root_rank, prefix="Parameter."):
    """Root's values to every worker: non-root zeros + push_pull(sum)
    (reference: torch/__init__.py:259-291)."""
    if isinstance(params, dict):
        items = sorted(params.items())
    elif isinstance(params, list):
        items = [p if isinstance(p, tuple) else (None, p) for p in params]
    else:
        raise ValueError(f"invalid params of type {type(params)}")
    if size() <= 1:
        return
    handles = []
    for name, p in items:
        if not isinstance(p, torch.Tensor):
            continue
        with torch.no_grad():
            if rank() != root_rank:
                p.fill_(0)
        handles.append((p, push_pull_async(
            p, average=False,
            name=(prefix + name) if name else None)))
    for p, h in handles:
        out = synchronize(h)
        with torch.no_grad():
            p.copy_(out)


def broadcast_optimizer_state(optimizer, root_rank,
                              prefix="OptimizerState."):
    """Root's optimizer state to every worker; scalar state entries are
    tensor-ized for the wire (reference: torch/__init__.py:293-409)."""
    if size() <= 1:
        return
    if not optimizer.state_dict().get("state"):
        # fresh optimizer: materialize state slots with a zero-grad step
        # (reference/horovod trick) so every worker pushes the SAME key
        # set — without this a checkpoint-loaded root would push keys
        # fresh workers never push and both sides stall on the server.
        # Params are snapshotted/restored around the step: optimizers
        # with weight decay would otherwise drift them.
        saved = [(p, p.detach().clone())
                 for g in optimizer.param_groups for p in g["params"]]
        grads = [p.grad for p, _ in saved]
        for p, _ in saved:
            p.grad = torch.zeros_like(p)
        optimizer.step()
        with torch.no_grad():
            for (p, v), g in zip(saved, grads):
                p.copy_(v)
                p.grad = g
    state = optimizer.state_dict()
    tensors = {}
    scalars = []                       # (pid, key, original python type)
    for pid, pstate in state.get("state", {}).items():
        for k, v in list(pstate.items()):
            key = f"{prefix}{pid}.{k}"
            if isinstance(v, torch.Tensor):
                tensors[key] = v
            elif isinstance(v, (int, float)) and not isinstance(v, bool):
                t = torch.tensor(float(v), dtype=torch.float64)
                tensors[key] = t
                pstate[k] = t
                scalars.append((pid, k, type(v)))
    broadcast_parameters(tensors, root_rank, prefix="")
    for pid, k, typ in scalars:        # back to python scalars
        state["state"][pid][k] = typ(state["state"][pid][k].item())
    optimizer.load_state_dict(state)

"""Cross-iteration barrier removal for the eager torch path.

Plain ``DistributedOptimizer.step()`` drains EVERY parameter's
push_pull before updating anything, so the next iteration's forward
waits for the slowest tensor (reference: the default torch mode).
``CrossBarrier`` removes that barrier the way the reference's
scheduled optimizer does (reference: byteps/torch/cross_barrier.py:
28-120, after the ByteScheduler paper): per-parameter locks + a
poller thread apply each parameter's update the moment ITS exchange
lands, and pre-forward hooks on leaf modules block only on the
parameters that module actually reads — the next forward starts while
late gradients are still on the wire.

Differences from the reference (better, not copied):

- **any optimizer**: the reference hand-implements SGD/Adam/RMSprop
  update math in the poller and rejects everything else; here each
  parameter gets a CHILD instance of the user's own optimizer class
  (sharing the parent's ``state`` dict, so
  ``broadcast_optimizer_state`` and checkpoints see one source of
  truth) and the poller calls its ``step()`` — torch's own kernels,
  any optimizer, live hyperparameter changes (lr schedules) mirrored
  each update;
- **clean teardown**: ``flush()`` blocks until all in-flight updates
  are applied (tests, eval boundaries) — the reference only drains at
  ``num_steps``.

Usage (reference-compatible)::

    opt = bps.DistributedOptimizer(opt, named_parameters=...)
    opt = bps.CrossBarrier(model, opt, num_steps)
    ...
    loss.backward()
    opt.step()        # returns immediately; poller applies updates
"""

from __future__ import annotations

import queue
import threading
import time

import torch

from .ops import size, synchronize

__all__ = ["CrossBarrier"]


class CrossBarrier:
    """Wraps a ``byteps_tpu.torch.DistributedOptimizer`` (and the model
    whose parameters it owns) with per-parameter cross-iteration
    scheduling. See module docstring."""

    def __init__(self, model: torch.nn.Module, optimizer,
                 num_steps: int = 10 ** 6) -> None:
        if getattr(optimizer, "_enable_async", False):
            raise ValueError("CrossBarrier is a sync-mode scheduler; "
                             "async-PS mode has no barrier to cross")
        self._model = model
        self._opt = optimizer
        self._step_count = 0
        self._final_step = num_steps
        self._locks = {p: threading.Lock()
                       for g in optimizer.param_groups for p in g["params"]}
        self._child = {}          # param -> single-param child optimizer
        self._child_group = {}    # param -> its group in the PARENT
        # the user's optimizer class: the parent is a dynamic subclass
        # created by DistributedOptimizer, so its immediate base is the
        # real torch optimizer class
        self._user_cls = type(optimizer).__mro__[1]
        for g in optimizer.param_groups:
            for p in g["params"]:
                self._child_group[p] = g
        self._queue: "queue.Queue" = queue.Queue()
        self._stop = threading.Event()
        self._error = None
        self._poller = None
        self._ungated: set = set()
        if size() > 1:
            # intercept the parent's dispatch: every push_pull now also
            # takes the param's lock and lands on the poller's queue
            self._orig_dispatch = optimizer._push_pull_grad_async
            optimizer._push_pull_grad_async = self._dispatch
            self._register_forward_hooks()
            self._poller = threading.Thread(target=self._poll_loop,
                                            daemon=True,
                                            name="bps-cross-barrier")
            self._poller.start()

    # -- attribute delegation (param_groups, state, zero_grad target...) --

    def __getattr__(self, item):
        return getattr(self._opt, item)

    # -- dispatch + per-parameter completion ------------------------------

    def _dispatch(self, p):
        """Replaces the parent's ``_push_pull_grad_async``: same
        exchange, plus the forward-blocking lock and the poller event.
        Hyperparameters are SNAPSHOTTED here: the poller may apply this
        update after the user already mutated lr for the next step (lr
        schedulers run at iteration top), and the update must use the
        values in force when its gradient was produced — serial
        semantics, exactly.

        EVENT-DRIVEN: the item lands on the applier queue from the
        exchange future's done-callback, so the applier thread only
        ever sees LANDED exchanges — no poll/re-queue spinning, and no
        wakeups charged against compute while results are still on the
        wire."""
        self._locks[p].acquire()
        try:
            g = self._child_group[p]
            hyper = {k: v for k, v in g.items() if k != "params"}
            handle, ctx = self._orig_dispatch(p)
            item = (p, handle, ctx, hyper)
            if handle is None:
                self._queue.put(item)
            else:
                from .ops import _Dispatcher
                fut, _, _ = _Dispatcher.peek(handle)
                fut.add_done_callback(
                    lambda _f, _item=item: self._queue.put(_item))
        except BaseException:
            # a leaked lock would hang the next forward forever; release
            # and let the exception surface retryably from backward
            self._locks[p].release()
            raise
        return handle, ctx

    def _child_opt(self, p, hyper):
        child = self._child.get(p)
        if child is None:
            # hyperparams ride in the group dict, not constructor kwargs:
            # groups may carry keys that aren't __init__ args (e.g.
            # AdamW's decoupled_weight_decay)
            child = self._user_cls([{"params": [p], **hyper}])
            self._child[p] = child
        else:
            child.param_groups[0].update(hyper)
        # ONE state table: momentum/exp_avg buffers live in the parent,
        # so broadcast_optimizer_state / state_dict see them. Re-bound
        # on EVERY update because torch's load_state_dict REPLACES the
        # parent's state dict — a cached reference would silently keep
        # updating the pre-checkpoint buffers
        child.state = self._opt.state
        return child

    def _poll_loop(self):
        """Applier loop: every queued item's exchange has ALREADY landed
        (done-callback enqueue, see _dispatch), so each pass is
        synchronize → decompress → child step, with no busy polling."""
        while not self._stop.is_set():
            try:
                item = self._queue.get(timeout=0.1)
            except queue.Empty:
                continue
            if item is None:
                break
            p, handle, ctx, hyper = item
            try:
                if handle is not None:
                    out = synchronize(handle)
                    with torch.no_grad():
                        p.grad.copy_(
                            self._opt._compression.decompress(out, ctx))
                self._opt._push_pull_delay[p] = \
                    self._opt.backward_passes_per_step
                self._child_opt(p, hyper).step()
                # None, not zero_(): serial training's default
                # zero_grad(set_to_none=True) leaves unused params'
                # grads None so torch SKIPS their update — a zeroed
                # (non-None) grad would be re-dispatched every step and
                # momentum/weight-decay would keep moving the param
                p.grad = None
                # drop the parent's stale handle entry so the next
                # backward's hook doesn't trip on an already-applied
                # exchange (safe: the hook can only write a NEW entry
                # from _dispatch, which blocks on the lock we hold)
                if self._opt._handles.get(p, (None,))[0] is handle:
                    self._opt._handles.pop(p, None)
            except BaseException as e:   # noqa: BLE001 — re-raised on the
                # restore dispatchability first: a delay stuck at 0 (or
                # a live grad) would raise the misleading "more than
                # backward_passes_per_step" assertion on the NEXT
                # backward before step() could surface the real error
                self._opt._push_pull_delay[p] = \
                    self._opt.backward_passes_per_step
                p.grad = None
                self._error = e          # training thread via step/flush
            finally:
                self._locks[p].release()

    # -- forward blocking --------------------------------------------------

    def _register_forward_hooks(self):
        def pre_hook(mod, inputs):
            for p in mod.parameters(recurse=False):
                self._opt._handles.pop(p, None)
                lock = self._locks.get(p)
                if lock is not None:
                    with lock:       # wait until the poller released it
                        pass
        covered = set()
        for mod in self._model.modules():
            direct = list(mod.parameters(recurse=False))
            if direct:
                mod.register_forward_pre_hook(pre_hook)
                covered.update(direct)
        # Params NOT read through their owning module's forward
        # (functional application, tied weights) bypass the gate above:
        # their backward hook can fire while last step's update is
        # still in flight. Those get a fallback wait in a WRAPPED
        # backward hook instead — correct, at the cost of blocking
        # backward on that one param's in-flight update.
        self._ungated = set(self._locks) - covered
        if self._ungated:
            opt = self._opt
            for h in opt._hook_handles:
                h.remove()
            opt._hook_handles = []
            inner = opt._make_hook()

            def gated_hook(p):
                if p in self._ungated:
                    lock = self._locks.get(p)
                    if lock is not None:
                        with lock:   # in-flight update applied
                            pass
                    opt._handles.pop(p, None)
                inner(p)

            for g in opt.param_groups:
                for p in g["params"]:
                    if p.requires_grad:
                        opt._hook_handles.append(
                            p.register_post_accumulate_grad_hook(
                                gated_hook))

    # -- optimizer surface -------------------------------------------------

    def step(self, closure=None):
        """Dispatch any parameters whose hooks never fired, then return
        WITHOUT waiting — per-parameter updates land in the poller."""
        if self._error is not None:
            err, self._error = self._error, None
            raise err
        # in-flight exchanges (locks held from dispatch until the poller
        # applies) mean the scheduled path MUST run, even at step 0: the
        # documented usage has no bare init step, and a plain local
        # update here would race the poller's averaged update
        inflight = any(l.locked() for l in self._locks.values())
        if size() > 1 and (self._step_count > 0 or inflight):
            opt = self._opt
            missing = {p for p in opt._requires_update - set(opt._handles)
                       if p.grad is not None}
            for p in missing:
                opt._handles[p] = opt._push_pull_grad_async(p)
            for p, (handle, ctx) in list(opt._handles.items()):
                if handle is None:
                    opt._handles[p] = opt._push_pull_grad_async(p)
            # ungated params (no owning-module forward to gate): the
            # next forward reads them with NO lock, so their in-flight
            # updates must land before step() returns — overlap is kept
            # for every module-gated param
            for p in self._ungated:
                lock = self._locks.get(p)
                if lock is not None:
                    with lock:
                        pass
            loss = closure() if closure is not None else None
            self._step_count += 1
            if self._step_count >= self._final_step:
                self.flush()
            return loss
        # step 0 (parameter-broadcast init) or single worker: plain step
        loss = self._user_cls.step(self._opt, closure)
        self._step_count += 1
        return loss

    def zero_grad(self, set_to_none: bool = True):
        """No-op after step 1: the poller zeroes each grad right after
        its per-parameter update (zeroing here would race in-flight
        exchanges)."""
        if size() <= 1 or self._step_count == 0:
            self._opt.zero_grad(set_to_none=set_to_none)

    def flush(self, timeout: float = 60.0):
        """Block until every in-flight exchange has been applied — use
        at eval boundaries, checkpoints, or end of training."""
        deadline = time.time() + timeout
        while not self._queue.empty():
            if time.time() > deadline:
                raise TimeoutError("cross-barrier flush timed out")
            time.sleep(0.001)
        # queue empty means *taken*, not applied: grab every lock once
        for p, lock in self._locks.items():
            if not lock.acquire(timeout=max(0.0, deadline - time.time())):
                raise TimeoutError("cross-barrier flush timed out")
            lock.release()
        if self._error is not None:
            err, self._error = self._error, None
            raise err

    def close(self):
        """Stop the poller (flushes first)."""
        if self._poller is not None:
            self.flush()
            self._stop.set()
            self._queue.put(None)
            self._poller.join(timeout=10)
            self._poller = None

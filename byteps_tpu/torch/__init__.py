"""PyTorch plugin: the reference's ``byteps.torch`` API over the
TPU-native runtime.

The reference's largest plugin (reference: torch/__init__.py 409 LoC +
ops.py + cross_barrier.py) hooks every parameter's grad accumulator,
push_pulls gradients asynchronously while backward still runs, and
drains the handles in ``step()``. Same surface here, redesigned for
this runtime:

  - torch tensors live on the HOST, so gradients take the PS host path
    directly (PSGradientExchange — sharded servers, compression,
    priorities) with no device round-trip; a single-thread dispatcher
    gives the backward/communication overlap the reference gets from
    its pipeline (order across workers doesn't matter: the PS server
    matches contributions per KEY, exactly like ps-lite).
  - world size is the PS worker count (``BPS_NUM_WORKER``); at world 1
    every op is a local no-op, like the reference built without
    distributed support.
  - ``BPS_ENABLE_ASYNC`` switches ``DistributedOptimizer`` to the
    async-PS protocol: local step, push weight DELTAS, pull fresh
    global weights (reference: torch/__init__.py:186-214).

Usage is byteps-torch-compatible::

    import byteps_tpu.torch as bps
    bps.init()
    optimizer = bps.DistributedOptimizer(
        optimizer, named_parameters=model.named_parameters())
    bps.broadcast_parameters(model.state_dict(), root_rank=0)
"""

from __future__ import annotations

from .compression import Compression
from .cross_barrier import CrossBarrier
from .ops import (declare, init, local_rank, local_size, poll, push_pull,
                  push_pull_async, push_pull_async_inplace, rank, shutdown,
                  size, synchronize)
from .optimizer import (DistributedOptimizer, broadcast_optimizer_state,
                        broadcast_parameters)
from .parallel import DistributedDataParallel

__all__ = [
    "Compression", "CrossBarrier", "DistributedDataParallel",
    "DistributedOptimizer", "broadcast_optimizer_state",
    "broadcast_parameters", "declare", "init", "local_rank", "local_size",
    "poll", "push_pull", "push_pull_async", "push_pull_async_inplace",
    "rank", "shutdown", "size", "synchronize",
]

"""torch-tensor push_pull ops (reference: torch/ops.py:48-236 +
handle_manager.{cc,h} — int handles over in-flight reductions).

Handles wrap futures on a priority-scheduled multi-channel pool
(``_Dispatcher``): dispatch returns immediately (backward keeps
running), exchanges drain lowest-priority-first across
``BPS_TORCH_CHANNELS`` push workers, and pulls resolve on separate
pull workers so a blocked pull never keeps pushes off the wire.
Exchange START order is therefore NOT per-process FIFO — anything
order-sensitive (name→key declaration) happens on the dispatching
thread in ``_dispatch``. Cross-worker matching is per KEY on the PS
server, so workers may run exchanges in different orders (the
reference relies on the same ps-lite property)."""

from __future__ import annotations

import threading
from concurrent.futures import Future
from typing import Dict, Optional, Tuple

import numpy as np
import torch

from ..common.global_state import GlobalState


def init(config=None, **kwargs) -> None:
    """bps.init() for torch scripts.

    Defaults to HOST-ONLY mode (no device mesh, no JAX backend
    discovery): the torch plugin's wire is numpy-over-TCP end to end,
    so touching accelerator discovery at init only added a hang risk
    when the TPU tunnel is unreachable. Set ``BPS_HOST_ONLY=0`` to get
    the full collective engine in the same process (mixed torch+JAX
    scripts)."""
    import byteps_tpu as bps
    if config is None and not GlobalState.initialized():
        from ..common.config import Config, _env_bool
        config = Config.from_env(
            host_only=_env_bool("BPS_HOST_ONLY", None, default=True))
    bps.init(config=config, **kwargs)


def shutdown() -> None:
    import byteps_tpu as bps
    _Dispatcher.reset()
    _async_inited.clear()
    bps.shutdown()


def size() -> int:
    """World size = PS worker-process count (torch processes are the
    replicas; the jax mesh inside each is an implementation detail)."""
    return GlobalState.get().config.num_worker


def rank() -> int:
    return GlobalState.get().config.worker_id


def local_rank() -> int:
    return GlobalState.get().config.local_rank


def local_size() -> int:
    return GlobalState.get().config.local_size


def declare(name: str, **kwargs) -> None:
    """Pre-declare a tensor (priority / compression kwargs — reference:
    byteps_declare_tensor)."""
    GlobalState.get().registry.declare(name, **kwargs)


def declare_model_keys(names) -> None:
    """Declare Gradient.* then Parameter.* keys for a model's parameter
    names — two sorted loops for key-range load balancing, the
    reference's exact pattern (torch/__init__.py:95-100); shared by
    DistributedOptimizer and DistributedDataParallel so both map params
    onto identical PS key ranges."""
    reg = GlobalState.get().registry
    for name in sorted(names):
        reg.declare("Gradient." + name)
    for name in sorted(names):
        reg.declare("Parameter." + name)


class _Dispatcher:
    """Process-wide handle table + PRIORITY-scheduled channel pool.

    Multi-channel (``BPS_TORCH_CHANNELS``, default 4): a slow tensor
    must not head-of-line-block every later exchange — the reference
    runs free multi-channel push/pull loops. Pending exchanges drain in
    PRIORITY order (lower value first; ties FIFO): backward produces
    the LAST layer's gradient first, but the next forward needs the
    FIRST layer's parameters first, so the optimizer submits each
    parameter with its forward position as priority and queued
    exchanges jump ahead of later layers' (the reference's
    BYTEPS_SCHEDULING priority / the ByteScheduler result its
    cross_barrier.py cites). Safe: PS keys/rounds are independent per
    tensor name, so cross-worker dispatch order may differ."""

    _lock = threading.Lock()
    _handles: Dict[int, Tuple[Future, torch.Tensor, bool]] = {}
    _next = 0
    _noname = 0
    _pq: Optional[list] = None      # heap of (priority, seq, start, fut)
    _cv: Optional[threading.Condition] = None
    _pullq = None                   # queue of (resolver, fut)
    _threads: list = []
    _stop_evt: Optional[threading.Event] = None   # per pool GENERATION

    @classmethod
    def _ensure_pool(cls) -> None:
        with cls._lock:
            if cls._pq is not None:
                return
            import os
            import queue as _queue
            cls._pq = []
            cls._cv = threading.Condition()
            cls._pullq = _queue.Queue()
            cls._stop_evt = threading.Event()
            width = max(1, int(os.environ.get("BPS_TORCH_CHANNELS", "4")))
            cls._threads = [
                threading.Thread(target=cls._push_worker, daemon=True,
                                 args=(cls._pq, cls._cv, cls._pullq,
                                       cls._stop_evt),
                                 name=f"bps-torch-push-{i}")
                for i in range(width)]
            cls._threads += [
                threading.Thread(target=cls._pull_worker, daemon=True,
                                 args=(cls._pullq,),
                                 name=f"bps-torch-pull-{i}")
                for i in range(width)]
            for t in cls._threads:
                t.start()

    @classmethod
    def _push_worker(cls, pq: list, cv: threading.Condition,
                     pullq, stop: threading.Event) -> None:
        # pq/cv/stop captured at spawn: reset() swaps the class attrs
        # for a fresh pool while old workers drain against their OWN
        # generation's objects (a shared class-level stop flag could
        # kill a freshly created pool racing the reset).
        # A push worker only STARTS an exchange (its pushes are in
        # flight when start() returns); the blocking pull drain happens
        # on the pull workers — pushes never queue behind pulls, so two
        # workers' channel pools cannot wedge on disjoint key sets
        # (reference: free-running separate push/pull loops,
        # core_loops.cc:538-618)
        import heapq
        while True:
            with cv:
                while not pq and not stop.is_set():
                    cv.wait()
                if stop.is_set():
                    return
                _, _, start, fut = heapq.heappop(pq)
            try:
                resolver = start()
            except BaseException as e:   # noqa: BLE001 — via future
                fut.set_exception(e)
                continue
            pullq.put((resolver, fut))

    @classmethod
    def _pull_worker(cls, pullq) -> None:
        while True:
            item = pullq.get()
            if item is None:
                return
            resolver, fut = item
            if not fut.set_running_or_notify_cancel():
                continue
            try:
                fut.set_result(resolver())
            except BaseException as e:   # noqa: BLE001 — via future
                fut.set_exception(e)

    @classmethod
    def submit(cls, start, out: torch.Tensor, inplace: bool,
               priority: int = 0) -> int:
        """``start`` runs on a push worker and must return a resolver
        whose call (on a pull worker) yields the reduced array."""
        import heapq
        fut: Future = Future()
        while True:
            cls._ensure_pool()
            with cls._lock:
                if cls._pq is None:
                    continue   # reset() raced _ensure_pool; rebuild
                # enqueue while STILL holding cls._lock: reset() swaps
                # the generation under the same lock, so capture-then-
                # push-outside would let it retire this generation (and
                # clear _handles) between the two — the exchange would
                # land on a dead queue and its future never resolve
                h = cls._next
                cls._next += 1
                cls._handles[h] = (fut, out, inplace)
                with cls._cv:
                    heapq.heappush(cls._pq, (priority, h, start, fut))
                    cls._cv.notify()
                return h

    @classmethod
    def take(cls, handle: int):
        with cls._lock:
            try:
                return cls._handles.pop(handle)
            except KeyError:
                raise RuntimeError(
                    f"unknown push_pull handle {handle} — already "
                    "synchronized, or the dispatcher was reset/"
                    "shut down") from None

    @classmethod
    def peek(cls, handle: int):
        with cls._lock:
            try:
                return cls._handles[handle]
            except KeyError:
                raise RuntimeError(
                    f"unknown push_pull handle {handle} — already "
                    "synchronized, or the dispatcher was reset/"
                    "shut down") from None

    @classmethod
    def auto_name(cls) -> str:
        with cls._lock:
            n = cls._noname
            cls._noname += 1
        return f"push_pull.noname.{n}"

    @classmethod
    def reset(cls) -> None:
        with cls._lock:
            threads, cls._threads = cls._threads, []
            cv, cls._cv = cls._cv, None
            pullq, cls._pullq = cls._pullq, None
            stop, cls._stop_evt = cls._stop_evt, None
            pq, cls._pq = cls._pq, None
            handles = dict(cls._handles)
            cls._handles.clear()
        if cv is not None:
            with cv:
                stop.set()            # this generation's flag only
                cv.notify_all()
            for _ in threads:
                pullq.put(None)       # wake & stop pull workers
            for t in threads:
                t.join(timeout=5)
            # push workers exit on stop without draining: fail any
            # leftover queued exchanges so their waiters get an error,
            # not a silent hang (shutdown with undrained handles is
            # already warned about upstream)
            with cv:
                leftovers, pq[:] = list(pq), []
            for _, h, _, f in leftovers:
                if not f.done():
                    f.set_exception(RuntimeError(
                        "push_pull dispatcher was shut down before this "
                        "exchange started"))
                    # re-expose the handle so the waiter's synchronize()
                    # surfaces THIS error rather than an unknown-handle
                    # one (the wholesale clear above removed it)
                    if h in handles:
                        with cls._lock:
                            cls._handles.setdefault(h, handles[h])


def _exchange_np(arr: np.ndarray, average: bool, name: str) -> np.ndarray:
    """One cross-worker sum (host path). World 1: identity."""
    gs = GlobalState.get()
    ex = gs.engine.ps_exchange
    if ex is None:
        return arr                    # single worker, nothing to reduce
    out = ex.exchange({"t": arr}, name=name)["t"]
    if average and gs.engine.ps_world > 1:
        out = out / gs.engine.ps_world
    return out


def _exchange_start(arr: np.ndarray, average: bool, name: str):
    """Split form for the dispatcher: pushes are IN FLIGHT when this
    returns; the returned resolver (run on a pull worker) blocks for
    the merged result. See _Dispatcher._push_worker for why."""
    gs = GlobalState.get()
    ex = gs.engine.ps_exchange
    if ex is None:
        # no wire: defer to _exchange_np on the pull side (also the
        # tests' monkeypatch point)
        return lambda: _exchange_np(arr, average, name)
    pend = ex.exchange_async({"t": arr}, name=name)
    world = gs.engine.ps_world

    def resolve():
        out = pend.result()["t"]
        if average and world > 1:
            out = out / world
        return out

    return resolve


_async_inited: set = set()


def async_param_exchange(name: str, delta: np.ndarray,
                         init: np.ndarray) -> np.ndarray:
    """Async-PS protocol for one parameter: seed the store with the
    initial weights (first-wins, idempotent — every worker broadcasts
    the same values first), push the weight DELTA, pull the latest
    global weights (reference: async server folds raw deltas,
    server.cc:310-314; our AsyncPSWorker protocol in server/ps_mode.py)."""
    gs = GlobalState.get()
    be = gs.ps_backend
    key = gs.registry.declare(name).key_for_partition(0)
    if key not in _async_inited:
        be.init_key(key, init.nbytes, str(init.dtype),
                    init=np.ascontiguousarray(init))
        _async_inited.add(key)
    be.push(key, np.ascontiguousarray(delta))
    out = np.empty(init.size, init.dtype)
    be.pull(key, out)                 # async mode: latest, never blocks
    return out.reshape(init.shape)


def _dispatch(tensor: torch.Tensor, average: bool, name: Optional[str],
              inplace: bool, priority: int = 0) -> int:
    if name is None:
        name = _Dispatcher.auto_name()
    # declare on the DISPATCHING thread: name→key assignment is
    # declaration-order (naming.py), and every worker dispatches in the
    # same order (same model, same hooks) — on the racing push workers
    # the order would be nondeterministic and the same name could get
    # different PS keys on different workers (silent mis-summation)
    GlobalState.get().registry.declare(name)
    arr = tensor.detach().cpu().numpy().copy()

    def start():
        return _exchange_start(arr, average, name)

    return _Dispatcher.submit(start, tensor, inplace, priority=priority)


def push_pull_async(tensor: torch.Tensor, average: bool = True,
                    name: Optional[str] = None, priority: int = 0) -> int:
    """Dispatch a reduction of ``tensor``; returns an int handle. The
    input is snapshotted — later in-place mutation doesn't affect the
    exchange; ``synchronize`` returns a NEW tensor. Lower ``priority``
    drains first when channels are busy (the reference's
    BYTEPS_SCHEDULING priority knob)."""
    return _dispatch(tensor, average, name, inplace=False,
                     priority=priority)


def push_pull_async_inplace(tensor: torch.Tensor, average: bool = True,
                            name: Optional[str] = None,
                            priority: int = 0) -> int:
    """Like ``push_pull_async`` but ``synchronize`` writes the result
    back INTO ``tensor`` (reference: the default grad path)."""
    return _dispatch(tensor, average, name, inplace=True,
                     priority=priority)


def poll(handle: int) -> bool:
    fut, _, _ = _Dispatcher.peek(handle)
    return fut.done()


def synchronize(handle: int) -> torch.Tensor:
    fut, tensor, inplace = _Dispatcher.take(handle)
    out = fut.result()
    result = torch.from_numpy(np.ascontiguousarray(out)).reshape(
        tensor.shape).to(tensor.dtype)
    if inplace:
        with torch.no_grad():
            tensor.copy_(result)
        return tensor
    return result


def push_pull(tensor: torch.Tensor, average: bool = True,
              name: Optional[str] = None) -> torch.Tensor:
    """Synchronous reduce; returns a new tensor (reference:
    torch/ops.py push_pull)."""
    return synchronize(push_pull_async(tensor, average=average, name=name))

"""torch-tensor push_pull ops (reference: torch/ops.py:48-236 +
handle_manager.{cc,h} — int handles over in-flight reductions).

Handles wrap futures on a single-thread dispatcher: dispatch returns
immediately (backward keeps running), the exchange executes on the
side thread, ``synchronize`` blocks on the future. One thread keeps
per-process dispatch serial; cross-worker matching is per KEY on the
PS server, so workers may dispatch in different orders (the reference
relies on the same ps-lite property)."""

from __future__ import annotations

import threading
from concurrent.futures import Future, ThreadPoolExecutor
from typing import Dict, Optional, Tuple

import numpy as np
import torch

from ..common.global_state import GlobalState


def init(config=None, **kwargs) -> None:
    """bps.init() for torch scripts (lazy import keeps jax out of the
    hot path)."""
    import byteps_tpu as bps
    bps.init(config=config, **kwargs)


def shutdown() -> None:
    import byteps_tpu as bps
    _Dispatcher.reset()
    _async_inited.clear()
    bps.shutdown()


def size() -> int:
    """World size = PS worker-process count (torch processes are the
    replicas; the jax mesh inside each is an implementation detail)."""
    return GlobalState.get().config.num_worker


def rank() -> int:
    return GlobalState.get().config.worker_id


def local_rank() -> int:
    return GlobalState.get().config.local_rank


def local_size() -> int:
    return GlobalState.get().config.local_size


def declare(name: str, **kwargs) -> None:
    """Pre-declare a tensor (priority / compression kwargs — reference:
    byteps_declare_tensor)."""
    GlobalState.get().registry.declare(name, **kwargs)


def declare_model_keys(names) -> None:
    """Declare Gradient.* then Parameter.* keys for a model's parameter
    names — two sorted loops for key-range load balancing, the
    reference's exact pattern (torch/__init__.py:95-100); shared by
    DistributedOptimizer and DistributedDataParallel so both map params
    onto identical PS key ranges."""
    reg = GlobalState.get().registry
    for name in sorted(names):
        reg.declare("Gradient." + name)
    for name in sorted(names):
        reg.declare("Parameter." + name)


class _Dispatcher:
    """Process-wide handle table + single-thread exchange executor."""

    _lock = threading.Lock()
    _ex: Optional[ThreadPoolExecutor] = None
    _handles: Dict[int, Tuple[Future, torch.Tensor, bool]] = {}
    _next = 0
    _noname = 0

    @classmethod
    def executor(cls) -> ThreadPoolExecutor:
        with cls._lock:
            if cls._ex is None:
                cls._ex = ThreadPoolExecutor(
                    1, thread_name_prefix="bps-torch-pushpull")
            return cls._ex

    @classmethod
    def submit(cls, fn, out: torch.Tensor, inplace: bool) -> int:
        fut = cls.executor().submit(fn)
        with cls._lock:
            h = cls._next
            cls._next += 1
            cls._handles[h] = (fut, out, inplace)
        return h

    @classmethod
    def take(cls, handle: int):
        with cls._lock:
            return cls._handles.pop(handle)

    @classmethod
    def peek(cls, handle: int):
        with cls._lock:
            return cls._handles[handle]

    @classmethod
    def auto_name(cls) -> str:
        with cls._lock:
            n = cls._noname
            cls._noname += 1
        return f"push_pull.noname.{n}"

    @classmethod
    def reset(cls) -> None:
        with cls._lock:
            ex, cls._ex = cls._ex, None
            cls._handles.clear()
        if ex is not None:
            ex.shutdown(wait=True)


def _exchange_np(arr: np.ndarray, average: bool, name: str) -> np.ndarray:
    """One cross-worker sum (host path). World 1: identity."""
    gs = GlobalState.get()
    ex = gs.engine.ps_exchange
    if ex is None:
        return arr                    # single worker, nothing to reduce
    out = ex.exchange({"t": arr}, name=name)["t"]
    if average and gs.engine.ps_world > 1:
        out = out / gs.engine.ps_world
    return out


_async_inited: set = set()


def async_param_exchange(name: str, delta: np.ndarray,
                         init: np.ndarray) -> np.ndarray:
    """Async-PS protocol for one parameter: seed the store with the
    initial weights (first-wins, idempotent — every worker broadcasts
    the same values first), push the weight DELTA, pull the latest
    global weights (reference: async server folds raw deltas,
    server.cc:310-314; our AsyncPSWorker protocol in server/ps_mode.py)."""
    gs = GlobalState.get()
    be = gs.ps_backend
    key = gs.registry.declare(name).key_for_partition(0)
    if key not in _async_inited:
        be.init_key(key, init.nbytes, str(init.dtype),
                    init=np.ascontiguousarray(init))
        _async_inited.add(key)
    be.push(key, np.ascontiguousarray(delta))
    out = np.empty(init.size, init.dtype)
    be.pull(key, out)                 # async mode: latest, never blocks
    return out.reshape(init.shape)


def _dispatch(tensor: torch.Tensor, average: bool, name: Optional[str],
              inplace: bool) -> int:
    if name is None:
        name = _Dispatcher.auto_name()
    arr = tensor.detach().cpu().numpy().copy()

    def run():
        return _exchange_np(arr, average, name)

    return _Dispatcher.submit(run, tensor, inplace)


def push_pull_async(tensor: torch.Tensor, average: bool = True,
                    name: Optional[str] = None) -> int:
    """Dispatch a reduction of ``tensor``; returns an int handle. The
    input is snapshotted — later in-place mutation doesn't affect the
    exchange; ``synchronize`` returns a NEW tensor."""
    return _dispatch(tensor, average, name, inplace=False)


def push_pull_async_inplace(tensor: torch.Tensor, average: bool = True,
                            name: Optional[str] = None) -> int:
    """Like ``push_pull_async`` but ``synchronize`` writes the result
    back INTO ``tensor`` (reference: the default grad path)."""
    return _dispatch(tensor, average, name, inplace=True)


def poll(handle: int) -> bool:
    fut, _, _ = _Dispatcher.peek(handle)
    return fut.done()


def synchronize(handle: int) -> torch.Tensor:
    fut, tensor, inplace = _Dispatcher.take(handle)
    out = fut.result()
    result = torch.from_numpy(np.ascontiguousarray(out)).reshape(
        tensor.shape).to(tensor.dtype)
    if inplace:
        with torch.no_grad():
            tensor.copy_(result)
        return tensor
    return result


def push_pull(tensor: torch.Tensor, average: bool = True,
              name: Optional[str] = None) -> torch.Tensor:
    """Synchronous reduce; returns a new tensor (reference:
    torch/ops.py push_pull)."""
    return synchronize(push_pull_async(tensor, average=average, name=name))

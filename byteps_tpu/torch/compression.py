"""Intra-worker gradient compression for the torch plugin (reference:
torch/compression.py:1-75 — fp16 wire compression decoupled from the
server-side compressor chain)."""

from __future__ import annotations

import torch


class NoneCompressor:
    @staticmethod
    def compress(tensor):
        return tensor, None

    @staticmethod
    def decompress(tensor, ctx):
        return tensor


class FP16Compressor:
    """Halve the wire bytes; decompress restores the original dtype."""

    @staticmethod
    def compress(tensor):
        if tensor.dtype.is_floating_point:
            return tensor.to(torch.float16), tensor.dtype
        return tensor, None

    @staticmethod
    def decompress(tensor, ctx):
        return tensor.to(ctx) if ctx is not None else tensor


class Compression:
    none = NoneCompressor
    fp16 = FP16Compressor

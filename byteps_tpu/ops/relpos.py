"""T5 relative-position bucketing, shared by the model layer and the
Pallas kernels.

The bucket index depends only on (memory_pos - query_pos), so the
flash kernels can derive it from block offsets with iotas and fold the
[num_buckets, heads] table into the scores INSIDE the kernel — no
[heads, sq, sk] bias ever materializes in HBM, which is what makes
RELATIVE-bias self-attention viable at long sequence lengths (a
materialized bias is 32 GB at s=32k, h=8). ``relative_bias`` (the
materializing form) remains for the XLA/naive reference paths and for
tests. Parity with the public T5 implementation is pinned in
tests/test_t5.py.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

__all__ = ["relative_position_bucket", "relative_bias"]


def relative_position_bucket(rel, bidirectional: bool,
                             num_buckets: int = 32,
                             max_distance: int = 128):
    """T5's log-spaced relative-position bucketing. ``rel`` is
    (memory_pos - query_pos), any int array. Bidirectional (encoder):
    half the buckets for each sign; causal (decoder): future positions
    collapse to bucket 0. Near offsets get exact buckets, far ones
    log-spaced up to ``max_distance``. jnp ops only, so it runs
    unchanged inside Pallas kernels."""
    ret = jnp.zeros_like(rel)
    n = -rel
    if bidirectional:
        num_buckets //= 2
        ret = ret + (n < 0).astype(rel.dtype) * num_buckets
        n = jnp.abs(n)
    else:
        n = jnp.maximum(n, 0)
    max_exact = num_buckets // 2
    is_small = n < max_exact
    val_large = max_exact + (
        jnp.log(jnp.maximum(n, 1).astype(jnp.float32) / max_exact)
        / np.log(max_distance / max_exact)
        * (num_buckets - max_exact)).astype(rel.dtype)
    val_large = jnp.minimum(val_large, num_buckets - 1)
    return ret + jnp.where(is_small, n, val_large)


def relative_bias(table, sq: int, sk: int, bidirectional: bool,
                  num_buckets: int = 32, max_distance: int = 128):
    """[num_buckets, heads] table → MATERIALIZED [heads, sq, sk]
    additive score bias (fp32). O(h·sq·sk) HBM — the reference path
    for tests and the XLA/naive impls; the flash kernels compute the
    same values in-block from the table instead."""
    ctx = jnp.arange(sq, dtype=jnp.int32)[:, None]
    mem = jnp.arange(sk, dtype=jnp.int32)[None, :]
    bucket = relative_position_bucket(mem - ctx, bidirectional,
                                      num_buckets, max_distance)
    bias = jnp.take(table.astype(jnp.float32), bucket, axis=0)
    return jnp.transpose(bias, (2, 0, 1))            # [heads, sq, sk]

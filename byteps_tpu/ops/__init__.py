from . import compression

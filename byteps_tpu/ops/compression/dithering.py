"""Stochastic dithering quantizer (reference: impl/dithering.{cc,h} —
QSGD-style: normalize by max or L2 norm, quantize onto s linear levels
{i/s} or natural levels {2^(i-s)} with stochastic (Bernoulli) rounding).

TPU-native representation: the reference Elias-delta-encodes the sparse
quantized stream into a bitstream (dithering.cc:71-107) — a strictly
sequential CPU encode with data-dependent length, which cannot map to XLA's
static shapes and would serialize on a TPU core. We keep the *math*
(normalization, level partition, stochastic rounding — verified by golden
tests) and ship the result as a dense low-bit integer payload
(int8/int16 + scale): on TPU the wire win comes from the reduced element
width of the collective payload, not from entropy coding.
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .base import Compressor, register

LINEAR, NATURAL = 0, 1   # dithering_partition (reference PartitionType)
MAX, L2 = 0, 1           # dithering_normalize (reference NomalizeType)


@register("dithering")
def _make(kwargs, size, dtype):
    s = int(float(kwargs.get("compressor_k", 4)))
    seed = int(kwargs.get("seed", 0))
    ptype = int(kwargs.get("dithering_partition", LINEAR))
    ntype = int(kwargs.get("dithering_normalize", MAX))
    return DitheringCompressor(size, dtype, s=s, seed=seed, ptype=ptype,
                               ntype=ntype)


def _round_next_pow2(v):
    """Smallest power of two >= v, elementwise on uint32 (reference:
    RoundNextPow2, utils.h)."""
    v = v.astype(jnp.uint32)
    v = jnp.maximum(v, 1) - 1
    for shift in (1, 2, 4, 8, 16):
        v = v | (v >> shift)
    return (v + 1).astype(jnp.uint32)


class DitheringCompressor(Compressor):
    name = "dithering"

    def __init__(self, size: int, dtype: str = "float32", s: int = 4,
                 seed: int = 0, ptype: int = LINEAR, ntype: int = MAX) -> None:
        super().__init__(size, dtype)
        self.s = s
        self.seed = seed
        self.ptype = ptype
        self.ntype = ntype
        # widest quantized magnitude: s for linear, 2^(s-1) for natural
        self.qmax = s if ptype == LINEAR else (1 << (s - 1))
        self.qdtype = jnp.int8 if self.qmax <= 127 else jnp.int16

    def init_state(self):
        return {"key": jax.random.PRNGKey(self.seed)}

    def _scale(self, x):
        if self.ntype == MAX:
            return jnp.max(jnp.abs(x))
        return jnp.sqrt(jnp.sum(x * x))

    def quantize(self, x: jnp.ndarray, u: jnp.ndarray) -> Tuple[jnp.ndarray, jnp.ndarray]:
        """Quantize with uniform randoms u in [0,1) driving the Bernoulli
        (separable from RNG so golden tests can inject reference-exact
        randoms). Returns (signed quantized levels, scale)."""
        scale = self._scale(x)
        absx = jnp.abs(x)
        safe = jnp.where(scale > 0, scale, 1.0)
        if self.ptype == LINEAR:
            normalized = absx / safe * self.s
            floor = jnp.floor(normalized)
            # Bernoulli(normalized - floor): u < p  (reference Bernoulli:
            # next() < p * 2^64)
            q = floor + (u < (normalized - floor))
        else:
            level = 1 << (self.s - 1)
            normalized = absx / safe * level
            fl = _round_next_pow2(jnp.ceil(normalized).astype(jnp.uint32)) >> 1
            fl = fl.astype(jnp.float32)
            length = jnp.where(fl != 0, fl, 1.0)
            p = (normalized - fl) / length
            q = fl + length * (u < p)
        q = jnp.sign(x) * q
        return q.astype(self.qdtype), scale.astype(jnp.float32)

    def compress(self, x: jnp.ndarray, state) -> Tuple[dict, dict]:
        key, sub = jax.random.split(state["key"])
        u = jax.random.uniform(sub, (self.size,))
        q, scale = self.quantize(x, u)
        return {"q": q, "scale": scale}, {"key": key}

    def decompress(self, payload: dict) -> jnp.ndarray:
        denom = self.s if self.ptype == LINEAR else (1 << (self.s - 1))
        out = payload["q"].astype(jnp.float32) * payload["scale"] / denom
        return out.astype(self.dtype)

    def payload_nbytes(self) -> int:
        return self.size * np.dtype(self.qdtype.__name__).itemsize + 4

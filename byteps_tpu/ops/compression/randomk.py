"""RandomK sparsifier: k uniformly-sampled coordinates as (index, value)
pairs, sampled **with replacement** like the reference (reference:
impl/randomk.cc CompressImpl draws Randint(0, len) k times; duplicates
possible and harmless since they carry identical values).

Determinism: the reference is deterministic only when seeded
(``seed`` kwarg → XorShift128+ with state {seed, seed}). Here:
  - the jit path threads a jax.random key through compressor state
    (different stream, same algorithm — documented deviation);
  - ``compress_with_indices`` takes host-provided indices, which the golden
    tests drive with the bit-exact XorShift128+ from .rng to verify the
    math against a numpy model, mirroring the reference's test strategy
    (tests/utils.py reimplements the RNG in numba).
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .base import Compressor, register
from .topk import resolve_k


@register("randomk")
def _make(kwargs, size, dtype):
    seed = int(kwargs.get("seed", 0))
    return RandomkCompressor(size, dtype, k=resolve_k(kwargs, size, dtype),
                             seed=seed)


class RandomkCompressor(Compressor):
    name = "randomk"

    def __init__(self, size: int, dtype: str = "float32", k: int = 1,
                 seed: int = 0) -> None:
        super().__init__(size, dtype)
        self.k = min(k, size)
        self.seed = seed

    def init_state(self):
        return {"key": jax.random.PRNGKey(self.seed)}

    def compress(self, x: jnp.ndarray, state) -> Tuple[dict, dict]:
        key, sub = jax.random.split(state["key"])
        idx = jax.random.randint(sub, (self.k,), 0, self.size, dtype=jnp.int32)
        return self.compress_with_indices(x, idx)[0], {"key": key}

    def compress_with_indices(self, x: jnp.ndarray,
                              idx: jnp.ndarray) -> Tuple[dict, tuple]:
        idx = jnp.asarray(idx, dtype=jnp.int32)
        return {"indices": idx, "values": x[idx]}, ()

    def decompress(self, payload: dict) -> jnp.ndarray:
        out = jnp.zeros((self.size,), dtype=self.dtype)
        return out.at[payload["indices"]].set(payload["values"])

    def payload_nbytes(self) -> int:
        return self.k * (4 + np.dtype(self.dtype).itemsize)

"""XorShift128+ RNG, bit-exact with the reference's
XorShift128PlusBitShifterRNG (reference: compressor/utils.h:72-158;
``set_seed(seed)`` sets state {a=seed, b=seed}; Randint(low,high) =
xorshift128p() % (high-low) + low; Bernoulli(p) = next() < p * 2^64).

The numpy implementation here serves golden tests and host-side index
generation — the same role the reference's tests/utils.py numba
reimplementation plays. In-jit compressors use jax.random instead (a
documented deviation: same algorithm, different random stream — the
reference itself is only deterministic when seeded).
"""

from __future__ import annotations

import numpy as np

_MASK = np.uint64(0xFFFFFFFFFFFFFFFF)


class XorShift128Plus:
    """Bit-exact xorshift128+ (Wikipedia variant used by the reference)."""

    def __init__(self, seed: int = 0) -> None:
        if seed:
            self.set_seed(seed)
        else:
            rd = np.random.RandomState()
            self._a = np.uint64(rd.randint(0, 2**32))
            self._b = np.uint64(rd.randint(0, 2**32))

    def set_seed(self, seed: int) -> None:
        self._a = np.uint64(seed)
        self._b = np.uint64(seed)

    def next(self) -> int:
        with np.errstate(over="ignore"):
            t = self._a
            s = self._b
            self._a = s
            t ^= (t << np.uint64(23)) & _MASK
            t ^= t >> np.uint64(17)
            t ^= s ^ (s >> np.uint64(26))
            self._b = t
            return int((t + s) & _MASK)

    def randint(self, low: int, high: int) -> int:
        """Uniform int in [low, high) — reference Randint."""
        return self.next() % (high - low) + low

    def rand(self) -> float:
        return self.next() / float(2**64)

    def bernoulli(self, p: float) -> bool:
        return self.next() < p * float(2**64)

    def randint_array(self, low: int, high: int, k: int) -> np.ndarray:
        return np.array([self.randint(low, high) for _ in range(k)],
                        dtype=np.int64)

"""Compressed cross-replica reduction.

The reference compresses on the CPU buffer right before PUSH and
decompresses after PULL, with the server summing decompressed payloads
(reference: core_loops.cc:498-536, server.cc:86-113). An XLA psum over
bit-packed payloads would be meaningless (the same reason NCCL allreduce
couldn't compress — docs/gradient-compression.md "Motivation"), so the
TPU-native exchange comes in two shapes, selected by the ``exchange``
compression kwarg:

- ``"gather"`` (default): every replica all-gathers the *compressed*
  payloads over ICI/DCN, then locally decompress-sums. Wire bytes per
  step drop from O(n) to O(world × payload); decompress latency is
  O(world × bucket). Right at small world.
- ``"rs"`` (reduce-scatter-shaped, the 1-bit-Adam/ps-lite scaling
  shape): each replica splits the bucket into ``world`` shards,
  compresses each, all_to_alls so replica r holds every replica's
  payload for shard r, decompress-sums ITS shard only, RE-compresses
  the merged shard once (the server-recompression role,
  server.cc:86-113 — the merge compressor carries its own EF state,
  matching ``create_server_chain``), and all_gathers the compressed
  merged shards. Wire bytes AND decompress work per replica are
  O(payload), independent of world — the scaling regime the gather
  shape loses.

``CompressionPlan`` binds the bucket plan to per-bucket compressor
instances and threads their state (EF memory, momentum, RNG keys) as one
pytree, so the whole reduction jits inside the train step. ``world``
must be the reduction-axes size for the "rs" shape (shard sizing is
static); the trainers thread it automatically.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ...common.partition import Bucket, LeafSpec, plan_buckets
from . import base


class CompressionPlan:
    """Per-bucket compressors over a fixed gradient-tree structure."""

    def __init__(self, specs: Sequence[LeafSpec], partition_bytes: int,
                 kwargs: Dict[str, str], min_compress_bytes: int = 65536,
                 world: int = 1):
        kwargs = dict(kwargs)
        self.exchange = kwargs.pop("exchange", "gather")
        if self.exchange not in ("gather", "rs"):
            raise ValueError(f"compression exchange must be gather|rs, "
                             f"got {self.exchange!r}")
        if self.exchange == "rs" and world < 1:
            raise ValueError("exchange='rs' needs the reduction world "
                             "size (trainers pass it automatically)")
        self.world = world
        self.buckets: List[Bucket] = plan_buckets(specs, partition_bytes,
                                                  reverse_order=True)
        self.compressors: List[Optional[base.Compressor]] = []
        self.merge_compressors: List[Optional[base.Compressor]] = []
        self.shard_sizes: List[int] = []
        # the merge recompression plays the SERVER's role, whose chain
        # skips only momentum (compressor_registry.cc:40-56 /
        # host.create_server_chain) — reusing the worker chain would
        # apply momentum a second time to the already-momentum'd merge
        merge_kwargs = {k: v for k, v in kwargs.items()
                        if k != "momentum_type"}
        for b in self.buckets:
            nbytes = b.size * np.dtype(b.dtype).itemsize
            if nbytes < min_compress_bytes:
                # small buckets skip compression (reference:
                # operations.cc:362-364, BYTEPS_MIN_COMPRESS_BYTES)
                self.compressors.append(None)
                self.merge_compressors.append(None)
                self.shard_sizes.append(0)
            elif self.exchange == "rs":
                shard = -(-b.size // world)          # ceil: zero-padded
                self.compressors.append(base.create(kwargs, shard, b.dtype))
                self.merge_compressors.append(
                    base.create(merge_kwargs, shard, b.dtype))
                self.shard_sizes.append(shard)
            else:
                self.compressors.append(base.create(kwargs, b.size, b.dtype))
                self.merge_compressors.append(None)
                self.shard_sizes.append(0)

    @classmethod
    def for_tree(cls, tree, partition_bytes: int, kwargs: Dict[str, str],
                 min_compress_bytes: int = 65536,
                 world: int = 1) -> "CompressionPlan":
        from ...parallel.collectives import leaf_specs_of_tree
        return cls(leaf_specs_of_tree(tree), partition_bytes, kwargs,
                   min_compress_bytes, world=world)

    def init_state(self):
        if self.exchange == "rs":
            out = []
            for c, mc in zip(self.compressors, self.merge_compressors):
                if c is None:
                    out.append(())
                    continue
                shard_state = jax.tree_util.tree_map(
                    lambda z: jnp.broadcast_to(z, (self.world,)
                                               + jnp.shape(z)),
                    c.init_state())
                out.append((shard_state, mc.init_state()))
            return tuple(out)
        return tuple(c.init_state() if c is not None else ()
                     for c in self.compressors)

    def reduce_tree(self, tree, states, axes: Tuple[str, ...],
                    average: bool = True):
        """Bucketed compressed allreduce; call inside shard_map. Returns
        (reduced tree, new compressor states)."""
        from ...parallel.collectives import _pack_bucket, _unpack_bucket
        leaves, treedef = jax.tree_util.tree_flatten(tree)
        shapes = [l.shape for l in leaves]
        flat = [l.ravel() for l in leaves]
        n = 1
        for ax in axes:
            n *= jax.lax.axis_size(ax)
        new_states = []
        for b, comp, mcomp, shard, st in zip(self.buckets, self.compressors,
                                             self.merge_compressors,
                                             self.shard_sizes, states):
            buf = _pack_bucket(flat, b)
            if comp is None or not axes:
                red = jax.lax.psum(buf, axes) if axes else buf
                if average:
                    red = red / n
                new_states.append(st)
            elif self.exchange == "rs":
                if n != self.world:
                    raise ValueError(
                        f"exchange='rs' plan was built for world "
                        f"{self.world} but the mesh reduces over {n} "
                        f"replicas — rebuild the plan (trainers do)")
                red, st = self._reduce_rs(buf, comp, mcomp, shard, st,
                                          axes, b, n, average)
                new_states.append(st)
            else:
                payload, st2 = comp.compress(buf, st)
                gathered = jax.tree_util.tree_map(
                    lambda p: jax.lax.all_gather(p, axes, axis=0, tiled=False),
                    payload)
                world = n

                def dec_one(i, acc):
                    pl = jax.tree_util.tree_map(lambda g: g[i], gathered)
                    return acc + comp.decompress(pl)

                red = jax.lax.fori_loop(
                    0, world, dec_one,
                    jnp.zeros((b.size,), dtype=b.dtype))
                if average:
                    red = red / n
                new_states.append(st2)
            _unpack_bucket(red, b, flat)
        out = [f.reshape(s) for f, s in zip(flat, shapes)]
        return jax.tree_util.tree_unflatten(treedef, out), tuple(new_states)

    def _reduce_rs(self, buf, comp, mcomp, shard: int, st, axes, b,
                   n: int, average: bool):
        """Reduce-scatter-shaped exchange for one bucket (see module
        docstring): compress per shard → all_to_all → decompress-sum MY
        shard → recompress the merge (momentum-free, EF-compensated
        merge compressor — the server-chain role) → all_gather →
        decompress every shard."""
        world = self.world
        shard_states, merge_state = st
        padded = jnp.zeros((shard * world,), buf.dtype).at[:b.size].set(buf)
        shards = padded.reshape(world, shard)
        payloads, new_shard_states = jax.vmap(comp.compress)(shards,
                                                             shard_states)
        # leading dim = destination shard: all_to_all leaves replica r
        # holding every replica's payload for shard r
        recv = jax.tree_util.tree_map(
            lambda p: jax.lax.all_to_all(p, axes, split_axis=0,
                                         concat_axis=0),
            payloads)

        def dec_one(i, acc):
            pl = jax.tree_util.tree_map(lambda g: g[i], recv)
            return acc + comp.decompress(pl)

        merged = jax.lax.fori_loop(0, world, dec_one,
                                   jnp.zeros((shard,), dtype=b.dtype))
        # mask the zero-pad tail: dense codecs decompress pad positions
        # to ±scale garbage that would inflate the merge compressor's
        # scale and poison its EF state (only the LAST shards can carry
        # padding). Linearized shard index = rank order over ``axes``,
        # the same row-major order all_to_all/all_gather use.
        my = jnp.zeros((), jnp.int32)
        for ax in axes:
            my = my * jax.lax.axis_size(ax) + jax.lax.axis_index(ax)
        pos = my * shard + jnp.arange(shard)
        merged = jnp.where(pos < b.size, merged, 0)
        if average:
            merged = merged / n       # averaged BEFORE the wire recompress
        mpay, new_merge_state = mcomp.compress(merged, merge_state)
        gathered = jax.tree_util.tree_map(
            lambda p: jax.lax.all_gather(p, axes, axis=0, tiled=False),
            mpay)

        def dec_shard(i, acc):
            pl = jax.tree_util.tree_map(lambda g: g[i], gathered)
            return acc.at[i].set(mcomp.decompress(pl))

        full = jax.lax.fori_loop(0, world, dec_shard,
                                 jnp.zeros((world, shard), dtype=b.dtype))
        red = full.reshape(-1)[:b.size]
        return red, (new_shard_states, new_merge_state)

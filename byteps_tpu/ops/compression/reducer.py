"""Compressed cross-replica reduction.

The reference compresses on the CPU buffer right before PUSH and
decompresses after PULL, with the server summing decompressed payloads
(reference: core_loops.cc:498-536, server.cc:86-113). An XLA psum over
bit-packed payloads would be meaningless (the same reason NCCL allreduce
couldn't compress — docs/gradient-compression.md "Motivation"), so the
TPU-native exchange is gather-based: every replica all-gathers the
*compressed* payloads over ICI/DCN, then locally decompress-sums. Wire
bytes per step drop from O(n) to O(world × payload) — a win whenever
payload ≪ n/world, exactly the regime compression targets.

``CompressionPlan`` binds the bucket plan to per-bucket compressor
instances and threads their state (EF memory, momentum, RNG keys) as one
pytree, so the whole reduction jits inside the train step.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ...common.partition import Bucket, LeafSpec, plan_buckets
from . import base


class CompressionPlan:
    """Per-bucket compressors over a fixed gradient-tree structure."""

    def __init__(self, specs: Sequence[LeafSpec], partition_bytes: int,
                 kwargs: Dict[str, str], min_compress_bytes: int = 65536):
        self.buckets: List[Bucket] = plan_buckets(specs, partition_bytes,
                                                  reverse_order=True)
        self.compressors: List[Optional[base.Compressor]] = []
        for b in self.buckets:
            nbytes = b.size * np.dtype(b.dtype).itemsize
            if nbytes < min_compress_bytes:
                # small buckets skip compression (reference:
                # operations.cc:362-364, BYTEPS_MIN_COMPRESS_BYTES)
                self.compressors.append(None)
            else:
                self.compressors.append(base.create(kwargs, b.size, b.dtype))

    @classmethod
    def for_tree(cls, tree, partition_bytes: int, kwargs: Dict[str, str],
                 min_compress_bytes: int = 65536) -> "CompressionPlan":
        from ...parallel.collectives import leaf_specs_of_tree
        return cls(leaf_specs_of_tree(tree), partition_bytes, kwargs,
                   min_compress_bytes)

    def init_state(self):
        return tuple(c.init_state() if c is not None else ()
                     for c in self.compressors)

    def reduce_tree(self, tree, states, axes: Tuple[str, ...],
                    average: bool = True):
        """Bucketed compressed allreduce; call inside shard_map. Returns
        (reduced tree, new compressor states)."""
        from ...parallel.collectives import _pack_bucket, _unpack_bucket
        leaves, treedef = jax.tree_util.tree_flatten(tree)
        shapes = [l.shape for l in leaves]
        flat = [l.ravel() for l in leaves]
        n = 1
        for ax in axes:
            n *= jax.lax.axis_size(ax)
        new_states = []
        for b, comp, st in zip(self.buckets, self.compressors, states):
            buf = _pack_bucket(flat, b)
            if comp is None or not axes:
                red = jax.lax.psum(buf, axes) if axes else buf
                new_states.append(st)
            else:
                payload, st2 = comp.compress(buf, st)
                gathered = jax.tree_util.tree_map(
                    lambda p: jax.lax.all_gather(p, axes, axis=0, tiled=False),
                    payload)
                world = n

                def dec_one(i, acc):
                    pl = jax.tree_util.tree_map(lambda g: g[i], gathered)
                    return acc + comp.decompress(pl)

                red = jax.lax.fori_loop(
                    0, world, dec_one,
                    jnp.zeros((b.size,), dtype=b.dtype))
                new_states.append(st2)
            if average:
                red = red / n
            _unpack_bucket(red, b, flat)
        out = [f.reshape(s) for f, s in zip(flat, shapes)]
        return jax.tree_util.tree_unflatten(treedef, out), tuple(new_states)

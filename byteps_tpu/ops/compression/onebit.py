"""Onebit (signSGD) compressor: 32:1 sign-bit packing with optional
L1-mean scale (reference: impl/onebit.{cc,h} — sign bits packed MSB-first
into words, scale = mean |x| appended when compressor_onebit_scaling on).

TPU-native: the pack/unpack is pure vectorized bit arithmetic on uint32
lanes (VPU-friendly, fuses into the surrounding program); payload is
(packed words, scale) with static shapes.
"""

from __future__ import annotations

from typing import Tuple

import jax.numpy as jnp
import numpy as np

from .base import Compressor, register

PACK = 32  # bits per word


@register("onebit")
def _make(kwargs, size, dtype):
    scaled = kwargs.get("compressor_onebit_scaling", "false").lower() in (
        "1", "true", "yes")
    backend = kwargs.get("compressor_backend", "auto")
    return OnebitCompressor(size, dtype, use_scale=scaled, backend=backend)


class OnebitCompressor(Compressor):
    name = "onebit"

    def __init__(self, size: int, dtype: str = "float32",
                 use_scale: bool = False, backend: str = "auto") -> None:
        super().__init__(size, dtype)
        self.use_scale = use_scale
        self.chunks = (size + PACK - 1) // PACK
        if backend not in ("auto", "pallas", "jnp"):
            raise ValueError(f"unknown onebit backend {backend!r}")
        if backend == "auto":
            # Pallas on TPU (8× the XLA path, measured); compiled jnp
            # elsewhere — interpret mode would serialize the grid.
            import jax
            self.use_pallas = jax.devices()[0].platform == "tpu"
        else:
            self.use_pallas = backend == "pallas"

    def compress(self, x: jnp.ndarray, state=()) -> Tuple[dict, tuple]:
        n = self.size
        if self.use_pallas:
            from .pallas_kernels import onebit_pack
            packed = onebit_pack(x, self.chunks)   # pads internally
        else:
            # padding with zeros: sign bit of 0.0 is 0 ("positive"),
            # matching the reference's zero-padded trailing word
            xp = jnp.pad(x, (0, self.chunks * PACK - n))
            neg = (xp < 0).astype(jnp.uint32).reshape(self.chunks, PACK)
            # MSB-first: element 0 of each chunk lands in the top bit
            shifts = jnp.arange(PACK - 1, -1, -1, dtype=jnp.uint32)
            # disjoint bits, so sum == bitwise OR
            packed = (neg << shifts).sum(axis=1, dtype=jnp.uint32)
        if self.use_scale:
            scale = jnp.mean(jnp.abs(x)).astype(jnp.float32)
        else:
            scale = jnp.float32(1.0)
        return {"packed": packed, "scale": scale}, state

    def decompress(self, payload: dict) -> jnp.ndarray:
        packed = payload["packed"]
        if self.use_pallas:
            from .pallas_kernels import onebit_unpack
            out = onebit_unpack(packed, self.size) * payload["scale"]
        else:
            shifts = jnp.arange(PACK - 1, -1, -1, dtype=jnp.uint32)
            bits = (packed[:, None] >> shifts) & jnp.uint32(1)
            # bit 1 → negative: value -scale; bit 0 → +scale (reference:
            # sign = 1 - ((x & 1) << 1))
            signs = 1.0 - 2.0 * bits.astype(jnp.float32)
            out = (signs * payload["scale"]).reshape(-1)[: self.size]
        return out.astype(self.dtype)

    def payload_nbytes(self) -> int:
        return self.chunks * 4 + 4

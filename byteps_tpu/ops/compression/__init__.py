from .base import Compressor, create, register, registered_names
from .rng import XorShift128Plus
from . import onebit, topk, randomk, dithering  # register implementations
from .decorators import VanillaErrorFeedback, NesterovMomentum
from .reducer import CompressionPlan

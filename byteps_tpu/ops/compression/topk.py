"""TopK sparsifier: keep the k largest-magnitude entries as (index, value)
pairs (reference: impl/topk.{cc,h}; k resolved from ``compressor_k`` — a
fraction of the buffer when < 1, an absolute count otherwise,
reference: topk.cc registry lambda).

TPU-native: jax.lax.top_k on |x| (MXU/VPU-friendly), static k; payload is
(int32 indices, values)."""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .base import Compressor, register


def resolve_k(kwargs, size: int, dtype: str) -> int:
    """compressor_k < 1 → fraction of the *byte* size over element size,
    i.e. a fraction of the element count (reference: randomk.cc/topk.cc
    registry: k = factor * size_bytes / dtype_len); ≥ 1 → absolute."""
    factor = float(kwargs.get("compressor_k", 0.01))
    if factor < 1:
        k = int(factor * size)
        return max(k, 1)
    return int(factor)


@register("topk")
def _make(kwargs, size, dtype):
    return TopkCompressor(size, dtype, k=resolve_k(kwargs, size, dtype))


class TopkCompressor(Compressor):
    name = "topk"

    def __init__(self, size: int, dtype: str = "float32", k: int = 1) -> None:
        super().__init__(size, dtype)
        self.k = min(k, size)

    def compress(self, x: jnp.ndarray, state=()) -> Tuple[dict, tuple]:
        _, idx = jax.lax.top_k(jnp.abs(x), self.k)
        vals = x[idx]
        return {"indices": idx.astype(jnp.int32), "values": vals}, state

    def decompress(self, payload: dict) -> jnp.ndarray:
        out = jnp.zeros((self.size,), dtype=self.dtype)
        return out.at[payload["indices"]].set(payload["values"])

    def payload_nbytes(self) -> int:
        return self.k * (4 + np.dtype(self.dtype).itemsize)

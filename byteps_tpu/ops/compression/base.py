"""Compressor interface + registry.

Reference: compressor.h:74-117 (Compress/Decompress/FastUpdateError),
compressor_registry.cc (name→ctor map; Create() resolves the decorator
chain momentum → error-feedback → compressor from string kwargs).

TPU-native differences:
  - Compressors are *pure functions* on 1-D bucket buffers: state (error
    feedback memory, momentum, RNG keys) is threaded explicitly as a
    pytree so the whole thing jits and lives inside the train step.
  - Payloads are fixed-shape pytrees of arrays (XLA needs static shapes),
    not byte blobs.
  - The kwargs surface is string-typed and uses the reference's key names
    (``compressor_type``, ``ef_type``, ``momentum_type``, ``compressor_k``,
    ``compressor_onebit_scaling``, ``momentum_mu``, ``seed``,
    ``dithering_partition``, ``dithering_normalize``) so per-tensor attrs
    written for the reference port directly. ``compressor_backend``
    (auto|pallas|jnp) selects the Pallas kernel path — currently honored
    by onebit only; other compressors ignore it.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Optional, Tuple

import jax.numpy as jnp

Payload = Any     # pytree of arrays, fixed shapes
State = Any       # pytree of arrays

_REGISTRY: Dict[str, Callable[..., "Compressor"]] = {}


def register(name: str):
    """Register under ``<name>`` (reference registers ``<name>_<kind>``;
    the kind suffix is implied by the kwargs key here)."""
    def deco(ctor):
        if name in _REGISTRY:
            raise ValueError(f"duplicate compressor {name!r}")
        _REGISTRY[name] = ctor
        return ctor
    return deco


class Compressor:
    """A pure, jit-safe compressor over a flat float buffer of length n."""

    #: bytes per element of the *payload* relative to input — informational
    name: str = "identity"

    def __init__(self, size: int, dtype: str = "float32") -> None:
        self.size = size       # number of elements in the buffer
        self.dtype = dtype

    def init_state(self) -> State:
        return ()

    def compress(self, x: jnp.ndarray, state: State) -> Tuple[Payload, State]:
        raise NotImplementedError

    def decompress(self, payload: Payload) -> jnp.ndarray:
        raise NotImplementedError

    def payload_nbytes(self) -> int:
        """Wire size of one compressed payload (for telemetry/ratio)."""
        raise NotImplementedError


def create(kwargs: Dict[str, str], size: int,
           dtype: str = "float32") -> Optional[Compressor]:
    """Resolve the decorator chain from string kwargs (reference:
    CompressorRegistry::Create, compressor_registry.cc:40-56: momentum →
    ef → compressor, outermost first). Returns None if no compressor_type.
    """
    if "compressor_type" not in kwargs:
        return None
    ctor = _REGISTRY.get(kwargs["compressor_type"])
    if ctor is None:
        raise ValueError(f"no compressor registered under "
                         f"{kwargs['compressor_type']!r}; have {sorted(_REGISTRY)}")
    comp = ctor(kwargs, size, dtype)
    if kwargs.get("ef_type") == "vanilla":
        from .decorators import VanillaErrorFeedback
        comp = VanillaErrorFeedback(comp)
    if kwargs.get("momentum_type") == "nesterov":
        from .decorators import NesterovMomentum
        mu = float(kwargs.get("momentum_mu", 0.9))
        comp = NesterovMomentum(comp, mu=mu)
    return comp


def registered_names():
    return sorted(_REGISTRY)

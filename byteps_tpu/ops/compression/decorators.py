"""Compressor decorators: error feedback and Nesterov momentum.

Reference:
  - error_feedback.h:26-46 — ``corrected = grad + error; compressed =
    Compress(corrected); error = corrected - Decompress(compressed)``.
  - vanilla_error_feedback.{cc,h} — additionally scales the carried error
    by η_{t-1}/η_t read from an mmap'd ``lr.s`` file the trainer writes
    each step (vanilla_error_feedback.h:26-38). Here the lr ratio is
    threaded through state explicitly (``set_lr``-style file IPC is
    replaced by a value in the train state — same math, no mmap).
  - momentum.h + nesterov_momentum.h:26-34 — ``m = μm + g; g += μm``;
    worker-only (compressor_registry.cc:41-46).

All decorators are pure: state in, state out, jit-safe.
"""

from __future__ import annotations

from typing import Tuple

import jax.numpy as jnp

from .base import Compressor


class VanillaErrorFeedback(Compressor):
    name = "vanilla_ef"

    def __init__(self, inner: Compressor) -> None:
        super().__init__(inner.size, inner.dtype)
        self.inner = inner

    def init_state(self):
        return {
            "error": jnp.zeros((self.size,), dtype=self.dtype),
            # lr_prev/lr_now scale carried error by η_{t-1}/η_t; equal by
            # default (ratio 1) when the schedule is constant/unknown.
            "lr_prev": jnp.float32(1.0),
            "lr_now": jnp.float32(1.0),
            "inner": self.inner.init_state(),
        }

    def compress(self, x: jnp.ndarray, state) -> Tuple[dict, dict]:
        ratio = state["lr_prev"] / jnp.maximum(state["lr_now"], 1e-30)
        corrected = x + ratio * state["error"]
        payload, inner_state = self.inner.compress(corrected, state["inner"])
        error = corrected - self.inner.decompress(payload)
        return payload, {"error": error, "lr_prev": state["lr_now"],
                         "lr_now": state["lr_now"], "inner": inner_state}

    def decompress(self, payload):
        return self.inner.decompress(payload)

    def payload_nbytes(self) -> int:
        return self.inner.payload_nbytes()


class NesterovMomentum(Compressor):
    name = "nesterov_momentum"

    def __init__(self, inner: Compressor, mu: float = 0.9) -> None:
        super().__init__(inner.size, inner.dtype)
        self.inner = inner
        self.mu = mu

    def init_state(self):
        return {"m": jnp.zeros((self.size,), dtype=self.dtype),
                "inner": self.inner.init_state()}

    def compress(self, x: jnp.ndarray, state) -> Tuple[dict, dict]:
        m = self.mu * state["m"] + x          # m = μm + g
        corrected = x + self.mu * m           # g += μm (nesterov lookahead)
        payload, inner_state = self.inner.compress(corrected, state["inner"])
        new_state = {"m": m, "inner": inner_state}
        # EF inner decorator keeps its own error on the corrected signal
        return payload, new_state

    def decompress(self, payload):
        return self.inner.decompress(payload)

    def payload_nbytes(self) -> int:
        return self.inner.payload_nbytes()

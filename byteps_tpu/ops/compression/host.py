"""Host-side (numpy) compressor codecs for the PS wire path.

The reference runs ONE C++ compressor implementation in two places: on
the worker's CPU staging buffer right before PUSH / after PULL
(core_loops.cc:498-536, 620-648) and inside the server engine, which
decompresses every worker's push, sums the dense values, and
RE-compresses the merged result once per round (server.cc:86-113,
registered from kwargs serialized worker→server, server.cc:222-252).

These codecs play that role here: plain numpy on the host data path (the
device path keeps the JAX/Pallas compressors in this package), shared by
``PSGradientExchange`` (worker) and the host reduction service (server).
Payloads are flat little-endian byte strings of deterministic size
(``payload_nbytes``), so the TCP transport can frame them like any other
buffer.

Numerics mirror the JAX compressors in this package elementwise:
onebit/topk are bit-exact; randomk draws indices from the reference's
seeded XorShift128+ (utils.h:72-158); dithering drives its Bernoulli
from the same RNG when a ``seed`` kwarg is given (the reference is only
deterministic when seeded) and a fast numpy stream otherwise.

Decorator chains mirror the reference's registry
(compressor_registry.cc:40-56), whose SERVER build skips only
``momentum_type`` — error feedback IS part of the server's chain, so the
reference compensates the merged-buffer recompression error. Workers use
``create_host_chain`` (momentum → ef → compressor); servers use
``create_server_chain`` (ef → compressor).
"""

from __future__ import annotations

import struct
from typing import Dict, Optional

import numpy as np

from .dithering import LINEAR, MAX
from .onebit import PACK
from .rng import XorShift128Plus
from .topk import resolve_k

_NATIVE_LIB = None     # cached CDLL (or False when unavailable)


def _native():
    """The native codec primitive library, or None.

    Gated per CALL on ``BPS_NATIVE_CODEC`` (the A/B knob the fused
    server paths already honor — tests flip it per-test) with the CDLL
    itself cached. The primitives run each codec's O(n) loops in C++
    with the GIL released while per-key CHAIN state (error feedback,
    momentum, XorShift words) stays in these Python objects — so every
    registered chain gets the native engine, not just the bare-fp32
    fused paths (reference: all codec work inside the C++ engine,
    server.cc:86-113)."""
    import os
    if os.environ.get("BPS_NATIVE_CODEC", "1") in ("0", "false"):
        return None
    global _NATIVE_LIB
    if _NATIVE_LIB is None:
        try:
            from ...server.engine import _lib
            _NATIVE_LIB = _lib()
        except Exception:      # no toolchain: numpy paths keep working
            _NATIVE_LIB = False
    return _NATIVE_LIB or None


def _ptr(arr: np.ndarray):
    import ctypes
    return arr.ctypes.data_as(ctypes.c_void_p)


def serialize_kwargs(kwargs: Dict[str, str]) -> bytes:
    """``k\\0v\\0...`` — the reference's wire form of the compression
    kwargs dict (utils.h:33-46, pushed to the server at init,
    operations.cc:396-408)."""
    out = []
    for k in sorted(kwargs):
        out.append(str(k).encode())
        out.append(str(kwargs[k]).encode())
    return b"\0".join(out)


def deserialize_kwargs(buf: bytes) -> Dict[str, str]:
    if not buf:
        return {}
    parts = bytes(buf).split(b"\0")
    if len(parts) % 2:
        raise ValueError("malformed kwargs blob")
    return {parts[i].decode(): parts[i + 1].decode()
            for i in range(0, len(parts), 2)}


class HostCodec:
    """compress(np [size]) -> bytes of payload_nbytes(); decompress -> np."""

    def __init__(self, size: int, dtype: str = "float32") -> None:
        self.size = int(size)
        self.dtype = np.dtype(dtype)

    def compress(self, x: np.ndarray) -> bytes:
        raise NotImplementedError

    def decompress(self, buf) -> np.ndarray:
        raise NotImplementedError

    def payload_nbytes(self) -> int:
        raise NotImplementedError


class HostOnebit(HostCodec):
    """Sign-bit packing 32:1, MSB-first uint32 words, optional L1-mean
    scale (reference: impl/onebit.cc:34-67; bit-exact with
    OnebitCompressor here)."""

    def __init__(self, size: int, dtype: str = "float32",
                 use_scale: bool = False) -> None:
        super().__init__(size, dtype)
        self.use_scale = use_scale
        self.chunks = (size + PACK - 1) // PACK

    def compress(self, x: np.ndarray) -> bytes:
        # internal math in fp32 regardless of wire dtype: the sign test
        # is dtype-invariant and the f32 L1 mean is strictly better
        # numerics for f16/bf16 keys — and it lets ONE native kernel
        # serve every store dtype
        x = np.ascontiguousarray(np.asarray(x).reshape(-1), np.float32)
        # compress stays numpy: packbits is SIMD-optimized and measured
        # FASTER than the native per-bit loop (1.3 vs 1.8 ms on 4 MB) —
        # the native onebit wins live on the fused server paths
        # (pull_onebit) and the decompress primitive below
        bits = np.zeros(self.chunks * PACK, np.uint8)
        bits[: self.size] = (x < 0)
        # packbits is MSB-first per byte; big-endian u4 view keeps element
        # 0 in the top bit of word 0, matching the JAX kernel
        packed = np.packbits(bits).view(">u4").astype(np.uint32)
        scale = np.float32(np.abs(x).mean()) if self.use_scale \
            else np.float32(1.0)
        return packed.tobytes() + struct.pack("<f", scale)

    def decompress(self, buf) -> np.ndarray:
        buf = bytes(buf)
        if len(buf) != self.payload_nbytes():
            # strict on BOTH paths: the native kernel reads exactly
            # chunks*4+4 bytes, so a truncated frame must never reach it
            raise ValueError(
                f"onebit payload is {len(buf)} bytes, expected "
                f"{self.payload_nbytes()}")
        lib = _native()
        if lib is not None:
            src = np.frombuffer(buf, np.uint8)
            out = np.empty(self.size, np.float32)
            lib.bps_codec_onebit_decompress(_ptr(src), self.size,
                                            _ptr(out))
            return out.astype(self.dtype, copy=False)
        packed = np.frombuffer(buf[:-4], np.uint32)
        (scale,) = struct.unpack("<f", buf[-4:])
        bits = np.unpackbits(
            np.frombuffer(packed.astype(">u4").tobytes(), np.uint8))
        signs = 1.0 - 2.0 * bits[: self.size].astype(np.float32)
        return (signs * scale).astype(self.dtype)

    def payload_nbytes(self) -> int:
        return self.chunks * 4 + 4


class _SparseCodec(HostCodec):
    """(int32 indices | values) wire layout shared by topk/randomk."""

    def __init__(self, size: int, dtype: str, k: int) -> None:
        super().__init__(size, dtype)
        self.k = min(int(k), size)

    def _pack(self, idx: np.ndarray, vals: np.ndarray) -> bytes:
        return idx.astype(np.int32).tobytes() + \
            vals.astype(self.dtype).tobytes()

    def decompress(self, buf) -> np.ndarray:
        buf = bytes(buf)
        if len(buf) != self.payload_nbytes():
            raise ValueError(
                f"sparse payload is {len(buf)} bytes, expected "
                f"{self.payload_nbytes()}")
        idx = np.frombuffer(buf[: self.k * 4], np.int32)
        vals = np.frombuffer(buf[self.k * 4:], self.dtype)
        lib = _native()
        if lib is not None and self.dtype == np.float32:
            out = np.empty(self.size, np.float32)
            rc = lib.bps_codec_scatter_f32(_ptr(idx), _ptr(vals),
                                           self.k, self.size, _ptr(out))
            if rc != 0:
                raise IndexError(
                    f"sparse payload index out of range 0..{self.size}")
            return out
        out = np.zeros(self.size, self.dtype)
        out[idx] = vals
        return out

    def payload_nbytes(self) -> int:
        return self.k * (4 + self.dtype.itemsize)


class HostTopk(_SparseCodec):
    """Largest-k magnitudes, ties to the lower index (matches
    jax.lax.top_k; reference: impl/topk.h:26-37). Selection runs in
    fp32 for every wire dtype (monotone and injective from f16/bf16,
    so the selected set is unchanged; values are packed in the wire
    dtype)."""

    def compress(self, x: np.ndarray) -> bytes:
        x = np.asarray(x).reshape(-1)
        lib = _native()
        if lib is not None and x.size >= self.k:
            x32 = np.ascontiguousarray(x, np.float32)
            idx = np.empty(self.k, np.int32)
            vals = np.empty(self.k, np.float32)
            rc = lib.bps_codec_topk_select(_ptr(x32), x32.size, self.k,
                                           _ptr(idx), _ptr(vals))
            if rc != 0:          # can't happen given the size guard —
                raise ValueError(  # but never pack uninitialized bytes
                    f"topk select failed: n={x32.size} k={self.k}")
            if self.dtype != np.float32:
                vals = np.asarray(x)[idx]       # exact wire-dtype values
            return self._pack(idx, vals)
        idx = np.argsort(-np.abs(x), kind="stable")[: self.k]
        return self._pack(idx, x[idx])


class HostRandomk(_SparseCodec):
    """k coordinates with replacement from the reference's seeded
    XorShift128+ (impl/randomk.cc; utils.h:72-92). The RNG state lives
    HERE (worker-synced across rounds); the native path draws from it
    in place, so the server's randomk recompress runs in C++ without
    forking the stream."""

    def __init__(self, size: int, dtype: str, k: int, seed: int = 0) -> None:
        super().__init__(size, dtype, k)
        self._rng = XorShift128Plus(seed)

    def compress(self, x: np.ndarray) -> bytes:
        x = np.asarray(x).reshape(-1)
        lib = _native()
        if lib is not None:
            state = np.array([self._rng._a, self._rng._b], np.uint64)
            idx = np.empty(self.k, np.int32)
            lib.bps_codec_xorshift_indices(self.size, self.k,
                                           _ptr(state), _ptr(idx))
            self._rng._a, self._rng._b = (np.uint64(state[0]),
                                          np.uint64(state[1]))
            return self._pack(idx, x[idx])
        idx = self._rng.randint_array(0, self.size, self.k)
        return self._pack(idx, x[idx])


class HostDithering(HostCodec):
    """Stochastic quantization onto linear {i/s} or natural {2^(i-s)}
    levels (reference: impl/dithering.{cc,h}); math mirrors
    DitheringCompressor.quantize elementwise."""

    def __init__(self, size: int, dtype: str = "float32", s: int = 4,
                 seed: int = 0, ptype: int = LINEAR, ntype: int = MAX) -> None:
        super().__init__(size, dtype)
        self.s, self.ptype, self.ntype = int(s), int(ptype), int(ntype)
        qmax = self.s if self.ptype == LINEAR else (1 << (self.s - 1))
        self.qdtype = np.dtype(np.int8 if qmax <= 127 else np.int16)
        # seeded → the reference's sequential RNG (bit-exact determinism);
        # unseeded → fast vectorized numpy stream (reference unseeded mode
        # is nondeterministic anyway)
        self._xs = XorShift128Plus(seed) if seed else None
        self._np_rng = None if seed else np.random.RandomState()

    def _uniform(self, n: int) -> np.ndarray:
        if self._xs is not None:
            return np.array([self._xs.rand() for _ in range(n)], np.float64)
        return self._np_rng.random_sample(n)

    def compress(self, x: np.ndarray) -> bytes:
        x = np.asarray(x, np.float32).reshape(-1)
        scale = (np.abs(x).max() if self.ntype == MAX
                 else np.sqrt(np.sum(x * x)))
        lib = _native() if self._xs is not None else None
        if lib is not None:
            # seeded: the RNG is sequential, so the numpy path below
            # degenerates to a per-element PYTHON loop in _uniform —
            # exactly the loop that belongs in C. Scale is computed
            # here (numpy) on both paths by construction; the state
            # words advance in place, one draw per element, matching
            # _uniform's stream.
            xc = np.ascontiguousarray(x)
            state = np.array([self._xs._a, self._xs._b], np.uint64)
            q = np.empty(self.size, self.qdtype)
            lib.bps_codec_dithering_compress(
                _ptr(xc), self.size, float(scale), self.s, self.ptype,
                self.qdtype.itemsize * 8, _ptr(state), _ptr(q))
            self._xs._a, self._xs._b = (np.uint64(state[0]),
                                        np.uint64(state[1]))
            return q.tobytes() + struct.pack("<f", np.float32(scale))
        u = self._uniform(self.size)
        safe = scale if scale > 0 else 1.0
        absx = np.abs(x)
        if self.ptype == LINEAR:
            normalized = absx / safe * self.s
            floor = np.floor(normalized)
            q = floor + (u < (normalized - floor))
        else:
            level = 1 << (self.s - 1)
            normalized = absx / safe * level
            c = np.ceil(normalized).astype(np.uint32)
            # round-next-pow2 >> 1 (reference RoundNextPow2, utils.h)
            v = np.maximum(c, 1).astype(np.uint32) - np.uint32(1)
            for shift in (1, 2, 4, 8, 16):
                v = v | (v >> np.uint32(shift))
            fl = ((v.astype(np.uint64) + 1) >> np.uint64(1)).astype(np.float32)
            length = np.where(fl != 0, fl, 1.0)
            p = (normalized - fl) / length
            q = fl + length * (u < p)
        q = (np.sign(x) * q).astype(self.qdtype)
        return q.tobytes() + struct.pack("<f", np.float32(scale))

    def decompress(self, buf) -> np.ndarray:
        buf = bytes(buf)
        q = np.frombuffer(buf[:-4], self.qdtype).astype(np.float32)
        (scale,) = struct.unpack("<f", buf[-4:])
        denom = self.s if self.ptype == LINEAR else (1 << (self.s - 1))
        return (q * scale / denom).astype(self.dtype)

    def payload_nbytes(self) -> int:
        return self.size * self.qdtype.itemsize + 4


def create_host_codec(kwargs: Dict[str, str], size: int,
                      dtype: str = "float32") -> Optional[HostCodec]:
    """Plain compressor from string kwargs, no decorators (servers add
    error feedback via ``create_server_chain``; workers add momentum+ef
    via ``create_host_chain``)."""
    ctype = kwargs.get("compressor_type")
    if ctype is None:
        return None
    if ctype == "onebit":
        scaled = str(kwargs.get("compressor_onebit_scaling",
                                "false")).lower() in ("1", "true", "yes")
        return HostOnebit(size, dtype, use_scale=scaled)
    if ctype == "topk":
        return HostTopk(size, dtype, k=resolve_k(kwargs, size, dtype))
    if ctype == "randomk":
        return HostRandomk(size, dtype, k=resolve_k(kwargs, size, dtype),
                           seed=int(kwargs.get("seed", 0)))
    if ctype == "dithering":
        return HostDithering(
            size, dtype, s=int(float(kwargs.get("compressor_k", 4))),
            seed=int(kwargs.get("seed", 0)),
            ptype=int(kwargs.get("dithering_partition", LINEAR)),
            ntype=int(kwargs.get("dithering_normalize", MAX)))
    raise ValueError(f"unknown compressor_type {ctype!r} for the host path")


class HostErrorFeedback:
    """Worker-side EF decorator: compress(g + e·lr_ratio); e = that − its
    decompressed value (reference: error_feedback.h:26-46; the vanilla
    variant's η_{t-1}/η_t scale arrives via ``set_lr`` instead of the
    reference's mmap'd lr.s file, vanilla_error_feedback.h:26-38)."""

    def __init__(self, inner: HostCodec) -> None:
        self.inner = inner
        self.size, self.dtype = inner.size, inner.dtype
        self._error = np.zeros(inner.size, np.float32)
        self._lr_prev = self._lr_now = 1.0

    def set_lr(self, lr: float) -> None:
        self._lr_prev, self._lr_now = self._lr_now, float(lr)

    def compress(self, x: np.ndarray) -> bytes:
        ratio = self._lr_prev / max(self._lr_now, 1e-30)
        corrected = np.asarray(x, np.float32).reshape(-1) + \
            self._error * ratio
        buf = self.inner.compress(corrected.astype(self.dtype))
        self._error = corrected - \
            self.inner.decompress(buf).astype(np.float32)
        return buf

    def decompress(self, buf) -> np.ndarray:
        return self.inner.decompress(buf)

    def payload_nbytes(self) -> int:
        return self.inner.payload_nbytes()


class HostNesterovMomentum:
    """Worker-side momentum decorator: m = μm + g; send g + μm
    (reference: nesterov_momentum.h:26-34)."""

    def __init__(self, inner, mu: float = 0.9) -> None:
        self.inner = inner
        self.size, self.dtype = inner.size, inner.dtype
        self.mu = float(mu)
        self._m = np.zeros(inner.size, np.float32)

    def compress(self, x: np.ndarray) -> bytes:
        g = np.asarray(x, np.float32).reshape(-1)
        self._m = self.mu * self._m + g
        return self.inner.compress((g + self.mu * self._m)
                                   .astype(self.dtype))

    def decompress(self, buf) -> np.ndarray:
        return self.inner.decompress(buf)

    def payload_nbytes(self) -> int:
        return self.inner.payload_nbytes()


def create_server_chain(kwargs: Dict[str, str], size: int,
                        dtype: str = "float32"):
    """Server-side chain: ef → compressor. The reference server's
    CompressorRegistry::Create skips ONLY momentum_type
    (compressor_registry.cc:40-56), so when ``ef_type`` is configured
    the merged buffer's recompression error is compensated round over
    round server-side, exactly like the reference."""
    comp = create_host_codec(kwargs, size, dtype)
    if comp is None:
        return None
    if kwargs.get("ef_type") == "vanilla":
        comp = HostErrorFeedback(comp)
    return comp


def create_host_chain(kwargs: Dict[str, str], size: int,
                      dtype: str = "float32"):
    """Worker-side chain: momentum → ef → compressor, outermost first
    (reference: CompressorRegistry::Create, compressor_registry.cc:40-56)."""
    comp = create_host_codec(kwargs, size, dtype)
    if comp is None:
        return None
    if kwargs.get("ef_type") == "vanilla":
        comp = HostErrorFeedback(comp)
    if kwargs.get("momentum_type") == "nesterov":
        comp = HostNesterovMomentum(
            comp, mu=float(kwargs.get("momentum_mu", 0.9)))
    return comp

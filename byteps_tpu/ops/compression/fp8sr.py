"""Deterministic counter-based stochastic rounding to fp8 (numpy
reference).

The fused compression plane's fp8 rungs (``compress/wire.py``
``fp8_e4m3`` / ``fp8_e5m2``, EQuARX-style quantized collectives, arXiv
2506.17615) need stochastic rounding — RNE-quantized gradients at 8
bits bias small coordinates to zero, SR keeps the quantizer unbiased —
WITHOUT breaking the plane's bit-reproducibility contract: no global
RNG, no hidden state. The noise here is a pure function of
``(element index, seed)`` via a murmur3-style 32-bit mixer, and the
whole rounding runs as integer bit-math on the f32 representation:

  1. ``y = clip(x / scale, ±MAX)`` (the int8 codec's divide + clip
     shape, MAX = the format's largest finite value),
  2. per element, the number of low f32-mantissa bits below the fp8
     grid is computed from the exponent (``base = 23 - mant`` for
     normals, growing toward the subnormal range; values under the
     subnormal quantum take an explicit Bernoulli branch),
  3. hashed noise of exactly that width is ADDED to the magnitude bits
     and the low bits truncated — the classic SR-by-integer-add, which
     rounds up with probability equal to the discarded fraction,
  4. the on-grid magnitude is re-packed into the fp8 byte encoding
     (sign | exp | mantissa) directly — no float8 cast is ever taken,
     so the kernel twin in ``pallas_kernels.fp8_sr_quantize`` can run
     the SAME uint32 ops on backends whose Mosaic has no fp8 support,
     and host↔device byte-identity holds by construction.

Both fp8 formats follow the OCP / ml_dtypes encodings (``e4m3fn``:
bias 7, no inf, max 448; ``e5m2``: IEEE-half-like, bias 15, max finite
57344). Encodes never produce nan/inf — overflow saturates at ±MAX,
exactly like the int8 codec's clip.
"""

from __future__ import annotations

import numpy as np

#: kind ids (shared with the Pallas kernel; NOT wire codec ids)
E4M3, E5M2 = 0, 1

#: per-format constants: (max finite, mantissa bits, min-normal biased
#: f32 exponent).  e4m3: min normal 2^-6 -> e=121; e5m2: 2^-14 -> 113.
_FMT = {
    E4M3: (448.0, 3, 121),
    E5M2: (57344.0, 2, 113),
}

_U32 = np.uint32


def fmt_max(kind: int) -> float:
    return _FMT[kind][0]


def fmt_params(kind: int):
    """(MAX, mant_bits, base_discard, emin, e_sub, quantum_bits) —
    ``base_discard`` = f32 mantissa bits below a normal fp8 grid point,
    ``e_sub`` = biased f32 exponent of the subnormal quantum, and
    ``quantum_bits`` = the f32 bit pattern of that quantum."""
    mx, mant, emin = _FMT[kind]
    base = 23 - mant
    e_sub = emin - mant
    return mx, mant, base, emin, e_sub, _U32(e_sub) << _U32(23)


def mix32(idx: np.ndarray, seed: int) -> np.ndarray:
    """murmur3 fmix32 over ``idx * golden ^ seed`` — the one noise
    source, identical (op for op, wraparound and all) in the numpy
    reference and the Pallas kernel."""
    h = (idx.astype(_U32) * _U32(0x9E3779B9)) ^ _U32(seed & 0xFFFFFFFF)
    h ^= h >> _U32(16)
    h *= _U32(0x85EBCA6B)
    h ^= h >> _U32(13)
    h *= _U32(0xC2B2AE35)
    h ^= h >> _U32(16)
    return h


def sr_quantize_bits(x: np.ndarray, scale: np.float32, kind: int,
                     seed: int) -> np.ndarray:
    """Stochastically round ``x / scale`` to fp8 ``kind``; returns the
    raw fp8 BYTE encodings (uint8). Deterministic in (x, scale, seed)."""
    mx, _, base, emin, e_sub, qbits = fmt_params(kind)
    x = np.ascontiguousarray(np.asarray(x, np.float32).reshape(-1))
    y = x / np.float32(scale)
    y = np.clip(y, np.float32(-mx), np.float32(mx))
    bits = y.view(_U32)
    sign = bits >> _U32(31)
    mag = bits & _U32(0x7FFFFFFF)
    e = mag >> _U32(23)
    h = mix32(np.arange(x.size, dtype=np.uint32), seed)
    # grid-binade case (value >= subnormal quantum): add noise of the
    # per-binade discard width, truncate — unbiased round within the
    # uniform-grid span of each binade
    d = np.clip(np.int64(emin + base) - e.astype(np.int64), base, 23) \
        .astype(_U32)
    mask = (_U32(1) << d) - _U32(1)
    mag_grid = (mag + (h & mask)) & ~mask
    # below-quantum case: neighbors are {0, quantum}; Bernoulli with
    # p = |y| / quantum via a 24-bit uniform from the same hash
    tiny = e < _U32(e_sub)
    u24 = (h >> _U32(8)).astype(np.float32) * np.float32(2.0 ** -24)
    t = np.abs(y) * np.float32(2.0 ** (127 - e_sub))   # |y| / quantum
    mag_tiny = np.where(u24 < t, qbits, _U32(0))
    mag2 = np.where(tiny, mag_tiny, mag_grid)
    mag2 = np.where(mag == 0, _U32(0), mag2)
    # pack the on-grid magnitude into the fp8 byte (mant = 23 - base)
    e2 = mag2 >> _U32(23)
    f2 = mag2 & _U32(0x7FFFFF)
    norm = ((e2 - _U32(emin - 1)) << _U32(23 - base)) | (f2 >> _U32(base))
    sub_shift = np.clip(np.int64(emin + base) - e2.astype(np.int64),
                        0, 31).astype(_U32)
    sub = ((_U32(1) << _U32(23)) | f2) >> sub_shift
    out = np.where(e2 >= _U32(emin), norm, sub)
    out = np.where(mag2 == 0, _U32(0), out)
    return ((sign << _U32(7)) | out).astype(np.uint8)


def fp8_view_dtype(kind: int):
    """The ml_dtypes numpy dtype that decodes these byte encodings."""
    import ml_dtypes
    return np.dtype(ml_dtypes.float8_e4m3fn if kind == E4M3
                    else ml_dtypes.float8_e5m2)


def decode_bits(q: np.ndarray, kind: int) -> np.ndarray:
    """fp8 byte encodings -> float32 values (unscaled)."""
    return np.asarray(q, np.uint8).view(fp8_view_dtype(kind)) \
        .astype(np.float32)

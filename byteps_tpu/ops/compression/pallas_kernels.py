"""Pallas TPU kernels for the compression hot ops.

The onebit pack/unpack is the per-step bandwidth hot path of compressed
push_pull (every gradient byte flows through it twice). The jnp fallback
lowers to a dozen XLA ops with intermediate materialization; these
kernels do the whole bit-twiddle in one VMEM pass on the VPU.

Layout: a flat buffer of n floats is viewed as ``[n/32, 32]`` — 32
consecutive elements per row, one packed uint32 word per row, MSB-first
within the row (payload-identical to the jnp path in onebit.py, which
follows the reference's packing, reference: impl/onebit.cc:34-67).

On non-TPU backends the same kernels run under Pallas interpret mode, so
tests validate the exact kernel logic on the CPU mesh.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

PACK = 32          # bits per packed word
_BLOCK_ROWS = 512  # words per kernel instance (512×32 f32 = 64 KiB VMEM)


def _cdiv(a: int, b: int) -> int:
    return (a + b - 1) // b


@functools.cache
def _interpret() -> bool:
    return jax.devices()[0].platform != "tpu"


def _pack_kernel(x_ref, out_ref):
    # int32 throughout: Mosaic has no unsigned reductions, and since the
    # bits are disjoint, two's-complement addition is still a bitwise OR
    x = x_ref[:]                                        # [B, 32] f32
    neg = (x < 0).astype(jnp.int32)
    shifts = (PACK - 1) - jax.lax.broadcasted_iota(jnp.int32, x.shape, 1)
    out_ref[:] = jnp.sum(neg << shifts, axis=1, keepdims=True)


def _unpack_kernel(p_ref, out_ref):
    w = p_ref[:]                                        # [B, 1] int32
    shifts = (PACK - 1) - jax.lax.broadcasted_iota(
        jnp.int32, (w.shape[0], PACK), 1)
    # arithmetic >> then &1 extracts the bit regardless of the sign bit
    bits = (w >> shifts) & jnp.int32(1)
    # bit 1 → negative (reference: sign = 1 - ((x & 1) << 1))
    out_ref[:] = 1.0 - 2.0 * bits.astype(jnp.float32)


def onebit_pack(x: jnp.ndarray, chunks: int) -> jnp.ndarray:
    """Sign-pack a flat float buffer into ``chunks`` uint32 words.

    ``x`` is zero-padded internally (sign bit of +0.0 is 0, matching the
    reference's padded tail).

    Layout note: the 32-wide minor dim uses a quarter of the 128-lane
    vreg; a [rows, 128]→4-words layout would fill it but needs cross-lane
    regrouping Mosaic lowers poorly. As-is the compiled kernel measures
    ~8× the fused-XLA path on a v5e chip — bandwidth-bound, not
    lane-bound.
    """
    rows = _cdiv(chunks, _BLOCK_ROWS) * _BLOCK_ROWS
    xp = jnp.pad(x.astype(jnp.float32), (0, rows * PACK - x.shape[0]))
    words = pl.pallas_call(
        _pack_kernel,
        out_shape=jax.ShapeDtypeStruct((rows, 1), jnp.int32),
        grid=(rows // _BLOCK_ROWS,),
        in_specs=[pl.BlockSpec((_BLOCK_ROWS, PACK), lambda i: (i, 0),
                               memory_space=pltpu.VMEM)],
        out_specs=pl.BlockSpec((_BLOCK_ROWS, 1), lambda i: (i, 0),
                               memory_space=pltpu.VMEM),
        interpret=_interpret(),
    )(xp.reshape(rows, PACK))
    return jax.lax.bitcast_convert_type(words.reshape(-1)[:chunks],
                                        jnp.uint32)


def onebit_unpack(packed: jnp.ndarray, n: int) -> jnp.ndarray:
    """Expand packed sign words to ±1.0 floats of length ``n`` (unscaled)."""
    chunks = packed.shape[0]
    rows = _cdiv(chunks, _BLOCK_ROWS) * _BLOCK_ROWS
    wi = jax.lax.bitcast_convert_type(packed, jnp.int32)
    wp = jnp.pad(wi, (0, rows - chunks)).reshape(rows, 1)
    signs = pl.pallas_call(
        _unpack_kernel,
        out_shape=jax.ShapeDtypeStruct((rows, PACK), jnp.float32),
        grid=(rows // _BLOCK_ROWS,),
        in_specs=[pl.BlockSpec((_BLOCK_ROWS, 1), lambda i: (i, 0),
                               memory_space=pltpu.VMEM)],
        out_specs=pl.BlockSpec((_BLOCK_ROWS, PACK), lambda i: (i, 0),
                               memory_space=pltpu.VMEM),
        interpret=_interpret(),
    )(wp)
    return signs.reshape(-1)[:n]


# ----------------------------------------------------- int8 quantization
#
# The fused compression plane's int8 hot path (byteps_tpu/compress):
# symmetric max-abs linear quantization with ONE fp32 scale per bucket,
# round-half-even — byte-identical to the host codec
# (compress.wire.encode CODEC_INT8), so a device-side quantize can feed
# the same wire format the numpy pack workers produce. Lanes are the
# full 128-wide vreg (unlike the onebit kernels' 32-wide packing
# geometry); the int8 output tile minimum is (32, 128), so the block
# row count stays a multiple of 32.

_LANES = 128
_Q_ROWS = 256      # 256×128 f32 in + int8 out ≈ 160 KiB VMEM per step


def _int8_q_kernel(x_ref, scale_ref, out_ref):
    # DIVIDE, exactly like the host codec's rint(x / scale): a
    # reciprocal-multiply is ~1 ulp off and flips round-half-even ties
    # on ~4e-7 of elements — enough to break byte-identity with the
    # wire codec on large buckets. scale <= 0 is substituted with 1.0
    # host-side (matching wire.encode's zero-amax rule).
    q = jnp.clip(jnp.round(x_ref[:] / scale_ref[0]), -127.0, 127.0)
    out_ref[:] = q.astype(jnp.int8)


def _int8_dq_kernel(q_ref, scale_ref, out_ref):
    out_ref[:] = q_ref[:].astype(jnp.float32) * scale_ref[0]


def _q_grid(n: int):
    rows = _cdiv(_cdiv(n, _LANES), _Q_ROWS) * _Q_ROWS
    return rows, rows // _Q_ROWS


def int8_quantize(x: jnp.ndarray, scale) -> jnp.ndarray:
    """Quantize a flat float buffer to int8 at ``scale`` (fp32 scalar;
    elements map to ``clip(round(x/scale), -127, 127)``). Zero-padded
    internally; the padding quantizes to 0 and is sliced off."""
    n = x.shape[0]
    rows, grid = _q_grid(n)
    xp = jnp.pad(x.astype(jnp.float32), (0, rows * _LANES - n))
    scale = jnp.asarray(scale, jnp.float32).reshape(1)
    # the host codec never divides by a non-positive scale (wire.encode
    # substitutes 1.0 for a zero amax) — mirror that rule here so the
    # kernel stays byte-identical AND total on degenerate inputs
    scale = jnp.where(scale > 0, scale, 1.0)
    q = pl.pallas_call(
        _int8_q_kernel,
        out_shape=jax.ShapeDtypeStruct((rows, _LANES), jnp.int8),
        grid=(grid,),
        in_specs=[pl.BlockSpec((_Q_ROWS, _LANES), lambda i: (i, 0),
                               memory_space=pltpu.VMEM),
                  pl.BlockSpec(memory_space=pltpu.SMEM)],
        out_specs=pl.BlockSpec((_Q_ROWS, _LANES), lambda i: (i, 0),
                               memory_space=pltpu.VMEM),
        interpret=_interpret(),
    )(xp.reshape(rows, _LANES), scale)
    return q.reshape(-1)[:n]


# ------------------------------------------------- fp8 stochastic round
#
# Kernel twin of ops/compression/fp8sr.py (the fused plane's fp8 rungs):
# deterministic counter-based stochastic rounding to the fp8 byte
# encoding, run ENTIRELY as uint32 bit-math — no float8 cast, so the
# kernel works on backends whose Mosaic has no fp8 type support and is
# byte-identical to the numpy reference by construction (same mixer,
# same integer adds, same truncation). The per-element noise counter is
# the element's flat index, so the payload is a pure function of
# (x, scale, seed) on every backend.

def _fp8_sr_kernel(x_ref, scale_ref, seed_ref, out_ref, *, kind: int,
                   block: int):
    from . import fp8sr
    mx, _, base, emin, e_sub, qbits = fp8sr.fmt_params(kind)
    u32 = jnp.uint32
    y = x_ref[:] / scale_ref[0]
    y = jnp.clip(y, -mx, mx)
    bits = jax.lax.bitcast_convert_type(y, jnp.uint32)
    sign = bits >> u32(31)
    mag = bits & u32(0x7FFFFFFF)
    e = (mag >> u32(23)).astype(jnp.int32)
    # flat element index = this block's offset + local (row, lane)
    off = (pl.program_id(0) * block * _LANES).astype(jnp.int32)
    local = (jax.lax.broadcasted_iota(jnp.int32, y.shape, 0) * _LANES
             + jax.lax.broadcasted_iota(jnp.int32, y.shape, 1))
    idx = (off + local).astype(jnp.uint32)
    h = (idx * u32(0x9E3779B9)) ^ seed_ref[0]
    h = h ^ (h >> u32(16))
    h = h * u32(0x85EBCA6B)
    h = h ^ (h >> u32(13))
    h = h * u32(0xC2B2AE35)
    h = h ^ (h >> u32(16))
    d = jnp.clip(jnp.int32(emin + base) - e, base, 23).astype(jnp.uint32)
    mask = (u32(1) << d) - u32(1)
    mag_grid = (mag + (h & mask)) & ~mask
    tiny = e < jnp.int32(e_sub)
    u24 = (h >> u32(8)).astype(jnp.float32) * jnp.float32(2.0 ** -24)
    t = jnp.abs(y) * jnp.float32(2.0 ** (127 - e_sub))
    mag_tiny = jnp.where(u24 < t, u32(qbits), u32(0))
    mag2 = jnp.where(tiny, mag_tiny, mag_grid)
    mag2 = jnp.where(mag == u32(0), u32(0), mag2)
    e2 = (mag2 >> u32(23)).astype(jnp.int32)
    f2 = mag2 & u32(0x7FFFFF)
    norm = (((e2 - jnp.int32(emin - 1)).astype(jnp.uint32)
             << u32(23 - base)) | (f2 >> u32(base)))
    sub_shift = jnp.clip(jnp.int32(emin + base) - e2, 0, 31) \
        .astype(jnp.uint32)
    sub = ((u32(1) << u32(23)) | f2) >> sub_shift
    out = jnp.where(e2 >= jnp.int32(emin), norm, sub)
    out = jnp.where(mag2 == u32(0), u32(0), out)
    out_ref[:] = ((sign << u32(7)) | out).astype(jnp.uint8)


def fp8_sr_quantize(x: jnp.ndarray, scale, seed, kind: int) -> jnp.ndarray:
    """Stochastically round a flat float buffer to fp8 byte encodings
    (uint8) at ``scale`` — byte-identical to
    ``fp8sr.sr_quantize_bits`` for the same (x, scale, seed). ``kind``
    is ``fp8sr.E4M3`` / ``fp8sr.E5M2``; zero-padding quantizes to 0 and
    is sliced off (the padded tail's noise never aliases real elements:
    the counter is the flat index)."""
    import functools as _ft
    n = x.shape[0]
    rows, grid = _q_grid(n)
    xp = jnp.pad(x.astype(jnp.float32), (0, rows * _LANES - n))
    scale = jnp.asarray(scale, jnp.float32).reshape(1)
    # zero-amax rule shared with the host codec (fp8sr divides too)
    scale = jnp.where(scale > 0, scale, 1.0)
    seed = jnp.asarray(seed, jnp.uint32).reshape(1)
    q = pl.pallas_call(
        _ft.partial(_fp8_sr_kernel, kind=kind, block=_Q_ROWS),
        out_shape=jax.ShapeDtypeStruct((rows, _LANES), jnp.uint8),
        grid=(grid,),
        in_specs=[pl.BlockSpec((_Q_ROWS, _LANES), lambda i: (i, 0),
                               memory_space=pltpu.VMEM),
                  pl.BlockSpec(memory_space=pltpu.SMEM),
                  pl.BlockSpec(memory_space=pltpu.SMEM)],
        out_specs=pl.BlockSpec((_Q_ROWS, _LANES), lambda i: (i, 0),
                               memory_space=pltpu.VMEM),
        interpret=_interpret(),
    )(xp.reshape(rows, _LANES), scale, seed)
    return q.reshape(-1)[:n]


def int8_dequantize(q: jnp.ndarray, scale, n: int = None) -> jnp.ndarray:
    """Expand int8 values back to fp32 (``q * scale``)."""
    m = q.shape[0]
    n = m if n is None else n
    rows, grid = _q_grid(m)
    qp = jnp.pad(q.astype(jnp.int8), (0, rows * _LANES - m))
    scale = jnp.asarray(scale, jnp.float32).reshape(1)
    out = pl.pallas_call(
        _int8_dq_kernel,
        out_shape=jax.ShapeDtypeStruct((rows, _LANES), jnp.float32),
        grid=(grid,),
        in_specs=[pl.BlockSpec((_Q_ROWS, _LANES), lambda i: (i, 0),
                               memory_space=pltpu.VMEM),
                  pl.BlockSpec(memory_space=pltpu.SMEM)],
        out_specs=pl.BlockSpec((_Q_ROWS, _LANES), lambda i: (i, 0),
                               memory_space=pltpu.VMEM),
        interpret=_interpret(),
    )(qp.reshape(rows, _LANES), scale)
    return out.reshape(-1)[:n]

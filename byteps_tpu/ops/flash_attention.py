"""Flash attention as Pallas TPU kernels (forward + backward).

The reference delegates all model math to torch/tf/mxnet (SURVEY §5
"Long-context: entirely absent"); here attention is the FLOPs/HBM hot
spot of the flagship BERT/GPT benchmarks, so it gets a hand-written
kernel pair:

  - forward: blockwise online-softmax attention — the [s, s] score
    matrix never leaves VMEM; O(s·block) HBM traffic instead of O(s²)
  - backward: two kernels (dq; dk+dv) recomputing probabilities from the
    saved log-sum-exp, the standard flash-attention-2 scheme
  - fp32 accumulation on the MXU (`preferred_element_type`), bf16 inputs
  - causal masking by block skipping + an iota mask on diagonal blocks

Layout contract matches the rest of the stack: [batch, seq, heads,
head_dim] in, same out. Kernels run per (batch, head) over a grid of
sequence blocks; the kv-block loop is the innermost grid dimension so
the accumulator scratch lives in VMEM across it.

`attention()` is the dispatcher the models call: Pallas on TPU when
shapes allow, pure-JAX blockwise otherwise (CPU tests, odd shapes).
"""

from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_NEG_INF = -1e30

# CompilerParams was named TPUCompilerParams in older jax releases
_CompilerParams = getattr(pltpu, "CompilerParams",
                          getattr(pltpu, "TPUCompilerParams", None))

# every kernel's grid is (outer..., carried): only the innermost dim
# carries scratch state across iterations; the rest are independent
# programs the pipeliner may reorder/overlap
_DIM_SEMANTICS = _CompilerParams(
    dimension_semantics=("parallel", "parallel", "parallel", "arbitrary"))


def _pick_block(s: int, want: int) -> int:
    for b in (want, 512, 256, 128):
        if b <= want and s % b == 0:
            return b
    return s


# ---- in-kernel T5 relative-position bias (see ops/relpos.py) ----
# The bucket index depends only on (col - row), so each (qb, kb) block
# derives its [bq, bk] bucket map from iotas and folds the small
# [heads, num_buckets] table into the scores — NO [h, sq, sk] bias in
# HBM, which is what keeps relative-bias self-attention O(s) memory at
# long sequence lengths.

def _bucket_block(qb, kb, bq, bk, bidirectional, nb, maxd):
    from .relpos import relative_position_bucket
    rows = qb * bq + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 0)
    cols = kb * bk + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
    return relative_position_bucket(cols - rows, bidirectional, nb, maxd)


def _table_bias(table_vec, bucket, nb):
    """[nb] table row + [bq, bk] bucket map → [bq, bk] bias. An
    unrolled select-sum (nb is 32): cheap VPU work next to the block's
    two MXU matmuls; a gather would not vectorize on TPU."""
    bias = jnp.zeros(bucket.shape, jnp.float32)
    for b in range(nb):
        bias = bias + jnp.where(bucket == b, table_vec[b], 0.0)
    return bias


def _rel_row(rel_ref, ih, ht, t):
    """Head (ih·ht + t)'s [nb] table row. The table rides as ONE
    full-array block (TPU block rules reject a (ht, nb) tile when
    ht < 8 — and the whole table is ~1 KB anyway). The row index is
    dynamic in the grid's head coordinate and Pallas TPU cannot lower
    dynamic_slice on values, so the row is selected by a masked
    reduction over the (tiny) head dim."""
    tab = rel_ref[...]                                   # [h, nb]
    idx = ih * ht + t
    mask = (jax.lax.broadcasted_iota(jnp.int32, tab.shape, 0)
            == idx)
    return jnp.sum(jnp.where(mask, tab, 0.0), axis=0)    # [nb]


# dtable output tile: padded to the minimum legal TPU block (8
# sublanes × 128 lanes); rows ≥ ht and lanes ≥ nb are zero
_DT_PAD = (8, 128)


def _clamp_ht(ht: int, h: int) -> int:
    """Clamp a head tile to the dtable row bound (_DT_PAD[0]) while
    keeping h % ht == 0. A plain min() can break divisibility — e.g. a
    BPS_FLASH_HT=12 override with h=12 clamps to 8, the grid covers only
    heads 0-7, and the kernel silently emits garbage for the rest — so
    fall back to the largest divisor of h that fits the bound."""
    clamped = min(ht, _DT_PAD[0])
    while clamped > 1 and h % clamped != 0:
        clamped -= 1
    if clamped != ht:
        from ..common.logging import get_logger
        get_logger().warning(
            "rel_table head tile clamped %d -> %d (dtable rows are "
            "hard-sized to %d and h=%d must divide)", ht, clamped,
            _DT_PAD[0], h)
    return clamped


def _table_grad(ds32, bucket, nb):
    """dL/d(table row), padded to the _DT_PAD lane count: sum of dS
    over positions in each bucket."""
    g = jnp.stack([jnp.sum(jnp.where(bucket == b, ds32, 0.0))
                   for b in range(nb)])
    return jnp.pad(g, (0, _DT_PAD[1] - nb))


def _head_tile(h: int, nq: int, nk: int, bq: int, bk: int, d: int,
               interpret: bool, mats: int = 1) -> int:
    """Heads per kernel program. Short sequences (one block pair per
    (b, h)) leave each program ~0.2 GFLOP — a 1024-program grid was
    overhead-bound (measured: BERT-large seq-512 fwd call 2.0 ms vs
    ~0.5 ms of matmul work; ht=8 recovered ~8%). Longer sequences get
    enough work per program from the block loops, and head-tiling would
    multiply the VMEM footprint, so keep 1. ``mats`` = number of
    [bq, bk] fp32 temporaries live per unrolled head (1 fwd; 3 bwd —
    the Mosaic stack allocator keeps each unrolled iteration's
    temporaries live, and the scoped-vmem limit is 16M). BPS_FLASH_HT
    overrides (0 = auto)."""
    import os as _os

    def _vmem(cand: int) -> int:
        return cand * (mats * bq * bk * 4 + 8 * max(bq, bk) * d)

    # scoped-VMEM budget for the tile chooser (heuristic: real usage
    # exceeds the estimate by the io double-buffers; 10M of estimate
    # keeps Mosaic's 16M limit safe). Raising it to 11M admits ht=8
    # for the d64 fwd — measured NEUTRAL (80.22 vs 80.2 sps), so the
    # validated default stands and the knob exists for experiments
    budget = int(_os.environ.get("BPS_FLASH_VMEM_BUDGET",
                                 str(10 << 20)))
    env = int(_os.environ.get("BPS_FLASH_HT", "0"))
    if env:
        if h % env != 0:
            return 1
        if _vmem(env) >= budget:
            # an oversized override would blow the 16M scoped-vmem limit
            # and fail Mosaic compilation at runtime — clamp to the same
            # budget the auto path enforces
            from ..common.logging import get_logger
            get_logger().warning(
                "BPS_FLASH_HT=%d exceeds the VMEM budget for this shape "
                "(bq=%d bk=%d d=%d mats=%d); falling back to auto tiling",
                env, bq, bk, d, mats)
        else:
            return env
    if interpret or nq != 1 or nk != 1:
        return 1
    for cand in (8, 4, 2):
        if h % cand == 0 and _vmem(cand) < budget:
            return cand
    return 1


# --------------------------------------------------------------- forward

def _fwd_kernel(q_ref, k_ref, v_ref, *rest, scale, causal, bq, bk, nk,
                ht, has_bias=False, rel=None):
    bias_ref = rel_ref = None
    if has_bias:
        bias_ref, o_ref, lse_ref, acc, m_scr, l_scr = rest
    elif rel is not None:
        rel_ref, o_ref, lse_ref, acc, m_scr, l_scr = rest
    else:
        o_ref, lse_ref, acc, m_scr, l_scr = rest
    kb = pl.program_id(3)
    qb = pl.program_id(2)
    ih = pl.program_id(1)     # evaluated OUTSIDE pl.when: the traced
                              # cond body can't introduce program_id

    @pl.when(kb == 0)
    def _init():
        acc[...] = jnp.zeros_like(acc)
        m_scr[...] = jnp.full_like(m_scr, _NEG_INF)
        l_scr[...] = jnp.zeros_like(l_scr)

    # causal: skip kv blocks strictly above the diagonal
    run = True if not causal else (kb * bk <= qb * bq + bq - 1)

    @pl.when(run)
    def _block():
        # ``ht`` heads per program (unrolled): amortizes grid/dispatch
        # overhead — at seq 512 the per-(b,h) program is only ~0.2 GFLOP
        # and a 1024-program grid was overhead-bound (measured 2.0 ms vs
        # ~0.5 ms of matmul work per BERT-large layer call)
        if rel is not None:
            bidirectional, nb, maxd = rel
            bucket = _bucket_block(qb, kb, bq, bk, bidirectional, nb,
                                   maxd)          # shared by the heads
        for t in range(ht):
            q = q_ref[0, t]                  # [bq, d]
            k = k_ref[0, t]                  # [bk, d]
            v = v_ref[0, t]
            s = jax.lax.dot_general(
                q, k, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32) * scale   # [bq, bk]
            if has_bias:
                # additive score bias (T5 relative position): S =
                # qkᵀ·scale + B — folded in BEFORE the online softmax
                s = s + bias_ref[t].astype(jnp.float32)
            if rel is not None:
                row = _rel_row(rel_ref, ih, ht, t)
                s = s + _table_bias(row.astype(jnp.float32), bucket,
                                    rel[1])
            if causal:
                rows = qb * bq + jax.lax.broadcasted_iota(
                    jnp.int32, (bq, bk), 0)
                cols = kb * bk + jax.lax.broadcasted_iota(
                    jnp.int32, (bq, bk), 1)
                s = jnp.where(rows >= cols, s, _NEG_INF)
            r = slice(t * bq, (t + 1) * bq)
            m_prev = m_scr[r, :1]                             # [bq, 1]
            m_new = jnp.maximum(m_prev, jnp.max(s, -1, keepdims=True))
            p = jnp.exp(s - m_new)
            alpha = jnp.exp(m_prev - m_new)
            l_new = l_scr[r, :1] * alpha + jnp.sum(p, -1, keepdims=True)
            pv = jax.lax.dot_general(
                p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)           # [bq, d]
            acc[r] = acc[r] * alpha + pv
            m_scr[r] = jnp.broadcast_to(m_new, (bq, 128))
            l_scr[r] = jnp.broadcast_to(l_new, (bq, 128))

    @pl.when(kb == nk - 1)
    def _finish():
        for t in range(ht):
            r = slice(t * bq, (t + 1) * bq)
            l = jnp.maximum(l_scr[r, :1], 1e-30)
            o_ref[0, t] = (acc[r] / l).astype(o_ref.dtype)
            lse_ref[0, t] = m_scr[r, :1] + jnp.log(l)


def _flash_fwd(q, k, v, causal, scale, bq, bk, interpret, out_dtype=None,
               bias=None, rel_table=None, rel=None):
    """q: [b, h, sq, d]; k,v: [b, h, sk, d] → (out [b,h,sq,d],
    lse [b,h,sq,1] fp32). sq and sk may DIFFER (cross-attention: the
    decoder's queries over the encoder's keys) — the kernels only ever
    see (bq, bk) blocks, so the tiling contract is per-axis.

    out_dtype overrides the output dtype (default q.dtype) — ring
    attention requests fp32 partials so the per-step LSE combine does
    not accumulate one bf16 rounding per ring step."""
    b, h, sq, d = q.shape
    sk = k.shape[2]
    nq, nk = sq // bq, sk // bk
    ht = _head_tile(h, nq, nk, bq, bk, d, interpret,
                    mats=3 if rel is not None else 1)
    if rel is not None:
        ht = _clamp_ht(ht, h)   # matches the bwd dtable tile bound
    grid = (b, h // ht, nq, nk)
    has_bias = bias is not None
    kernel = functools.partial(_fwd_kernel, scale=scale, causal=causal,
                               bq=bq, bk=bk, nk=nk, ht=ht,
                               has_bias=has_bias, rel=rel)
    in_specs = [
        pl.BlockSpec((1, ht, bq, d), lambda ib, ih, iq, ik: (ib, ih, iq, 0)),
        pl.BlockSpec((1, ht, bk, d), lambda ib, ih, iq, ik: (ib, ih, ik, 0)),
        pl.BlockSpec((1, ht, bk, d), lambda ib, ih, iq, ik: (ib, ih, ik, 0)),
    ]
    inputs = [q, k, v]
    if has_bias:
        in_specs.append(pl.BlockSpec(
            (ht, bq, bk), lambda ib, ih, iq, ik: (ih, iq, ik)))
        inputs.append(bias)
    elif rel is not None:
        in_specs.append(pl.BlockSpec(
            rel_table.shape, lambda ib, ih, iq, ik: (0, 0)))
        inputs.append(rel_table)
    out, lse = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=in_specs,
        out_specs=[
            pl.BlockSpec((1, ht, bq, d), lambda ib, ih, iq, ik: (ib, ih, iq, 0)),
            pl.BlockSpec((1, ht, bq, 1), lambda ib, ih, iq, ik: (ib, ih, iq, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b, h, sq, d), out_dtype or q.dtype),
            jax.ShapeDtypeStruct((b, h, sq, 1), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((ht * bq, d), jnp.float32),
            pltpu.VMEM((ht * bq, 128), jnp.float32),
            pltpu.VMEM((ht * bq, 128), jnp.float32),
        ],
        compiler_params=_DIM_SEMANTICS,
        interpret=interpret,
    )(*inputs)
    return out, lse


def _xla_fwd(qt, kt, vt, causal, scale, out_dtype=None, bias=None):
    """[b,h,s,d] → (out, lse [b,h,s,1] fp32) with plain XLA ops.

    At moderate sequence lengths the XLA-fused softmax-attention forward
    beats the Pallas forward kernel (measured: BERT-large seq 512 fwd
    261→239 ms — the [s,s] scores fit HBM easily and XLA's fusion wins),
    while the flash BACKWARD kernels still beat XLA's backward (which
    must materialize softmax gradients). The hybrid uses this forward +
    the same (out, lse) residual contract the Pallas backward needs."""
    s = jax.lax.dot_general(qt, kt, (((3,), (3,)), ((0, 1), (0, 1))),
                            preferred_element_type=jnp.float32) * scale
    if bias is not None:
        s = s + bias[None].astype(jnp.float32)
    if causal:
        rows = jax.lax.broadcasted_iota(jnp.int32, s.shape, 2)
        cols = jax.lax.broadcasted_iota(jnp.int32, s.shape, 3)
        s = jnp.where(rows >= cols, s, _NEG_INF)
    m = jnp.max(s, -1, keepdims=True)
    p = jnp.exp(s - m)
    l = jnp.sum(p, -1, keepdims=True)
    out = jax.lax.dot_general((p / l).astype(vt.dtype), vt,
                              (((3,), (2,)), ((0, 1), (0, 1))),
                              preferred_element_type=jnp.float32)
    return out.astype(out_dtype or qt.dtype), m + jnp.log(l)


# -------------------------------------------------------------- backward

def _dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, *rest,
               scale, causal, bq, bk, nk, ht, has_bias=False, rel=None,
               nq=0):
    bias_ref = dbias_ref = rel_ref = dt_ref = dt_scr = None
    if has_bias:
        bias_ref, dq_ref, dbias_ref, dq_acc = rest
    elif rel is not None:
        rel_ref, dq_ref, dt_ref, dq_acc, dt_scr = rest
    else:
        dq_ref, dq_acc = rest
    kb = pl.program_id(3)
    qb = pl.program_id(2)
    ih = pl.program_id(1)     # outside pl.when (see _fwd_kernel)

    @pl.when(kb == 0)
    def _init():
        dq_acc[...] = jnp.zeros_like(dq_acc)

    if rel is not None:
        # dtable accumulates across BOTH block dims (its output block
        # is per (b, h)); the rel grid runs iq as carried too
        @pl.when(jnp.logical_and(kb == 0, qb == 0))
        def _init_dt():
            dt_scr[...] = jnp.zeros_like(dt_scr)

    run = True if not causal else (kb * bk <= qb * bq + bq - 1)

    if has_bias:
        # every (iq, ik) grid point owns its own dbias block, INCLUDING
        # causally-skipped ones — an unwritten output block is garbage
        @pl.when(jnp.logical_not(run))
        def _zero_dbias():
            dbias_ref[...] = jnp.zeros_like(dbias_ref)

    @pl.when(run)
    def _block():
        if rel is not None:
            bucket = _bucket_block(qb, kb, bq, bk, rel[0], rel[1], rel[2])
        for t in range(ht):                  # heads per program (see fwd)
            q = q_ref[0, t]
            k = k_ref[0, t]
            v = v_ref[0, t]
            do = do_ref[0, t]
            lse = lse_ref[0, t]                             # [bq, 1]
            delta = delta_ref[0, t]                         # [bq, 1]
            s = jax.lax.dot_general(
                q, k, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32) * scale
            if has_bias:
                s = s + bias_ref[t].astype(jnp.float32)
            if rel is not None:
                row = _rel_row(rel_ref, ih, ht, t)
                s = s + _table_bias(row.astype(jnp.float32), bucket,
                                    rel[1])
            p = jnp.exp(s - lse)                            # [bq, bk]
            if causal:
                rows = qb * bq + jax.lax.broadcasted_iota(
                    jnp.int32, (bq, bk), 0)
                cols = kb * bk + jax.lax.broadcasted_iota(
                    jnp.int32, (bq, bk), 1)
                p = jnp.where(rows >= cols, p, 0.0)
            dp = jax.lax.dot_general(
                do, v, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32)         # [bq, bk]
            ds32 = p * (dp - delta)           # dL/dS, S = qkᵀ·scale + B
            if has_bias:
                dbias_ref[0, t] = ds32        # dB = dS (summed over batch
            if rel is not None:               # by the caller)
                dt_scr[t] += _table_grad(ds32, bucket, rel[1])
            ds = ds32.astype(k.dtype)
            r = slice(t * bq, (t + 1) * bq)
            dq_acc[r] += jax.lax.dot_general(
                ds, k, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32) * scale

    @pl.when(kb == nk - 1)
    def _finish():
        for t in range(ht):
            dq_ref[0, t] = dq_acc[t * bq:(t + 1) * bq].astype(dq_ref.dtype)

    if rel is not None:
        @pl.when(jnp.logical_and(kb == nk - 1, qb == nq - 1))
        def _finish_dt():
            dt_ref[0, 0] = dt_scr[...]


def _dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, *rest,
                scale, causal, bq, bk, nq, ht, has_bias=False, rel=None):
    bias_ref = rel_ref = None
    if has_bias:
        bias_ref, dk_ref, dv_ref, dk_acc, dv_acc = rest
    elif rel is not None:
        rel_ref, dk_ref, dv_ref, dk_acc, dv_acc = rest
    else:
        dk_ref, dv_ref, dk_acc, dv_acc = rest
    qb = pl.program_id(3)
    kb = pl.program_id(2)
    ih = pl.program_id(1)     # outside pl.when (see _fwd_kernel)

    @pl.when(qb == 0)
    def _init():
        dk_acc[...] = jnp.zeros_like(dk_acc)
        dv_acc[...] = jnp.zeros_like(dv_acc)

    run = True if not causal else (kb * bk <= qb * bq + bq - 1)

    @pl.when(run)
    def _block():
        if rel is not None:
            bucket = _bucket_block(qb, kb, bq, bk, rel[0], rel[1], rel[2])
        for t in range(ht):                  # heads per program (see fwd)
            q = q_ref[0, t]                                 # [bq, d]
            k = k_ref[0, t]                                 # [bk, d]
            v = v_ref[0, t]
            do = do_ref[0, t]                               # [bq, d]
            lse = lse_ref[0, t]                             # [bq, 1]
            delta = delta_ref[0, t]
            s = jax.lax.dot_general(
                q, k, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32) * scale  # [bq, bk]
            if has_bias:
                s = s + bias_ref[t].astype(jnp.float32)
            if rel is not None:
                row = _rel_row(rel_ref, ih, ht, t)
                s = s + _table_bias(row.astype(jnp.float32), bucket,
                                    rel[1])
            p = jnp.exp(s - lse)
            if causal:
                rows = qb * bq + jax.lax.broadcasted_iota(
                    jnp.int32, (bq, bk), 0)
                cols = kb * bk + jax.lax.broadcasted_iota(
                    jnp.int32, (bq, bk), 1)
                p = jnp.where(rows >= cols, p, 0.0)
            pt = p.astype(do.dtype)
            r = slice(t * bk, (t + 1) * bk)
            dv_acc[r] += jax.lax.dot_general(
                pt, do, (((0,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)         # [bk, d]
            dp = jax.lax.dot_general(
                do, v, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32)         # [bq, bk]
            ds = (p * (dp - delta)).astype(q.dtype)
            dk_acc[r] += jax.lax.dot_general(
                ds, q, (((0,), (0,)), ((), ())),
                preferred_element_type=jnp.float32) * scale  # [bk, d]

    @pl.when(qb == nq - 1)
    def _finish():
        for t in range(ht):
            r = slice(t * bk, (t + 1) * bk)
            dk_ref[0, t] = dk_acc[r].astype(dk_ref.dtype)
            dv_ref[0, t] = dv_acc[r].astype(dv_ref.dtype)


def _dqkv_fused_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, *rest,
                       scale, causal, bq, bk, ht, has_delta):
    """Single-block-pair fused backward: when the whole sequence is one
    (bq, bk) block per (b, head) — the flagship seq-512 geometry — the
    split dq / dkv kernels each recompute s, p and dp just to emit
    their own outputs (7 matmuls + 2 exp sweeps total). One kernel
    computes the shared recompute once and emits all three gradients:
    5 matmuls + 1 exp, and q/k/v/do cross HBM once instead of twice.

    ``has_delta=False`` computes the softmax-gradient correction
    IN-KERNEL via the identity delta_i = sum_j p_ij·dp_ij (equal to
    sum_d do_id·out_id since out = p̂V) — valid because nk == 1 means
    the whole kv row is in this block. That removes ``out`` from the
    backward's inputs entirely, so under remat XLA dead-code-eliminates
    the recompute's p·V matmul (1 of its 2 matmuls) AND the host-level
    delta pass over out/do. Ring callers pass their hoisted GLOBAL
    delta instead (has_delta=True): a local p·dp sum cannot span the
    other kv shards' contributions."""
    if has_delta:
        delta_ref, dq_ref, dk_ref, dv_ref = rest
    else:
        dq_ref, dk_ref, dv_ref = rest
    for t in range(ht):
        q = q_ref[0, t]                                     # [bq, d]
        k = k_ref[0, t]                                     # [bk, d]
        v = v_ref[0, t]
        do = do_ref[0, t]
        lse = lse_ref[0, t]                                 # [bq, 1]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale     # [bq, bk]
        p = jnp.exp(s - lse)
        if causal:
            rows = jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 0)
            cols = jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
            p = jnp.where(rows >= cols, p, 0.0)
        pt = p.astype(do.dtype)
        dv_ref[0, t] = jax.lax.dot_general(
            pt, do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32).astype(dv_ref.dtype)
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)             # [bq, bk]
        if has_delta:
            delta = delta_ref[0, t]                         # [bq, 1]
        else:
            delta = jnp.sum(p * dp, -1, keepdims=True)      # [bq, 1]
        ds32 = p * (dp - delta)
        ds = ds32.astype(q.dtype)
        dq_ref[0, t] = (jax.lax.dot_general(
            ds, k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
            * scale).astype(dq_ref.dtype)
        dk_ref[0, t] = (jax.lax.dot_general(
            ds, q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
            * scale).astype(dk_ref.dtype)


def _flash_bwd_fused(q, k, v, lse, do, delta, causal, scale, bq, bk,
                     interpret, ht):
    """One pallas_call emitting (dq, dk, dv); caller guarantees
    nq == nk == 1 and no bias/rel_table. ``delta=None`` computes it
    in-kernel (see _dqkv_fused_kernel) — the no-``out``-input form."""
    b, h, sq, d = q.shape
    sk = k.shape[2]
    spec_q = pl.BlockSpec((1, ht, bq, d), lambda ib, ih: (ib, ih, 0, 0))
    spec_k = pl.BlockSpec((1, ht, bk, d), lambda ib, ih: (ib, ih, 0, 0))
    spec_r1 = pl.BlockSpec((1, ht, bq, 1), lambda ib, ih: (ib, ih, 0, 0))
    has_delta = delta is not None
    in_specs = [spec_q, spec_k, spec_k, spec_q, spec_r1]
    inputs = [q, k, v, do, lse]
    if has_delta:
        in_specs.append(spec_r1)
        inputs.append(delta)
    return pl.pallas_call(
        functools.partial(_dqkv_fused_kernel, scale=scale, causal=causal,
                          bq=bq, bk=bk, ht=ht, has_delta=has_delta),
        grid=(b, h // ht),
        in_specs=in_specs,
        out_specs=[spec_q, spec_k, spec_k],
        out_shape=[jax.ShapeDtypeStruct((b, h, sq, d), q.dtype),
                   jax.ShapeDtypeStruct((b, h, sk, d), k.dtype),
                   jax.ShapeDtypeStruct((b, h, sk, d), v.dtype)],
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "parallel")),
        interpret=interpret,
    )(*inputs)


def _flash_bwd(q, k, v, out, lse, do, causal, scale, bq, bk, interpret,
               delta=None, bias=None, rel_table=None, rel=None):
    b, h, sq, d = q.shape
    sk = k.shape[2]
    nq, nk = sq // bq, sk // bk

    has_bias = bias is not None
    has_rel = rel is not None
    if (not has_bias and not has_rel and nq == 1 and nk == 1
            and os.environ.get("BPS_FLASH_FUSED_BWD", "1") != "0"):
        # mats=4: p, dp, ds32 and the cast ds are live per unrolled
        # head. delta passes through as given: None lets the kernel
        # compute it in-kernel (dropping `out` from the backward's
        # inputs — under remat the recompute's p·V matmul DCEs away);
        # ring callers' hoisted GLOBAL delta is honored
        ht_f = _head_tile(h, nq, nk, bq, bk, d, interpret, mats=4)
        dq, dk, dv = _flash_bwd_fused(q, k, v, lse, do, delta, causal,
                                      scale, bq, bk, interpret, ht_f)
        return dq, dk, dv, None, None

    if delta is None:      # ring callers hoist this loop-invariant reduction
        delta = jnp.sum(do.astype(jnp.float32) * out.astype(jnp.float32),
                        axis=-1, keepdims=True)             # [b,h,s,1]
    ht = _head_tile(h, nq, nk, bq, bk, d, interpret,
                    mats=5 if has_rel else (4 if has_bias else 3))
    if has_rel:
        # the dtable scratch and output tiles are hard-sized to
        # _DT_PAD rows — a BPS_FLASH_HT override above that would
        # write out of bounds and break the drel reshape
        ht = _clamp_ht(ht, h)
    qspec = pl.BlockSpec((1, ht, bq, d), lambda ib, ih, iq, ik: (ib, ih, iq, 0))
    kspec = pl.BlockSpec((1, ht, bk, d), lambda ib, ih, iq, ik: (ib, ih, ik, 0))
    r1spec = pl.BlockSpec((1, ht, bq, 1), lambda ib, ih, iq, ik: (ib, ih, iq, 0))

    in_specs = [qspec, kspec, kspec, qspec, r1spec, r1spec]
    inputs = [q, k, v, do, lse, delta]
    out_specs = qspec
    out_shape = jax.ShapeDtypeStruct((b, h, sq, d), q.dtype)
    scratches = [pltpu.VMEM((ht * bq, d), jnp.float32)]
    params = _DIM_SEMANTICS
    if has_bias:
        bspec = pl.BlockSpec((ht, bq, bk), lambda ib, ih, iq, ik: (ih, iq, ik))
        in_specs.append(bspec)
        inputs.append(bias)
        # per-batch dbias blocks (dB = dS); summed over batch below.
        # O(b·h·sq·sk) fp32 — the biased path is for MODERATE lengths;
        # the rel_table path below is the O(s)-memory long-length form.
        out_specs = [qspec, pl.BlockSpec(
            (1, ht, bq, bk), lambda ib, ih, iq, ik: (ib, ih, iq, ik))]
        out_shape = [out_shape,
                     jax.ShapeDtypeStruct((b, h, sq, sk), jnp.float32)]
    elif has_rel:
        nb = rel_table.shape[1]
        in_specs.append(pl.BlockSpec(
            rel_table.shape, lambda ib, ih, iq, ik: (0, 0)))
        inputs.append(rel_table)
        # dtable accumulates in VMEM scratch across BOTH block dims —
        # iq must therefore be CARRIED (arbitrary), not parallel.
        # Output tiles are padded to the minimum legal TPU block
        # (_DT_PAD); real rows/lanes sliced back out below.
        out_specs = [qspec, pl.BlockSpec(
            (1, 1) + _DT_PAD, lambda ib, ih, iq, ik: (ib, ih, 0, 0))]
        out_shape = [out_shape, jax.ShapeDtypeStruct(
            (b, h // ht) + _DT_PAD, jnp.float32)]
        scratches.append(pltpu.VMEM(_DT_PAD, jnp.float32))
        params = _CompilerParams(dimension_semantics=(
            "parallel", "parallel", "arbitrary", "arbitrary"))
    res = pl.pallas_call(
        functools.partial(_dq_kernel, scale=scale, causal=causal,
                          bq=bq, bk=bk, nk=nk, ht=ht, has_bias=has_bias,
                          rel=rel, nq=nq),
        grid=(b, h // ht, nq, nk),
        in_specs=in_specs,
        out_specs=out_specs,
        out_shape=out_shape,
        scratch_shapes=scratches,
        compiler_params=params,
        interpret=interpret,
    )(*inputs)
    dbias = drel = None
    if has_bias:
        dq, dbias_b = res
        dbias = jnp.sum(dbias_b, axis=0)                   # [h, sq, sk]
    elif has_rel:
        dq, dt_b = res                 # [b, h//ht, 8, 128] padded tiles
        nb = rel_table.shape[1]
        drel = jnp.sum(dt_b[:, :, :ht, :nb], axis=0).reshape(h, nb)
    else:
        dq = res

    # dk/dv: kv block is the outer (carried) grid dim, q block inner
    qspec2 = pl.BlockSpec((1, ht, bq, d), lambda ib, ih, ik, iq: (ib, ih, iq, 0))
    kspec2 = pl.BlockSpec((1, ht, bk, d), lambda ib, ih, ik, iq: (ib, ih, ik, 0))
    r1spec2 = pl.BlockSpec((1, ht, bq, 1), lambda ib, ih, ik, iq: (ib, ih, iq, 0))
    in_specs2 = [qspec2, kspec2, kspec2, qspec2, r1spec2, r1spec2]
    inputs2 = [q, k, v, do, lse, delta]
    if has_bias:
        in_specs2.append(pl.BlockSpec(
            (ht, bq, bk), lambda ib, ih, ik, iq: (ih, iq, ik)))
        inputs2.append(bias)
    elif has_rel:
        in_specs2.append(pl.BlockSpec(
            rel_table.shape, lambda ib, ih, ik, iq: (0, 0)))
        inputs2.append(rel_table)
    dk, dv = pl.pallas_call(
        functools.partial(_dkv_kernel, scale=scale, causal=causal,
                          bq=bq, bk=bk, nq=nq, ht=ht, has_bias=has_bias,
                          rel=rel),
        grid=(b, h // ht, nk, nq),
        in_specs=in_specs2,
        out_specs=[kspec2, kspec2],
        out_shape=[jax.ShapeDtypeStruct((b, h, sk, d), k.dtype),
                   jax.ShapeDtypeStruct((b, h, sk, d), v.dtype)],
        scratch_shapes=[pltpu.VMEM((ht * bk, d), jnp.float32),
                        pltpu.VMEM((ht * bk, d), jnp.float32)],
        compiler_params=_DIM_SEMANTICS,
        interpret=interpret,
    )(*inputs2)
    return dq, dk, dv, dbias, drel


# ------------------------------------------------------------ public API

@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7, 8, 11, 12))
def flash_attention(q, k, v, causal=False, scale=None,
                    block_q=512, block_k=512, interpret=False,
                    fwd_xla=False, bias=None, rel_table=None,
                    rel_bidirectional=True, rel_max_distance=128):
    """Pallas flash attention. q: [b, sq, heads, d]; k,v: [b, sk, heads,
    d] → [b, sq, heads, d]. sq and sk may differ (cross-attention).

    Each seq must be divisible by the (auto-shrunk) block sizes.
    Differentiable via the flash backward kernels. 512 blocks measured
    ~29% faster than 256 on BERT-large seq-512 (fewer grid steps,
    full-width MXU tiles); VMEM stays comfortable through d=256
    (p-block 1MB + acc 512KB). ``fwd_xla`` swaps the forward for the
    XLA-fused one (see ``_xla_fwd``) while keeping the flash backward —
    the "hybrid" impl.

    Two additive-score-bias forms (mutually exclusive):

    - ``rel_table`` [heads, num_buckets]: T5 relative-position bias
      computed IN-KERNEL from block offsets — no [h, sq, sk] bias ever
      materializes (O(s) memory at any length), dtable accumulated in
      VMEM scratch. This is the long-sequence form.
    - ``bias`` [heads, sq, sk]: an arbitrary materialized bias; its
      BACKWARD materializes per-batch dbias blocks
      (O(batch·heads·sq·sk) fp32) before the batch sum — moderate
      lengths only.
    """
    out, _ = _fwd_rule(q, k, v, causal, scale, block_q, block_k, interpret,
                       fwd_xla, bias, rel_table, rel_bidirectional,
                       rel_max_distance)
    return out


def _resolve(q, k, scale, block_q, block_k):
    _, sq, _, d = q.shape
    sk = k.shape[1]
    if scale is None:
        scale = d ** -0.5
    # sweep/tuning overrides (examples/flash_block_sweep.py): applied
    # before the shape-shrink so every call site is covered uniformly
    env_q = int(os.environ.get("BPS_FLASH_BQ", "0"))
    env_k = int(os.environ.get("BPS_FLASH_BK", "0"))
    if env_q:
        block_q = env_q
    if env_k:
        block_k = env_k
    bq = _pick_block(sq, min(block_q, sq))
    bk = _pick_block(sk, min(block_k, sk))
    return scale, bq, bk


def _rel_static(rel_table, bidirectional, max_distance):
    """(bidirectional, num_buckets, max_distance) static tuple the
    kernels close over, or None."""
    if rel_table is None:
        return None
    return (bool(bidirectional), int(rel_table.shape[1]),
            int(max_distance))


def _fwd_rule(q, k, v, causal, scale, block_q, block_k, interpret,
              fwd_xla=False, bias=None, rel_table=None,
              rel_bidirectional=True, rel_max_distance=128):
    if rel_table is not None and rel_table.shape[1] > _DT_PAD[1]:
        raise ValueError(
            f"rel_table has {rel_table.shape[1]} buckets; the in-kernel "
            f"path supports at most {_DT_PAD[1]} (one dtable lane tile)")
    if causal and q.shape[1] != k.shape[1]:
        raise ValueError(
            "causal masking requires equal q/kv lengths (got "
            f"{q.shape[1]} vs {k.shape[1]}); cross-attention is "
            "bidirectional")
    if bias is not None and rel_table is not None:
        raise ValueError("bias and rel_table are mutually exclusive")
    rel = _rel_static(rel_table, rel_bidirectional, rel_max_distance)
    scale, bq, bk = _resolve(q, k, scale, block_q, block_k)
    qt = jnp.swapaxes(q, 1, 2)       # [b, h, s, d]
    kt = jnp.swapaxes(k, 1, 2)
    vt = jnp.swapaxes(v, 1, 2)
    if fwd_xla:
        xbias = bias
        if rel is not None:
            from .relpos import relative_bias
            xbias = relative_bias(rel_table.T, q.shape[1], k.shape[1],
                                  rel[0], rel[1], rel[2])
        out, lse = _xla_fwd(qt, kt, vt, causal, scale, bias=xbias)
    else:
        out, lse = _flash_fwd(qt, kt, vt, causal, scale, bq, bk, interpret,
                              bias=bias, rel_table=rel_table, rel=rel)
    # store lse as [b,h,s]: a trailing dim of 1 lane-pads to 128 on TPU,
    # bloating the saved residual 128x when it survives to the backward
    from jax.ad_checkpoint import checkpoint_name
    # named so a remat policy can pin the flash residuals while everything
    # around them recomputes (remat_policy="save_attn"); name the SQUEEZED
    # lse — pinning the [b,h,s,1] form would lane-pad 128x (comment above)
    out = checkpoint_name(out, "flash_out")
    lse = checkpoint_name(lse[..., 0], "flash_lse")
    return jnp.swapaxes(out, 1, 2), (qt, kt, vt, out, lse, bias, rel_table)


def _vjp_fwd(q, k, v, causal, scale, block_q, block_k, interpret,
             fwd_xla=False, bias=None, rel_table=None,
             rel_bidirectional=True, rel_max_distance=128):
    out, res = _fwd_rule(q, k, v, causal, scale, block_q, block_k, interpret,
                         fwd_xla, bias, rel_table, rel_bidirectional,
                         rel_max_distance)
    return out, res


def _vjp_bwd(causal, scale, block_q, block_k, interpret, fwd_xla,
             rel_bidirectional, rel_max_distance, res, g):
    qt, kt, vt, out, lse, bias, rel_table = res
    scale, bq, bk = _resolve(jnp.swapaxes(qt, 1, 2), jnp.swapaxes(kt, 1, 2),
                             scale, block_q, block_k)
    rel = _rel_static(rel_table, rel_bidirectional, rel_max_distance)
    do = jnp.swapaxes(g, 1, 2)
    dq, dk, dv, dbias, drel = _flash_bwd(
        qt, kt, vt, out, lse[..., None], do, causal, scale, bq, bk,
        interpret, bias=bias, rel_table=rel_table, rel=rel)
    return (jnp.swapaxes(dq, 1, 2), jnp.swapaxes(dk, 1, 2),
            jnp.swapaxes(dv, 1, 2), dbias, drel)


flash_attention.defvjp(_vjp_fwd, _vjp_bwd)


def supported(q_shape, k_shape=None) -> bool:
    """Shapes the Pallas kernels handle: each sequence a multiple of
    128, head_dim ≤ 256 (one VMEM tile of lanes per block row). q and
    kv lengths may differ (cross-attention)."""
    _, sq, _, d = q_shape
    sk = sq if k_shape is None else k_shape[1]
    return sq % 128 == 0 and sk % 128 == 0 and d <= 256


_warned_fallback = set()


def attention(q, k, v, causal=False, scale=None, impl="auto", bias=None,
              rel_table=None, rel_bidirectional=True,
              rel_max_distance=128):
    """Dispatcher: Pallas flash kernels on TPU, blockwise JAX elsewhere.

    impl: "auto" | "flash" | "hybrid" | "naive". "hybrid" = XLA-fused
    forward + flash backward kernels: wins on FORWARD-dominated work
    (inference/eval: BERT-large seq-512 fwd measured 261→239 ms) but
    loses on the rematted train step (69.0 vs 73.7 samples/s — the
    recompute re-materializes the [s,s] scores inside the backward),
    so "auto" stays pure flash and hybrid is opt-in.

    ``rel_table`` [heads, num_buckets]: T5 relative-position bias,
    computed in-kernel on the flash path (no materialized [h, sq, sk]
    bias); materialized only on the naive/hybrid fallbacks. ``bias``
    [heads, sq, sk]: arbitrary materialized bias. Mutually exclusive.
    """
    if impl not in ("auto", "flash", "hybrid", "naive"):
        raise ValueError(
            f"attn impl must be auto|flash|hybrid|naive, got {impl!r}")
    from ..parallel.ring import local_attention

    def _naive():
        b = bias
        if rel_table is not None:
            from .relpos import relative_bias
            b = relative_bias(rel_table.T, q.shape[1], k.shape[1],
                              rel_bidirectional, rel_table.shape[1],
                              rel_max_distance)
        return local_attention(q, k, v, causal=causal, scale=scale,
                               bias=b)

    if impl == "naive":
        return _naive()
    on_tpu = jax.default_backend() == "tpu"
    if impl == "hybrid":
        return flash_attention(q, k, v, causal=causal, scale=scale,
                               fwd_xla=True, bias=bias,
                               rel_table=rel_table,
                               rel_bidirectional=rel_bidirectional,
                               rel_max_distance=rel_max_distance)
    if impl == "flash" or (on_tpu and supported(q.shape, k.shape)):
        return flash_attention(q, k, v, causal=causal, scale=scale,
                               bias=bias, rel_table=rel_table,
                               rel_bidirectional=rel_bidirectional,
                               rel_max_distance=rel_max_distance)
    if on_tpu and tuple(q.shape) not in _warned_fallback:
        # a silent fall-through here once cost 28x at seq 8k (an s-1 shift
        # broke seq % 128) — make the downgrade loud, once per shape
        _warned_fallback.add(tuple(q.shape))
        from ..common.logging import get_logger
        get_logger().warning(
            "attention %s falls back to naive O(s^2) on TPU (flash needs "
            "seq %% 128 == 0 and head_dim <= 256)", tuple(q.shape))
    return _naive()

"""Flash attention as Pallas TPU kernels (forward + backward).

The reference delegates all model math to torch/tf/mxnet (SURVEY §5
"Long-context: entirely absent"); here attention is the FLOPs/HBM hot
spot of the flagship BERT/GPT benchmarks, so it gets a hand-written
kernel pair:

  - forward: blockwise online-softmax attention — the [s, s] score
    matrix never leaves VMEM; O(s·block) HBM traffic instead of O(s²)
  - backward: two kernels (dq; dk+dv) recomputing probabilities from the
    saved log-sum-exp, the standard flash-attention-2 scheme
  - fp32 accumulation on the MXU (`preferred_element_type`), bf16 inputs
  - causal masking by block skipping + an iota mask on diagonal blocks

Layout contract matches the rest of the stack: [batch, seq, heads,
head_dim] in, same out. Kernels run per (batch, head) over a grid of
sequence blocks; the kv-block loop is the innermost grid dimension so
the accumulator scratch lives in VMEM across it.

`attention()` is the dispatcher the models call: Pallas on TPU when
shapes allow, pure-JAX blockwise otherwise (CPU tests, odd shapes).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_NEG_INF = -1e30


def _pick_block(s: int, want: int) -> int:
    for b in (want, 512, 256, 128):
        if b <= want and s % b == 0:
            return b
    return s


# --------------------------------------------------------------- forward

def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref,
                acc, m_scr, l_scr, *, scale, causal, bq, bk, nk):
    kb = pl.program_id(3)
    qb = pl.program_id(2)

    @pl.when(kb == 0)
    def _init():
        acc[...] = jnp.zeros_like(acc)
        m_scr[...] = jnp.full_like(m_scr, _NEG_INF)
        l_scr[...] = jnp.zeros_like(l_scr)

    # causal: skip kv blocks strictly above the diagonal
    run = True if not causal else (kb * bk <= qb * bq + bq - 1)

    @pl.when(run)
    def _block():
        q = q_ref[0, 0]                      # [bq, d]
        k = k_ref[0, 0]                      # [bk, d]
        v = v_ref[0, 0]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale   # [bq, bk]
        if causal:
            rows = qb * bq + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 0)
            cols = kb * bk + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
            s = jnp.where(rows >= cols, s, _NEG_INF)
        m_prev = m_scr[:, :1]                               # [bq, 1]
        m_new = jnp.maximum(m_prev, jnp.max(s, -1, keepdims=True))
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m_prev - m_new)
        l_new = l_scr[:, :1] * alpha + jnp.sum(p, -1, keepdims=True)
        pv = jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)             # [bq, d]
        acc[...] = acc[...] * alpha + pv
        m_scr[...] = jnp.broadcast_to(m_new, m_scr.shape)
        l_scr[...] = jnp.broadcast_to(l_new, l_scr.shape)

    @pl.when(kb == nk - 1)
    def _finish():
        l = jnp.maximum(l_scr[:, :1], 1e-30)
        o_ref[0, 0] = (acc[...] / l).astype(o_ref.dtype)
        lse_ref[0, 0] = m_scr[:, :1] + jnp.log(l)


def _flash_fwd(q, k, v, causal, scale, bq, bk, interpret, out_dtype=None):
    """q,k,v: [b, h, s, d] → (out [b,h,s,d], lse [b,h,s,1] fp32).

    out_dtype overrides the output dtype (default q.dtype) — ring
    attention requests fp32 partials so the per-step LSE combine does
    not accumulate one bf16 rounding per ring step."""
    b, h, s, d = q.shape
    nq, nk = s // bq, s // bk
    grid = (b, h, nq, nk)
    kernel = functools.partial(_fwd_kernel, scale=scale, causal=causal,
                               bq=bq, bk=bk, nk=nk)
    out, lse = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, 1, bq, d), lambda ib, ih, iq, ik: (ib, ih, iq, 0)),
            pl.BlockSpec((1, 1, bk, d), lambda ib, ih, iq, ik: (ib, ih, ik, 0)),
            pl.BlockSpec((1, 1, bk, d), lambda ib, ih, iq, ik: (ib, ih, ik, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, 1, bq, d), lambda ib, ih, iq, ik: (ib, ih, iq, 0)),
            pl.BlockSpec((1, 1, bq, 1), lambda ib, ih, iq, ik: (ib, ih, iq, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b, h, s, d), out_dtype or q.dtype),
            jax.ShapeDtypeStruct((b, h, s, 1), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((bq, d), jnp.float32),
            pltpu.VMEM((bq, 128), jnp.float32),
            pltpu.VMEM((bq, 128), jnp.float32),
        ],
        interpret=interpret,
    )(q, k, v)
    return out, lse


# -------------------------------------------------------------- backward

def _dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref,
               dq_acc, *, scale, causal, bq, bk, nk):
    kb = pl.program_id(3)
    qb = pl.program_id(2)

    @pl.when(kb == 0)
    def _init():
        dq_acc[...] = jnp.zeros_like(dq_acc)

    run = True if not causal else (kb * bk <= qb * bq + bq - 1)

    @pl.when(run)
    def _block():
        q = q_ref[0, 0]
        k = k_ref[0, 0]
        v = v_ref[0, 0]
        do = do_ref[0, 0]
        lse = lse_ref[0, 0]                                 # [bq, 1]
        delta = delta_ref[0, 0]                             # [bq, 1]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale
        p = jnp.exp(s - lse)                                # [bq, bk]
        if causal:
            rows = qb * bq + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 0)
            cols = kb * bk + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
            p = jnp.where(rows >= cols, p, 0.0)
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)             # [bq, bk]
        ds = (p * (dp - delta)).astype(k.dtype)
        dq_acc[...] += jax.lax.dot_general(
            ds, k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32) * scale

    @pl.when(kb == nk - 1)
    def _finish():
        dq_ref[0, 0] = dq_acc[...].astype(dq_ref.dtype)


def _dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                dk_ref, dv_ref, dk_acc, dv_acc,
                *, scale, causal, bq, bk, nq):
    qb = pl.program_id(3)
    kb = pl.program_id(2)

    @pl.when(qb == 0)
    def _init():
        dk_acc[...] = jnp.zeros_like(dk_acc)
        dv_acc[...] = jnp.zeros_like(dv_acc)

    run = True if not causal else (kb * bk <= qb * bq + bq - 1)

    @pl.when(run)
    def _block():
        q = q_ref[0, 0]                                     # [bq, d]
        k = k_ref[0, 0]                                     # [bk, d]
        v = v_ref[0, 0]
        do = do_ref[0, 0]                                   # [bq, d]
        lse = lse_ref[0, 0]                                 # [bq, 1]
        delta = delta_ref[0, 0]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale     # [bq, bk]
        p = jnp.exp(s - lse)
        if causal:
            rows = qb * bq + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 0)
            cols = kb * bk + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
            p = jnp.where(rows >= cols, p, 0.0)
        pt = p.astype(do.dtype)
        dv_acc[...] += jax.lax.dot_general(
            pt, do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)             # [bk, d]
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)             # [bq, bk]
        ds = (p * (dp - delta)).astype(q.dtype)
        dk_acc[...] += jax.lax.dot_general(
            ds, q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32) * scale     # [bk, d]

    @pl.when(qb == nq - 1)
    def _finish():
        dk_ref[0, 0] = dk_acc[...].astype(dk_ref.dtype)
        dv_ref[0, 0] = dv_acc[...].astype(dv_ref.dtype)


def _flash_bwd(q, k, v, out, lse, do, causal, scale, bq, bk, interpret,
               delta=None):
    b, h, s, d = q.shape
    nq, nk = s // bq, s // bk
    if delta is None:      # ring callers hoist this loop-invariant reduction
        delta = jnp.sum(do.astype(jnp.float32) * out.astype(jnp.float32),
                        axis=-1, keepdims=True)             # [b,h,s,1]

    qspec = pl.BlockSpec((1, 1, bq, d), lambda ib, ih, iq, ik: (ib, ih, iq, 0))
    kspec = pl.BlockSpec((1, 1, bk, d), lambda ib, ih, iq, ik: (ib, ih, ik, 0))
    r1spec = pl.BlockSpec((1, 1, bq, 1), lambda ib, ih, iq, ik: (ib, ih, iq, 0))

    dq = pl.pallas_call(
        functools.partial(_dq_kernel, scale=scale, causal=causal,
                          bq=bq, bk=bk, nk=nk),
        grid=(b, h, nq, nk),
        in_specs=[qspec, kspec, kspec, qspec, r1spec, r1spec],
        out_specs=qspec,
        out_shape=jax.ShapeDtypeStruct((b, h, s, d), q.dtype),
        scratch_shapes=[pltpu.VMEM((bq, d), jnp.float32)],
        interpret=interpret,
    )(q, k, v, do, lse, delta)

    # dk/dv: kv block is the outer (carried) grid dim, q block inner
    qspec2 = pl.BlockSpec((1, 1, bq, d), lambda ib, ih, ik, iq: (ib, ih, iq, 0))
    kspec2 = pl.BlockSpec((1, 1, bk, d), lambda ib, ih, ik, iq: (ib, ih, ik, 0))
    r1spec2 = pl.BlockSpec((1, 1, bq, 1), lambda ib, ih, ik, iq: (ib, ih, iq, 0))
    dk, dv = pl.pallas_call(
        functools.partial(_dkv_kernel, scale=scale, causal=causal,
                          bq=bq, bk=bk, nq=nq),
        grid=(b, h, nk, nq),
        in_specs=[qspec2, kspec2, kspec2, qspec2, r1spec2, r1spec2],
        out_specs=[kspec2, kspec2],
        out_shape=[jax.ShapeDtypeStruct((b, h, s, d), k.dtype),
                   jax.ShapeDtypeStruct((b, h, s, d), v.dtype)],
        scratch_shapes=[pltpu.VMEM((bk, d), jnp.float32),
                        pltpu.VMEM((bk, d), jnp.float32)],
        interpret=interpret,
    )(q, k, v, do, lse, delta)
    return dq, dk, dv


# ------------------------------------------------------------ public API

@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def flash_attention(q, k, v, causal=False, scale=None,
                    block_q=512, block_k=512, interpret=False):
    """Pallas flash attention. q,k,v: [b, s, heads, d] → [b, s, heads, d].

    seq must be divisible by the (auto-shrunk) block sizes. Differentiable
    via the flash backward kernels. 512 blocks measured ~29% faster than
    256 on BERT-large seq-512 (fewer grid steps, full-width MXU tiles);
    VMEM stays comfortable through d=256 (p-block 1MB + acc 512KB).
    """
    out, _ = _fwd_rule(q, k, v, causal, scale, block_q, block_k, interpret)
    return out


def _resolve(q, scale, block_q, block_k):
    b, s, h, d = q.shape
    if scale is None:
        scale = d ** -0.5
    bq = _pick_block(s, min(block_q, s))
    bk = _pick_block(s, min(block_k, s))
    return scale, bq, bk


def _fwd_rule(q, k, v, causal, scale, block_q, block_k, interpret):
    scale, bq, bk = _resolve(q, scale, block_q, block_k)
    qt = jnp.swapaxes(q, 1, 2)       # [b, h, s, d]
    kt = jnp.swapaxes(k, 1, 2)
    vt = jnp.swapaxes(v, 1, 2)
    out, lse = _flash_fwd(qt, kt, vt, causal, scale, bq, bk, interpret)
    # store lse as [b,h,s]: a trailing dim of 1 lane-pads to 128 on TPU,
    # bloating the saved residual 128x when it survives to the backward
    from jax.ad_checkpoint import checkpoint_name
    # named so a remat policy can pin the flash residuals while everything
    # around them recomputes (remat_policy="save_attn"); name the SQUEEZED
    # lse — pinning the [b,h,s,1] form would lane-pad 128x (comment above)
    out = checkpoint_name(out, "flash_out")
    lse = checkpoint_name(lse[..., 0], "flash_lse")
    return jnp.swapaxes(out, 1, 2), (qt, kt, vt, out, lse)


def _vjp_fwd(q, k, v, causal, scale, block_q, block_k, interpret):
    out, res = _fwd_rule(q, k, v, causal, scale, block_q, block_k, interpret)
    return out, res


def _vjp_bwd(causal, scale, block_q, block_k, interpret, res, g):
    qt, kt, vt, out, lse = res
    scale, bq, bk = _resolve(jnp.swapaxes(qt, 1, 2), scale, block_q, block_k)
    do = jnp.swapaxes(g, 1, 2)
    dq, dk, dv = _flash_bwd(qt, kt, vt, out, lse[..., None], do,
                            causal, scale, bq, bk, interpret)
    return (jnp.swapaxes(dq, 1, 2), jnp.swapaxes(dk, 1, 2),
            jnp.swapaxes(dv, 1, 2))


flash_attention.defvjp(_vjp_fwd, _vjp_bwd)


def supported(q_shape) -> bool:
    """Shapes the Pallas kernels handle: seq a multiple of 128, head_dim
    ≤ 256 (one VMEM tile of lanes per block row)."""
    _, s, _, d = q_shape
    return s % 128 == 0 and d <= 256


_warned_fallback = set()


def attention(q, k, v, causal=False, scale=None, impl="auto"):
    """Dispatcher: Pallas flash kernels on TPU, blockwise JAX elsewhere.

    impl: "auto" | "flash" | "naive".
    """
    if impl not in ("auto", "flash", "naive"):
        raise ValueError(f"attn impl must be auto|flash|naive, got {impl!r}")
    from ..parallel.ring import local_attention
    if impl == "naive":
        return local_attention(q, k, v, causal=causal, scale=scale)
    on_tpu = jax.default_backend() == "tpu"
    if impl == "flash" or (on_tpu and supported(q.shape)):
        return flash_attention(q, k, v, causal=causal, scale=scale)
    if on_tpu and tuple(q.shape) not in _warned_fallback:
        # a silent fall-through here once cost 28x at seq 8k (an s-1 shift
        # broke seq % 128) — make the downgrade loud, once per shape
        _warned_fallback.add(tuple(q.shape))
        from ..common.logging import get_logger
        get_logger().warning(
            "attention %s falls back to naive O(s^2) on TPU (flash needs "
            "seq %% 128 == 0 and head_dim <= 256)", tuple(q.shape))
    return local_attention(q, k, v, causal=causal, scale=scale)

"""Host input pipeline: sharded device placement with double-buffered
prefetch.

The reference delegates data loading to the frameworks and ships only a
synthetic generator for tests (reference: tests/utils.py fake_data,
example/pytorch/benchmark_byteps.py synthetic inputs). Here the input
path is part of the framework because on TPU it is a real bottleneck
class: the host must overlap (a) producing the next batch and (b) the
host→device transfer with the current step's compute.

``prefetch_to_mesh`` is the workhorse: a background thread device_puts
batches with the data-axis sharding while the caller trains on the
previous one — the JAX-native equivalent of a framework DataLoader's
pinned-memory prefetch queue.
"""

from __future__ import annotations

import queue
import threading
from typing import Callable, Iterable, Iterator, Optional

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .parallel.mesh import data_axes


def data_sharding(mesh: Mesh, spec: Optional[P] = None) -> NamedSharding:
    """The batch placement: split over the mesh's data axes by default."""
    if spec is None:
        axes = data_axes(mesh)
        spec = P(axes) if axes else P()
    return NamedSharding(mesh, spec)


def shard_batch(batch, mesh: Mesh, spec: Optional[P] = None,
                sharding: Optional[NamedSharding] = None):
    """Place one host batch onto the mesh, split over the data axes.

    Hot loops should build the sharding once with ``data_sharding`` and
    pass it, avoiding per-batch construction.
    """
    if sharding is None:
        sharding = data_sharding(mesh, spec)
    return jax.tree_util.tree_map(lambda x: jax.device_put(x, sharding),
                                  batch)


def shard_local_batch(batch, mesh: Mesh, spec: Optional[P] = None,
                      sharding: Optional[NamedSharding] = None):
    """Assemble a GLOBAL array from this process's LOCAL batch shard.

    Multi-host input pipelines: each process loads only its slice of the
    global batch (global = local × process_count along the batch dim)
    and JAX stitches the distributed array — no host ships data it
    doesn't own. Single-process: identical to ``shard_batch``."""
    if sharding is None:
        sharding = data_sharding(mesh, spec)
    if jax.process_count() == 1:
        return jax.tree_util.tree_map(
            lambda x: jax.device_put(x, sharding), batch)
    return jax.tree_util.tree_map(
        lambda x: jax.make_array_from_process_local_data(sharding, x),
        batch)


def prefetch_to_mesh(it: Iterable, mesh: Mesh, spec: Optional[P] = None,
                     buffer_size: int = 2, local: bool = False) -> Iterator:
    """Iterate ``it``, yielding mesh-sharded batches, transferring up to
    ``buffer_size`` batches ahead on a background thread.

    device_put is async, but issuing it from a separate thread also
    overlaps the host-side work (pytree traversal, layout, page pinning)
    with the training loop's Python time.

    ``local=True``: each process's iterator yields only ITS slice of
    the global batch (``shard_local_batch`` assembly) — the multi-host
    input contract; identical to the default in a single process.
    """
    q: "queue.Queue" = queue.Queue(maxsize=buffer_size)
    stop = threading.Event()
    _END = object()
    sharding = data_sharding(mesh, spec)
    place = shard_local_batch if local else shard_batch

    def producer():
        try:
            for batch in it:
                if stop.is_set():
                    return
                q.put(place(batch, mesh, sharding=sharding))
            q.put(_END)
        except BaseException as e:          # propagate into the consumer
            q.put(e)

    t = threading.Thread(target=producer, daemon=True,
                         name="bps-prefetch")
    t.start()
    try:
        while True:
            item = q.get()
            if item is _END:
                return
            if isinstance(item, BaseException):
                raise item
            yield item
    finally:
        stop.set()
        # drain so the producer's blocked put() can observe stop
        try:
            while True:
                q.get_nowait()
        except queue.Empty:
            pass


# ------------------------------------------------------ synthetic sources

def synthetic_batches(make_batch: Callable[[np.random.RandomState], object],
                      seed: int = 0, steps: Optional[int] = None) -> Iterator:
    """Endless (or ``steps``-long) stream from a batch factory — the
    fake_data equivalent for benchmarks/tests."""
    rng = np.random.RandomState(seed)
    i = 0
    while steps is None or i < steps:
        yield make_batch(rng)
        i += 1


def mlm_stream(batch: int, seq: int, vocab: int, seed: int = 0,
               steps: Optional[int] = None) -> Iterator:
    """Synthetic MLM batches (tokens, targets) for BERT-style pretraining."""
    from .models.bert import synth_mlm_batch
    return synthetic_batches(
        lambda rng: synth_mlm_batch(rng, batch, seq, vocab),
        seed=seed, steps=steps)


def imagenet_stream(batch: int, seed: int = 0,
                    steps: Optional[int] = None) -> Iterator:
    """Synthetic 224×224 image batches (images, labels) for ResNet/VGG."""
    from .models.resnet import synth_imagenet_batch
    return synthetic_batches(
        lambda rng: synth_imagenet_batch(rng, batch),
        seed=seed, steps=steps)


# ---------------------------------------------------- file-backed sources

def write_npz_shards(path, arrays_fn: Callable[[int], dict],
                     n_shards: int) -> list:
    """Write ``n_shards`` dataset shard files (``shard-00042.npz``) to
    ``path``; ``arrays_fn(i)`` returns shard i's named arrays. Returns
    the file list. The reference's recipes read RecordIO/ImageRecord
    shard files (example/mxnet/train_gluon_imagenet_byteps_gc.py) —
    npz is the dependency-free stand-in with the same access pattern:
    many sequential-read shard files, sample-addressable after load."""
    import os
    os.makedirs(path, exist_ok=True)
    files = []
    for i in range(n_shards):
        f = os.path.join(path, f"shard-{i:05d}.npz")
        np.savez(f, **arrays_fn(i))
        files.append(f)
    return files


def _npz_sample_count(path) -> int:
    """Leading-axis length of the arrays in an .npz, read from the npy
    headers only — no array data is decompressed.

    EVERY member's header is checked and their leading axes must agree:
    zip member order is whatever the writer produced (externally built
    shards reorder freely), so "first member in zip order" was not a
    stable notion of the shard's sample count — two workers reading
    differently-ordered but equal shards could disagree, and a shard
    whose arrays disagree internally (truncated write) must fail here,
    loudly, not desynchronize a collective mid-epoch."""
    import zipfile
    with zipfile.ZipFile(path) as zf:
        names = sorted(n for n in zf.namelist() if n.endswith(".npy"))
        if not names:
            raise ValueError(f"{path} holds no arrays — not a dataset shard")
        counts = {}
        for name in names:
            with zf.open(name) as f:
                version = np.lib.format.read_magic(f)
                reader = (np.lib.format.read_array_header_1_0
                          if version[0] == 1
                          else np.lib.format.read_array_header_2_0)
                shape, _, _ = reader(f)
            counts[name[:-4]] = shape[0] if shape else 0
    if len(set(counts.values())) > 1:
        raise ValueError(
            f"{path}: arrays disagree on the leading (sample) axis: "
            f"{counts} — not a consistent dataset shard")
    return next(iter(counts.values()))


class NpzShardDataset:
    """File-backed training dataset over a directory of .npz shards.

    The distributed contract (reference: every per-framework recipe
    shards its record files by rank —
    train_gluon_imagenet_byteps_gc.py's split DataLoader): worker
    ``rank`` of ``world`` reads only shard files ``rank::world``
    (disjoint and complete), shuffles WITHIN its shards per epoch with
    a seed derived from (seed, epoch) — the same permutation on every
    restart, different every epoch — and yields ``batch``-sized dicts
    of arrays. Ragged tails are dropped (distributed steps need
    identical batch shapes on every worker).

    Every rank must take the SAME number of steps per epoch or the
    stragglers' collectives hang the job, so the shard count must
    divide evenly by ``world`` AND every shard must hold the same
    number of samples. Both are enforced at construction when
    ``world > 1`` — sample counts are read from the npz headers
    (cheap; no array data is loaded) so externally produced unequal
    shards fail loudly here instead of hanging a collective
    mid-epoch. Single-process runs skip the size check: with one
    rank there is no collective to hang and a short tail shard is
    harmless.

    Feed the iterator to ``prefetch_to_mesh`` for the device side."""

    def __init__(self, path, rank: int = 0, world: int = 1,
                 seed: int = 0) -> None:
        import glob
        import os
        self.files = sorted(glob.glob(os.path.join(path, "shard-*.npz")))
        if not self.files:
            raise FileNotFoundError(f"no shard-*.npz files under {path}")
        if len(self.files) % max(world, 1) != 0:
            raise ValueError(
                f"{len(self.files)} shard files don't divide over "
                f"{world} workers — unequal per-rank step counts would "
                f"hang the stragglers' collectives; re-shard the "
                f"dataset to a multiple of the worker count")
        counts = ([_npz_sample_count(f) for f in self.files]
                  if world > 1 else [])
        if len(set(counts)) > 1:
            detail = ", ".join(
                f"{os.path.basename(f)}={c}"
                for f, c in zip(self.files, counts))
            raise ValueError(
                f"shard sample counts differ ({detail}) — ranks would "
                f"take different per-epoch step counts and hang the "
                f"stragglers' collectives; re-shard to equal sizes")
        self.rank, self.world, self.seed = rank, world, seed
        self.my_files = self.files[rank::world]

    def epoch(self, epoch: int, batch: int) -> Iterator:
        """One epoch of ``batch``-sized dicts from this rank's shards."""
        rng = np.random.RandomState((self.seed * 1000003 + epoch)
                                    & 0x7FFFFFFF)
        order = rng.permutation(len(self.my_files))
        yielded = 0
        for fi in order:
            with np.load(self.my_files[fi]) as z:
                arrays = {k: z[k] for k in z.files}
            n = len(next(iter(arrays.values())))
            perm = rng.permutation(n)
            for s in range(0, n - batch + 1, batch):
                idx = perm[s:s + batch]
                yield {k: v[idx] for k, v in arrays.items()}
                yielded += 1
        if yielded == 0:
            # without this a too-large batch silently trains for zero
            # steps and reports untrained "results"
            raise ValueError(
                f"batch={batch} exceeds every shard's sample count — "
                f"no batches produced (batches never span shard files)")

    def batches(self, batch: int, epochs: Optional[int] = None) -> Iterator:
        """Epoch-concatenated stream (``epochs=None`` → endless)."""
        e = 0
        while epochs is None or e < epochs:
            yield from self.epoch(e, batch)
            e += 1

"""Fleet orchestration: one-command multi-process training fleets.

The reference ships this as its L5 launcher (``bpslaunch`` spawning
per-device workers + ``dist_launcher.py`` SSHing servers/schedulers
across hosts, PAPER.md); until now this repo's launcher exec'd exactly
ONE process and every multi-process proof hand-rolled its own
``subprocess.Popen`` choreography. This module is the missing layer:

  - **Role manifest** (``FleetManifest``): a declarative description of
    the job — P pipeline stages x dp data-parallel replicas x S server
    plane shards (+ chain replicas), microbatches/virtual chunks, and
    the training spec — from which the FULL per-process ``BPS_*`` env
    contract is derived (docs/launcher.md has the role/env table):
    worker ranks, stage ranks, activation-mailbox ring addresses
    (``BPS_PP_ACT_ADDRS``, one mailbox per stage per replica), server
    shard addresses, plane replication, and the round-gate
    ``BPS_NUM_WORKER``.
  - **Supervisor** (``FleetSupervisor``): spawns every role as a real
    OS process over real sockets, captures per-role stdout/stderr to a
    log directory, watches liveness (process exit + the PR-12 fleet
    telemetry plane over the servers' never-credit-gated OP_STATS
    channel), restarts dead roles with backoff up to a restart budget
    — a restarted WORKER rejoins through the PR-13 elasticity path
    (``PSGradientExchange`` per-key round counters seed from the
    server, so it resumes the job's round, not round 1), a dead SERVER
    shard is absorbed by the plane's chain failover while the
    supervisor respawns it — and drains the fleet cleanly (workers
    exit 0 on completion, then servers get SIGTERM).
  - **One command**: ``python -m byteps_tpu.launcher.fleet --stages 4
    --dp 2 --shards 2 --steps 5`` (or ``bpslaunch-tpu --fleet ...``)
    stands the whole thing up locally; ``bench.py fleet`` drives the
    same manifest for the headline number.
  - **Command fan-out** (``run_command_fleet``): the generic N-process
    form — derive the coordinator/rank env for an arbitrary command
    and supervise it to completion — which tests/test_multiprocess.py
    and examples/scaling_bench.py ride instead of bespoke Popen loops.

Every role is an ordinary subprocess of THIS machine (the local-fleet
form the acceptance bench runs); the same manifest prints per-role
env/argv so an operator can lift it onto k8s/SSH (docs/launcher.md,
docker/k8s-psjob.yaml).
"""

from __future__ import annotations

import argparse
import json
import os
import signal
import socket
import subprocess
import sys
import tempfile
import time
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from ..common.logging import get_logger

log = get_logger()


def free_port(host: str = "127.0.0.1") -> int:
    with socket.socket() as s:
        s.bind((host, 0))
        return s.getsockname()[1]


class PortLease:
    """A held-open port reservation: bind an ephemeral port with
    SO_REUSEPORT and KEEP the socket open for the lease's lifetime.
    ``free_port``'s bind-close leaves a window where the kernel can
    hand the same ephemeral port to anyone — including a lingering
    reconnect dialer from an earlier test in the same process, whose
    foreign frame then SIGABRTs gloo's pair listener mid-init. The
    lease socket never listens (no backlog, no accepts), so it eats no
    traffic; the real server (gRPC/gloo both set SO_REUSEPORT on
    Linux) binds alongside it, and the kernel won't recycle a port
    that still has a live bound socket."""

    def __init__(self, host: str = "127.0.0.1"):
        self._sock = socket.socket()
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEPORT, 1)
        self._sock.bind((host, 0))
        self.port: int = self._sock.getsockname()[1]

    def close(self) -> None:
        self._sock.close()


def wait_for_ports(addrs: Sequence[str], timeout_s: float = 30.0,
                   interval_s: float = 0.05) -> None:
    """Block until every ``host:port`` accepts a TCP connect — the
    worker-side readiness gate before dialing a peer mailbox or server
    shard (a connect-refused here is a supervisor ordering bug, not a
    dead peer; loud after the timeout)."""
    deadline = time.monotonic() + timeout_s
    for addr in addrs:
        host, port = addr.rsplit(":", 1)
        while True:
            try:
                with socket.create_connection((host, int(port)),
                                              timeout=1.0):
                    break
            except OSError:
                if time.monotonic() > deadline:
                    raise TimeoutError(
                        f"peer {addr} never came up within "
                        f"{timeout_s:.0f}s")
                time.sleep(interval_s)


# Topology/bootstrap keys the launcher itself derives. They are
# STRIPPED from the inherited environment before a role's contract is
# applied: the manifest owns the FULL topology, so a stale value from
# the invoking process (a prior job in the same shell, an earlier test
# in the same pytest process) must never leak into a role — a recycled
# port behind a stale BPS_SERVER_ADDRS can belong to ANYTHING by spawn
# time. Tuning knobs (compression, credits, stats, ...) still inherit.
_TOPOLOGY_KEYS = frozenset({
    "BPS_ROLE", "BPS_WORKER_ID", "BPS_NUM_WORKER", "BPS_LOCAL_RANK",
    "BPS_LOCAL_SIZE", "BPS_COORDINATOR_ADDRESS", "BPS_NUM_PROCESSES",
    "BPS_PROCESS_ID", "BPS_FORCE_DISTRIBUTED", "BPS_ENABLE_PS",
    "BPS_SERVER_ADDRS", "BPS_SERVER_PORT", "BPS_PLANE_REPLICAS",
    "BPS_PP_STAGES", "BPS_PP_RANK", "BPS_PP_MICROBATCH",
    "BPS_PP_VIRTUAL", "BPS_PP_ACT_ADDRS",
    "BPS_HIER_UPSTREAM_ADDRS", "BPS_HIER_HOST_ID",
})


# ------------------------------------------------------------ shm sweep

_SHM_DIR = "/dev/shm"
_SHM_PREFIX = "bps-shm-"


def _live_shm_names() -> set:
    """Names of bps shm segments currently mapped by any live process
    this uid can inspect via /proc/*/maps — which is exactly the set of
    processes that could hold a mapping of our 0600 segments."""
    live = set()
    try:
        pids = [p for p in os.listdir("/proc") if p.isdigit()]
    except OSError:
        return live
    for pid in pids:
        try:
            with open(f"/proc/{pid}/maps") as f:
                for line in f:
                    i = line.find(_SHM_DIR + "/" + _SHM_PREFIX)
                    if i < 0:
                        continue
                    path = line[i:].strip()
                    if path.endswith(" (deleted)"):
                        path = path[:-len(" (deleted)")]
                    live.add(os.path.basename(path))
        except OSError:
            continue      # raced an exit, or not ours to read
    return live


def sweep_stale_shm(grace_s: float = 5.0) -> List[str]:
    """Unlink ``/dev/shm/bps-shm-*`` segments stranded by SIGKILLed
    processes (the hazard transport.py documents on ``_PosixShm``: the
    worker normally unlinks its own segments, so only an unclean death
    leaves one behind). The supervisor runs this on every role restart
    and at drain, so a long-lived fleet's kill/heal churn cannot leak
    host shm. Returns the swept names.

    A segment is swept only when it is (a) owned by this uid, (b)
    older than ``grace_s`` — a just-created segment's open→mmap window
    must not race the sweep — and (c) mapped by NO live process: a
    running worker's own mapping, or a server's ``_ShmCache``
    attachment, protects it (unlinking under a live mapping would be
    harmless to the mapping itself but would break the server's next
    attach-by-name)."""
    swept: List[str] = []
    try:
        names = [n for n in os.listdir(_SHM_DIR)
                 if n.startswith(_SHM_PREFIX)]
    except OSError:
        return swept
    if not names:
        return swept
    live = _live_shm_names()
    now = time.time()
    uid = os.getuid()
    for name in names:
        path = os.path.join(_SHM_DIR, name)
        try:
            st = os.stat(path)
        except OSError:
            continue
        if (st.st_uid != uid or name in live
                or now - max(st.st_ctime, st.st_mtime) < grace_s):
            continue
        try:
            os.unlink(path)
            swept.append(name)
        except OSError:
            pass
    return swept


def _inherited_env() -> Dict[str, str]:
    return {k: v for k, v in os.environ.items()
            if k not in _TOPOLOGY_KEYS
            and not k.startswith("BPS_FLEET_")}


# =====================================================================
# Process specs + manifest
# =====================================================================

@dataclass
class ProcessSpec:
    """One supervised OS process: the full argv + env contract."""
    name: str                      # unique role instance, e.g. "w-s0r1"
    role: str                      # "server" | "worker"
    argv: List[str]
    env: Dict[str, str]
    restartable: bool = True       # supervisor may respawn on death
    expect_exit: bool = False      # exit 0 == job done (workers), vs
    #                                long-running until drained (servers)
    group: Optional[str] = None    # co-restart group: one member's
    #                                death restarts the whole group
    #                                (a dead pipeline stage wedges its
    #                                neighbors' blocking recvs)


@dataclass
class FleetManifest:
    """Declarative fleet shape -> derived ProcessSpecs.

    ``stages`` x ``dp`` worker grid (each stage worker hosts an
    activation mailbox; replicas of a stage share PS keys), ``shards``
    standalone reduction servers (wrapped in the managed plane with
    chain replication when ``plane_replicas`` > 0), and the training
    spec the built-in fleet worker (launcher/fleet_worker.py) reads
    from its ``BPS_FLEET_*`` env. ``build()`` allocates ports and
    freezes the per-process env contract.
    """
    stages: int = 1
    dp: int = 1
    virtual: int = 1               # BPS_PP_VIRTUAL model chunks/stage
    micro: int = 4                 # microbatches per step
    shards: int = 0                # 0 = auto: servers only when needed
    plane_replicas: int = 0
    # hierarchical aggregation (server/hier.py): replicas are grouped
    # into "hosts" of local_size; each host gets a local aggregator
    # role its workers push/pull against, and only the host SUM rides
    # the cross-host wire to the shards (whose round gate becomes
    # dp // local_size hosts). Gated by BPS_HIER_AGG on/off/auto —
    # local_size == 1 derives a manifest byte-identical to the flat one.
    local_size: int = 1
    steps: int = 4
    schedule: str = "1f1b"
    # training spec (the built-in mlp fleet worker)
    dim: int = 64
    depth: int = 8
    batch: int = 32
    seed: int = 0
    host: str = "127.0.0.1"
    scheduling_credit: int = 0
    extra_env: Dict[str, str] = field(default_factory=dict)
    # targeted overrides: {selector: {ENV: val}} where selector is a
    # role class ("worker"/"server") or one process name ("w-s0r1") —
    # name beats class. How a bench arm makes exactly ONE replica a
    # straggler (bench.py ps_lag) without touching its peers.
    role_env: Dict[str, Dict[str, str]] = field(default_factory=dict)
    # filled by build()
    server_addrs: List[str] = field(default_factory=list)
    act_addrs: List[List[str]] = field(default_factory=list)
    agg_addrs: List[str] = field(default_factory=list)

    def needs_servers(self) -> bool:
        return self.dp > 1 or self.shards > 0

    def validate(self) -> None:
        from ..pipeline.topology import validate_topology
        validate_topology(self.stages, self.virtual, self.micro)
        # the worker slices batch // dp rows per replica and splits
        # THOSE into micro microbatches — validate what it will
        # actually do, or a bad shape burns the restart budget on a
        # deterministic step-1 crash (or silently drops rows)
        if self.batch % self.dp:
            raise ValueError(f"batch {self.batch} not divisible by "
                             f"dp {self.dp} (rows would be dropped)")
        if (self.batch // self.dp) % self.micro:
            raise ValueError(
                f"per-replica batch {self.batch // self.dp} not "
                f"divisible by micro {self.micro}")
        if self.plane_replicas > 0 and self.shards < 2:
            raise ValueError("plane replication needs shards >= 2")
        if self.local_size > 1:
            if self.dp % self.local_size:
                raise ValueError(
                    f"dp {self.dp} not divisible by local_size "
                    f"{self.local_size} (hosts must be uniform — the "
                    "shards' round gate counts hosts)")
            if not self.needs_servers():
                raise ValueError("local_size > 1 needs a server plane "
                                 "(there is no remote tier to shrink)")

    # ------------------------------------------------------------ build

    def build(self) -> List[ProcessSpec]:
        self.validate()
        specs: List[ProcessSpec] = []
        nshards = self.shards if self.shards > 0 else (
            1 if self.needs_servers() else 0)
        # decide the tier shape BEFORE any env contract is derived —
        # the SERVERS' round gate depends on it (hosts, not workers)
        self._use_hier = False
        if self.local_size > 1 and nshards > 0:
            from ..server.hier import hier_enabled
            self._use_hier = hier_enabled(self.local_size)
        self.server_addrs = []
        for i in range(nshards):
            port = free_port(self.host)
            self.server_addrs.append(f"{self.host}:{port}")
            specs.append(ProcessSpec(
                name=f"srv{i}", role="server",
                argv=[sys.executable, "-m", "byteps_tpu.launcher.launch",
                      "--server"],
                env=self._server_env(port),
                restartable=True, expect_exit=False))
        # hierarchical tier: one local aggregator per host group of
        # local_size replicas — its workers' whole PS plane IS this
        # endpoint (one addr → every key client-shards to it), and it
        # alone speaks to the real shards
        self.agg_addrs = []
        if self._use_hier:
            for h in range(self.dp // self.local_size):
                port = free_port(self.host)
                self.agg_addrs.append(f"{self.host}:{port}")
                specs.append(ProcessSpec(
                    name=f"agg{h}", role="agg",
                    argv=[sys.executable, "-m",
                          "byteps_tpu.launcher.hier_agg"],
                    env=self._agg_env(h, port),
                    restartable=True, expect_exit=False))
        # one activation mailbox per (replica, stage); replica-private
        # rings — activations never cross replicas
        self.act_addrs = [[f"{self.host}:{free_port(self.host)}"
                           for _ in range(self.stages)]
                          for _ in range(self.dp)]
        for r in range(self.dp):
            for s in range(self.stages):
                specs.append(ProcessSpec(
                    name=f"w-s{s}r{r}", role="worker",
                    argv=[sys.executable, "-m",
                          "byteps_tpu.launcher.fleet_worker"],
                    env=self._worker_env(s, r),
                    restartable=True, expect_exit=True,
                    # a dead stage wedges its ring neighbors' blocking
                    # recvs: restart the whole replica's stage group
                    # together (docs/launcher.md failure matrix). Pure
                    # DP fleets (stages == 1) restart singly — the
                    # PR-13 per-key reseed path.
                    group=(f"r{r}" if self.stages > 1 else None)))
        for sp in specs:
            for sel in (sp.role, sp.name):    # name wins over class
                if sel in self.role_env:
                    sp.env.update(self.role_env[sel])
        return specs

    # ----------------------------------------------------- env contracts

    def _base_env(self) -> Dict[str, str]:
        env = _inherited_env()
        env.update({
            "JAX_PLATFORMS": env.get("JAX_PLATFORMS", "cpu"),
            "BPS_STATS": env.get("BPS_STATS", "1"),
        })
        env.update(self.extra_env)
        return env

    def _server_env(self, port: int) -> Dict[str, str]:
        env = self._base_env()
        env.update({
            "BPS_ROLE": "server",
            "BPS_SERVER_PORT": str(port),
            # round gate: each PS key is pushed by the dp replicas of
            # ONE stage (stage-suffixed declaration names keep stages
            # disjoint in the keyspace). Under the hierarchical tier
            # the shard sees one logical contribution per HOST seal —
            # a host sum already carries local_size worker gradients —
            # so the gate counts hosts (the see-through contract:
            # engine rounds, StaleStore counts, span arrivals all stay
            # exact at host granularity, docs/server-plane.md)
            "BPS_NUM_WORKER": str(
                self.dp // self.local_size
                if getattr(self, "_use_hier", False) else self.dp),
            "BPS_SERVER_ENGINE_THREAD":
                env.get("BPS_SERVER_ENGINE_THREAD", "2"),
        })
        return env

    def _worker_env(self, stage: int, replica: int) -> Dict[str, str]:
        env = self._base_env()
        env.update({
            "BPS_ROLE": "worker",
            "BPS_WORKER_ID": str(replica),
            "BPS_NUM_WORKER": str(self.dp),
            "BPS_PP_STAGES": str(self.stages),
            "BPS_PP_RANK": str(stage),
            "BPS_PP_MICROBATCH": str(self.micro),
            "BPS_PP_VIRTUAL": str(self.virtual),
            "BPS_PP_ACT_ADDRS": ",".join(self.act_addrs[replica]),
            "BPS_FLEET_STEPS": str(self.steps),
            "BPS_FLEET_DIM": str(self.dim),
            "BPS_FLEET_DEPTH": str(self.depth),
            "BPS_FLEET_BATCH": str(self.batch),
            "BPS_FLEET_SEED": str(self.seed),
            "BPS_FLEET_SCHEDULE": self.schedule,
        })
        if self.scheduling_credit:
            env["BPS_SCHEDULING_CREDIT"] = str(self.scheduling_credit)
        if self.server_addrs:
            env["BPS_ENABLE_PS"] = "1"
            env["BPS_SERVER_ADDRS"] = ",".join(self.server_addrs)
            if self.plane_replicas > 0:
                env["BPS_PLANE_REPLICAS"] = str(self.plane_replicas)
        if getattr(self, "_use_hier", False) and self.agg_addrs:
            # the worker's whole PS plane is its host's aggregator:
            # one addr, so every key client-shards to it; the agg's
            # upstream client re-shards with the same hash, preserving
            # flat-mode key placement across the real shards
            host = replica // self.local_size
            env["BPS_SERVER_ADDRS"] = self.agg_addrs[host]
            env["BPS_LOCAL_SIZE"] = str(self.local_size)
            env["BPS_LOCAL_RANK"] = str(replica % self.local_size)
        return env

    def _agg_env(self, host_id: int, port: int) -> Dict[str, str]:
        env = self._base_env()
        env.update({
            "BPS_ROLE": "agg",
            "BPS_SERVER_PORT": str(port),
            "BPS_LOCAL_SIZE": str(self.local_size),
            "BPS_HIER_HOST_ID": str(host_id),
            "BPS_HIER_UPSTREAM_ADDRS": ",".join(self.server_addrs),
        })
        return env


# =====================================================================
# Supervisor
# =====================================================================

class _Managed:
    __slots__ = ("spec", "proc", "log_path", "log_file", "restarts",
                 "state", "rc", "started_at")

    def __init__(self, spec: ProcessSpec, log_path: str) -> None:
        self.spec = spec
        self.proc: Optional[subprocess.Popen] = None
        self.log_path = log_path
        self.log_file = None
        self.restarts = 0
        self.state = "pending"    # pending|running|done|failed|draining
        self.rc: Optional[int] = None
        self.started_at = 0.0


class FleetSupervisor:
    """Spawn, watch, restart, drain.

    Liveness is process-level (``poll``) plus — when the manifest has
    server shards — the PR-12 telemetry plane: a ``FleetScraper`` over
    the shards' OP_STATS channel feeds ``status()`` with per-shard
    up/stale/restart gauges, so a silently-restarted or black-holed
    server is visible even while its process object still looks alive.
    Restart policy: an unexpected death (nonzero exit, or any exit of
    a long-running role) respawns the role — or its whole co-restart
    ``group`` (pipeline replicas: a dead stage wedges its neighbors'
    blocking recvs, so the group restarts together and every member
    re-derives "steps remaining" from the PS plane's round counters,
    the PR-13 rejoin path) — after ``backoff_s``, up to
    ``max_restarts`` per role; past the budget the fleet FAILS loudly.
    ``events`` records every transition for the tests/bench to assert
    (restart evidence, stall accounting).
    """

    def __init__(self, specs: Sequence[ProcessSpec],
                 logdir: Optional[str] = None,
                 max_restarts: int = 2, backoff_s: float = 0.5,
                 scrape_addrs: Optional[Sequence[str]] = None,
                 scrape_sec: float = 0.0,
                 on_event: Optional[Callable] = None) -> None:
        names = [s.name for s in specs]
        if len(set(names)) != len(names):
            raise ValueError(f"duplicate role names in manifest: {names}")
        self.logdir = logdir or tempfile.mkdtemp(prefix="bps-fleet-")
        os.makedirs(self.logdir, exist_ok=True)
        self._managed: Dict[str, _Managed] = {
            s.name: _Managed(s, os.path.join(self.logdir,
                                             f"{s.name}.log"))
            for s in specs}
        self.max_restarts = int(max_restarts)
        self.backoff_s = float(backoff_s)
        self._shm_grace_s = float(os.environ.get(
            "BPS_SHM_SWEEP_GRACE_S", "5"))
        self.events: List[dict] = []
        self._on_event = on_event
        self._scraper = None
        self._scrape_backend = None
        if scrape_addrs and scrape_sec > 0:
            # telemetry-plane liveness: stats-only client (OP_STATS is
            # served on a dedicated never-credit-gated channel, so this
            # cannot perturb the data plane), lazy-dialed so a shard
            # that is still booting reads as down, not a crash here
            from ..obs.fleet import FleetScraper
            from ..server.transport import RemotePSBackend
            self._scrape_backend = RemotePSBackend(
                list(scrape_addrs), lazy_dial=True)
            self._scraper = FleetScraper(self._scrape_backend,
                                         interval_sec=scrape_sec)
        # watchtower incidents surface in the supervisor's event log:
        # the scraper's detector bank (BPS_AUTOTUNE=observe) runs in
        # THIS process, so a confirmed regime shift / dead shard lands
        # next to the spawn/died/restart transitions it explains
        self._incident_cb = None
        if self._scraper is not None and self._scraper.watch is not None:
            from ..obs import watchtower as _watchtower

            def _on_incident(inc: dict) -> None:
                self._event(
                    "watchtower", "incident", id=inc.get("id"),
                    incident_kind=inc.get("kind"),
                    signal=inc.get("signal"),
                    verdict=inc.get("verdict"), blamed=inc.get("blamed"))

            self._incident_cb = _on_incident
            _watchtower.get_engine().add_callback(_on_incident)

    # ------------------------------------------------------------ events

    def _event(self, name: str, kind: str, **detail) -> None:
        ev = {"t": time.time(), "role": name, "event": kind, **detail}
        self.events.append(ev)
        log.info("fleet: %s %s %s", name, kind,
                 {k: v for k, v in detail.items()} or "")
        if self._on_event is not None:
            try:
                self._on_event(ev)
            except Exception:   # noqa: BLE001 — observer must not kill us
                pass

    # ------------------------------------------------------------- spawn

    def start(self) -> "FleetSupervisor":
        for m in self._managed.values():
            self._spawn(m)
        if self._scraper is not None:
            self._scraper.start()
        return self

    def _spawn(self, m: _Managed) -> None:
        # the spec's env (ports included) is FROZEN at build time and
        # reused across restarts on purpose: peers hold this role's
        # address (workers redial a respawned server; stage neighbors
        # redial a respawned mailbox), so a fresh port would strand
        # every survivor. The cost is a small allocate-to-bind window
        # where another process can steal the port (EADDRINUSE on
        # every respawn) — surfaced by the restart-budget failure with
        # the bind error in the role's log (docs/launcher.md).
        env = dict(m.spec.env)
        env["BPS_FLEET_INCARNATION"] = str(m.restarts)
        m.log_file = open(m.log_path, "ab", buffering=0)
        m.log_file.write(
            f"\n--- fleet spawn {m.spec.name} incarnation "
            f"{m.restarts} ---\n".encode())
        m.proc = subprocess.Popen(
            m.spec.argv, env=env, stdout=m.log_file,
            stderr=subprocess.STDOUT,
            start_new_session=True)   # own process group: a drain
        #                               signal never leaks to us
        m.state = "running"
        m.rc = None
        m.started_at = time.monotonic()
        self._event(m.spec.name, "spawned", pid=m.proc.pid,
                    incarnation=m.restarts)

    # ------------------------------------------------------- supervision

    def poll_once(self) -> None:
        """One watch pass: reap exits, apply the restart policy."""
        dead_groups: Dict[str, List[_Managed]] = {}
        for m in self._managed.values():
            if m.state != "running" or m.proc is None:
                continue
            rc = m.proc.poll()
            if rc is None:
                continue
            m.rc = rc
            self._close_log(m)
            if rc == 0 and m.spec.expect_exit:
                m.state = "done"
                self._event(m.spec.name, "done", rc=0)
                continue
            # unexpected death (nonzero, or a long-running role exited)
            self._event(m.spec.name, "died", rc=rc)
            if not m.spec.restartable:
                m.state = "failed"
                continue
            if m.spec.group is not None:
                dead_groups.setdefault(m.spec.group, []).append(m)
            else:
                self._restart(m)
        for group, members in dead_groups.items():
            self._restart_group(group, members)

    def _restart(self, m: _Managed) -> None:
        if m.restarts >= self.max_restarts:
            m.state = "failed"
            self._event(m.spec.name, "restart_budget_exhausted",
                        restarts=m.restarts)
            return
        m.restarts += 1
        self._event(m.spec.name, "restarting", attempt=m.restarts)
        self._sweep_shm(m.spec.name)
        time.sleep(self.backoff_s)
        self._spawn(m)

    def _sweep_shm(self, role: str) -> None:
        """Reclaim shm stranded by a SIGKILLed incarnation before its
        replacement spawns (and at drain) — liveness-checked, so any
        OTHER role's segments survive untouched."""
        swept = sweep_stale_shm(grace_s=self._shm_grace_s)
        if swept:
            self._event(role, "shm_swept", segments=swept)

    def _restart_group(self, group: str, dead: List[_Managed]) -> None:
        """Co-restart: terminate every still-running member (their
        blocking recvs are already wedged on the dead one), then
        respawn the whole group. Counts one restart against each
        member's budget."""
        members = [m for m in self._managed.values()
                   if m.spec.group == group]
        if any(m.restarts >= self.max_restarts for m in members):
            for m in members:
                m.state = "failed"
            self._event(dead[0].spec.name,
                        "group_restart_budget_exhausted", group=group)
            return
        self._event(dead[0].spec.name, "group_restart", group=group,
                    members=[m.spec.name for m in members])
        for m in members:
            if m.state == "running" and m.proc is not None \
                    and m.proc.poll() is None:
                self._terminate(m, kill_after=5.0)
            self._close_log(m)
        self._sweep_shm(dead[0].spec.name)
        time.sleep(self.backoff_s)
        for m in members:
            if m.state in ("running", "done"):
                m.restarts += 1
                self._spawn(m)

    def wait(self, timeout_s: float = 600.0,
             poll_interval: float = 0.1) -> bool:
        """Supervise until every ``expect_exit`` role is done (True) or
        one fails past its budget / the deadline passes (False)."""
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            self.poll_once()
            states = [m.state for m in self._managed.values()
                      if m.spec.expect_exit]
            if states and all(s == "done" for s in states):
                return True
            if any(m.state == "failed" for m in self._managed.values()):
                return False
            time.sleep(poll_interval)
        self._event("fleet", "timeout", timeout_s=timeout_s)
        return False

    # ----------------------------------------------------------- control

    def kill(self, name: str, sig: int = signal.SIGKILL) -> None:
        """Kill one role (the fault-injection hook the slow-lane kill
        test drives). The next poll sees the death and applies the
        restart policy — exactly what a real crash would do."""
        m = self._managed[name]
        if m.proc is not None and m.proc.poll() is None:
            try:
                os.killpg(m.proc.pid, sig)
            except ProcessLookupError:
                return      # lost the race with its own exit: the
                #             poll pass will classify the death
            self._event(name, "killed_by_operator", sig=int(sig))

    def drain(self, timeout_s: float = 30.0) -> Dict[str, Optional[int]]:
        """Clean shutdown: workers should already be done; every
        still-running role gets SIGTERM (the server loop's drain
        signal), then SIGKILL past the timeout. Returns {role: rc}."""
        for m in self._managed.values():
            if m.state == "running" and m.proc is not None \
                    and m.proc.poll() is None:
                m.state = "draining"
                self._terminate(m, kill_after=timeout_s)
                self._event(m.spec.name, "drained", rc=m.rc)
        if self._incident_cb is not None:
            from ..obs import watchtower as _watchtower
            _watchtower.get_engine().remove_callback(self._incident_cb)
            self._incident_cb = None
        if self._scraper is not None:
            self._scraper.stop()
            self._scraper = None
        if self._scrape_backend is not None:
            self._scrape_backend.close()
            self._scrape_backend = None
        for m in self._managed.values():
            self._close_log(m)
        self._sweep_shm("fleet")
        return {n: m.rc for n, m in self._managed.items()}

    def _terminate(self, m: _Managed, kill_after: float) -> None:
        try:
            os.killpg(m.proc.pid, signal.SIGTERM)
        except ProcessLookupError:
            pass
        try:
            m.rc = m.proc.wait(timeout=kill_after)
        except subprocess.TimeoutExpired:
            try:
                os.killpg(m.proc.pid, signal.SIGKILL)
            except ProcessLookupError:
                pass
            m.rc = m.proc.wait()

    def _close_log(self, m: _Managed) -> None:
        if m.log_file is not None:
            try:
                m.log_file.close()
            except OSError:
                pass
            m.log_file = None

    # ------------------------------------------------------------- views

    def tail(self, name: str, nbytes: int = 4000) -> str:
        try:
            with open(self._managed[name].log_path, "rb") as f:
                f.seek(0, os.SEEK_END)
                size = f.tell()
                f.seek(max(0, size - nbytes))
                return f.read().decode(errors="replace")
        except OSError:
            return ""

    def output_lines(self, name: str, prefix: str = "") -> List[str]:
        """Captured stdout lines of a role (optionally filtered) — the
        result-collection surface for benches/tests."""
        return [l for l in self.tail(name, 1 << 20).splitlines()
                if l.startswith(prefix)]

    def restarts(self, name: str) -> int:
        return self._managed[name].restarts

    def status(self) -> Dict[str, dict]:
        out = {}
        fleet_view = self._scraper.view() if self._scraper else {}
        for n, m in self._managed.items():
            out[n] = {
                "state": m.state,
                "pid": m.proc.pid if m.proc is not None else None,
                "rc": m.rc,
                "restarts": m.restarts,
                "log": m.log_path,
            }
        if fleet_view:
            out["_telemetry"] = fleet_view
        return out

    def roles(self, role: Optional[str] = None) -> List[str]:
        return [n for n, m in self._managed.items()
                if role is None or m.spec.role == role]


# =====================================================================
# Generic command fan-out (the test_multiprocess / scaling_bench path)
# =====================================================================

@dataclass
class ProcResult:
    name: str
    rc: Optional[int]
    output: str


def run_command_fleet(cmd: Sequence[str], num_processes: int,
                      env_extra: Optional[Dict[str, str]] = None,
                      local_devices: int = 1,
                      timeout_s: float = 600.0,
                      logdir: Optional[str] = None) -> List[ProcResult]:
    """Run ``cmd`` as ``num_processes`` coordinated JAX processes on
    this host and supervise to completion (no restarts: a rank death
    is the result under test, not something to heal — jax.distributed
    jobs cannot re-admit a rank mid-job anyway).

    Derives the whole rendezvous env contract per rank — coordinator
    address on a fresh port, ``BPS_NUM_PROCESSES`` / ``BPS_PROCESS_ID``,
    and the virtual CPU device count. CPU collectives are enabled
    in-process by ``bps.init()`` (gloo; see GlobalState — jax 0.4.37
    does not read the flag from the env, so the launcher cannot carry
    it). Returns per-rank (rc, captured output).
    """
    lease = PortLease()       # held open: port can't be recycled under us
    port = lease.port
    specs = []
    for pid in range(int(num_processes)):
        env = _inherited_env()
        env.update({
            "XLA_FLAGS":
                f"--xla_force_host_platform_device_count={local_devices}",
            "JAX_PLATFORMS": "cpu",
            "BPS_COORDINATOR_ADDRESS": f"127.0.0.1:{port}",
            "BPS_NUM_PROCESSES": str(num_processes),
            "BPS_PROCESS_ID": str(pid),
        })
        env.update(env_extra or {})
        specs.append(ProcessSpec(
            name=f"rank{pid}", role="worker", argv=list(cmd), env=env,
            restartable=False, expect_exit=True))
    sup = FleetSupervisor(specs, logdir=logdir, max_restarts=0).start()
    try:
        sup.wait(timeout_s=timeout_s)
    finally:
        sup.drain(timeout_s=10.0)
        lease.close()
    return [ProcResult(n, sup._managed[n].rc, sup.tail(n, 1 << 20))
            for n in sup.roles()]


# =====================================================================
# One-command local fleet
# =====================================================================

def run_fleet(manifest: FleetManifest, timeout_s: float = 600.0,
              logdir: Optional[str] = None,
              max_restarts: int = 2,
              kill_after: Optional[Tuple[str, float]] = None) -> dict:
    """Stand up the manifest's fleet, supervise to completion, drain,
    and fold every worker's FLEET_RESULT line into one summary.
    ``kill_after=(role, delay_s)`` arms the fault-injection hook: the
    named role is SIGKILLed ``delay_s`` after spawn and the restart
    path heals it (the slow-lane kill test's entry point).
    """
    specs = manifest.build()
    sup = FleetSupervisor(
        specs, logdir=logdir, max_restarts=max_restarts,
        scrape_addrs=manifest.server_addrs or None,
        scrape_sec=1.0 if manifest.server_addrs else 0.0)
    t0 = time.time()
    sup.start()
    killer = None
    if kill_after is not None:
        import threading
        role, delay = kill_after
        killer = threading.Timer(delay, lambda: sup.kill(role))
        killer.daemon = True
        killer.start()
    try:
        ok = sup.wait(timeout_s=timeout_s)
    finally:
        if killer is not None:
            killer.cancel()
        rcs = sup.drain()
    wall = time.time() - t0
    results = {}
    for name in sup.roles("worker"):
        for line in sup.output_lines(name, "FLEET_RESULT "):
            try:
                results[name] = json.loads(line[len("FLEET_RESULT "):])
            except ValueError:
                pass
    aggs = {}
    for name in sup.roles("agg"):
        for line in sup.output_lines(name, "AGG_RESULT "):
            try:
                aggs[name] = json.loads(line[len("AGG_RESULT "):])
            except ValueError:
                pass
    return {
        "ok": ok and all(
            (rcs.get(n) == 0) for n in sup.roles("worker")),
        "wall_s": round(wall, 3),
        "exit_codes": rcs,
        "restarts": {n: sup.restarts(n) for n in sup.roles()},
        "events": sup.events,
        "logdir": sup.logdir,
        "workers": results,
        "aggs": aggs,
        "server_addrs": manifest.server_addrs,
        "agg_addrs": manifest.agg_addrs,
    }


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="byteps_tpu.launcher.fleet",
        description="one-command supervised local training fleet "
                    "(P pipeline stages x dp replicas x plane shards)")
    ap.add_argument("--stages", type=int, default=1)
    ap.add_argument("--dp", type=int, default=1)
    ap.add_argument("--virtual", type=int, default=1)
    ap.add_argument("--micro", type=int, default=4)
    ap.add_argument("--shards", type=int, default=0)
    ap.add_argument("--plane-replicas", type=int, default=0)
    ap.add_argument("--local-size", type=int, default=1,
                    help="workers per emulated host; >1 inserts a "
                         "per-host local aggregator tier (BPS_HIER_AGG)")
    ap.add_argument("--steps", type=int, default=4)
    ap.add_argument("--schedule", default="1f1b",
                    choices=("1f1b", "sequential"))
    ap.add_argument("--dim", type=int, default=64)
    ap.add_argument("--depth", type=int, default=8)
    ap.add_argument("--batch", type=int, default=32)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--timeout", type=float, default=600.0)
    ap.add_argument("--max-restarts", type=int, default=2)
    ap.add_argument("--logdir", default=None)
    ap.add_argument("--dry-run", action="store_true",
                    help="print the derived per-role env/argv manifest "
                         "and exit (the lift-to-k8s/SSH view)")
    args = ap.parse_args(argv)
    man = FleetManifest(
        stages=args.stages, dp=args.dp, virtual=args.virtual,
        micro=args.micro, shards=args.shards,
        plane_replicas=args.plane_replicas, steps=args.steps,
        schedule=args.schedule, dim=args.dim, depth=args.depth,
        batch=args.batch, seed=args.seed, local_size=args.local_size)
    if args.dry_run:
        for spec in man.build():
            derived = {k: v for k, v in spec.env.items()
                       if k.startswith("BPS_") or k.startswith("JAX_")}
            print(json.dumps({"name": spec.name, "role": spec.role,
                              "argv": spec.argv, "env": derived,
                              "group": spec.group}))
        return 0
    out = run_fleet(man, timeout_s=args.timeout, logdir=args.logdir,
                    max_restarts=args.max_restarts)
    for name, res in sorted(out["workers"].items()):
        print(f"{name:10s} steps={res.get('steps'):>3} "
              f"samples/sec={res.get('sps', 0):>8.2f} "
              f"wall={res.get('wall_s', 0):>7.3f}s "
              f"loss={res.get('last_loss')}")
    print(json.dumps({k: v for k, v in out.items()
                      if k not in ("events", "workers")}))
    return 0 if out["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())

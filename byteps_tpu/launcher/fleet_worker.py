"""Built-in fleet worker: one process of the launcher's P x dp grid.

Spawned by ``byteps_tpu.launcher.fleet`` with the full BPS_* env
contract (docs/launcher.md has the table); everything here is DERIVED
from that env — no argv, no shared state, exactly what a k8s pod or
SSH-launched rank would see.

Three modes (``BPS_FLEET_MODE``):

  - ``train`` (default): the pipeline stage worker. Builds the shared
    mlp program deterministically from ``BPS_FLEET_SEED``, partitions
    it into P*V stages (the SAME program every peer builds — the
    declaration-order determinism the PS keyspace relies on), binds
    its activation mailbox (a ``PSTransportServer`` on its
    ``BPS_PP_ACT_ADDRS[rank]`` slot), dials its ring peers, runs
    ``BPS_FLEET_STEPS`` 1F1B (or interleaved) steps with per-stage DP
    through the PS plane when dp > 1, and prints one ``FLEET_RESULT``
    JSON line: per-role throughput, losses (last stage), wire
    counters. Exit 0 == clean drain.
  - ``rounds``: the PR-13 elasticity proof ride-along — a plain
    deterministic PS exchange loop (constant grads, sum must equal
    dp x value every round — relaxed to a uniform 1..dp under
    ``BPS_MAX_LAG>1``, where sealed rounds carry fewer contributions)
    that a supervisor-restarted replacement
    REJOINS mid-job: its fresh exchange seeds per-key round counters
    from the server, so it resumes the JOB's round, not round 1
    (tests/_elastic_ps_worker.py's contract, now supervisor-driven).
    Prints per-round ``FLEET_STEP`` walls — the kill test's stall
    accounting reads them.
  - ``embed``: the ISSUE-18 feature-store loop — a DLRM-style worker
    driving the sharded embedding store (server/embed.py) with a
    Zipfian request trace: per step, sparse-pull the batch's rows
    (hot-row cache on unless ``BPS_EMBED_CACHE_ROWS=0``), push
    deterministic dyadic per-(worker, step, row) deltas, tick the
    cache round. ``BPS_EMBED_DENSE=1`` turns the PULL side into a
    full-table dense fetch (the bench's wire-bytes control arm; pushes
    stay trace-based so both arms converge to the same table).
    ``BPS_EMBED_VERIFY=1`` makes worker 0 re-derive the expected final
    table analytically (dyadic deltas make fp32 sums exact, so the
    comparison is BITWISE) and poll with a no-cache client until the
    server matches — the bench's convergence-parity column. Prints
    per-step ``FLEET_STEP`` walls/fetch times and one ``FLEET_RESULT``
    with hit/miss counters, fetch p50/p99, and the parity verdict.
"""

from __future__ import annotations

import json
import os
import sys
import time


def _env_int(name: str, default: int) -> int:
    v = os.environ.get(name, "")
    return int(v) if v else default


def _run_rounds() -> int:
    """PS rounds mode: no jax import needed — pure numpy over TCP."""
    import zlib

    import numpy as np

    from ..server.ps_mode import PSGradientExchange
    from ..server.transport import RemotePSBackend
    from .fleet import wait_for_ports

    dp = _env_int("BPS_NUM_WORKER", 1)
    steps = _env_int("BPS_FLEET_STEPS", 4)
    nbytes = _env_int("BPS_FLEET_NBYTES", 1 << 16)
    wid = _env_int("BPS_WORKER_ID", 0)
    max_lag = _env_int("BPS_MAX_LAG", 1)
    incarnation = _env_int("BPS_FLEET_INCARNATION", 0)
    grad_mode = os.environ.get("BPS_FLEET_GRAD", "ones").strip() or "ones"
    addrs = [a for a in os.environ.get("BPS_SERVER_ADDRS", "").split(",")
             if a]
    if not addrs:
        print("FLEET_ERROR rounds mode needs BPS_SERVER_ADDRS",
              flush=True)
        return 2
    wait_for_ports(addrs, timeout_s=60.0)
    be = RemotePSBackend(addrs)
    ex = PSGradientExchange(be, partition_bytes=nbytes // 4)
    # per-round pacing (simulated compute): gives the kill tests a
    # window to land a SIGKILL mid-job, and makes the survivor's
    # per-round walls a meaningful stall measurement. BPS_FLEET_SEG_MS
    # (the train mode's emulated-compute knob) adds on top — the
    # ps_lag bench sets it on ONE worker via the manifest's role_env
    # to make that worker the straggler.
    pace = (float(os.environ.get("BPS_FLEET_STEP_SLEEP", "0") or 0)
            + float(os.environ.get("BPS_FLEET_SEG_MS", "0") or 0) / 1e3)
    # mid-run pacing injection (BPS_FLEET_PACE_FILE): the spawn env is
    # frozen at manifest build, so a bench that wants to turn a healthy
    # worker into a straggler MID-RUN (the ps_watch regime-flip rig)
    # names a file here; each round adds the extra milliseconds it
    # currently holds (missing/empty/garbled file = 0 — the quiet arm)
    pace_file = os.environ.get("BPS_FLEET_PACE_FILE", "").strip() or None

    def extra_pace() -> float:
        if pace_file is None:
            return 0.0
        try:
            with open(pace_file) as f:
                return max(0.0, float(f.read().strip() or 0)) / 1e3
        except (OSError, ValueError):
            return 0.0
    # grad_mode="dyadic": per-(worker, round, element) gradients drawn
    # from the dyadic rationals k/1024, k ∈ [-512, 512) — sums of ≤ dp
    # such values are EXACT in float32, so any association order (flat
    # per-worker sum vs hierarchical host-sum-of-sums) yields bitwise
    # identical results. The ps_hier bench's parity assertion compares
    # the crc32 digests across arms. Round-prediction assumes no
    # restarts, so dyadic mode is for parity benches, not kill tests.
    n_elems = nbytes // 4
    idx = np.arange(n_elems, dtype=np.int64)

    def dyadic(w: int, r: int) -> "np.ndarray":
        k = (idx * 37 + w * 1009 + r * 2003) % 1024
        return ((k - 512) / 1024.0).astype(np.float32)

    tree = {"g": np.ones(n_elems, np.float32)}
    done = 0
    resumed_at = None
    digests = []
    while True:
        t0 = time.time()
        p = pace + extra_pace()
        if p:
            time.sleep(p)
        if grad_mode == "dyadic":
            tree = {"g": dyadic(wid, done + 1)}
        out = ex.exchange(tree, name="g")
        done = ex.completed_rounds()
        if resumed_at is None:
            # the round the FIRST exchange landed on: 1 for a fresh
            # worker, k+1 for a supervisor-restarted replacement (the
            # per-key server seeding — the PR-13 rejoin proof)
            resumed_at = done
        wall = time.time() - t0
        if grad_mode == "dyadic" and max_lag <= 1:
            expect = np.zeros(n_elems, np.float32)
            for w in range(dp):
                expect += dyadic(w, done)
            ok = bool(np.array_equal(out["g"], expect))
        elif max_lag > 1:
            # bounded staleness: a sealed round publishes WITHOUT some
            # workers (they late-fold into a later round, which then
            # carries their push twice — once late, once current), and
            # each PARTITION seals independently. The per-round relaxed
            # contract is: every element is a whole contribution count
            # in [1, dp*max_lag]; exactly-once delivery ACROSS rounds
            # is the store's conservation invariant, asserted in
            # tests/test_admission.py (docs/admission.md)
            g = out["g"]
            ok = bool(np.all((g >= 1 - 1e-6)
                             & (g <= dp * max_lag + 1e-6))
                      and np.allclose(g, np.round(g)))
        else:
            ok = bool(np.allclose(out["g"], float(dp)))
        if not ok:
            print(f"FLEET_ERROR round {done}: sum {out['g'][0]} != {dp}"
                  f" (max_lag={max_lag}, grad={grad_mode})", flush=True)
            return 3
        # digest of the pulled sum: the arm-vs-arm bitwise-parity
        # evidence (two arms agree per (worker, round) iff the summed
        # float32 payloads are byte-identical)
        digest = zlib.crc32(out["g"].tobytes()) & 0xFFFFFFFF
        digests.append(digest)
        print("FLEET_STEP " + json.dumps(
            {"worker": wid, "round": done, "wall_s": round(wall, 4),
             "incarnation": incarnation, "digest": digest}), flush=True)
        if done >= steps:
            break
    # the backend's push-dedup incarnation id is what server span
    # records carry as the per-arrival worker id — print it so a
    # driver can map a watchtower incident's blamed id to this role
    push_id = int(getattr(be, "incarnation", 0))
    be.close()
    from ..obs.metrics import get_registry
    reg = get_registry()
    print("FLEET_RESULT " + json.dumps(
        {"mode": "rounds", "worker": wid, "steps": done,
         "incarnation": incarnation, "resumed_at": resumed_at,
         "push_id": push_id,
         "push_bytes": int(reg.counter("ps/push_bytes").value),
         "pull_bytes": int(reg.counter("ps/pull_bytes").value),
         "digests": digests}),
        flush=True)
    return 0


def embed_trace(seed: int, wid: int, step: int, batch: int, rows: int,
                zipf_a: float):
    """The (worker, step) slice of the Zipfian request trace: ``batch``
    row ids drawn Zipf(a) over [0, rows). Legacy ``RandomState`` keeps
    the stream stable across numpy versions, and seeding per
    (seed, wid, step) makes any slice recomputable in isolation — the
    verify pass re-derives every worker's whole trace from scalars."""
    import numpy as np
    rng = np.random.RandomState(
        (int(seed) * 1000003 + wid * 8191 + step) % (2 ** 32 - 1))
    return ((rng.zipf(zipf_a, batch).astype(np.uint64) - np.uint64(1))
            % np.uint64(rows))


def embed_delta(seed: int, wid: int, step: int, rids, cols: int):
    """Deterministic per-(worker, step, row) push deltas: dyadic
    rationals from the store's own ``init_rows`` hash under a
    (seed, wid, step)-mixed seed. Dyadic values keep every fp32 sum on
    the path EXACT — client dedup fold, server row accumulation, and
    the verify pass's count-weighted expectation all land on the same
    bytes regardless of association order."""
    from ..server.embed import init_rows
    return init_rows(int(seed) * 1000003 + wid * 8191 + step, rids,
                     cols)


def _embed_verify(addrs, seed: int, dp: int, steps: int, rows: int,
                  cols: int, batch: int, zipf_a: float,
                  timeout_s: float = 60.0) -> bool:
    """Worker 0's convergence-parity check: re-derive the expected
    final table (init + every worker's trace-weighted deltas — all
    dyadic, so the fp32 expectation is exact) and poll the plane with a
    NO-CACHE client until the pulled bytes match bitwise. Polling,
    because peers finish their last push asynchronously."""
    import numpy as np

    from ..server.embed import EmbedClient, init_rows

    expect = init_rows(seed, np.arange(rows, dtype=np.uint64), cols)
    for w in range(dp):
        for s in range(1, steps + 1):
            tids = embed_trace(seed, w, s, batch, rows, zipf_a)
            uniq, counts = np.unique(tids, return_counts=True)
            d = embed_delta(seed, w, s, uniq, cols)
            expect[uniq.astype(np.int64)] += (
                d * counts[:, None].astype(d.dtype))
    ver = EmbedClient.connect(addrs, table_id=0, num_rows=rows,
                              cols=cols, seed=seed, cache_rows=0)
    all_ids = np.arange(rows, dtype=np.uint64)
    deadline = time.time() + timeout_s
    while True:
        ok = bool(np.array_equal(ver.pull(all_ids), expect))
        if ok or time.time() > deadline:
            break
        time.sleep(0.25)
    ver.close()
    return ok


def _run_embed() -> int:
    """Embedding feature-store mode: Zipfian sparse pull/push loop
    against the row-sharded table on the plane (no jax import — pure
    numpy over TCP, like rounds mode)."""
    import numpy as np

    from ..obs.metrics import get_registry
    from ..server.embed import EmbedClient
    from .fleet import wait_for_ports

    dp = _env_int("BPS_NUM_WORKER", 1)
    wid = _env_int("BPS_WORKER_ID", 0)
    steps = _env_int("BPS_FLEET_STEPS", 8)
    seed = _env_int("BPS_FLEET_SEED", 0)
    rows = _env_int("BPS_EMBED_ROWS", 1 << 20)
    cols = _env_int("BPS_EMBED_COLS", 32)
    batch = _env_int("BPS_EMBED_BATCH", 256)
    dense = _env_int("BPS_EMBED_DENSE", 0)
    verify = _env_int("BPS_EMBED_VERIFY", 0)
    # push accumulation (BPS_EMBED_PUSH_EVERY=R): fold R steps of
    # deltas client-side and push once — the DLRM grad-accumulation
    # idiom. Between flushes a worker's hot rows STAY cached (a push
    # drops its rows from the cache — the hot-row staleness contract —
    # so push-every-step traces re-fetch everything and the cache only
    # saves validation bytes). Deltas are dyadic, so the folded sums
    # are exact and the verify expectation is unchanged.
    push_every = max(1, _env_int("BPS_EMBED_PUSH_EVERY", 1))
    zipf_a = float(os.environ.get("BPS_EMBED_ZIPF_A", "1.1") or 1.1)
    # same knob rounds mode honors: the kill-shard bench stretches the
    # run so the mid-run fault lands between steps, not after drain
    sleep_s = float(os.environ.get("BPS_FLEET_STEP_SLEEP", "0") or 0)
    addrs = [a for a in os.environ.get("BPS_SERVER_ADDRS", "").split(",")
             if a]
    if not addrs:
        print("FLEET_ERROR embed mode needs BPS_SERVER_ADDRS",
              flush=True)
        return 2
    wait_for_ports(addrs, timeout_s=60.0)
    # replication rides env (BPS_EMBED_REPLICAS, defaulting to
    # BPS_PLANE_REPLICAS) straight into the client ctor
    cli = EmbedClient.connect(addrs, table_id=0, num_rows=rows,
                              cols=cols, seed=seed)
    scraper = None
    if cli.replicas > 0:
        # acted-on liveness: a black-holed shard (not just a refused
        # dial) is declared dead by the scrape cadence and failed over
        # through cli.note_stale — the same scraper/failover_backend
        # wiring the dense plane uses (docs/elasticity.md)
        from ..obs.fleet import FleetScraper
        interval = float(os.environ.get("BPS_EMBED_SCRAPE_SEC", "0.5")
                         or 0.5)
        scraper = FleetScraper(cli, interval_sec=interval,
                               failover_backend=cli).start()
    dense_ids = (np.arange(rows, dtype=np.uint64) if dense else None)
    fetch = []
    acc_ids, acc_deltas = [], []
    t_all = time.time()
    for s in range(1, steps + 1):
        t0 = time.time()
        tids = embed_trace(seed, wid, s, batch, rows, zipf_a)
        vals = cli.pull(dense_ids if dense else tids)
        fetch.append(cli.last_fetch_s)
        loss = float(np.mean(np.abs(vals)))
        acc_ids.append(tids)
        acc_deltas.append(embed_delta(seed, wid, s, tids, cols))
        if s % push_every == 0 or s == steps:
            cli.push(np.concatenate(acc_ids),
                     np.concatenate(acc_deltas, axis=0))
            acc_ids, acc_deltas = [], []
        cli.tick()
        if sleep_s:
            time.sleep(sleep_s)
        print("FLEET_STEP " + json.dumps(
            {"worker": wid, "step": s,
             "wall_s": round(time.time() - t0, 4),
             "fetch_s": round(fetch[-1], 4),
             "loss": round(loss, 6)}), flush=True)
    wall = time.time() - t_all
    # snapshot counters BEFORE any verify traffic — the verify client
    # shares this process's registry and would pollute the byte and
    # hit-rate columns the bench reports
    reg = get_registry()
    hits = int(reg.counter("embed/cache_hits").value)
    misses = int(reg.counter("embed/cache_misses").value)
    fbytes = int(reg.counter("embed/row_fetch_bytes").value)
    pushed = int(reg.counter("embed/rows_pushed").value)
    parity = None
    if verify and wid == 0:
        parity = _embed_verify(addrs, seed, dp, steps, rows, cols,
                               batch, zipf_a)
    if scraper is not None:
        scraper.stop()
    failovers = cli.failovers
    cli.close()
    fs = sorted(fetch)

    def q(p: float) -> float:
        return fs[min(len(fs) - 1, int(p * len(fs)))]

    print("FLEET_RESULT " + json.dumps(
        {"mode": "embed", "worker": wid, "steps": steps, "rows": rows,
         "cols": cols, "batch": batch, "dense": dense, "hits": hits,
         "misses": misses,
         "hit_rate": round(hits / max(1, hits + misses), 4),
         "row_fetch_bytes": fbytes, "rows_pushed": pushed,
         "fetch_p50_s": round(q(0.50), 5),
         "fetch_p99_s": round(q(0.99), 5),
         "lookups_per_s": round(batch * steps / wall, 1),
         "wall_s": round(wall, 3), "parity": parity,
         "failovers": failovers}), flush=True)
    return 0 if parity in (None, True) else 3


def _run_train() -> int:
    os.environ.setdefault("XLA_FLAGS",
                          "--xla_force_host_platform_device_count=1")
    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax

    from ..common.naming import NameRegistry
    from ..models.mlp import mlp_init, mlp_loss
    from ..pipeline import (ActivationExchange, PipelineStageDriver,
                            StagePartitioner)
    from ..pipeline.topology import act_peer_addrs
    from ..server.engine import PSServer
    from ..server.ps_mode import PSGradientExchange
    from ..server.transport import PSTransportServer, RemotePSBackend
    from .fleet import wait_for_ports

    P = _env_int("BPS_PP_STAGES", 1)
    V = _env_int("BPS_PP_VIRTUAL", 1)
    M = _env_int("BPS_PP_MICROBATCH", 1)
    stage = _env_int("BPS_PP_RANK", 0)
    dp = _env_int("BPS_NUM_WORKER", 1)
    replica = _env_int("BPS_WORKER_ID", 0)
    steps = _env_int("BPS_FLEET_STEPS", 4)
    dim = _env_int("BPS_FLEET_DIM", 64)
    depth = _env_int("BPS_FLEET_DEPTH", 8)
    batch = _env_int("BPS_FLEET_BATCH", 32)
    seed = _env_int("BPS_FLEET_SEED", 0)
    schedule = os.environ.get("BPS_FLEET_SCHEDULE", "1f1b")
    act_addrs = [a for a in os.environ.get("BPS_PP_ACT_ADDRS",
                                           "").split(",") if a]
    srv_addrs = [a for a in os.environ.get("BPS_SERVER_ADDRS",
                                           "").split(",") if a]

    # ---- the shared program: every peer derives the SAME model, data
    # and partition from the seed — nothing is shipped
    rng = np.random.RandomState(seed)
    params = mlp_init(jax.random.PRNGKey(seed), dim, depth)
    xs = rng.randn(batch, dim).astype(np.float32)
    full = (jnp.asarray(xs), jnp.asarray(np.tanh(xs)))
    per = batch // dp
    mine = tuple(l[replica * per:(replica + 1) * per] for l in full)
    mb = tuple(l[:per // M] for l in mine)
    prog = StagePartitioner(P * V).build(mlp_loss, params, mb,
                                         name="fleet")
    if prog is None:
        print(f"FLEET_ERROR partitioner refused {P}x{V} stages for "
              f"mlp(dim={dim}, depth={depth})", flush=True)
        return 3

    # BPS_FLEET_SEG_MS: emulated per-segment accelerator compute (the
    # repo's emulated-NIC idiom applied to compute) — sleep this many
    # ms per PHYSICAL-stage segment at V=1, scaled by 1/V because a
    # chunk holds 1/V of a stage's layers. On a shared-core dev box
    # real matmul time serializes across the fleet's processes and
    # erases the schedule's overlap; sleep-paced segments make step
    # wall track the SCHEDULE's critical path — which is exactly what
    # `bench.py fleet` compares across plain/interleaved arms. Purely
    # additive: numerics are untouched.
    seg_ms = float(os.environ.get("BPS_FLEET_SEG_MS", "0") or 0)
    if seg_ms > 0:
        pace_s = seg_ms / 1000.0 / V

        def _paced(fn, delay):
            def run(*a):
                time.sleep(delay)
                return fn(*a)
            return run

        for seg in prog.segments:
            seg.fn = _paced(seg.fn, pace_s)

    # ---- activation plane: bind my mailbox, dial ring peers
    engine = act_srv = None
    peers = {}
    clients = []
    if P > 1:
        my_addr = act_addrs[stage]
        engine = PSServer(num_workers=1, engine_threads=1)
        act_srv = PSTransportServer(
            engine, host=my_addr.rsplit(":", 1)[0],
            port=int(my_addr.rsplit(":", 1)[1]))
        store = act_srv.act_store()
        peer_addrs = act_peer_addrs(stage, act_addrs, V)
        wait_for_ports(list(peer_addrs.values()), timeout_s=60.0)
        for p, addr in peer_addrs.items():
            c = RemotePSBackend([addr], lazy_dial=True)
            clients.append(c)
            peers[p] = c
    else:
        from ..pipeline.exchange import ActStore
        store = ActStore()
    act = ActivationExchange(stage, store, peers=peers or None,
                             num_phys=P, timeout_ms=120000)

    # ---- gradient plane: per-stage DP sum through the UNCHANGED PS
    # path (stage-suffixed names; the servers' round gate is dp)
    ps_ex = backend = None
    if dp > 1:
        if not srv_addrs:
            print("FLEET_ERROR dp>1 needs BPS_SERVER_ADDRS", flush=True)
            return 2
        wait_for_ports(srv_addrs, timeout_s=60.0)
        replicas = _env_int("BPS_PLANE_REPLICAS", 0)
        if replicas > 0 and len(srv_addrs) > 1:
            from ..server.plane import PlanePSBackend
            backend = PlanePSBackend(
                [RemotePSBackend([a], lazy_dial=True)
                 for a in srv_addrs],
                num_workers=dp, replicas=replicas, owns_shards=True,
                worker_id=replica)
        else:
            backend = RemotePSBackend(srv_addrs)
        ps_ex = PSGradientExchange(backend, registry=NameRegistry())

    drv = PipelineStageDriver(prog, stage, params, optax.adam(1e-2),
                              act, M, exchange=ps_ex, world=dp,
                              name="fleet", schedule=schedule,
                              virtual=V)
    losses = []
    walls = []
    t_all = time.time()
    for i in range(steps):
        t0 = time.time()
        loss = drv.step(mine)
        walls.append(time.time() - t0)
        if loss is not None:
            losses.append(float(np.asarray(loss)))
        print("FLEET_STEP " + json.dumps(
            {"stage": stage, "replica": replica, "step": i + 1,
             "wall_s": round(walls[-1], 4),
             "loss": losses[-1] if loss is not None else None}),
            flush=True)
    wall = time.time() - t_all

    from ..obs.metrics import get_registry
    reg = get_registry()
    print("FLEET_RESULT " + json.dumps({
        "mode": "train", "stage": stage, "replica": replica,
        "virtual": V, "schedule": schedule, "steps": steps,
        "wall_s": round(wall, 3),
        "sps": round(per * steps / wall, 2),
        "last_loss": losses[-1] if losses else None,
        "losses": losses,
        "act_send_bytes": reg.counter("pp/act_send_bytes").value,
        "act_recv_bytes": reg.counter("pp/act_recv_bytes").value,
        "microbatches": reg.counter("pp/microbatches").value,
    }), flush=True)

    # ---- clean drain: my schedule is complete, so every frame
    # addressed to me was consumed and every frame I owed my peers was
    # ACKed into their mailboxes before my last step returned — closing
    # now can strand nobody (docs/launcher.md drain protocol)
    if ps_ex is not None:
        ps_ex.close()
    if backend is not None:
        backend.close()
    for c in clients:
        c.close()
    if act_srv is not None:
        act_srv.close()
    if engine is not None:
        engine.close()
    return 0


def main() -> int:
    mode = os.environ.get("BPS_FLEET_MODE", "train").strip() or "train"
    if mode == "rounds":
        return _run_rounds()
    if mode == "embed":
        return _run_embed()
    if mode == "train":
        return _run_train()
    print(f"FLEET_ERROR unknown BPS_FLEET_MODE={mode!r}", flush=True)
    return 2


if __name__ == "__main__":
    sys.exit(main())

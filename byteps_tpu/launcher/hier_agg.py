"""Entrypoint for the ``agg`` fleet role: one per-host local
aggregator.

The process is a thin sandwich: a ``PSTransportServer`` facing the
host's ``local_size`` workers (they speak the ordinary wire protocol —
shm fast path included, since agg and workers share the "host"), with a
``LocalAggBackend`` behind it that folds the local pushes and forwards
ONE host-sum per key/round to the remote plane over a plain
``RemotePSBackend`` client. Cross-host bytes ≈ dense / local_size;
see server/hier.py for the accounting contract.

On SIGTERM (the supervisor's drain) it prints one ``AGG_RESULT`` JSON
line carrying the local/remote byte counters, which ``run_fleet``
scrapes into the summary's ``aggs`` dict — that line is the
measurement the ps_hier bench's cross-host-bytes assertion reads.
"""

from __future__ import annotations

import json
import os
import signal
import sys
import time

from ..server.hier import LocalAggBackend
from ..server.transport import PSTransportServer, RemotePSBackend
from .fleet import wait_for_ports


def main() -> int:
    env = os.environ
    upstream = [a for a in env.get(
        "BPS_HIER_UPSTREAM_ADDRS", "").split(",") if a]
    if not upstream:
        print("AGG_ERROR no BPS_HIER_UPSTREAM_ADDRS", file=sys.stderr,
              flush=True)
        return 2
    local_size = int(env.get("BPS_LOCAL_SIZE", "1"))
    host_id = int(env.get("BPS_HIER_HOST_ID", "0"))
    port = int(env.get("BPS_SERVER_PORT", "0"))

    wait_for_ports(upstream)
    be = RemotePSBackend(upstream)
    agg = LocalAggBackend(be, local_size, host_id=host_id)
    tsrv = PSTransportServer(agg, port=port)
    print(f"[hier-agg] host {host_id} up on :{tsrv.port} "
          f"(local_size={local_size}, upstream={len(upstream)} shards)",
          file=sys.stderr, flush=True)

    stop = []
    signal.signal(signal.SIGTERM, lambda *_: stop.append(1))
    try:
        while not stop:
            time.sleep(0.2)
    except KeyboardInterrupt:
        pass
    # the counters line must go out BEFORE teardown: drain() gives the
    # process a bounded grace window and the bench needs this line
    print("AGG_RESULT " + json.dumps({
        "host": host_id,
        "local_size": local_size,
        "local_agg_bytes": int(agg.m_local_bytes.value),
        "remote_push_bytes": int(agg.m_remote_bytes.value),
    }), flush=True)
    tsrv.close()
    agg.close()
    return 0


if __name__ == "__main__":
    sys.exit(main())

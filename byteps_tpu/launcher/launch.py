"""bpslaunch-tpu: process launcher (reference: launcher/launch.py —
bpslaunch reads DMLC_ROLE, spawns per-GPU workers with BYTEPS_LOCAL_RANK
set, numactl-pins them, and execs servers/schedulers; launcher/
dist_launcher.py SSHes to hosts propagating DMLC_* env).

TPU-native differences:
  - one JAX process per *host* drives all local chips, so there is no
    per-GPU fanout; the launcher's job is to resolve the process's place
    in the job (process_id / num_processes / coordinator) and exec the
    training script with BPS_* env set.
  - rendezvous is jax.distributed's coordinator (no scheduler role); TPU
    pod metadata supplies topology when present, with env-var overrides
    (same precedence model as the reference's env contract).
  - optional numactl pinning survives (useful for the host PS service:
    BPS_NUMA_ON, reference launcher/launch.py:44-122).
  - ``--server`` runs a standalone host reduction server process
    (reference: python3 -c 'import byteps.server').

Usage:
  bpslaunch-tpu [--coordinator HOST:PORT] [--num-processes N]
                [--process-id I] [--numa] [--server] -- CMD [ARGS...]
  bpslaunch-tpu --hosts h1,h2,... -- CMD [ARGS...]      # SSH fan-out
  bpslaunch-tpu --fleet [FLEET ARGS...]   # one-command supervised
                # local fleet (launcher/fleet.py: P stages x dp
                # replicas x plane shards, restart-on-death)
"""

from __future__ import annotations

import argparse
import os
import shlex
import shutil
import subprocess
import sys
from typing import List, Optional


def _tpu_metadata_env() -> dict:
    """Topology from TPU pod metadata env (set by the TPU runtime), with
    graceful fallback to single-process."""
    env = {}
    hostnames = os.environ.get("TPU_WORKER_HOSTNAMES", "")
    worker_id = os.environ.get("TPU_WORKER_ID", os.environ.get("CLOUD_TPU_TASK_ID"))
    if hostnames and worker_id is not None:
        hosts = [h for h in hostnames.split(",") if h]
        env["BPS_NUM_PROCESSES"] = str(len(hosts))
        env["BPS_PROCESS_ID"] = str(worker_id)
        port = os.environ.get("BPS_COORDINATOR_PORT", "8476")
        env["BPS_COORDINATOR_ADDRESS"] = f"{hosts[0]}:{port}"
    return env


def build_env(args) -> dict:
    env = dict(os.environ)
    env.update(_tpu_metadata_env())
    if args.coordinator:
        env["BPS_COORDINATOR_ADDRESS"] = args.coordinator
    if args.num_processes is not None:
        env["BPS_NUM_PROCESSES"] = str(args.num_processes)
    if args.process_id is not None:
        env["BPS_PROCESS_ID"] = str(args.process_id)
    env.setdefault("BPS_ROLE", "server" if args.server else "worker")
    return env


def numa_prefix(enabled: bool) -> List[str]:
    """numactl pinning for the host-side services (reference:
    launcher/launch.py:44-122 NUMA binding)."""
    if not enabled or shutil.which("numactl") is None:
        return []
    node = os.environ.get("BPS_NUMA_NODE", "0")
    return ["numactl", f"--cpunodebind={node}", f"--membind={node}"]


def run_local(args, cmd: List[str]) -> int:
    env = build_env(args)
    if args.server:
        # standalone reduction server (reference: byteps.server import),
        # reachable over TCP (reference: ps-lite van) on BPS_SERVER_PORT
        from ..server.engine import PSServer
        from ..server.transport import PSTransportServer
        import signal
        import time
        # the round-completion gate: how many workers push each key.
        # BPS_NUM_WORKER is the deployment-wide contract every worker
        # already sets (docs/env.md); BPS_NUM_PROCESSES remains as the
        # launcher-local spelling for single-host fan-outs
        n = int(env.get("BPS_NUM_WORKER",
                        env.get("BPS_NUM_PROCESSES", "1")))
        srv = PSServer(num_workers=n,
                       engine_threads=int(env.get("BPS_SERVER_ENGINE_THREAD", "4")),
                       enable_schedule=env.get("BPS_SERVER_ENABLE_SCHEDULE", "") == "1",
                       async_mode=env.get("BPS_ENABLE_ASYNC", "") == "1")
        # PS-state checkpointing (ours — the reference loses the async
        # store on server death): restore the BACKEND before the
        # transport starts accepting, so a fast-reconnecting worker's
        # INIT can't allocate a key first and pin its own stale values
        # (server-side init is first-wins)
        snap = env.get("BPS_SERVER_SNAPSHOT", "")
        snap_secs = int(env.get("BPS_SERVER_SNAPSHOT_SECS", "60"))
        meta = {}
        if snap and os.path.exists(snap):
            from ..server.transport import restore_snapshot
            meta = restore_snapshot(srv, snap)
            print(f"[bpslaunch-tpu] restored {len(meta)} PS keys from "
                  f"{snap}", file=sys.stderr)
        # optional emulated-NIC throttle on this server endpoint
        # (BPS_NIC_RATE bytes/sec + BPS_NIC_LATENCY_S per frame): the
        # wire-bound fleet benches (bench.py ps_hier) constrain the
        # cross-host link here, where real processes meet real sockets
        nic = None
        rate = float(env.get("BPS_NIC_RATE", "0") or 0)
        if rate > 0:
            from ..server.throttle import Nic
            nic = Nic(rate,
                      latency=float(env.get("BPS_NIC_LATENCY_S", "0") or 0))
        tsrv = PSTransportServer(srv,
                                 port=int(env.get("BPS_SERVER_PORT", "9090")),
                                 key_meta=meta, nic=nic)
        print(f"[bpslaunch-tpu] server up on :{tsrv.port} (workers={n}); "
              "Ctrl-C to stop", file=sys.stderr)
        stop = []
        signal.signal(signal.SIGTERM, lambda *a: stop.append(1))
        last_snap = time.time()

        def try_snapshot():
            # best-effort: a full disk must degrade the checkpoint, not
            # kill the live data plane
            try:
                tsrv.snapshot(snap)
            except Exception as e:
                print(f"[bpslaunch-tpu] snapshot failed: {e}",
                      file=sys.stderr)

        try:
            while not stop:
                time.sleep(1)
                if snap and time.time() - last_snap >= snap_secs:
                    try_snapshot()
                    last_snap = time.time()
        except KeyboardInterrupt:
            pass
        if snap:
            try_snapshot()
        tsrv.close()
        srv.close()
        return 0
    full = numa_prefix(args.numa) + cmd
    gdb_flag = env.get("BPS_ENABLE_GDB", env.get("BYTEPS_ENABLE_GDB", "0"))
    if gdb_flag.strip().lower() in ("1", "true", "yes", "on"):
        # crash-triage wrap (reference: launcher/launch.py:144-148): run the
        # worker under gdb and print a backtrace on abnormal exit; degrade
        # like numa_prefix does when the tool is missing.
        # --return-child-result: the launcher's exit code must stay the
        # WORKER's (supervisors restart on it), not gdb's own
        if shutil.which("gdb"):
            full = ["gdb", "--return-child-result", "-ex", "run", "-ex",
                    "bt", "-batch", "--args"] + full
        else:
            print("[bpslaunch-tpu] BPS_ENABLE_GDB set but gdb not found; "
                  "running unwrapped", file=sys.stderr)
    return subprocess.call(full, env=env)


def run_ssh(args, cmd: List[str]) -> int:
    """SSH fan-out (reference: launcher/dist_launcher.py)."""
    hosts = [h for h in args.hosts.split(",") if h]
    coordinator = args.coordinator or f"{hosts[0]}:8476"
    procs = []
    for i, host in enumerate(hosts):
        envs = " ".join([
            f"BPS_COORDINATOR_ADDRESS={shlex.quote(coordinator)}",
            f"BPS_NUM_PROCESSES={len(hosts)}",
            f"BPS_PROCESS_ID={i}",
        ])
        remote = f"cd {shlex.quote(os.getcwd())} && {envs} {' '.join(map(shlex.quote, cmd))}"
        procs.append(subprocess.Popen(["ssh", "-o", "StrictHostKeyChecking=no",
                                       host, remote]))
    rc = 0
    for p in procs:
        rc = p.wait() or rc
    return rc


def main(argv: Optional[List[str]] = None) -> int:
    # --fleet delegates everything after the flag to the fleet
    # orchestrator (its own argparse) — one entry point, two layers
    args_in = list(sys.argv[1:] if argv is None else argv)
    if args_in and args_in[0] == "--fleet":
        from .fleet import main as fleet_main
        return fleet_main(args_in[1:])
    parser = argparse.ArgumentParser(prog="bpslaunch-tpu", description=__doc__)
    parser.add_argument("--coordinator", help="coordinator HOST:PORT")
    parser.add_argument("--num-processes", type=int)
    parser.add_argument("--process-id", type=int)
    parser.add_argument("--hosts", help="comma-separated hosts for SSH fan-out")
    parser.add_argument("--numa", action="store_true", help="numactl pinning")
    parser.add_argument("--server", action="store_true",
                        help="run a standalone host reduction server")
    parser.add_argument("cmd", nargs=argparse.REMAINDER,
                        help="-- command to launch")
    args = parser.parse_args(argv)
    cmd = args.cmd
    if cmd and cmd[0] == "--":
        cmd = cmd[1:]
    if not cmd and not args.server:
        parser.error("no command given")
    if args.hosts:
        return run_ssh(args, cmd)
    return run_local(args, cmd)


if __name__ == "__main__":
    sys.exit(main())

"""Launchers: the single-process env resolver (``launch`` /
``bpslaunch-tpu``) and the L5 fleet orchestrator (``fleet`` — role
manifests, supervised multi-process local fleets, restart-on-death;
docs/launcher.md)."""

from .fleet import (FleetManifest, FleetSupervisor, ProcessSpec,
                    run_command_fleet, run_fleet)

__all__ = ["FleetManifest", "FleetSupervisor", "ProcessSpec",
           "run_command_fleet", "run_fleet"]

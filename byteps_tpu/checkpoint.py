"""Checkpoint / resume helpers.

The reference delegates checkpointing to the frameworks and only
guarantees consistent init + stable name→key across elastic resume
(reference: SURVEY §5 checkpoint; parallel/distributed.py:43-47 note).
Here checkpointing is first-class via orbax: save/restore the full train
state (params + optimizer state + step + declared-tensor registry) so
elastic resume restores byte-identical state on a new mesh size.

Sharded variant (docs/elasticity.md): under ``BPS_SHARDED_UPDATE=1``
each replica owns 1/dp of the optimizer state —
``save_sharded_checkpoint`` persists exactly the owned slices (per-step
directories, meta as the commit marker) and
``DistributedTrainer.restore_sharded`` re-installs them into the
sharded tail, so restore composes with the sharded update instead of
falling back to the full-tree apply.
"""

from __future__ import annotations

import json
import os
from typing import Any, Optional

import jax
import numpy as np

try:
    import orbax.checkpoint as ocp
    _HAS_ORBAX = True
except Exception:  # pragma: no cover - orbax is in the image, but be safe
    _HAS_ORBAX = False


def _save_state(path: str, state: Any) -> None:
    """THE state serialization (orbax, npz fallback) — one copy shared
    by the full-tree and sharded savers so the two formats cannot
    drift."""
    if _HAS_ORBAX:
        ckptr = ocp.StandardCheckpointer()
        ckptr.save(os.path.join(path, "state"), state, force=True)
        ckptr.wait_until_finished()
    else:
        flat, _ = jax.tree_util.tree_flatten(state)
        np.savez(os.path.join(path, "state.npz"),
                 **{str(i): np.asarray(l) for i, l in enumerate(flat)})


def _restore_state(path: str, template: Any) -> Any:
    """Dual of ``_save_state`` — shared by both restore paths."""
    if _HAS_ORBAX:
        ckptr = ocp.StandardCheckpointer()
        return ckptr.restore(os.path.join(path, "state"), template)
    data = np.load(os.path.join(path, "state.npz"))
    flat, treedef = jax.tree_util.tree_flatten(template)
    return jax.tree_util.tree_unflatten(
        treedef, [data[str(i)] for i in range(len(flat))])


def save_checkpoint(path: str, params: Any, opt_state: Any = None,
                    step: int = 0, registry=None) -> None:
    """Save train state; registry declarations ride along so name→key
    survives restarts (reference: ReDeclareTensor replay).

    With gradient accumulation (``backward_passes_per_step=k``), save only
    at sync boundaries (``step % k == 0``): between them the MultiSteps
    accumulators hold per-replica local gradients, and a host read takes
    one replica's values (see ShardedTrainer docstring)."""
    path = os.path.abspath(path)
    os.makedirs(path, exist_ok=True)
    meta = {"step": step}
    if registry is not None:
        meta["declared"] = [
            {"name": d.name, "priority": d.priority,
             "kwargs": d.compression_kwargs}
            for d in (registry.get(n) for n in registry.declared_names())]
    with open(os.path.join(path, "bps_meta.json"), "w") as f:
        json.dump(meta, f)
    state = {"params": params}
    if opt_state is not None:
        state["opt_state"] = opt_state
    _save_state(path, state)


def save_sharded_checkpoint(path: str, trainer, step: Optional[int] = None,
                            embed=None) -> None:
    """Durable SHARDED state (``BPS_SHARDED_UPDATE=1``,
    docs/elasticity.md): full params (replicated — every rank holds
    them) plus THIS replica's owned 1/dp optimizer-state slices, one
    frame per owned group in the same ``pack_opt_state`` format the
    membership handoff ships through the param mailbox. Every replica
    calls this against the same path at the same step boundary: slice
    files are disjoint by ownership, and rank 0 also writes the params
    + membership meta (identical on every rank by the plan determinism
    contract). Restore composes with the sharded tail through
    ``DistributedTrainer.restore_sharded`` — the per-group slices
    install into the chunked states, so the full-tree-opt_state
    fallback never fires.

    Crash consistency: slices land in a PER-STEP directory
    (``opt_shard/s<step>/``) and ``bps_meta.json`` is renamed into
    place LAST — the meta is the checkpoint's commit marker, and it
    names the slice directory it pairs with, so an interrupted re-save
    to the same path can never mix one save's slices with another's
    params or meta.

    ``embed`` (optional, an ``EmbedClient``): the feature-store tables
    ride the same checkpoint — rank 0 fans a per-shard row snapshot
    into ``embed/s<step>/`` (``EmbedClient.save_checkpoint``, its own
    meta-last marker inside) BEFORE the top-level meta rename, and the
    meta records the embed step it pairs with. Never-written rows are
    not dumped and lazy-materialize identically after restore
    (docs/embedding.md)."""
    st = getattr(trainer, "_sharded", None)
    chunked = getattr(trainer, "_chunked", None)
    if st is None or chunked is None or not chunked.decomposable:
        raise RuntimeError(
            "save_sharded_checkpoint needs an engaged sharded update "
            "(BPS_SHARDED_UPDATE=1, dp>1, at least one step run) — use "
            "save_checkpoint for the full-tree state")
    params = trainer.params          # sync point: drains in-flight tails
    step_val = int(trainer.step_count if step is None else step)
    path = os.path.abspath(path)
    shard_dir = os.path.join(path, "opt_shard", f"s{step_val}")
    os.makedirs(shard_dir, exist_ok=True)
    from .sharded_update import pack_opt_state
    plan = st.plan
    for gi in plan.owned:
        blob = pack_opt_state(chunked.states[gi])
        tmp = os.path.join(shard_dir, f".g{gi}.bin.tmp.{os.getpid()}")
        with open(tmp, "wb") as f:
            f.write(blob)
        os.replace(tmp, os.path.join(shard_dir, f"g{gi}.bin"))
    if plan.rank != 0:
        return
    # params next, then embed (its own committed sub-marker), the
    # top-level meta rename LAST (commit marker — see docstring)
    _save_state(path, {"params": params})
    embed_meta = None
    if embed is not None:
        embed_meta = embed.save_checkpoint(
            os.path.join(path, "embed"), step_val)
    meta = {
        "step": step_val,
        "sharded": {
            "member_epoch": st.member_epoch,
            "world": plan.world,
            "live": sorted(plan.live),
            "owner": list(plan.owner),
            "name": st.name,
            "groups": [list(g) for g in plan.groups],
        },
    }
    if embed_meta is not None:
        meta["embed"] = {"dir": "embed", "step": step_val,
                         "table": embed_meta.get("table"),
                         "shards": embed_meta.get("shards")}
    tmp = os.path.join(path, f".bps_meta.json.tmp.{os.getpid()}")
    with open(tmp, "w") as f:
        json.dump(meta, f)
    os.replace(tmp, os.path.join(path, "bps_meta.json"))


def restore_sharded_checkpoint(path: str, params_like: Any, embed=None):
    """Read a sharded checkpoint: (params, {group: opt-state blob},
    step, meta). Blobs are raw ``pack_opt_state`` bytes — the caller
    (``DistributedTrainer.restore_sharded``) unpacks each against a
    fresh per-group ``inner.init`` template once the chunked tail
    builds, so structure mismatches refuse loudly there. ALL group
    slices of the COMMITTED step (the meta names its slice directory)
    are returned regardless of the saved owner map — any rank can
    adopt any group (the kill-and-replace path). Stale slices from an
    interrupted or superseded save live in other per-step directories
    and are never read.

    ``embed`` (optional, an ``EmbedClient`` dialed at the restored
    plane): when the meta carries an embed marker, the feature-store
    rows are fanned back to their shards (``restore_checkpoint`` on the
    client — epoch-bumped server-side so stale worker caches drop)."""
    path = os.path.abspath(path)
    with open(os.path.join(path, "bps_meta.json")) as f:
        meta = json.load(f)
    if embed is not None and "embed" in meta:
        em = meta["embed"]
        embed.restore_checkpoint(
            os.path.join(path, em.get("dir", "embed")),
            step=em.get("step"))
    if "sharded" not in meta:
        raise ValueError(
            f"{path} is not a sharded checkpoint (no membership meta) "
            f"— restore_checkpoint handles full-tree saves")
    state = _restore_state(path, {"params": params_like})
    shard_dir = os.path.join(path, "opt_shard", f"s{meta.get('step', 0)}")
    n_groups = len(meta["sharded"].get("groups") or []) or None
    blobs = {}
    if os.path.isdir(shard_dir):
        for fn in sorted(os.listdir(shard_dir)):
            if fn.startswith("g") and fn.endswith(".bin"):
                gi = int(fn[1:-4])
                if n_groups is not None and gi >= n_groups:
                    continue
                with open(os.path.join(shard_dir, fn), "rb") as f:
                    blobs[gi] = f.read()
    return state["params"], blobs, meta.get("step", 0), meta


def restore_checkpoint(path: str, params_like: Any, opt_state_like: Any = None):
    """Restore into the given shape/sharding templates. Returns
    (params, opt_state, step, declared)."""
    path = os.path.abspath(path)
    with open(os.path.join(path, "bps_meta.json")) as f:
        meta = json.load(f)
    template = {"params": params_like}
    if opt_state_like is not None:
        template["opt_state"] = opt_state_like
    state = _restore_state(path, template)
    return (state["params"], state.get("opt_state"), meta.get("step", 0),
            meta.get("declared", []))

"""Checkpoint / resume helpers.

The reference delegates checkpointing to the frameworks and only
guarantees consistent init + stable name→key across elastic resume
(reference: SURVEY §5 checkpoint; parallel/distributed.py:43-47 note).
Here checkpointing is first-class via orbax: save/restore the full train
state (params + optimizer state + step + declared-tensor registry) so
elastic resume restores byte-identical state on a new mesh size.
"""

from __future__ import annotations

import json
import os
from typing import Any

import jax
import numpy as np

try:
    import orbax.checkpoint as ocp
    _HAS_ORBAX = True
except Exception:  # pragma: no cover - orbax is in the image, but be safe
    _HAS_ORBAX = False


def save_checkpoint(path: str, params: Any, opt_state: Any = None,
                    step: int = 0, registry=None) -> None:
    """Save train state; registry declarations ride along so name→key
    survives restarts (reference: ReDeclareTensor replay).

    With gradient accumulation (``backward_passes_per_step=k``), save only
    at sync boundaries (``step % k == 0``): between them the MultiSteps
    accumulators hold per-replica local gradients, and a host read takes
    one replica's values (see ShardedTrainer docstring)."""
    path = os.path.abspath(path)
    os.makedirs(path, exist_ok=True)
    meta = {"step": step}
    if registry is not None:
        meta["declared"] = [
            {"name": d.name, "priority": d.priority,
             "kwargs": d.compression_kwargs}
            for d in (registry.get(n) for n in registry.declared_names())]
    with open(os.path.join(path, "bps_meta.json"), "w") as f:
        json.dump(meta, f)
    state = {"params": params}
    if opt_state is not None:
        state["opt_state"] = opt_state
    if _HAS_ORBAX:
        ckptr = ocp.StandardCheckpointer()
        ckptr.save(os.path.join(path, "state"), state, force=True)
        ckptr.wait_until_finished()
    else:
        flat, _ = jax.tree_util.tree_flatten(state)
        np.savez(os.path.join(path, "state.npz"),
                 **{str(i): np.asarray(l) for i, l in enumerate(flat)})


def restore_checkpoint(path: str, params_like: Any, opt_state_like: Any = None):
    """Restore into the given shape/sharding templates. Returns
    (params, opt_state, step, declared)."""
    path = os.path.abspath(path)
    with open(os.path.join(path, "bps_meta.json")) as f:
        meta = json.load(f)
    template = {"params": params_like}
    if opt_state_like is not None:
        template["opt_state"] = opt_state_like
    if _HAS_ORBAX:
        ckptr = ocp.StandardCheckpointer()
        state = ckptr.restore(os.path.join(path, "state"), template)
    else:
        data = np.load(os.path.join(path, "state.npz"))
        flat, treedef = jax.tree_util.tree_flatten(template)
        state = jax.tree_util.tree_unflatten(
            treedef, [data[str(i)] for i in range(len(flat))])
    return (state["params"], state.get("opt_state"), meta.get("step", 0),
            meta.get("declared", []))

"""Sharded embedding store: the PS plane as a feature store (ISSUE 18).

DLRM-style workloads touch ~100 of 10⁷–10⁸ rows per request — the
regime where a parameter server beats allreduce outright (PAPER.md:
servers sum, workers own the optimizer; arXiv 2103.00543's sparse-
regime analysis). The existing rowsparse path (server/rowsparse.py)
still DENSIFIES server-side, so a 10⁷-row table is infeasible there.
This module keeps the table sparse end to end:

  - **row-sharded tables**: a table lives in key-space above the
    bit-41/42 param/state tags (``EMBED_KEY_BASE = 1 << 43``); its
    ROWS are hash-placed across plane shards by ``row_shard`` (a pure
    fmix64 of the row id — every worker derives the identical
    placement with no coordination), and a batch's rows travel as ONE
    vectored request per shard (ids in the payload, not per-row wire
    keys).
  - **lazy materialization**: the server allocates a row on first
    touch, initialized by ``init_rows`` — a counter-based dyadic hash
    shared by server and workers, so a 10⁷-row declaration costs
    nothing and any party can reproduce a never-touched row's value
    exactly.
  - **worker-side hot-row cache** with round-versioned invalidation:
    the server bumps a per-row version on every applied push batch
    (StaleStore's per-key rounds, generalized to row granularity); a
    pull carries the cached versions and the server answers
    "unchanged" (one flag byte) or the full row. Per-row staleness
    rides the ``BPS_MAX_LAG`` contract: a COLD row may be served
    locally for up to K rounds without wire contact; a HOT row (one
    this worker pushed to) is invalidated immediately and never served
    stale. K defaults to 1 — validate every round, which makes the
    cache bitwise-transparent (tests/test_embed.py).
  - **dedup'd rowsparse push**: duplicate row hits in a batch fold
    client-side (``np.add.at`` over the unique ids) before the wire;
    the server applies the sparse sums row-wise — no densify at any
    layer.

Wire formats (transport ops OP_EMBED_INIT/PULL/PUSH, all u64 ids
little-endian via numpy, lengths framed by the transport header):

  INIT  payload = JSON table meta {table, rows, cols, dtype, seed,
        shard, shards[, replicas, addrs]}; idempotent first-wins,
        conflicting re-declare refused loudly. ``replicas``/``addrs``
        (present when replication is on) teach each server its slice's
        chain successors and how to dial them.
  PULL  payload  = n:u32 | ids:u64[n] | cached_versions:u64[n]
                   [| table_epoch:u64]
        response = table_epoch:u64 | flags:u8[n] | versions:u64[n] |
        rows (full row for each flag==1, request order). flag==0 means
        the cached version is current — no row bytes cross the wire. A
        request epoch BEHIND the table's (a failover promoted this
        server, or a snapshot restore re-seeded it, since the client
        last looked) forces every row full — cached versions from the
        pre-epoch server must never validate as "unchanged"
        (docs/embedding.md failure matrix).
  PUSH  payload = n:u32 | ids:u64[n] | deltas:dtype[n·cols]; server
        folds any remaining duplicates and applies row += delta with a
        version bump per touched row; rides the push-dedup token so a
        reconnect retry applies exactly once. With replication on, the
        applied rows' ABSOLUTE post-apply state (+ versions + the dedup
        token) is forward-logged to the slice's chain successors BEFORE
        the ack (OP_EMBED_REPL) — an acked push is never lost, and a
        retry across a failover is deduped by the token the log carried
        (exactly-once, tests/test_embed.py).

Durability (ISSUE 20): rows are replicated per SLICE — the (table,
origin shard) unit ``row_shard`` carves — along the same consistent-
hash successor walk the dense plane's ``backups_of`` rides (PR 13).
``slice_chain``/``slice_primary`` are pure functions of (key, shard
count, dead set), so every worker and every server derive identical
chains and failover routing with no coordination. A promoted successor
installs the logged absolute rows + versions (``failover``), seeds the
push-dedup tokens the log carried, and bumps the table's EPOCH so
worker hot-row caches drop versions the promoted replica never issued.
Snapshots (``snapshot_state``/``save_shard``) dump live rows +
versions + metas per shard with the checkpoint module's atomic
tmp+rename discipline; restore bumps the epoch past the saved one and
leaves never-written rows to lazy init.

The hierarchical tier (server/hier.py) is NOT a valid front for these
ops: an aggregator's local fold has no row store, and silently passing
through would split a table's rows across the agg's own upstream
sharding. ``PSTransportServer.embed_store`` refuses loudly instead —
point ``EmbedClient`` at the plane shards directly (docs/embedding.md
has the failure matrix).
"""

from __future__ import annotations

import json
import os
import struct
import threading
from collections import OrderedDict
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

# key-space room above every existing tag: bit 40 = activation
# channels, bit 41 = param-class keys, bit 42 = state/handoff keys,
# bits 48+ = striping sub-keys. Embedding tables take bit 43; the low
# 16 bits carry the table id (matching the decl<<16 convention).
EMBED_KEY_BASE = 1 << 43

_M64 = np.uint64(0xFFFFFFFFFFFFFFFF)
_FMIX_C1 = np.uint64(0xFF51AFD7ED558CCD)
_FMIX_C2 = np.uint64(0xC4CEB9FE1A85EC53)
_GOLDEN = np.uint64(0x9E3779B97F4A7C15)


def table_key(table_id: int) -> int:
    """The wire key for table ``table_id`` — one key per table (rows
    are addressed in the payload, not the key space)."""
    if not 0 <= int(table_id) < (1 << 16):
        raise ValueError(f"table id {table_id} outside [0, 65536)")
    return EMBED_KEY_BASE | (int(table_id) << 16)


def _fmix64(x: np.ndarray) -> np.ndarray:
    """MurmurHash3's 64-bit finalizer, vectorized — a full-avalanche
    integer hash, so consecutive row ids land on uncorrelated shards."""
    x = x.astype(np.uint64, copy=True)
    x ^= x >> np.uint64(33)
    x *= _FMIX_C1
    x ^= x >> np.uint64(33)
    x *= _FMIX_C2
    x ^= x >> np.uint64(33)
    return x


def row_shard(ids, num_shards: int) -> np.ndarray:
    """Deterministic row → shard placement: a PURE function of
    (row id, shard count), so every worker (and the bench's control
    arithmetic) derives the identical placement with no coordination —
    the determinism tests pin golden values against drift."""
    ids = np.asarray(ids, dtype=np.uint64).reshape(-1)
    return (_fmix64(ids) % np.uint64(num_shards)).astype(np.int64)


def init_rows(seed: int, ids, cols: int, dtype: str = "float32"
              ) -> np.ndarray:
    """Deterministic per-row initial values, counter-based (no RNG
    state): value[i, j] is a dyadic rational k/1024 · 2⁻³ derived from
    fmix64(seed, row, col). Server-side lazy materialization and any
    client-side control arithmetic reproduce a never-touched row
    byte-identically — and dyadic values keep fp32 sums EXACT, the
    property every bitwise-parity assertion in this plane rides on."""
    ids = np.asarray(ids, dtype=np.uint64).reshape(-1, 1)
    col = np.arange(int(cols), dtype=np.uint64).reshape(1, -1)
    # seed folded via Python ints (numpy SCALAR uint64 overflow warns;
    # array overflow wraps silently, which the hash relies on)
    seed_term = np.uint64((int(seed) * 0xC4CEB9FE1A85EC53)
                          & 0xFFFFFFFFFFFFFFFF)
    h = _fmix64(ids * _GOLDEN + col + seed_term)
    k = (h % np.uint64(1024)).astype(np.int64)
    return (((k - 512) / 1024.0) / 8.0).astype(np.dtype(dtype))


# ------------------------------------------------- replication chain
#
# The replication/failover unit is the SLICE: the set of a table's rows
# that ``row_shard`` places on one origin shard. Its wire key packs the
# origin into the table key's free low 16 bits (table_key uses
# ``tid << 16``), and its chain is the consistent-hash successor walk
# of that key — the same HashRing the dense plane's ``backups_of``
# rides, so placement and replication speak one geometry. All three
# functions are PURE in (key, num_shards, dead set): every worker and
# every server derive the identical chain with no coordination.

_RINGS: Dict[int, object] = {}
_RINGS_LOCK = threading.Lock()


def _ring(num_shards: int):
    r = _RINGS.get(num_shards)
    if r is None:
        with _RINGS_LOCK:
            r = _RINGS.get(num_shards)
            if r is None:
                from .plane.placement import HashRing
                r = _RINGS[num_shards] = HashRing(int(num_shards))
    return r


def slice_key(key: int, shard: int) -> int:
    """Wire key of the (table, origin shard) slice — the low 16 bits of
    a table key are free (``table_key`` packs the id at bit 16)."""
    if not 0 <= int(shard) < (1 << 16):
        raise ValueError(f"shard {shard} outside [0, 65536)")
    return int(key) | int(shard)


def slice_chain(key: int, shard: int, num_shards: int, replicas: int,
                dead=()) -> List[int]:
    """The slice's replication chain: its first ``replicas`` LIVE ring
    successors (origin excluded). ``BPS_EMBED_REPLICAS=R`` forward-logs
    every applied row here, so R successive shard deaths leave at least
    one chain member holding the slice's absolute row state."""
    skip = {int(d) for d in dead}
    skip.add(int(shard))
    return _ring(num_shards).successors(slice_key(key, shard),
                                        int(replicas), skip=skip)


def slice_primary(key: int, shard: int, num_shards: int, dead=()) -> int:
    """The shard SERVING the slice: the origin while it lives, else the
    first live ring successor — exactly where the forward log went, so
    promotion lands on the replica that already holds the rows."""
    dead = {int(d) for d in dead}
    if int(shard) not in dead:
        return int(shard)
    order = _ring(num_shards).successors(slice_key(key, shard),
                                         int(num_shards), skip=dead)
    if not order:
        raise RuntimeError(
            f"embed slice {slice_key(key, shard):#x}: no live shards "
            f"left to serve it")
    return order[0]


# ------------------------------------------------------------- server


class _Table:
    """One shard's slice of a table: rows materialize on first touch,
    each carrying a version bumped per applied push batch (the per-row
    generalization of StaleStore's per-key rounds)."""

    __slots__ = ("meta", "num_rows", "cols", "dtype", "seed", "row_nbytes",
                 "rows", "vers", "epoch", "lock")

    def __init__(self, meta: dict) -> None:
        self.meta = dict(meta)
        self.num_rows = int(meta["rows"])
        self.cols = int(meta["cols"])
        self.dtype = np.dtype(str(meta.get("dtype", "float32")))
        self.seed = int(meta.get("seed", 0))
        self.row_nbytes = self.cols * self.dtype.itemsize
        if self.num_rows <= 0 or self.cols <= 0:
            raise ValueError(f"bad table shape {self.num_rows}x{self.cols}")
        self.rows: Dict[int, np.ndarray] = {}
        self.vers: Dict[int, int] = {}
        # per-table epoch, carried in every pull response: bumped when a
        # failover promotes this server for one of the table's slices or
        # a snapshot restore re-seeds the store — a client seeing a new
        # epoch drops its cached row versions for the table instead of
        # validating them against versions this server never issued
        self.epoch = 0
        self.lock = threading.Lock()

    def _row(self, rid: int) -> np.ndarray:
        r = self.rows.get(rid)
        if r is None:
            r = init_rows(self.seed, [rid], self.cols,
                          str(self.dtype)).reshape(-1)
            self.rows[rid] = r
            self.vers[rid] = 1   # versions start at 1: a client's
            #                      "not cached" sentinel is 0
        return r

    def materialize(self, ids) -> None:
        """Batch-materialize every missing row in ``ids`` with ONE
        ``init_rows`` call (caller holds ``lock``). Per-row lazy init
        was the cold-pull bottleneck: ~2000 tiny vectorized-hash calls
        cost ~25× one 2000-row call. Values are identical by
        construction (the hash is per-(row, col), not per-batch), so
        this is pure mechanics. Rows are stored as VIEWS into the batch
        block — safe because ``apply`` rebinds rows, never writes in
        place."""
        missing = [int(r) for r in ids if int(r) not in self.rows]
        if not missing:
            return
        vals = init_rows(self.seed, missing, self.cols, str(self.dtype))
        for j, rid in enumerate(missing):
            self.rows[rid] = vals[j]
            self.vers[rid] = 1


# recent dedup tokens retained per replica slice: far beyond any retry
# window (the transport's exact-membership window is 256 seqs per
# incarnation), bounded so a long-lived chain member cannot grow without
# limit
_SLICE_TOKENS = 4096


class EmbedRowStore:
    """Server-side sharded row store (transport-owned, like the act and
    param mailboxes — every deployment's server role speaks it, raw
    PSServer engines included).

    ``dedup_seed(table_key, token)`` — when given (the transport passes
    its push-dedup adopter) — lets a failover promotion seed the tokens
    its replica log carried, so a worker retrying a push across the
    failover is acknowledged without re-applying (exactly-once)."""

    def __init__(self, dedup_seed=None) -> None:
        self._tables: Dict[int, _Table] = {}
        self._lock = threading.Lock()
        self._dedup_seed = dedup_seed
        # replication config, learned from the first INIT meta carrying
        # it (the client sends replicas+addrs when replication is on;
        # with replicas == 0 none of the state below is ever touched —
        # the serve path stays byte-for-byte the PR-18 one)
        self.shard = 0
        self.num_shards = 1
        self.replicas = 0
        self.addrs: List[str] = []
        self._dead: set = set()
        # slices hosted FOR other shards: slice key -> {"rows":
        # {rid: (bytes, version)}, "tokens": OrderedDict (recency)}
        self._replica: Dict[int, dict] = {}
        # slices this server was promoted for (idempotent failover) and,
        # per slice this server forwards, the chain members known to
        # hold every record so far (a member joining after a chain death
        # gets one full-slice sync before deltas resume)
        self._promoted: set = set()
        self._chain_ok: Dict[int, set] = {}
        self._peers: Dict[int, object] = {}
        self._peer_lock = threading.Lock()
        from ..obs.metrics import get_registry
        reg = get_registry()
        self._m_repl_rows = reg.counter("embed/replicated_rows")
        self._m_replays = reg.counter("embed/failover_replays")
        self._m_epochs = reg.counter("embed/epoch_bumps")

    def init_table(self, key: int, meta: dict) -> None:
        """Idempotent first-wins declaration; a conflicting re-declare
        (different shape/dtype/seed) is a mis-built worker and refused
        loudly rather than silently serving rows at wrong offsets."""
        fresh = _Table(meta)
        with self._lock:
            # replication config rides the INIT meta (first-wins, like
            # the table declaration itself); shard/shards describe THIS
            # server's place in the plane, so a later table re-declares
            # the same values
            if int(meta.get("replicas", 0) or 0) > 0 and not self.addrs:
                self.shard = int(meta.get("shard", 0))
                self.num_shards = int(meta.get("shards", 1))
                self.replicas = max(0, min(int(meta["replicas"]),
                                           self.num_shards - 1))
                self.addrs = list(meta.get("addrs") or [])
            cur = self._tables.get(key)
            if cur is None:
                self._tables[key] = fresh
                return
            for f in ("rows", "cols", "dtype", "seed"):
                a, b = cur.meta.get(f), fresh.meta.get(f)
                if str(a) != str(b):
                    raise ValueError(
                        f"embed table {key:#x}: conflicting re-declare "
                        f"({f}: {a} != {b}) — workers disagree on the "
                        f"table")

    def table(self, key: int) -> _Table:
        t = self._tables.get(key)
        if t is None:
            raise KeyError(f"embed table {key:#x} not declared "
                           f"(OP_EMBED_INIT first)")
        return t

    def pull(self, key: int, payload) -> Tuple[bytes, bytes, bytes, bytes]:
        """Conditional sparse pull. Parses ``n | ids | cached_vers
        [| epoch]``; returns (epoch u64, flags u8[n], versions u64[n],
        row bytes for the flagged ids, request order). A client epoch
        BEHIND the table's means the cached versions were issued by a
        server this one replaced (failover) or a pre-restore
        incarnation — every row is served FULL rather than trusting a
        version match that means nothing across the epoch. Rows are
        copied into ONE contiguous buffer under the table lock — a
        concurrent push mutates rows in place, and a torn row on the
        wire would be a silent corruption; the epoch/flags/vers/rowbuf
        quad then rides one vectored sendmsg with no further join."""
        t = self.table(key)
        (n,) = struct.unpack_from("<I", payload, 0)
        ids = np.frombuffer(payload, np.uint64, count=n, offset=4)
        vers = np.frombuffer(payload, np.uint64, count=n, offset=4 + 8 * n)
        cep = 0
        if len(payload) >= 4 + 16 * n + 8:
            (cep,) = struct.unpack_from("<Q", payload, 4 + 16 * n)
        if np.any(ids >= np.uint64(t.num_rows)):
            raise ValueError(f"row id out of range [0, {t.num_rows})")
        flags = np.zeros(n, np.uint8)
        out_vers = np.zeros(n, np.uint64)
        chunks: List[np.ndarray] = []
        with t.lock:
            ep = t.epoch
            stale_epoch = cep < ep   # pre-epoch cache (or a legacy
            #                          epochless request): versions do
            #                          not validate — full rows
            t.materialize(ids)
            for i in range(n):
                rid = int(ids[i])
                row = t.rows[rid]
                v = t.vers[rid]
                out_vers[i] = v
                if stale_epoch or v != int(vers[i]):
                    flags[i] = 1
                    chunks.append(row)
            rowbuf = (np.concatenate(chunks).tobytes() if chunks
                      else b"")
        return (struct.pack("<Q", ep), flags.tobytes(),
                out_vers.tobytes(), rowbuf)

    def apply(self, key: int, payload, token: int = 0) -> int:
        """Row-wise sparse apply: ``row += delta`` with a version bump
        per touched row — NO dense expansion at any size. Clients fold
        duplicates before the wire; any that remain (a raw client) fold
        here first so each row's version moves once per push batch.
        Returns the number of rows touched.

        With replication on, the touched rows' ABSOLUTE post-apply
        state + versions are forward-logged to the slice's chain
        successors before this returns (and therefore before the
        transport acks) — chain-replication's invariant that an acked
        mutation survives the primary. ``token`` is the push-dedup
        token; it rides the log so a promoted replica can refuse a
        worker's cross-failover retry of an already-replicated push."""
        t = self.table(key)
        (n,) = struct.unpack_from("<I", payload, 0)
        ids = np.frombuffer(payload, np.uint64, count=n, offset=4)
        deltas = np.frombuffer(payload, t.dtype, offset=4 + 8 * n)
        if n == 0:
            return 0
        if deltas.size != n * t.cols:
            raise ValueError(f"delta payload {deltas.size} != "
                             f"{n}x{t.cols} rows")
        if np.any(ids >= np.uint64(t.num_rows)):
            raise ValueError(f"row id out of range [0, {t.num_rows})")
        deltas = deltas.reshape(n, t.cols)
        uniq, inv = np.unique(ids, return_inverse=True)
        if uniq.size != n:
            folded = np.zeros((uniq.size, t.cols), t.dtype)
            np.add.at(folded, inv, deltas)
        else:
            folded = deltas
        fwd = None
        with t.lock:
            t.materialize(uniq)
            for i in range(uniq.size):
                rid = int(uniq[i])
                t.rows[rid] = t.rows[rid] + folded[i]
                t.vers[rid] += 1
            if self.replicas > 0 and self.num_shards > 1 and self.addrs:
                # snapshot the post-apply state INSIDE the lock — the
                # forwarded record must be the exact bytes this apply
                # produced, not whatever a racing push left behind
                fwd = (np.stack([t.rows[int(r)] for r in uniq]),
                       np.array([t.vers[int(r)] for r in uniq],
                                np.uint64))
        if fwd is not None:
            self._forward(key, uniq, fwd[1], fwd[0], token)
        return int(uniq.size)

    # ------------------------------------------------ replication chain

    def _peer(self, b: int):
        """Lazily-dialed transport client for peer shard ``b`` —
        single-address, like the plane's shard clients."""
        p = self._peers.get(b)
        if p is None:
            with self._peer_lock:
                p = self._peers.get(b)
                if p is None:
                    from .transport import RemotePSBackend
                    p = self._peers[b] = RemotePSBackend(
                        [self.addrs[b]], lazy_dial=True,
                        conns_per_shard=1,
                        reconnect_secs=_embed_reconnect_secs())
        return p

    def _forward(self, key: int, uniq: np.ndarray, vers: np.ndarray,
                 rows: np.ndarray, token: int) -> None:
        """Forward one apply's absolute row state to the chain of every
        origin slice it touched (one slice on the healthy path — the
        client groups pushes per origin; several only after failovers
        landed foreign slices here). A chain member dying mid-forward
        is a shard death like any other: mark it dead, recompute the
        chain, full-sync any member that joined it, keep forwarding —
        the apply that produced this record was healthy and must not
        error. TimeoutError stays an application answer and surfaces."""
        origins = row_shard(uniq, self.num_shards)
        for o in np.unique(origins):
            o = int(o)
            mask = origins == o
            rec = (struct.pack("<I", int(mask.sum()))
                   + uniq[mask].tobytes() + vers[mask].tobytes()
                   + np.ascontiguousarray(rows[mask]).tobytes())
            skey = slice_key(key, o)
            chain = [b for b in slice_chain(key, o, self.num_shards,
                                            self.replicas, self._dead)
                     if b != self.shard]
            known = self._chain_ok.setdefault(skey, set(chain))
            fails = 0
            while chain:
                b = chain[0]
                try:
                    if b not in known:
                        self._sync_slice(key, o, b)
                        known.add(b)
                    self._peer(b).embed_repl(skey, token, rec)
                    self._m_repl_rows.inc(int(mask.sum()))
                    chain = chain[1:]
                except TimeoutError:
                    raise
                except (ConnectionError, OSError) as e:
                    fails += 1
                    if fails > self.num_shards:
                        raise
                    self._dead.add(b)
                    from ..common.logging import get_logger
                    get_logger().warning(
                        "embed: chain member s%d unreachable (%s) — "
                        "recomputing slice %#x's chain", b, e, skey)
                    chain = [c for c in slice_chain(
                        key, o, self.num_shards, self.replicas,
                        self._dead) if c != self.shard]

    def _sync_slice(self, key: int, origin: int, peer: int) -> None:
        """Full-slice catch-up for a chain member that joined after the
        slice's birth (a prior member died): every live row of the
        origin's slice, absolute, token-less. Rare (membership events
        only) — never on the per-push path."""
        t = self.table(key)
        with t.lock:
            live = np.array(sorted(t.rows), np.uint64)
            if not live.size:
                return
            arr = live[row_shard(live, self.num_shards) == origin]
            if not arr.size:
                return
            rids = [int(r) for r in arr]
            rec = (struct.pack("<I", len(rids)) + arr.tobytes()
                   + np.array([t.vers[r] for r in rids],
                              np.uint64).tobytes()
                   + np.stack([t.rows[r] for r in rids]).tobytes())
        self._peer(peer).embed_repl(slice_key(key, origin), 0, rec)

    def repl_apply(self, skey: int, token: int, payload) -> int:
        """Install a forwarded record into the slice's replica log:
        absolute rows + versions, last-wins per row by version (frames
        from one primary are ordered per connection; a full-sync frame
        racing a delta must not roll a row back). The dedup token is
        retained (bounded recency window) so a failover promotion can
        seed the transport's push dedup with every replicated push."""
        tkey = int(skey) & ~0xFFFF
        t = self.table(tkey)   # declared on every shard by the client
        (n,) = struct.unpack_from("<I", payload, 0)
        ids = np.frombuffer(payload, np.uint64, count=n, offset=4)
        vers = np.frombuffer(payload, np.uint64, count=n, offset=4 + 8 * n)
        rows = np.frombuffer(payload, t.dtype, offset=4 + 16 * n)
        if rows.size != n * t.cols:
            raise ValueError(f"replica payload {rows.size} != "
                             f"{n}x{t.cols} rows")
        rows = rows.reshape(n, t.cols)
        with self._lock:
            sl = self._replica.get(int(skey))
            if sl is None:
                sl = self._replica[int(skey)] = {
                    "rows": {}, "tokens": OrderedDict()}
            for i in range(n):
                rid = int(ids[i])
                old = sl["rows"].get(rid)
                if old is None or int(vers[i]) >= old[1]:
                    sl["rows"][rid] = (rows[i].tobytes(), int(vers[i]))
            if token:
                sl["tokens"][int(token)] = None
                sl["tokens"].move_to_end(int(token))
                while len(sl["tokens"]) > _SLICE_TOKENS:
                    sl["tokens"].popitem(last=False)
        return int(n)

    def failover(self, skey: int, dead, observe: bool = False) -> dict:
        """Promote this server for a slice whose primary died: install
        the replica log's absolute rows + versions into the serving
        table, seed the replicated dedup tokens, bump the table epoch.
        Idempotent per slice (a second client racing the first gets the
        same answer without a second epoch bump). Per-row install
        errors are COLLECTED — every remaining row is still installed
        and the epoch still bumps — and the first is re-raised after
        the loop (the PR-13 ``fail_shard`` hardening: a double death
        mid-replay must never leave the slice half-promoted forever).

        ``observe=True`` adopts the dead set WITHOUT promoting — the
        client broadcasts it to the healthy shards so their forward
        chains skip the corpse immediately instead of each paying one
        dial window discovering it on their next push."""
        skey = int(skey)
        tkey = skey & ~0xFFFF
        src = skey & 0xFFFF
        t = self.table(tkey)
        if observe:
            with self._lock:
                self._dead.update(int(d) for d in (dead or ()))
                self._dead.discard(self.shard)
            with t.lock:
                return {"observed": True, "epoch": t.epoch}
        with self._lock:
            self._dead.update(int(d) for d in (dead or ()))
            self._dead.discard(self.shard)
            already = skey in self._promoted
            self._promoted.add(skey)
            sl = self._replica.get(skey)
            tokens = list(sl["tokens"]) if sl is not None else []
        installed = 0
        errors = 0
        first_err: Optional[BaseException] = None
        if not already:
            with t.lock:
                if sl is not None:
                    for rid, (buf, ver) in list(sl["rows"].items()):
                        try:
                            arr = np.frombuffer(buf, t.dtype)
                            if arr.size != t.cols:
                                raise ValueError(
                                    f"row {rid}: {arr.size} elems != "
                                    f"{t.cols} cols")
                            t.rows[rid] = arr.copy()
                            t.vers[rid] = int(ver)
                            installed += 1
                        except Exception as e:   # noqa: BLE001 — collected
                            errors += 1
                            if first_err is None:
                                first_err = e
                t.epoch += 1
                epoch = t.epoch
            self._m_replays.inc()
            self._m_epochs.inc()
            if self._dedup_seed is not None:
                for tok in tokens:
                    self._dedup_seed(tkey, tok)
            from ..common.logging import get_logger
            get_logger().warning(
                "embed: promoted for slice %#x (origin shard s%d): "
                "%d row(s) installed, %d error(s), table epoch -> %d",
                skey, src, installed, errors, epoch)
        else:
            with t.lock:
                epoch = t.epoch
        if first_err is not None:
            raise first_err
        return {"table": (tkey >> 16) & 0xFFFF, "slice": src,
                "rows": installed, "errors": errors, "epoch": epoch,
                "already": bool(already)}

    # ---------------------------------------------------- durable state

    def snapshot_state(self) -> Dict[str, np.ndarray]:
        """This shard's live embed state as npz-ready arrays — one
        ``e<key>|{meta,ids,vers,rows}`` quad per table. Only
        MATERIALIZED rows are dumped (never-written rows lazy-init
        identically after restore); the replica log is NOT dumped (the
        primary's own snapshot is the durable copy of its slice)."""
        out: Dict[str, np.ndarray] = {}
        with self._lock:
            tables = list(self._tables.items())
        for key, t in tables:
            with t.lock:
                rids = sorted(t.rows)
                meta = dict(t.meta)
                meta["epoch"] = t.epoch
                out[f"e{key}|meta"] = np.frombuffer(
                    json.dumps(meta).encode(), np.uint8)
                out[f"e{key}|ids"] = np.array(rids, np.uint64)
                out[f"e{key}|vers"] = np.array(
                    [t.vers[r] for r in rids], np.uint64)
                rows = (np.stack([t.rows[r] for r in rids])
                        if rids else np.zeros((0, t.cols), t.dtype))
                out[f"e{key}|rows"] = rows.reshape(-1).view(np.uint8)
        return out

    def restore_state(self, entries: Dict[str, np.ndarray]) -> int:
        """Re-seed tables from ``snapshot_state`` arrays. The restored
        epoch is the saved one PLUS ONE: any client still holding row
        versions from the pre-restart server must drop them (pushes
        applied after the snapshot are gone — serving "unchanged"
        against their versions would resurrect lost writes silently).
        Never-written rows stay absent and lazy-materialize exactly as
        before. Returns the number of rows restored."""
        keys = sorted({int(name[1:].split("|", 1)[0])
                       for name in entries if name.startswith("e")})
        total = 0
        for key in keys:
            meta = json.loads(bytes(entries[f"e{key}|meta"].tobytes()
                                    ).decode())
            saved_epoch = int(meta.pop("epoch", 0))
            self.init_table(key, meta)
            t = self.table(key)
            ids = entries[f"e{key}|ids"].astype(np.uint64)
            vers = entries[f"e{key}|vers"].astype(np.uint64)
            rows = np.frombuffer(entries[f"e{key}|rows"].tobytes(),
                                 t.dtype).reshape(ids.size, t.cols)
            with t.lock:
                for i in range(ids.size):
                    rid = int(ids[i])
                    t.rows[rid] = rows[i].copy()
                    t.vers[rid] = int(vers[i])
                t.epoch = max(t.epoch, saved_epoch + 1)
            total += int(ids.size)
            self._m_epochs.inc()
        return total

    def save_shard(self, path: str) -> dict:
        """Atomic npz dump of ``snapshot_state`` (tmp + os.replace, the
        checkpoint module's discipline) — the OP_EMBED_SNAP handler."""
        state = self.snapshot_state()
        tmp = f"{path}.tmp.{os.getpid()}.npz"
        np.savez(tmp, **state)
        os.replace(tmp, path)
        rows = sum(int(v.size) for k, v in state.items()
                   if k.endswith("|ids"))
        return {"tables": sum(1 for k in state if k.endswith("|meta")),
                "rows": rows, "path": path}

    def restore_shard(self, path: str) -> dict:
        data = np.load(path)
        rows = self.restore_state({n: data[n] for n in data.files})
        return {"rows": rows, "path": path}

    def close(self) -> None:
        with self._peer_lock:
            peers, self._peers = list(self._peers.values()), {}
        for p in peers:
            try:
                p.close()
            except Exception:   # noqa: BLE001 — best-effort teardown
                pass


# ------------------------------------------------------------- client


def _env_int(name: str, default: int) -> int:
    v = os.environ.get(name, "")
    return int(v) if v else default


def _env_float(name: str, default: float) -> float:
    v = os.environ.get(name, "")
    return float(v) if v else default


def _embed_reconnect_secs() -> float:
    """Dial-retry window for replicated embed connections (client→
    shard and server→successor). The plane's 30s BPS_RECONNECT_SECS
    default assumes reconnect IS the recovery story; with a replica
    chain it inverts — a dead peer should surface fast so the ring
    reroutes, bounding the stall a death injects to ~one dial window
    (BPS_EMBED_RECONNECT_SECS, default 2s)."""
    return _env_float("BPS_EMBED_RECONNECT_SECS", 2.0)


class EmbedClient:
    """Worker-side sharded table client: sparse row pull with a
    hot-row cache, dedup'd rowsparse push, one vectored request per
    shard.

    ``handles`` are per-shard transport clients (single-address
    ``RemotePSBackend``s — the plane-backend idiom), indexed by the
    SAME shard order on every worker; ``row_shard`` routes rows.

    Cache protocol (docs/embedding.md): an entry is (row, version,
    validated_round). A row is served purely locally while
    ``round - validated_round < K`` (K = ``BPS_EMBED_MAX_LAG``,
    defaulting to ``BPS_MAX_LAG``, defaulting to 1); past that window
    it is re-validated CONDITIONALLY — the cached version rides the
    pull and the server sends one flag byte instead of the row when
    nothing changed. A push from THIS worker invalidates its rows
    immediately (the hot-row half of the staleness contract). At K=1
    the cache is bitwise-transparent: every served value is validated
    against the server's current version each round."""

    def __init__(self, handles: Sequence, table_id: int, num_rows: int,
                 cols: int, dtype: str = "float32", seed: int = 0,
                 cache_rows: Optional[int] = None,
                 max_lag: Optional[int] = None,
                 timeout_ms: int = 30000,
                 replicas: Optional[int] = None,
                 addrs: Optional[Sequence[str]] = None) -> None:
        if not handles:
            raise ValueError("EmbedClient needs at least one shard handle")
        self._handles = list(handles)
        self._owned: List = []
        self.key = table_key(table_id)
        self.table_id = int(table_id)
        # replication: BPS_EMBED_REPLICAS defaults to the dense plane's
        # BPS_PLANE_REPLICAS (one survivability story per deployment),
        # clamped to the shard count like the plane does. addrs teach
        # the SERVERS how to dial their chain successors — replication
        # needs them (EmbedClient.connect supplies its dial list).
        if replicas is None:
            replicas = _env_int("BPS_EMBED_REPLICAS",
                                _env_int("BPS_PLANE_REPLICAS", 0))
        self.replicas = max(0, min(int(replicas), len(self._handles) - 1))
        self._addrs = list(addrs or [])
        if self.replicas > 0 and not self._addrs:
            raise ValueError(
                "embed replication needs the shard address list (use "
                "EmbedClient.connect, or pass addrs=) — servers "
                "forward-log row state to their chain successors and "
                "must be able to dial them")
        self._dead: set = set()
        self._fail_lock = threading.Lock()
        self._epoch = 0
        self.failovers = 0
        self._liveness_warned: set = set()
        # cross-failover push-dedup tokens: ONE generator for the whole
        # client (not per shard handle) — a retried push must land on
        # the promoted replica with the token the dead primary's chain
        # already logged, and tokens from different origin slices must
        # never collide once a failover merges them onto one server
        self._inc = int.from_bytes(os.urandom(4), "big") or 1
        self._seq = 0
        self._seq_lock = threading.Lock()
        self.num_rows = int(num_rows)
        self.cols = int(cols)
        self.dtype = np.dtype(dtype)
        self.seed = int(seed)
        self.row_nbytes = self.cols * self.dtype.itemsize
        self._timeout_ms = int(timeout_ms)
        self.cache_rows = (_env_int("BPS_EMBED_CACHE_ROWS", 65536)
                           if cache_rows is None else int(cache_rows))
        self.max_lag = (max(1, _env_int("BPS_EMBED_MAX_LAG",
                                        _env_int("BPS_MAX_LAG", 1)))
                        if max_lag is None else max(1, int(max_lag)))
        # row_id -> [row array, server version, validated_round]; LRU
        # by OrderedDict recency
        self._cache: "OrderedDict[int, list]" = OrderedDict()
        self._round = 1
        self._pool = None
        self._pool_lock = threading.Lock()
        self.last_fetch_s = 0.0   # wire time of the latest pull's
        #                           fan-out — the p99 row-fetch column
        from ..obs.metrics import get_registry
        reg = get_registry()
        self._m_hits = reg.counter("embed/cache_hits")
        self._m_miss = reg.counter("embed/cache_misses")
        self._m_fetch_bytes = reg.counter("embed/row_fetch_bytes")
        self._m_rows_pushed = reg.counter("embed/rows_pushed")
        self._m_epoch_bumps = reg.counter("embed/epoch_bumps")
        self._m_hot = reg.gauge("embed/hot_set_size")
        meta = {"table": int(table_id), "rows": self.num_rows,
                "cols": self.cols, "dtype": str(self.dtype),
                "seed": self.seed, "shards": len(self._handles)}
        if self.replicas > 0:
            meta["replicas"] = self.replicas
            meta["addrs"] = self._addrs
        failed: List[Tuple[int, BaseException]] = []
        for s, h in enumerate(self._handles):
            try:
                h.embed_init(self.key, dict(meta, shard=s))
            except TimeoutError:
                raise
            except (ConnectionError, OSError) as e:
                # a client joining a plane that ALREADY lost a shard
                # (the verify client after a kill, an elastic
                # replacement worker): construction must survive and
                # promote, not crash — replicas=0 keeps the old loud
                # failure via fail_shard below
                failed.append((s, e))
        for s, e in failed:
            self.fail_shard(s, cause=e)

    @classmethod
    def connect(cls, addrs: Sequence[str], table_id: int, num_rows: int,
                cols: int, **kw) -> "EmbedClient":
        """Dial one single-address transport client per shard (owned —
        closed by ``close``) and declare the table on each. Lazy dial:
        a dead shard surfaces on its INIT rpc (handled by the ctor's
        failover path when replication is on), never as a constructor
        crash before the live shards were even declared."""
        from .transport import RemotePSBackend
        reps = kw.get("replicas")
        if reps is None:
            reps = _env_int("BPS_EMBED_REPLICAS",
                            _env_int("BPS_PLANE_REPLICAS", 0))
        # replication inverts the reconnect story (see
        # _embed_reconnect_secs); without it, keep the plane default
        rc = ({"reconnect_secs": _embed_reconnect_secs()}
              if int(reps) > 0 else {})
        handles = [RemotePSBackend([a], lazy_dial=True, **rc)
                   for a in addrs]
        cli = cls(handles, table_id, num_rows, cols,
                  addrs=list(addrs), **kw)
        cli._owned = handles
        return cli

    # ------------------------------------------------------- liveness

    def _token(self) -> int:
        """Next push-dedup token (incarnation<<32 | seq) — allocated
        once per shard batch and REUSED verbatim by the cross-failover
        retry, so the promoted replica's seeded dedup recognizes it."""
        with self._seq_lock:
            self._seq += 1
            if self._seq > 0xFFFFFFFF:
                self._inc = int.from_bytes(os.urandom(4), "big") or 1
                self._seq = 1
            return (self._inc << 32) | self._seq

    def _primary(self, shard: int) -> int:
        """The shard SERVING origin ``shard``'s slice under the current
        dead set — the pure ring walk every party shares."""
        if shard not in self._dead:
            return shard
        return slice_primary(self.key, shard, len(self._handles),
                             self._dead)

    def fail_shard(self, shard: int,
                   cause: Optional[BaseException] = None) -> None:
        """Reroute + promote: mark the shard dead and ask the acting
        primary of every dead origin's slice to install its replica log
        (OP_EMBED_FAILOVER — idempotent server-side, so racing workers
        and repeated deaths converge). Without replication there is
        nothing to promote — the original error propagates loudly, the
        plane's contract. Per-slice promotion errors are collected and
        the first re-raised AFTER every slice was attempted (double
        death must not strand later slices unpromoted forever)."""
        shard = int(shard)
        with self._fail_lock:
            if shard in self._dead or not 0 <= shard < len(self._handles):
                return
            if self.replicas <= 0:
                if cause is not None:
                    raise cause
                raise RuntimeError(
                    f"embed shard {shard} unreachable and replication "
                    f"is off (BPS_EMBED_REPLICAS=0) — no replica log "
                    f"to fail over onto")
            self._dead.add(shard)
            dead = set(self._dead)
        self.failovers += 1
        if len(dead) > len(self._handles) - 1:
            raise RuntimeError("embed plane: no live shards left")
        from ..common.logging import get_logger
        from ..obs import flight
        get_logger().warning(
            "embed: shard %d unreachable (%s) — failing table %d over "
            "(dead=%s)", shard, cause, self.table_id, sorted(dead))
        first_err: Optional[BaseException] = None
        for o in sorted(dead):
            p = self._primary(o)
            body = json.dumps({"dead": sorted(dead)}).encode()
            try:
                resp = self._handles[p].embed_failover(
                    slice_key(self.key, o), body,
                    timeout_ms=self._timeout_ms)
                st = json.loads(bytes(resp).decode())
            except TimeoutError:
                raise
            except (ConnectionError, OSError) as e:
                if first_err is None:
                    first_err = e
                continue
            # membership events are FIRST-CLASS flight events, recorded
            # key-less like the dense plane's (a postmortem under any
            # key filter sees the epoch transition)
            flight.record(
                "embed_failover", outcome="failover",
                detail=f"table {st.get('table', self.table_id)} slice "
                       f"s{o} -> s{p}; rows={st.get('rows', 0)} "
                       f"epoch={st.get('epoch', 0)}")
            self._adopt_epoch(int(st.get("epoch", 0)))
        # broadcast the dead set to the OTHER live shards (observe-only
        # — no promotion) so their forward chains skip the corpse now
        # instead of each paying one dial window on its next push.
        # Best-effort: a shard that misses it discovers on its own.
        primaries = {self._primary(o) for o in dead}
        obs = json.dumps({"dead": sorted(dead),
                          "observe": True}).encode()
        for s in range(len(self._handles)):
            if s in dead or s in primaries:
                continue
            try:
                self._handles[s].embed_failover(
                    self.key, obs, timeout_ms=self._timeout_ms)
            except (TimeoutError, ConnectionError, OSError):
                pass
        if first_err is not None:
            raise first_err

    def note_stale(self, shard: int, age_s: Optional[float] = None,
                   source: str = "fleet") -> bool:
        """Scraper-observed liveness, ACTED ON (the plane backend's
        contract, mirrored): a black-holed shard — answering no scrape
        for 3 cadences, not just refusing connections — is declared
        dead and failed over. replicas=0 keeps the verdict
        observed-only with one warning per shard."""
        if not 0 <= int(shard) < len(self._handles):
            return False
        shard = int(shard)
        if shard in self._dead:
            return False
        if self.replicas <= 0:
            if shard not in self._liveness_warned:
                self._liveness_warned.add(shard)
                from ..common.logging import get_logger
                get_logger().warning(
                    "embed: shard %d stale per %s (scrape age %.1fs) "
                    "but replication is off — liveness verdict stays "
                    "observed-only (no replica log to fail over onto)",
                    shard, source,
                    age_s if age_s is not None else -1.0)
            return False
        from ..obs import flight
        flight.record(
            "member_leave",
            detail=f"embed shard {shard} declared dead by {source} "
                   f"(scrape age {age_s if age_s is not None else '?'}s)")
        self.fail_shard(shard, cause=TimeoutError(
            f"{source}: scrape age "
            f"{age_s if age_s is not None else '?'}s past the "
            f"staleness line — black-holed embed shard declared dead"))
        return True

    def stats(self, timeout_ms: int = 5000) -> Dict[str, dict]:
        """Fleet stats surface over the shard handles (the plane
        backend's shape) so a ``FleetScraper`` can watch embed shards
        and drive ``note_stale`` — per-shard failures become error
        entries, never exceptions on the scrape thread."""
        out: Dict[str, dict] = {}
        for i, h in enumerate(self._handles):
            label = f"s{i}"
            if i in self._dead:
                out[label] = {"error": "failed over (shard marked dead)"}
                continue
            try:
                out[label] = h.stats_shard(0, timeout_ms)
            except Exception as e:   # noqa: BLE001 — per-shard isolation
                out[label] = {"error": f"{type(e).__name__}: {e}"}
        return out

    def _adopt_epoch(self, epoch: int) -> None:
        """A pull response (or failover answer) carried a table epoch
        ahead of ours: the rows we cached were versioned by a server
        that no longer serves them — drop the WHOLE table cache rather
        than ever validating a stale version as \"unchanged\"
        (satellite fix, docs/embedding.md failure matrix)."""
        if epoch <= self._epoch:
            return
        dropped = len(self._cache)
        self._cache.clear()
        self._epoch = int(epoch)
        self._m_epoch_bumps.inc()
        self._m_hot.set(0)
        if dropped:
            from ..obs import flight
            flight.record("cache_inval", round=self._round,
                          detail=f"epoch {epoch}: rows={dropped}")

    # ------------------------------------------------------------ pull

    def tick(self) -> None:
        """Advance the client's round — one call per training step; the
        denominator of every staleness-window decision."""
        self._round += 1

    def _fanout(self, fn, items):
        """Run ``fn`` over per-shard work items, in parallel when more
        than one shard has work (one small pool, shards-wide)."""
        if len(items) == 1:
            return [fn(items[0])]
        if self._pool is None:
            with self._pool_lock:
                if self._pool is None:
                    from concurrent.futures import ThreadPoolExecutor
                    self._pool = ThreadPoolExecutor(
                        max_workers=len(self._handles),
                        thread_name_prefix="bps-embed")
        return list(self._pool.map(fn, items))

    def pull(self, ids) -> np.ndarray:
        """Fetch the current rows for ``ids`` (duplicates allowed —
        resolved through one lookup per unique row). Only rows outside
        the local staleness window touch the wire, one vectored request
        per shard; of those, only rows whose version MOVED transfer
        bytes."""
        import time as _time
        ids = np.asarray(ids, dtype=np.uint64).reshape(-1)
        uniq, inv = np.unique(ids, return_inverse=True)
        out = np.empty((uniq.size, self.cols), self.dtype)
        need: List[int] = []       # positions in uniq that go to the wire
        hits = 0
        for i in range(uniq.size):
            rid = int(uniq[i])
            ent = self._cache.get(rid)
            if ent is not None and self._round - ent[2] < self.max_lag:
                out[i] = ent[0]          # cold row inside the K window:
                self._cache.move_to_end(rid)   # no wire contact at all
                hits += 1
            else:
                need.append(i)
        fetched_bytes = 0
        t0 = _time.time()
        if need:
            shards = row_shard(uniq[need], len(self._handles))
            work = []
            for s in range(len(self._handles)):
                pos = [need[j] for j in range(len(need)) if shards[j] == s]
                if pos:
                    # grouped per ORIGIN shard, ROUTED to its acting
                    # primary — one unit per slice, so the failover
                    # retry below re-resolves routing per item
                    work.append((s, pos))

            def one(item):
                s, pos = item
                rids = uniq[pos]
                # cached rows captured WITH the versions we send: a
                # flag==0 answer references these, and they must
                # survive an epoch bump from ANOTHER shard's response
                # clearing the cache while this one is decoded
                kept = {int(r): self._cache[int(r)]
                        for r in rids if int(r) in self._cache}
                vers = np.array(
                    [kept[int(r)][1] if int(r) in kept else 0
                     for r in rids], np.uint64)
                payload = (struct.pack("<I", len(pos)) + rids.tobytes()
                           + vers.tobytes()
                           + struct.pack("<Q", self._epoch))
                # the acting primary is captured WITH the attempt — the
                # failover below must blame the shard the op actually
                # ran on, not whatever routing resolves to after a
                # concurrent failure already moved it
                p = self._primary(s)
                try:
                    return pos, kept, self._handles[p].embed_pull(
                        self.key, payload,
                        timeout_ms=self._timeout_ms), None
                except TimeoutError:
                    raise
                except (ConnectionError, OSError) as e:
                    return item, p, None, e

            results = self._fanout(one, work)
            retries = [(item, p, err) for item, p, _r, err in results
                       if err is not None]
            if retries:
                # one failover + one retry against the new routing —
                # the plane backend's shape. fail_shard is idempotent;
                # replicas=0 re-raises the cause there (loud).
                for _item, p, err in retries:
                    self.fail_shard(p, cause=err)
                for item, _p, _err in retries:
                    pos, kept, resp, err = one(item)
                    if err is not None:
                        raise err
                    results.append((pos, kept, resp, None))
            for pos, kept, resp, err in results:
                if err is not None:
                    continue                 # retried above
                n = len(pos)
                (rep,) = struct.unpack_from("<Q", resp, 0)
                if rep > self._epoch:
                    # a failover/restore bumped the table since we last
                    # looked: every cached version is void. The server
                    # already force-sent full rows for THIS response
                    # (our request epoch was behind) — drop the rest.
                    self._adopt_epoch(rep)
                flags = np.frombuffer(resp, np.uint8, count=n, offset=8)
                vers = np.frombuffer(resp, np.uint64, count=n,
                                     offset=8 + n)
                rows = np.frombuffer(resp, self.dtype,
                                     offset=8 + n + 8 * n)
                rows = rows.reshape(-1, self.cols).copy()
                fetched_bytes += rows.nbytes
                # cache entries hold VIEWS into the one block copy
                # above — a per-row np copy on this path measurably
                # rivals the wire time at DLRM batch sizes
                r = 0
                for j in range(n):
                    i = pos[j]
                    rid = int(uniq[i])
                    if flags[j]:
                        row = rows[r]
                        out[i] = row
                        r += 1
                        self._m_miss.inc()
                    else:
                        # version unchanged: the cached bytes are
                        # current — a validated hit, zero row bytes
                        row = kept[rid][0]
                        out[i] = row
                        self._m_hits.inc()
                    self._cache_put(rid, row, int(vers[j]))
        self.last_fetch_s = _time.time() - t0
        if hits:
            self._m_hits.inc(hits)
        if fetched_bytes:
            self._m_fetch_bytes.inc(fetched_bytes)
        self._m_hot.set(len(self._cache))
        return out[inv].reshape(ids.size, self.cols)

    def _cache_put(self, rid: int, row: np.ndarray, version: int) -> None:
        """``row`` must be client-owned (a fetched-block view or an
        already-cached array) — never a view into a caller's buffer."""
        if self.cache_rows <= 0:
            return
        self._cache[rid] = [row, version, self._round]
        self._cache.move_to_end(rid)
        evicted = 0
        while len(self._cache) > self.cache_rows:
            self._cache.popitem(last=False)
            evicted += 1
        if evicted:
            from ..obs import flight
            flight.record("row_evict", nbytes=evicted * self.row_nbytes,
                          round=self._round, detail=f"rows={evicted}")

    # ------------------------------------------------------------ push

    def push(self, ids, deltas) -> None:
        """Dedup'd rowsparse gradient push: duplicate row hits fold
        client-side (scatter-add over the unique ids) BEFORE the wire,
        then one vectored request per shard. Pushed rows are dropped
        from the cache — this worker's next pull of a row it just
        updated must see the merged value (the hot-row half of the
        staleness contract)."""
        ids = np.asarray(ids, dtype=np.uint64).reshape(-1)
        deltas = np.ascontiguousarray(deltas, dtype=self.dtype)
        if deltas.ndim != 2 or deltas.shape != (ids.size, self.cols):
            raise ValueError(f"deltas must be [{ids.size}, {self.cols}]; "
                             f"got {deltas.shape}")
        uniq, inv = np.unique(ids, return_inverse=True)
        if uniq.size != ids.size:
            folded = np.zeros((uniq.size, self.cols), self.dtype)
            np.add.at(folded, inv, deltas)
        else:
            folded = deltas
        shards = row_shard(uniq, len(self._handles))
        work = []
        for s in range(len(self._handles)):
            mask = shards == s
            if np.any(mask):
                # one request per ORIGIN slice (not per acting primary):
                # the dedup token then maps to exactly one slice chain,
                # so a cross-failover retry of this request is either
                # fully replicated (deduped) or fully unseen (applied
                # fresh) — never half of each
                payload = (struct.pack("<I", int(mask.sum()))
                           + uniq[mask].tobytes()
                           + np.ascontiguousarray(folded[mask]).tobytes())
                work.append((s, payload, self._token()))

        def one(item):
            s, payload, tok = item
            p = self._primary(s)   # blamed on failure — see pull()
            try:
                # the token is allocated once per slice batch and rides
                # the retry VERBATIM: the promoted replica seeded it
                # from the replicated log iff the dead primary finished
                # forwarding, which is exactly the applied-or-not line
                self._handles[p].embed_push(self.key, payload, token=tok)
                return None
            except TimeoutError:
                raise
            except (ConnectionError, OSError) as e:
                return item, p, e

        fails = [f for f in self._fanout(one, work) if f is not None]
        if fails:
            for _item, p, err in fails:
                self.fail_shard(p, cause=err)
            for item, _p, err in fails:
                res = one(item)
                if res is not None:
                    raise res[2]
        self._m_rows_pushed.inc(int(uniq.size))
        inval = 0
        for rid in uniq:
            if self._cache.pop(int(rid), None) is not None:
                inval += 1
        if inval:
            from ..obs import flight
            flight.record("cache_inval", round=self._round,
                          detail=f"rows={inval}")
            self._m_hot.set(len(self._cache))

    # ----------------------------------------------------- checkpoints

    def save_checkpoint(self, path: str, step: int) -> dict:
        """Durable sharded embed snapshot: every live acting shard dumps
        its row store (OP_EMBED_SNAP → atomic tmp+rename server-side)
        into the per-step directory ``path/s<step>/``, then the client
        commits by writing ``bps_embed_meta.json`` LAST (same meta-last
        marker discipline as ``save_sharded_checkpoint``). A directory
        without the meta file is an aborted save — restore ignores it."""
        d = os.path.join(path, f"s{int(step)}")
        os.makedirs(d, exist_ok=True)
        with self._fail_lock:
            dead = sorted(self._dead)
        live = [s for s in range(len(self._handles)) if s not in set(dead)]

        def one(s):
            body = json.dumps(
                {"path": os.path.join(d, f"shard{s}.npz")}).encode()
            return s, json.loads(bytes(self._handles[s].embed_snap(
                self.key, body, timeout_ms=self._timeout_ms)))

        shards = {s: st for s, st in self._fanout(one, live)}
        meta = {"step": int(step), "table": self.table_id,
                "shards": len(self._handles), "live": live, "dead": dead,
                "rows": sum(int(st.get("rows", 0))
                            for st in shards.values())}
        tmp = os.path.join(d, f".bps_embed_meta.{os.getpid()}.tmp")
        with open(tmp, "w") as f:
            json.dump(meta, f)
        os.replace(tmp, os.path.join(d, "bps_embed_meta.json"))
        from ..obs import flight
        flight.record("embed_snap", round=self._round,
                      detail=f"step {int(step)}: shards={len(live)} "
                             f"rows={meta['rows']}")
        return meta

    def restore_checkpoint(self, path: str,
                           step: Optional[int] = None) -> dict:
        """Restore from the newest COMMITTED per-step directory (or an
        explicit ``step``): adopts the saved dead-set so routing matches
        the topology the files were cut against, then fans each shard
        file back to the server it came from (OP_EMBED_RESTORE).
        Server-side ``restore_state`` bumps the table epoch past the
        saved one, so every client's next pull drops its cache; rows
        never written before the save stay lazily materialized."""
        if step is None:
            steps = sorted(
                int(n[1:]) for n in os.listdir(path)
                if n.startswith("s") and n[1:].isdigit()
                and os.path.exists(os.path.join(path, n,
                                                "bps_embed_meta.json")))
            if not steps:
                raise FileNotFoundError(
                    f"no committed embed checkpoint under {path}")
            step = steps[-1]
        d = os.path.join(path, f"s{int(step)}")
        with open(os.path.join(d, "bps_embed_meta.json")) as f:
            meta = json.load(f)
        if int(meta.get("shards", 0)) != len(self._handles):
            raise ValueError(
                f"embed checkpoint cut at {meta.get('shards')} shards; "
                f"this client has {len(self._handles)} — resharding a "
                f"row-hashed table needs a rebalance pass, not a restore")
        live = [int(s) for s in meta.get("live", [])]
        with self._fail_lock:
            self._dead = {int(s) for s in meta.get("dead", [])}

        def one(s):
            body = json.dumps(
                {"path": os.path.join(d, f"shard{s}.npz")}).encode()
            return s, json.loads(bytes(self._handles[s].embed_restore(
                self.key, body, timeout_ms=self._timeout_ms)))

        shards = {s: st for s, st in self._fanout(one, live)}
        # the restored servers re-issued their epochs; drop everything
        # local rather than waiting for the next pull to notice
        self._adopt_epoch(self._epoch + 1)
        from ..obs import flight
        flight.record("embed_restore", round=self._round,
                      detail=f"step {int(step)}: shards={len(live)} "
                             f"rows={sum(int(st.get('rows', 0)) for st in shards.values())}")
        return meta

    def close(self) -> None:
        if self._pool is not None:
            self._pool.shutdown(wait=True)
            self._pool = None
        for h in self._owned:
            h.close()
        self._owned = []

"""Sharded embedding store: the PS plane as a feature store (ISSUE 18).

DLRM-style workloads touch ~100 of 10⁷–10⁸ rows per request — the
regime where a parameter server beats allreduce outright (PAPER.md:
servers sum, workers own the optimizer; arXiv 2103.00543's sparse-
regime analysis). The existing rowsparse path (server/rowsparse.py)
still DENSIFIES server-side, so a 10⁷-row table is infeasible there.
This module keeps the table sparse end to end:

  - **row-sharded tables**: a table lives in key-space above the
    bit-41/42 param/state tags (``EMBED_KEY_BASE = 1 << 43``); its
    ROWS are hash-placed across plane shards by ``row_shard`` (a pure
    fmix64 of the row id — every worker derives the identical
    placement with no coordination), and a batch's rows travel as ONE
    vectored request per shard (ids in the payload, not per-row wire
    keys).
  - **lazy materialization**: the server allocates a row on first
    touch, initialized by ``init_rows`` — a counter-based dyadic hash
    shared by server and workers, so a 10⁷-row declaration costs
    nothing and any party can reproduce a never-touched row's value
    exactly.
  - **worker-side hot-row cache** with round-versioned invalidation:
    the server bumps a per-row version on every applied push batch
    (StaleStore's per-key rounds, generalized to row granularity); a
    pull carries the cached versions and the server answers
    "unchanged" (one flag byte) or the full row. Per-row staleness
    rides the ``BPS_MAX_LAG`` contract: a COLD row may be served
    locally for up to K rounds without wire contact; a HOT row (one
    this worker pushed to) is invalidated immediately and never served
    stale. K defaults to 1 — validate every round, which makes the
    cache bitwise-transparent (tests/test_embed.py).
  - **dedup'd rowsparse push**: duplicate row hits in a batch fold
    client-side (``np.add.at`` over the unique ids) before the wire;
    the server applies the sparse sums row-wise — no densify at any
    layer.

Wire formats (transport ops OP_EMBED_INIT/PULL/PUSH, all u64 ids
little-endian via numpy, lengths framed by the transport header):

  INIT  payload = JSON table meta {table, rows, cols, dtype, seed,
        shard, shards}; idempotent first-wins, conflicting re-declare
        refused loudly.
  PULL  payload  = n:u32 | ids:u64[n] | cached_versions:u64[n]
        response = flags:u8[n] | versions:u64[n] | rows (full row for
        each flag==1, request order). flag==0 means the cached version
        is current — no row bytes cross the wire.
  PUSH  payload = n:u32 | ids:u64[n] | deltas:dtype[n·cols]; server
        folds any remaining duplicates and applies row += delta with a
        version bump per touched row; rides the push-dedup token so a
        reconnect retry applies exactly once.

The hierarchical tier (server/hier.py) is NOT a valid front for these
ops: an aggregator's local fold has no row store, and silently passing
through would split a table's rows across the agg's own upstream
sharding. ``PSTransportServer.embed_store`` refuses loudly instead —
point ``EmbedClient`` at the plane shards directly (docs/embedding.md
has the failure matrix).
"""

from __future__ import annotations

import json
import os
import struct
import threading
from collections import OrderedDict
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

# key-space room above every existing tag: bit 40 = activation
# channels, bit 41 = param-class keys, bit 42 = state/handoff keys,
# bits 48+ = striping sub-keys. Embedding tables take bit 43; the low
# 16 bits carry the table id (matching the decl<<16 convention).
EMBED_KEY_BASE = 1 << 43

_M64 = np.uint64(0xFFFFFFFFFFFFFFFF)
_FMIX_C1 = np.uint64(0xFF51AFD7ED558CCD)
_FMIX_C2 = np.uint64(0xC4CEB9FE1A85EC53)
_GOLDEN = np.uint64(0x9E3779B97F4A7C15)


def table_key(table_id: int) -> int:
    """The wire key for table ``table_id`` — one key per table (rows
    are addressed in the payload, not the key space)."""
    if not 0 <= int(table_id) < (1 << 16):
        raise ValueError(f"table id {table_id} outside [0, 65536)")
    return EMBED_KEY_BASE | (int(table_id) << 16)


def _fmix64(x: np.ndarray) -> np.ndarray:
    """MurmurHash3's 64-bit finalizer, vectorized — a full-avalanche
    integer hash, so consecutive row ids land on uncorrelated shards."""
    x = x.astype(np.uint64, copy=True)
    x ^= x >> np.uint64(33)
    x *= _FMIX_C1
    x ^= x >> np.uint64(33)
    x *= _FMIX_C2
    x ^= x >> np.uint64(33)
    return x


def row_shard(ids, num_shards: int) -> np.ndarray:
    """Deterministic row → shard placement: a PURE function of
    (row id, shard count), so every worker (and the bench's control
    arithmetic) derives the identical placement with no coordination —
    the determinism tests pin golden values against drift."""
    ids = np.asarray(ids, dtype=np.uint64).reshape(-1)
    return (_fmix64(ids) % np.uint64(num_shards)).astype(np.int64)


def init_rows(seed: int, ids, cols: int, dtype: str = "float32"
              ) -> np.ndarray:
    """Deterministic per-row initial values, counter-based (no RNG
    state): value[i, j] is a dyadic rational k/1024 · 2⁻³ derived from
    fmix64(seed, row, col). Server-side lazy materialization and any
    client-side control arithmetic reproduce a never-touched row
    byte-identically — and dyadic values keep fp32 sums EXACT, the
    property every bitwise-parity assertion in this plane rides on."""
    ids = np.asarray(ids, dtype=np.uint64).reshape(-1, 1)
    col = np.arange(int(cols), dtype=np.uint64).reshape(1, -1)
    # seed folded via Python ints (numpy SCALAR uint64 overflow warns;
    # array overflow wraps silently, which the hash relies on)
    seed_term = np.uint64((int(seed) * 0xC4CEB9FE1A85EC53)
                          & 0xFFFFFFFFFFFFFFFF)
    h = _fmix64(ids * _GOLDEN + col + seed_term)
    k = (h % np.uint64(1024)).astype(np.int64)
    return (((k - 512) / 1024.0) / 8.0).astype(np.dtype(dtype))


# ------------------------------------------------------------- server


class _Table:
    """One shard's slice of a table: rows materialize on first touch,
    each carrying a version bumped per applied push batch (the per-row
    generalization of StaleStore's per-key rounds)."""

    __slots__ = ("meta", "num_rows", "cols", "dtype", "seed", "row_nbytes",
                 "rows", "vers", "lock")

    def __init__(self, meta: dict) -> None:
        self.meta = dict(meta)
        self.num_rows = int(meta["rows"])
        self.cols = int(meta["cols"])
        self.dtype = np.dtype(str(meta.get("dtype", "float32")))
        self.seed = int(meta.get("seed", 0))
        self.row_nbytes = self.cols * self.dtype.itemsize
        if self.num_rows <= 0 or self.cols <= 0:
            raise ValueError(f"bad table shape {self.num_rows}x{self.cols}")
        self.rows: Dict[int, np.ndarray] = {}
        self.vers: Dict[int, int] = {}
        self.lock = threading.Lock()

    def _row(self, rid: int) -> np.ndarray:
        r = self.rows.get(rid)
        if r is None:
            r = init_rows(self.seed, [rid], self.cols,
                          str(self.dtype)).reshape(-1)
            self.rows[rid] = r
            self.vers[rid] = 1   # versions start at 1: a client's
            #                      "not cached" sentinel is 0
        return r

    def materialize(self, ids) -> None:
        """Batch-materialize every missing row in ``ids`` with ONE
        ``init_rows`` call (caller holds ``lock``). Per-row lazy init
        was the cold-pull bottleneck: ~2000 tiny vectorized-hash calls
        cost ~25× one 2000-row call. Values are identical by
        construction (the hash is per-(row, col), not per-batch), so
        this is pure mechanics. Rows are stored as VIEWS into the batch
        block — safe because ``apply`` rebinds rows, never writes in
        place."""
        missing = [int(r) for r in ids if int(r) not in self.rows]
        if not missing:
            return
        vals = init_rows(self.seed, missing, self.cols, str(self.dtype))
        for j, rid in enumerate(missing):
            self.rows[rid] = vals[j]
            self.vers[rid] = 1


class EmbedRowStore:
    """Server-side sharded row store (transport-owned, like the act and
    param mailboxes — every deployment's server role speaks it, raw
    PSServer engines included)."""

    def __init__(self) -> None:
        self._tables: Dict[int, _Table] = {}
        self._lock = threading.Lock()

    def init_table(self, key: int, meta: dict) -> None:
        """Idempotent first-wins declaration; a conflicting re-declare
        (different shape/dtype/seed) is a mis-built worker and refused
        loudly rather than silently serving rows at wrong offsets."""
        fresh = _Table(meta)
        with self._lock:
            cur = self._tables.get(key)
            if cur is None:
                self._tables[key] = fresh
                return
            for f in ("rows", "cols", "dtype", "seed"):
                a, b = cur.meta.get(f), fresh.meta.get(f)
                if str(a) != str(b):
                    raise ValueError(
                        f"embed table {key:#x}: conflicting re-declare "
                        f"({f}: {a} != {b}) — workers disagree on the "
                        f"table")

    def table(self, key: int) -> _Table:
        t = self._tables.get(key)
        if t is None:
            raise KeyError(f"embed table {key:#x} not declared "
                           f"(OP_EMBED_INIT first)")
        return t

    def pull(self, key: int, payload) -> Tuple[bytes, bytes, bytes]:
        """Conditional sparse pull. Parses ``n | ids | cached_vers``;
        returns (flags u8[n], versions u64[n], row bytes for the
        flagged ids, request order). Rows are copied into ONE
        contiguous buffer under the table lock — a concurrent push
        mutates rows in place, and a torn row on the wire would be a
        silent corruption; the flags/vers/rowbuf triple then rides one
        vectored sendmsg with no further join."""
        t = self.table(key)
        (n,) = struct.unpack_from("<I", payload, 0)
        ids = np.frombuffer(payload, np.uint64, count=n, offset=4)
        vers = np.frombuffer(payload, np.uint64, count=n, offset=4 + 8 * n)
        if np.any(ids >= np.uint64(t.num_rows)):
            raise ValueError(f"row id out of range [0, {t.num_rows})")
        flags = np.zeros(n, np.uint8)
        out_vers = np.zeros(n, np.uint64)
        chunks: List[np.ndarray] = []
        with t.lock:
            t.materialize(ids)
            for i in range(n):
                rid = int(ids[i])
                row = t.rows[rid]
                v = t.vers[rid]
                out_vers[i] = v
                if v != int(vers[i]):
                    flags[i] = 1
                    chunks.append(row)
            rowbuf = (np.concatenate(chunks).tobytes() if chunks
                      else b"")
        return flags.tobytes(), out_vers.tobytes(), rowbuf

    def apply(self, key: int, payload) -> int:
        """Row-wise sparse apply: ``row += delta`` with a version bump
        per touched row — NO dense expansion at any size. Clients fold
        duplicates before the wire; any that remain (a raw client) fold
        here first so each row's version moves once per push batch.
        Returns the number of rows touched."""
        t = self.table(key)
        (n,) = struct.unpack_from("<I", payload, 0)
        ids = np.frombuffer(payload, np.uint64, count=n, offset=4)
        deltas = np.frombuffer(payload, t.dtype, offset=4 + 8 * n)
        if n == 0:
            return 0
        if deltas.size != n * t.cols:
            raise ValueError(f"delta payload {deltas.size} != "
                             f"{n}x{t.cols} rows")
        if np.any(ids >= np.uint64(t.num_rows)):
            raise ValueError(f"row id out of range [0, {t.num_rows})")
        deltas = deltas.reshape(n, t.cols)
        uniq, inv = np.unique(ids, return_inverse=True)
        if uniq.size != n:
            folded = np.zeros((uniq.size, t.cols), t.dtype)
            np.add.at(folded, inv, deltas)
        else:
            folded = deltas
        with t.lock:
            t.materialize(uniq)
            for i in range(uniq.size):
                rid = int(uniq[i])
                t.rows[rid] = t.rows[rid] + folded[i]
                t.vers[rid] += 1
        return int(uniq.size)


# ------------------------------------------------------------- client


def _env_int(name: str, default: int) -> int:
    v = os.environ.get(name, "")
    return int(v) if v else default


class EmbedClient:
    """Worker-side sharded table client: sparse row pull with a
    hot-row cache, dedup'd rowsparse push, one vectored request per
    shard.

    ``handles`` are per-shard transport clients (single-address
    ``RemotePSBackend``s — the plane-backend idiom), indexed by the
    SAME shard order on every worker; ``row_shard`` routes rows.

    Cache protocol (docs/embedding.md): an entry is (row, version,
    validated_round). A row is served purely locally while
    ``round - validated_round < K`` (K = ``BPS_EMBED_MAX_LAG``,
    defaulting to ``BPS_MAX_LAG``, defaulting to 1); past that window
    it is re-validated CONDITIONALLY — the cached version rides the
    pull and the server sends one flag byte instead of the row when
    nothing changed. A push from THIS worker invalidates its rows
    immediately (the hot-row half of the staleness contract). At K=1
    the cache is bitwise-transparent: every served value is validated
    against the server's current version each round."""

    def __init__(self, handles: Sequence, table_id: int, num_rows: int,
                 cols: int, dtype: str = "float32", seed: int = 0,
                 cache_rows: Optional[int] = None,
                 max_lag: Optional[int] = None,
                 timeout_ms: int = 30000) -> None:
        if not handles:
            raise ValueError("EmbedClient needs at least one shard handle")
        self._handles = list(handles)
        self._owned: List = []
        self.key = table_key(table_id)
        self.num_rows = int(num_rows)
        self.cols = int(cols)
        self.dtype = np.dtype(dtype)
        self.seed = int(seed)
        self.row_nbytes = self.cols * self.dtype.itemsize
        self._timeout_ms = int(timeout_ms)
        self.cache_rows = (_env_int("BPS_EMBED_CACHE_ROWS", 65536)
                           if cache_rows is None else int(cache_rows))
        self.max_lag = (max(1, _env_int("BPS_EMBED_MAX_LAG",
                                        _env_int("BPS_MAX_LAG", 1)))
                        if max_lag is None else max(1, int(max_lag)))
        # row_id -> [row array, server version, validated_round]; LRU
        # by OrderedDict recency
        self._cache: "OrderedDict[int, list]" = OrderedDict()
        self._round = 1
        self._pool = None
        self._pool_lock = threading.Lock()
        self.last_fetch_s = 0.0   # wire time of the latest pull's
        #                           fan-out — the p99 row-fetch column
        from ..obs.metrics import get_registry
        reg = get_registry()
        self._m_hits = reg.counter("embed/cache_hits")
        self._m_miss = reg.counter("embed/cache_misses")
        self._m_fetch_bytes = reg.counter("embed/row_fetch_bytes")
        self._m_rows_pushed = reg.counter("embed/rows_pushed")
        self._m_hot = reg.gauge("embed/hot_set_size")
        meta = {"table": int(table_id), "rows": self.num_rows,
                "cols": self.cols, "dtype": str(self.dtype),
                "seed": self.seed, "shards": len(self._handles)}
        for s, h in enumerate(self._handles):
            h.embed_init(self.key, dict(meta, shard=s))

    @classmethod
    def connect(cls, addrs: Sequence[str], table_id: int, num_rows: int,
                cols: int, **kw) -> "EmbedClient":
        """Dial one single-address transport client per shard (owned —
        closed by ``close``) and declare the table on each."""
        from .transport import RemotePSBackend
        handles = [RemotePSBackend([a]) for a in addrs]
        cli = cls(handles, table_id, num_rows, cols, **kw)
        cli._owned = handles
        return cli

    # ------------------------------------------------------------ pull

    def tick(self) -> None:
        """Advance the client's round — one call per training step; the
        denominator of every staleness-window decision."""
        self._round += 1

    def _fanout(self, fn, items):
        """Run ``fn`` over per-shard work items, in parallel when more
        than one shard has work (one small pool, shards-wide)."""
        if len(items) == 1:
            return [fn(items[0])]
        if self._pool is None:
            with self._pool_lock:
                if self._pool is None:
                    from concurrent.futures import ThreadPoolExecutor
                    self._pool = ThreadPoolExecutor(
                        max_workers=len(self._handles),
                        thread_name_prefix="bps-embed")
        return list(self._pool.map(fn, items))

    def pull(self, ids) -> np.ndarray:
        """Fetch the current rows for ``ids`` (duplicates allowed —
        resolved through one lookup per unique row). Only rows outside
        the local staleness window touch the wire, one vectored request
        per shard; of those, only rows whose version MOVED transfer
        bytes."""
        import time as _time
        ids = np.asarray(ids, dtype=np.uint64).reshape(-1)
        uniq, inv = np.unique(ids, return_inverse=True)
        out = np.empty((uniq.size, self.cols), self.dtype)
        need: List[int] = []       # positions in uniq that go to the wire
        hits = 0
        for i in range(uniq.size):
            rid = int(uniq[i])
            ent = self._cache.get(rid)
            if ent is not None and self._round - ent[2] < self.max_lag:
                out[i] = ent[0]          # cold row inside the K window:
                self._cache.move_to_end(rid)   # no wire contact at all
                hits += 1
            else:
                need.append(i)
        fetched_bytes = 0
        t0 = _time.time()
        if need:
            shards = row_shard(uniq[need], len(self._handles))
            work = []
            for s in range(len(self._handles)):
                pos = [need[j] for j in range(len(need)) if shards[j] == s]
                if pos:
                    work.append((s, pos))

            def one(item):
                s, pos = item
                rids = uniq[pos]
                vers = np.array(
                    [self._cache[int(r)][1] if int(r) in self._cache
                     else 0 for r in rids], np.uint64)
                payload = (struct.pack("<I", len(pos)) + rids.tobytes()
                           + vers.tobytes())
                return pos, self._handles[s].embed_pull(
                    self.key, payload, timeout_ms=self._timeout_ms)

            for pos, resp in self._fanout(one, work):
                n = len(pos)
                flags = np.frombuffer(resp, np.uint8, count=n)
                vers = np.frombuffer(resp, np.uint64, count=n, offset=n)
                rows = np.frombuffer(resp, self.dtype, offset=n + 8 * n)
                rows = rows.reshape(-1, self.cols).copy()
                fetched_bytes += rows.nbytes
                # cache entries hold VIEWS into the one block copy
                # above — a per-row np copy on this path measurably
                # rivals the wire time at DLRM batch sizes
                r = 0
                for j in range(n):
                    i = pos[j]
                    rid = int(uniq[i])
                    if flags[j]:
                        row = rows[r]
                        out[i] = row
                        r += 1
                        self._m_miss.inc()
                    else:
                        # version unchanged: the cached bytes are
                        # current — a validated hit, zero row bytes
                        row = self._cache[rid][0]
                        out[i] = row
                        self._m_hits.inc()
                    self._cache_put(rid, row, int(vers[j]))
        self.last_fetch_s = _time.time() - t0
        if hits:
            self._m_hits.inc(hits)
        if fetched_bytes:
            self._m_fetch_bytes.inc(fetched_bytes)
        self._m_hot.set(len(self._cache))
        return out[inv].reshape(ids.size, self.cols)

    def _cache_put(self, rid: int, row: np.ndarray, version: int) -> None:
        """``row`` must be client-owned (a fetched-block view or an
        already-cached array) — never a view into a caller's buffer."""
        if self.cache_rows <= 0:
            return
        self._cache[rid] = [row, version, self._round]
        self._cache.move_to_end(rid)
        evicted = 0
        while len(self._cache) > self.cache_rows:
            self._cache.popitem(last=False)
            evicted += 1
        if evicted:
            from ..obs import flight
            flight.record("row_evict", nbytes=evicted * self.row_nbytes,
                          round=self._round, detail=f"rows={evicted}")

    # ------------------------------------------------------------ push

    def push(self, ids, deltas) -> None:
        """Dedup'd rowsparse gradient push: duplicate row hits fold
        client-side (scatter-add over the unique ids) BEFORE the wire,
        then one vectored request per shard. Pushed rows are dropped
        from the cache — this worker's next pull of a row it just
        updated must see the merged value (the hot-row half of the
        staleness contract)."""
        ids = np.asarray(ids, dtype=np.uint64).reshape(-1)
        deltas = np.ascontiguousarray(deltas, dtype=self.dtype)
        if deltas.ndim != 2 or deltas.shape != (ids.size, self.cols):
            raise ValueError(f"deltas must be [{ids.size}, {self.cols}]; "
                             f"got {deltas.shape}")
        uniq, inv = np.unique(ids, return_inverse=True)
        if uniq.size != ids.size:
            folded = np.zeros((uniq.size, self.cols), self.dtype)
            np.add.at(folded, inv, deltas)
        else:
            folded = deltas
        shards = row_shard(uniq, len(self._handles))
        work = []
        for s in range(len(self._handles)):
            mask = shards == s
            if np.any(mask):
                work.append((s, uniq[mask],
                             np.ascontiguousarray(folded[mask])))

        def one(item):
            s, rids, rows = item
            payload = (struct.pack("<I", rids.size) + rids.tobytes()
                       + rows.tobytes())
            self._handles[s].embed_push(self.key, payload)

        self._fanout(one, work)
        self._m_rows_pushed.inc(int(uniq.size))
        inval = 0
        for rid in uniq:
            if self._cache.pop(int(rid), None) is not None:
                inval += 1
        if inval:
            from ..obs import flight
            flight.record("cache_inval", round=self._round,
                          detail=f"rows={inval}")
            self._m_hot.set(len(self._cache))

    def close(self) -> None:
        if self._pool is not None:
            self._pool.shutdown(wait=True)
            self._pool = None
        for h in self._owned:
            h.close()
        self._owned = []

"""PS-mode data paths: device ↔ host reduction service.

Two modes, mirroring the reference's two PS deployments:

  - **Sync** (``PSGradientExchange``): gradients already reduced over the
    local ICI mesh hop to the host and are summed across worker processes
    by the sharded key stores — the reference's steady-state push/pull
    pipeline (core_loops.cc:538-618) with the ICI collective playing the
    role of the intra-node NCCL stage. Buckets are pushed in priority
    (backward-completion) order, so the server sums bucket k while
    bucket k+1 is still uploading (the reference's
    pipelining-by-partition, operations.cc:140-180); LANDED buckets are
    pulled by next-step first-use priority (forward order), and up to
    two rounds may be in flight per key (cross-step) under a per-key
    admission gate.

  - **Async** (``AsyncPSWorker``): no worker barrier at all — each worker
    pushes *weight deltas* and pulls fresh weights whenever it finishes a
    local step (reference: BYTEPS_ENABLE_ASYNC server.cc:310-314; torch
    `__init__.py`:186-214 pushing ``w_new - w_old``).
"""

from __future__ import annotations

import os
import threading
from concurrent.futures import ThreadPoolExecutor
from typing import Dict, List, Optional

import jax
import numpy as np

from ..common.naming import NameRegistry
from ..common.partition import LeafSpec, plan_buckets
from ..obs import flight
from ..obs.metrics import get_registry, observe_stage
from .admission import LAG_BARRIER, AdmissionPlane
from .engine import HostPSBackend


class _PendingExchange:
    """Handle returned by ``PSGradientExchange.exchange_async``: the
    pushes are already in flight; ``result()`` drains the pulls."""

    __slots__ = ("_drain",)

    def __init__(self, drain) -> None:
        self._drain = drain

    def result(self):
        return self._drain()


class _StreamingExchange:
    """Handle returned by ``PSGradientExchange.exchange_stream``: the
    pushes are in flight and ``ready()`` yields ``(leaf_index, flat host
    array)`` in COMPLETION order, each the moment its last covering
    bucket's pull unpacks — the consumer can start H2D / apply work for
    early buckets while later buckets are still on the wire. A failed
    push or pull surfaces as an exception from the iterator (and from
    ``result()``)."""

    __slots__ = ("_r",)

    def __init__(self, round_) -> None:
        self._r = round_

    @property
    def round_state(self):
        """The underlying ``_Round`` (sharded-update tail plumbing)."""
        return self._r

    def ready(self):
        """Iterate (leaf_index, flat host array) as leaves complete."""
        return self._r.ready_iter()

    def result(self):
        """Drain every pull and return the assembled summed tree (usable
        with or without consuming ``ready()``)."""
        return self._r.drain()


class _IngestExchange:
    """Handle returned by ``PSGradientExchange.exchange_ingest``: the
    INGRESS mirror of ``exchange_stream``. The caller ``feed``s leaves
    the moment their values materialize (the staged backward hands over
    each layer group as its segment finishes); every bucket's D2H +
    pack + push is submitted the instant its last covering leaf arrives
    — no waiting for the full tree, the head analogue of the
    reference's per-tensor push interception. The pull side is the same
    leaf-completion stream as ``exchange_stream``: ``ready()`` /
    ``result()`` behave identically, so the streamed step tail composes
    unchanged. ``finish()`` asserts every leaf was fed; ``abort(exc)``
    unblocks a consumer when the producer dies mid-backward."""

    __slots__ = ("_r",)

    def __init__(self, round_) -> None:
        self._r = round_

    @property
    def round_state(self):
        """The underlying ``_Round`` (sharded-update tail plumbing)."""
        return self._r

    def feed(self, leaf_ids, values) -> None:
        """Hand over device (or host) arrays for ``leaf_ids`` (flat
        indices). Starts ``copy_to_host_async`` immediately; buckets
        completed by these leaves are packed+pushed on worker threads."""
        self._r.feed(leaf_ids, values)

    def finish(self) -> None:
        """Declare feeding complete; raises if any leaf is missing."""
        self._r.finish_feed()

    def abort(self, exc: BaseException) -> None:
        """Producer-side failure: wake ``ready()``/``result()`` with
        ``exc`` instead of leaving them blocked on leaves that will
        never complete."""
        self._r.abort(exc)

    def ready(self):
        """Iterate (leaf_index, flat host array) as leaves complete."""
        return self._r.ready_iter()

    def result(self):
        """Drain every pull and return the assembled summed tree."""
        return self._r.drain()


class _Round:
    """One sync exchange round's machinery, shared by the all-at-once
    paths (``exchange``/``exchange_async``/``exchange_stream``) and the
    incremental head path (``exchange_ingest``): lazily-materialized
    host leaves with PER-LEAF locks (one slow D2H can no longer block
    another bucket's pack worker behind a global lock), bucket
    pack+push, pull+unpack, and leaf-completion streaming."""

    def __init__(self, ex: "PSGradientExchange", tree,
                 name: Optional[str], stream: bool,
                 ingest: bool = False,
                 step: Optional[int] = None,
                 sharded=None) -> None:
        import queue as _queue
        self.ex = ex
        # sharded weight update (byteps_tpu.sharded_update): push EVERY
        # bucket (the server sum needs all contributions) but pull only
        # the buckets covering this replica's OWNED groups; the rest
        # sit in ``await_param`` until the owner's param frames land
        # and ``release_skipped`` frees their admission keys. None =
        # classic full-pull round.
        self.sharded = sharded
        self.skip_buckets = frozenset()     # filled once keyed is known
        # cross-step rounds tag their timeline spans with the TRUE
        # owning step: the round's spans outlive the step that started
        # it, and the overlap aggregates group per step
        self.step_tag = step
        self.decl_name, self.treedef, self.keyed = ex._plan(tree, name)
        # fused-compression decision trace, PINNED per round: the
        # controller (re)decides at this round boundary and the
        # snapshot below is what BOTH the push and the pull of every
        # bucket in this round use — with two rounds in flight
        # (cross-step) each carries its own trace, so a mid-round
        # re-decision can never make a worker pull a codec the server
        # didn't encode
        self.clevels = None
        if ex._cplane is not None:
            ex._cplane.on_round()
            self.clevels = [ex._cplane.level_of(pskey)
                            for pskey, _ in self.keyed]
        # device-side PS_COMPRESS plan (compress/device.py): buckets
        # whose pinned level has a device codec encode ON DEVICE and
        # D2H only the payload — their leaves skip the eager
        # copy_to_host_async (that copy is exactly what the device
        # encode exists to shrink); leaves any HOST bucket covers keep
        # it. Resolved per round from the pinned trace.
        self.dev_bucket = None
        self.host_leaves = None
        if self.clevels is not None and ex._device_encode_on():
            from ..compress.device import DEVICE_CODECS
            self.dev_bucket = [
                bool(lvl in DEVICE_CODECS and ex._cplane.active(pskey)
                     and pskey not in ex._chains)
                for (pskey, _), lvl in zip(self.keyed, self.clevels)]
            if any(self.dev_bucket):
                need = set()
                for dev, (_, b) in zip(self.dev_bucket, self.keyed):
                    if not dev:
                        need.update(s.leaf_index for s in b.segments)
                self.host_leaves = need
            else:
                self.dev_bucket = None
        # epoch-tagged routing (server plane): the placement view this
        # round resolved its routes under. Every push/pull carries it;
        # a key that migrated since is refused with WrongEpoch (an
        # explicit reroute, never a torn assembly) and the exchange
        # refreshes + retries once. None = placement-less backend.
        self.route_epoch = (ex.backend.placement_epoch()
                            if hasattr(ex.backend, "placement_epoch")
                            else None)
        leaves, _ = jax.tree_util.tree_flatten(tree)
        self.shapes = [l.shape for l in leaves]
        # ingest rounds get their sources fed later; the template tree
        # (typically the params) only supplies structure/shapes/dtypes
        self.sources: List = [None] * len(leaves) if ingest else list(leaves)
        self.flat: List[Optional[np.ndarray]] = [None] * len(leaves)
        self.flat_locks = [threading.Lock() for _ in leaves]
        self.out = [np.empty(int(np.prod(l.shape)), np.dtype(l.dtype))
                    for l in leaves]
        self.rounds: List[Optional[int]] = [None] * len(self.keyed)
        # pull ORDER is decoupled from push order: pushes go out in
        # backward-completion (bucket) order, but landed buckets are
        # pulled by NEXT-STEP FIRST-USE priority — the bucket holding
        # the earliest-declared (input-side) leaves first, since those
        # params gate the next forward's first layers (the reference's
        # BYTEPS_SCHEDULING forward-position priority, here on the pull
        # side). Lower = pulled earlier among landed buckets.
        self.pull_prio = [min((s.leaf_index for s in b.segments),
                              default=0) for _, b in self.keyed]
        self.round_seq = ex._next_round_seq()
        if self.sharded is not None:
            self.skip_buckets = frozenset(
                i for i in range(len(self.keyed))
                if i not in self.sharded.pull_buckets)
        self._pulls_left = len(self.keyed) - len(self.skip_buckets)
        self._skips_left = len(self.skip_buckets)
        self._skip_lock = threading.Lock()
        # skipped buckets whose release arrived BEFORE their own push
        # landed (the owner can publish the moment every worker's push
        # reached the server, racing this worker's push bookkeeping)
        self._skip_release_pending: set = set()
        self._pull_lock = threading.Lock()
        self._pull_err: Optional[BaseException] = None
        self._pull_done = threading.Event()
        # per-bucket lifecycle for the watchdog's per-key diagnostic:
        # pending -> pushed -> pulled (or failed); sharded rounds add
        # pending -> await_param -> param_done for non-pulled buckets.
        # "pushed"/"await_param" forever is the wedge signature (a lost
        # pull — or a dead owner's missing param frame — holding the
        # admission gate).
        self.bucket_state = ["pending"] * len(self.keyed)
        self._finished = False
        if not self.keyed:
            self._pull_done.set()
        else:
            ex._register_round(self)
            if self._pulls_left <= 0:
                self._pull_done.set()
        self.aborted: Optional[BaseException] = None
        self.readyq = None
        if stream or ingest:
            self.readyq = _queue.Queue()
            self.seg_left = [0] * len(leaves)
            for _, b in self.keyed:
                for s in b.segments:
                    self.seg_left[s.leaf_index] += 1
            # sharded rounds stream only the OWNED groups' leaves —
            # the rest complete via the param-fetch path, and their
            # partial grad data (shared boundary buckets) must never
            # reach the consumer as if it were a finished leaf
            if self.sharded is not None:
                for li in range(len(leaves)):
                    if li not in self.sharded.stream_leaves:
                        self.seg_left[li] = -1      # never enqueued
            self._stream_n = sum(1 for n in self.seg_left if n >= 0)
            self.seg_lock = threading.Lock()
            for li, n in enumerate(self.seg_left):
                if n == 0:          # zero-size leaf: no covering bucket,
                    self.readyq.put((li, self.out[li]))  # ready at once
        self.ingest = ingest
        if ingest:
            self.dtypes = [np.dtype(l.dtype) for l in leaves]
            # bucket -> distinct covering leaves; a bucket is pushable
            # when all of them have been fed
            self.bucket_leaves = [
                sorted({s.leaf_index for s in b.segments})
                for _, b in self.keyed]
            self.bucket_need = [len(ls) for ls in self.bucket_leaves]
            self.leaf_buckets: Dict[int, List[int]] = {}
            for bi, ls in enumerate(self.bucket_leaves):
                for li in ls:
                    self.leaf_buckets.setdefault(li, []).append(bi)
            self.fed = [False] * len(leaves)
            self.feed_lock = threading.Lock()
            self.feed_done = False

    # ------------------------------------------------------ host leaves

    def get_flat(self, i: int) -> np.ndarray:
        v = self.flat[i]         # double-checked: a ready leaf never waits
        if v is not None:        # behind its own (or any) lock
            return v
        with self.flat_locks[i]:
            if self.flat[i] is None:
                import time
                t0 = time.time()
                # ascontiguousarray: the native pack does raw pointer
                # math (no-op for device readbacks). np.asarray blocks
                # on the leaf's D2H copy — only ITS OWN copy, per-leaf
                self.flat[i] = np.ascontiguousarray(
                    np.asarray(self.sources[i])).reshape(-1)
                observe_stage("PS_D2H", time.time() - t0)
                if self.ex.timeline is not None:
                    self.ex.timeline.record(self.decl_name, "PS_D2H", t0,
                                            time.time() - t0, i,
                                            step=self.step_tag)
            return self.flat[i]

    # ------------------------------------------------------ push / pull

    def push_one(self, idx: int) -> np.ndarray:
        import time
        ex = self.ex
        pskey, b = self.keyed[idx]
        self.rounds[idx] = ex._next_round(pskey)
        if self.dev_bucket is not None and self.dev_bucket[idx]:
            buf = ex._push_bucket_device(self, idx)
            if buf is not None:
                self.bucket_state[idx] = "pushed"
                ex._mark_progress()
                return buf
            # device fallback (host-fed leaf / kernel failure): the
            # eager D2H was skipped for device-only leaves, but
            # get_flat's np.asarray below blocks on its own copy —
            # slower, never wrong
        t0 = time.time()
        buf = np.empty(b.size, dtype=b.dtype)
        if ex._native_pack:
            # native gather: one GIL-released call per bucket instead
            # of a GIL-held numpy copy per segment (VERDICT r4 #5 — the
            # uncompressed hop's interpreter cost; reference
            # core_loops.cc:538-618 stages zero-copy in C++ too)
            item = np.dtype(b.dtype).itemsize
            from .engine import pack_segments
            pack_segments(
                [self.get_flat(s.leaf_index).ctypes.data
                 + s.leaf_offset * item for s in b.segments],
                [s.bucket_offset * item for s in b.segments],
                [s.length * item for s in b.segments], buf)
        else:
            for s in b.segments:
                buf[s.bucket_offset:s.bucket_offset + s.length] = \
                    self.get_flat(s.leaf_index)[
                        s.leaf_offset:s.leaf_offset + s.length]
        t0 = ex._record(self.decl_name, "PS_PACK", pskey, t0,
                        step=self.step_tag)
        # host-path D2H accounting: this bucket's segments crossed
        # PCIe dense (segments partition leaves, so per-bucket sums
        # tile the real copy exactly)
        ex._d2h_account(pskey, buf.nbytes)
        try:
            ex._push_bucket(pskey, b, buf, rnd=self, idx=idx)
        except Exception:
            # the round counter advanced but the push never landed: drop
            # the entry so a retried exchange() re-seeds from the
            # server's round instead of pulling a round that will never
            # complete (permanent sliced-pull timeout)
            with ex._key_rounds_lock:
                ex._key_rounds.pop(pskey, None)
            raise
        ex._record(self.decl_name, "PS_PUSH", pskey, t0,
                   step=self.step_tag, round=self.rounds[idx])
        self.bucket_state[idx] = "pushed"
        ex._mark_progress()
        return buf

    def pull_one(self, idx: int, buf: np.ndarray) -> None:
        import time
        ex = self.ex
        pskey, b = self.keyed[idx]
        t0 = time.time()
        merged = ex._pull_bucket(pskey, b, buf, self.rounds[idx],
                                 rnd=self, idx=idx)
        t0 = ex._record(self.decl_name, "PS_PULL", pskey, t0,
                        step=self.step_tag, round=self.rounds[idx])
        if ex._native_pack and merged.flags["C_CONTIGUOUS"]:
            item = np.dtype(b.dtype).itemsize
            from .engine import unpack_segments
            unpack_segments(
                merged,
                [s.bucket_offset * item for s in b.segments],
                [self.out[s.leaf_index].ctypes.data + s.leaf_offset * item
                 for s in b.segments],
                [s.length * item for s in b.segments])
        else:
            for s in b.segments:        # disjoint segments: thread-safe
                self.out[s.leaf_index][
                    s.leaf_offset:s.leaf_offset + s.length] = \
                    merged[s.bucket_offset:s.bucket_offset + s.length]
        ex._record(self.decl_name, "PS_UNPACK", pskey, t0,
                   step=self.step_tag)
        self.bucket_state[idx] = "pulled"
        ex._m_buckets.inc()
        ex._mark_progress()
        if self.readyq is not None:
            for s in b.segments:
                self._segment_done(s.leaf_index)

    def _segment_done(self, li: int) -> None:
        with self.seg_lock:
            if self.seg_left[li] < 0:    # sharded: non-streamed leaf
                return                   # (completes via param fetch)
            self.seg_left[li] -= 1
            done = self.seg_left[li] == 0
        if done:
            self.readyq.put((li, self.out[li]))

    def assemble(self):
        shaped = [o.reshape(shp) for o, shp in zip(self.out, self.shapes)]
        return jax.tree_util.tree_unflatten(self.treedef, shaped)

    def submit_bucket(self, idx: int) -> None:
        """Queue bucket ``idx``'s pack+push; its pull is enqueued into
        the exchange's priority scheduler when the push lands. The push
        is ADMITTED per PS key by the admission plane's KeyGate: at
        K=1 (two rounds in flight, cross-step) round k+1's push for a
        key waits until round k's pull of that key completed — the
        server publishes one round per key at a time, so an earlier
        push would overwrite the merge a straggler pull still needs
        (torn assembly). Under ``BPS_MAX_LAG=K`` the gate is a
        counting semaphore of depth K and the server versions rounds
        (docs/admission.md), so up to K+1 rounds overlap per key."""
        ex = self.ex
        pskey, _ = self.keyed[idx]
        ex.plane.gate.admit(pskey,
                            lambda: ex._push_ex.submit(self._push_task,
                                                       idx))

    def _push_task(self, idx: int) -> None:
        pskey, _ = self.keyed[idx]
        skip = idx in self.skip_buckets
        try:
            buf = self.push_one(idx)
        except BaseException as e:   # noqa: BLE001 — relayed to consumers
            self.bucket_state[idx] = "failed"
            self.ex.plane.gate.release(pskey)
            if skip:
                self._skip_finished(e)
            else:
                self._pull_finished(e)
            return
        if not skip:
            self.ex._enqueue_pull(self, idx, buf)
            return
        # sharded round, non-owned bucket: no pull — the admission key
        # stays held until the owner's param frames for every group this
        # bucket covers have landed (release_skipped). If the release
        # raced ahead of this push's bookkeeping, complete it inline.
        with self._skip_lock:
            self.bucket_state[idx] = "await_param"
            fire = idx in self._skip_release_pending
            if fire:
                self._skip_release_pending.discard(idx)
        if fire:
            self._finish_skip_release(idx)

    def release_skipped(self, idx: int) -> None:
        """Param frames for every group bucket ``idx`` covers have
        landed (sharded update): release the bucket's admission key so
        the next round's push can go, and COMMIT the compression
        plane's pending EF residual — the frame's arrival proves the
        owner consumed this round's merge, the same signal a pull gives
        the unsharded path."""
        if idx not in self.skip_buckets:
            raise ValueError(f"bucket {idx} is not a skipped bucket of "
                             f"this round")
        with self._skip_lock:
            if self.bucket_state[idx] == "param_done":
                return
            if self.bucket_state[idx] != "await_param":
                # the owner published before OUR push bookkeeping
                # finished (its publish only needs the push to have
                # REACHED the server): defer to the push task
                self._skip_release_pending.add(idx)
                return
        self._finish_skip_release(idx)

    def _finish_skip_release(self, idx: int) -> None:
        ex = self.ex
        pskey, _ = self.keyed[idx]
        plane = ex._cplane
        if plane is not None and plane.active(pskey):
            plane.commit(pskey, self.rounds[idx])
        self.bucket_state[idx] = "param_done"
        ex._mark_progress()
        ex.plane.gate.release(pskey)
        self._skip_finished(None)

    def _skip_finished(self, exc: Optional[BaseException]) -> None:
        if exc is not None:
            if self._pull_err is None:
                self._pull_err = exc
            if self.readyq is not None:
                self.readyq.put(exc)
        with self._pull_lock:
            self._skips_left -= 1
            done = self._pulls_left <= 0 and self._skips_left <= 0
        if done:
            self._mark_finished()

    def _pull_finished(self, exc: Optional[BaseException]) -> None:
        """Bucket-terminal accounting (pull done, or push/pull failed):
        completes ``drain()`` and surfaces the first failure to the
        ready-stream consumer."""
        if exc is not None:
            if self._pull_err is None:
                self._pull_err = exc
            if self.readyq is not None:
                self.readyq.put(exc)
        with self._pull_lock:
            self._pulls_left -= 1
            grads_done = self._pulls_left <= 0
            all_done = grads_done and self._skips_left <= 0
        if all_done:
            self._mark_finished()
        if grads_done:
            self._pull_done.set()

    def _mark_finished(self) -> None:
        """Terminal accounting for the rounds-in-flight gauge / watchdog
        (idempotent: a drained round that is later abort()ed must not
        double-decrement)."""
        with self._pull_lock:
            if self._finished:
                return
            self._finished = True
        self.ex._m_rounds.dec()

    def drain(self):
        if getattr(self, "aborted", None) is not None:
            raise self.aborted
        if self.ingest:
            # an incompletely-fed round never submits some buckets, so
            # their terminal accounting never fires — waiting would
            # hang; fail loudly instead (and an abort() racing this
            # drain must win over a silent partial result)
            with self.feed_lock:
                missing = sum(not f for f in self.fed)
            if missing:
                raise RuntimeError(
                    f"exchange_ingest result() with {missing} leaves "
                    f"never fed — call feed() for every leaf and "
                    f"finish() before draining")
        self._pull_done.wait()
        if self.aborted is not None:
            raise self.aborted
        if self._pull_err is not None:
            raise self._pull_err
        return self.assemble()

    def ready_iter(self):
        yielded = 0
        n = getattr(self, "_stream_n", len(self.out))
        while yielded < n:
            item = self.readyq.get()
            if isinstance(item, BaseException):
                raise item
            yield item
            yielded += 1

    # ------------------------------------------------------ ingest path

    def feed(self, leaf_ids, values) -> None:
        pairs = list(zip(leaf_ids, values))   # values may be one-shot
        for li, v in pairs:
            # the bucket plan's segment offsets were computed from the
            # template — a mismatched leaf would make the native pack's
            # pointer math read out of bounds, silently
            if (int(np.prod(getattr(v, "shape", ()))) !=
                    int(np.prod(self.shapes[li]))
                    or np.dtype(v.dtype) != self.dtypes[li]):
                raise ValueError(
                    f"fed leaf {li} is {getattr(v, 'shape', ())}/"
                    f"{v.dtype}, plan expects {self.shapes[li]}/"
                    f"{self.dtypes[li]}")
            if hasattr(v, "copy_to_host_async") and (
                    self.host_leaves is None or li in self.host_leaves):
                v.copy_to_host_async()   # start D2H before any pack —
                #                          skipped for leaves only
                #                          device-encoded buckets cover
                #                          (their payload IS the D2H)
        self.ex._mark_progress()
        fire: List[int] = []
        with self.feed_lock:
            if self.feed_done:
                raise RuntimeError("feed() after finish()")
            for li, v in pairs:
                if self.fed[li]:
                    raise ValueError(f"leaf {li} fed twice")
                self.fed[li] = True
                self.sources[li] = v
                for bi in self.leaf_buckets.get(li, ()):
                    self.bucket_need[bi] -= 1
                    if self.bucket_need[bi] == 0:
                        fire.append(bi)
        for bi in fire:
            self.submit_bucket(bi)

    def finish_feed(self) -> None:
        with self.feed_lock:
            missing = [li for li, f in enumerate(self.fed) if not f]
            self.feed_done = True
        if missing:
            raise ValueError(
                f"exchange_ingest round finished with {len(missing)} "
                f"leaves never fed (first missing: {missing[:5]}) — every "
                f"flat leaf must be handed over exactly once")

    def abort(self, exc: BaseException) -> None:
        self.aborted = exc
        if self.keyed:              # keep the in-flight gauge/watchdog
            self._mark_finished()   # from counting a dead round forever
        self._pull_done.set()       # a drain() blocked on straggler
        if self.readyq is not None:  # pulls must wake and raise
            self.readyq.put(exc)


class PSGradientExchange:
    """Sync-mode bucketed gradient exchange through the host PS service.

    The exchange is PIPELINED per bucket (BPS_PS_PIPELINE threads,
    default 4; ≤1 = serial): bucket k+1's pack+push runs while bucket
    k's pull is blocked on the server's merge, and the pull lands as
    soon as that merge publishes — the reference's free-running
    push/pull loops (core_loops.cc:538-618) rather than a
    push-everything-then-pull-everything barrier. Requires a transport
    with >1 connection per shard (RemotePSBackend pools,
    BPS_PS_CONNS) so a round-blocked PULL doesn't stall later PUSH
    frames; the in-process backend is natively concurrent."""

    def __init__(self, backend: HostPSBackend, partition_bytes: int = 4 << 20,
                 registry: Optional[NameRegistry] = None,
                 min_compress_bytes: int = 65536,
                 pipeline_depth: Optional[int] = None,
                 watchdog_sec: Optional[float] = None,
                 compress: Optional[str] = None,
                 max_lag: Optional[int] = None,
                 worker_id: Optional[int] = None) -> None:
        self.backend = backend
        self.partition_bytes = partition_bytes
        self.registry = registry or NameRegistry()
        self.min_compress_bytes = min_compress_bytes
        # fused compression plane (byteps_tpu.compress): per-bucket
        # codecs composed into THIS pipeline — compress on the pack
        # worker right before PUSH, decompress on the pull path feeding
        # the H2D/apply tail — with the codec level decided per layer
        # at round boundaries (BPS_COMPRESS=auto) or pinned
        # (=fp16|int8|topk). None (=none, the default) keeps the dense
        # path bit-identical to a plane-less build. The explicit arg
        # (Config.compress, wired by GlobalState and the trainer) wins;
        # the env fallback covers directly-constructed exchanges.
        from ..compress.plane import CompressionPlane
        self._cplane = CompressionPlane.from_config(
            compress, min_bytes=min_compress_bytes)
        if self._cplane is not None:
            # capability check at CONFIG time, not mid-training: with
            # auto mode an incapable backend would otherwise train fine
            # on an idle wire for hours and crash the moment the
            # controller first ratchets a layer up
            if not hasattr(backend, "push_fused"):
                raise ValueError(
                    f"BPS_COMPRESS={self._cplane.mode!r} needs a "
                    f"backend with push_fused/pull_fused; "
                    f"{type(backend).__name__} has neither")
            chk = getattr(backend, "_check_fused_shards", None)
            if chk is not None:
                chk()    # a plane backend also vets its shard list
        self.pipeline_depth = (int(os.environ.get("BPS_PS_PIPELINE", "4"))
                               if pipeline_depth is None else pipeline_depth)
        self.timeline = None            # set by GlobalState when tracing
        self._plans: Dict = {}
        # pskey -> per-layer ps/pull_bytes/<decl>.<bucket> counter,
        # registered at plan time (see _plan)
        self._pull_layer: Dict[int, object] = {}
        # pskey -> per-layer ps/d2h_bytes/<decl>.<bucket> counter —
        # bytes a bucket moved across D2H (its dense segments on the
        # host path, the encoded payload on the device-encode path)
        self._d2h_layer: Dict[int, object] = {}
        # can the backend carry the fused-managed declaration on init?
        # (duck-typed test backends may speak push_fused without it)
        import inspect as _inspect
        try:
            self._init_fused_ok = "fused" in _inspect.signature(
                backend.init_key).parameters
        except (TypeError, ValueError):
            self._init_fused_ok = False
        # device-side PS_COMPRESS (compress/device.py): resolved + probed
        # lazily at the first eligible bucket so CPU rigs with the
        # default auto mode never pay the probe
        self._dev_enc: Optional[bool] = None
        self._key_rounds: Dict[int, int] = {}
        self._key_rounds_lock = threading.Lock()
        self._push_ex: Optional[ThreadPoolExecutor] = None
        self._pull_ex: Optional[ThreadPoolExecutor] = None
        self._ex_lock = threading.Lock()
        # unified admission plane (server/admission.py): owns the
        # per-key push gate (depth K — a key with K pushed-but-unpulled
        # rounds holds later pushes in a per-key FIFO), the
        # landed-bucket pull priority queue, and — via the process
        # global — the two-class wire send scheduler. K=1 (the default)
        # is the classic two-rounds-in-flight cross-step window; K>1
        # routes dense rounds through the server's bounded-staleness
        # store (BPS_MAX_LAG / push_lag / pull_lag).
        self.plane = AdmissionPlane(max_lag=max_lag, worker_id=worker_id)
        if self.plane.max_lag > 1 and not hasattr(backend, "push_lag"):
            # config-time capability check, mirroring the compression
            # plane's: a backend without the versioned-round surface
            # would silently train at K=1 while the worker runs ahead
            raise ValueError(
                f"BPS_MAX_LAG={self.plane.max_lag} needs a backend "
                f"with declare_lag/push_lag/pull_lag; "
                f"{type(backend).__name__} has none")
        # per-PS-key worker compressor chain (momentum→ef→codec) — holds
        # EF error / momentum state, so it outlives the plan cache entry
        # (reference: per-partition compressor_list in BPSContext,
        # common.h:202, operations.cc:380-385)
        self._chains: Dict[int, object] = {}
        # native bucket pack/unpack (BPS_NATIVE_PACK=0 forces the numpy
        # per-segment path for A/B); falls back when the .so is absent
        self._native_pack = os.environ.get("BPS_NATIVE_PACK", "1") != "0"
        if self._native_pack:
            try:
                from .engine import _lib
                _lib()
            except Exception:   # noqa: BLE001 — toolchain-less install
                self._native_pack = False
        # observability: always-on registry handles (cached — the
        # registry lookup is locked, the hot-path inc/observe is not)
        # plus the stall watchdog (BPS_WATCHDOG_SEC>0), started with
        # the first exchange so idle constructions stay thread-free
        reg = get_registry()
        self._m_push_bytes = reg.counter("ps/push_bytes")
        self._m_pull_bytes = reg.counter("ps/pull_bytes")
        self._m_d2h_bytes = reg.counter("ps/d2h_bytes")
        self._m_buckets = reg.counter("ps/buckets_completed")
        self._m_rounds = reg.gauge("ps/rounds_in_flight")
        import time as _time
        # MONOTONIC: an NTP step on the wall clock must neither fake a
        # stall nor hide one (the watchdog diffs this against its own
        # monotonic now)
        self._progress_t = _time.monotonic()
        self._live_rounds: List = []      # weakrefs, pruned on register
        self._rounds_reg_lock = threading.Lock()
        self._watchdog = None
        # explicit arg (Config.watchdog_sec, wired by GlobalState and
        # the trainer) wins; the env fallback covers directly-
        # constructed exchanges (tests, scripts without bps.init)
        self._watchdog_sec = (float(watchdog_sec)
                              if watchdog_sec is not None else float(
                                  os.environ.get("BPS_WATCHDOG_SEC", "0")
                                  or 0))

    def close(self) -> None:
        """Stop the pipeline executors and the watchdog (idempotent).
        bps.shutdown() calls this — without it every init/shutdown
        cycle would strand 2×pipeline_depth idle threads."""
        if self._watchdog is not None:
            self._watchdog.stop()
            self._watchdog = None
        for ex in (self._push_ex, self._pull_ex):
            if ex is not None:
                ex.shutdown(wait=False)
        self._push_ex = self._pull_ex = None

    # -------------------------------------------- observability hooks

    def _mark_progress(self) -> None:
        """A bucket advanced (push landed / pull completed / leaf fed):
        re-arm the stall watchdog's clock (monotonic — see __init__)."""
        import time
        self._progress_t = time.monotonic()

    def _register_round(self, rnd: "_Round") -> None:
        import weakref
        with self._rounds_reg_lock:
            alive = []
            for ref in self._live_rounds:
                r = ref()           # deref once: the target may be
                if r is not None and not r._finished:   # GC'd between
                    alive.append(ref)                   # two calls
            alive.append(weakref.ref(rnd))
            self._live_rounds = alive
        self._m_rounds.inc()

    def in_flight_buckets(self) -> int:
        """Buckets of live rounds whose pull has not completed."""
        n = 0
        with self._rounds_reg_lock:
            for ref in self._live_rounds:
                r = ref()
                if r is not None and not r._finished:
                    # sharded rounds: buckets awaiting the owner's param
                    # publish are in flight too (their admission keys
                    # are held) — the watchdog must see a dead owner's
                    # wedge, not an idle exchange
                    n += max(0, r._pulls_left) + max(0, r._skips_left)
        return n

    def progress_state(self):
        """(last progress MONOTONIC timestamp, in-flight bucket count)
        — the StallWatchdog's poll target."""
        return self._progress_t, self.in_flight_buckets()

    def debug_state(self) -> dict:
        """Per-key snapshot of the live exchange state: every unfinished
        round's buckets (round number + pending/pushed/pulled/failed)
        and the admission gate's holders and queued waiters — what the
        watchdog dumps when the pipeline wedges."""
        rounds = []
        with self._rounds_reg_lock:
            live = [r() for r in self._live_rounds]
        for r in live:
            if r is None or r._finished:
                continue
            buckets = []
            for i, (pskey, _) in enumerate(r.keyed):
                b = {"pskey": pskey, "round": r.rounds[i],
                     "state": r.bucket_state[i]}
                if r.sharded is not None and i in r.skip_buckets:
                    # param-publish state (sharded update): name EVERY
                    # owner replica a frame must come from (boundary
                    # buckets can wait on two), so a dead-owner wedge
                    # is attributable from the dump
                    owners = r.sharded.skip_owner.get(i, ())
                    b["owner"] = (owners[0] if len(owners) == 1
                                  else list(owners))
                buckets.append(b)
            rounds.append({
                "name": r.decl_name,
                "step": r.step_tag,
                "seq": r.round_seq,
                "pulls_left": r._pulls_left,
                "skips_left": r._skips_left,
                "buckets": buckets,
            })
        return {"in_flight": self.in_flight_buckets(),
                "rounds": rounds, "admission": self.plane.gate.state()}

    def _ensure_watchdog(self) -> None:
        if self._watchdog is not None or self._watchdog_sec <= 0:
            return
        from ..obs.watchdog import StallWatchdog
        # locked check-and-create: two concurrent first exchanges must
        # not each start a watchdog thread (close() could only ever
        # stop the survivor)
        with self._rounds_reg_lock:
            if self._watchdog is None:
                self._watchdog = StallWatchdog(self, self._watchdog_sec)

    def _plan(self, tree, name: Optional[str]):
        leaves, treedef = jax.tree_util.tree_flatten(tree)
        key = (name, treedef, tuple((l.shape, str(l.dtype)) for l in leaves))
        if key in self._plans:
            return self._plans[key]
        # Distinct trees must land on distinct PS keys. Anonymous trees
        # get position-stable auto names, so key assignment matches across
        # workers as long as their exchange order matches — the same
        # declaration-order contract the reference has (global.cc:412-429).
        decl_name = name or f"grads{len(self._plans)}"
        decl = (self.registry.get(decl_name)
                if decl_name in self.registry.declared_names()
                else self.registry.declare(decl_name))
        paths = [jax.tree_util.keystr(p)
                 for p, _ in jax.tree_util.tree_leaves_with_path(tree)]
        specs = [LeafSpec(name=p, size=int(np.prod(l.shape)),
                          dtype=str(np.dtype(l.dtype)))
                 for p, l in zip(paths, leaves)]
        buckets = plan_buckets(specs, self.partition_bytes, reverse_order=True)
        # per-bucket PS keys: declared_key<<16 | bucket (reference:
        # operations.cc:301-317)
        keyed = [(decl.key_for_partition(b.index), b) for b in buckets]
        ckw = decl.compression_kwargs
        compress = bool(ckw.get("compressor_type"))
        for pskey, b in keyed:
            nbytes = b.size * np.dtype(b.dtype).itemsize
            # tensors below the floor skip compression (reference:
            # BYTEPS_MIN_COMPRESS_BYTES, operations.cc:362-364)
            if compress and nbytes >= self.min_compress_bytes:
                from ..ops.compression.host import create_host_chain
                if pskey not in self._chains:
                    self._chains[pskey] = create_host_chain(
                        ckw, b.size, b.dtype)
                self.backend.init_key(pskey, nbytes, b.dtype,
                                      compression=ckw)
                continue
            # fused-plane eligibility decided BEFORE init so the server
            # learns it with the declaration: fused-managed keys get
            # their rounds owned by the homogeneous sum store (legacy
            # kwargs chains keep precedence and stay dense-keyed)
            fused = (self._cplane is not None
                     and self._cplane.register(
                         pskey, b.size, b.dtype,
                         layer=f"{decl_name}.{b.index}"))
            if fused and self._init_fused_ok:
                self.backend.init_key(pskey, nbytes, b.dtype, fused=True)
            else:
                self.backend.init_key(pskey, nbytes, b.dtype)
        # per-layer pull-byte + D2H-byte counters, dynamically
        # registered at plan time exactly like the compress plane's
        # ps/push_bytes/<layer> — the 1/dp pull reduction of the
        # sharded update and the device-encode D2H halving are both
        # directly observable per layer
        for pskey, b in keyed:
            if pskey not in self._pull_layer:
                self._pull_layer[pskey] = get_registry().counter(
                    f"ps/pull_bytes/{decl_name}.{b.index}")
            if pskey not in self._d2h_layer:
                self._d2h_layer[pskey] = get_registry().counter(
                    f"ps/d2h_bytes/{decl_name}.{b.index}")
        if self.plane.max_lag > 1:
            # bounded staleness covers the DENSE path only: compressed
            # chains and fused-plane keys keep their classic one-round
            # stores (their codecs assume complete sums), so they stay
            # at the K=1 contract while dense keys absorb stragglers
            for pskey, b in keyed:
                if self._lag_routes(pskey):
                    self.backend.declare_lag(pskey, self.plane.max_lag)
        if hasattr(self.backend, "set_send_priority"):
            # two-class wire scheduler (admission plane): gradient
            # frames carry reverse-FIRST-USE priority — the bucket
            # holding the earliest-declared (input-side) leaves sends
            # first under BPS_SCHEDULING_CREDIT, the same order the
            # cross-step pull heap drains (pull_prio), so the send and
            # pull sides agree on who gates the next forward
            nleaves = len(leaves)
            for pskey, b in keyed:
                first = min((s.leaf_index for s in b.segments),
                            default=0)
                self.backend.set_send_priority(pskey, nleaves - first)
        plan = (decl_name, treedef, keyed)
        self._plans[key] = plan
        return plan

    def plan_for(self, tree, name: Optional[str] = None) -> None:
        """Pre-declare keys for ``tree`` NOW. Deferred-exchange callers
        (async handles) use this at dispatch so key assignment follows
        program order on every worker even if their synchronize order
        later diverges (the declaration-order contract above)."""
        self._plan(tree, name)

    def leaf_groups(self, tree, name: Optional[str] = None):
        """Partition ``tree``'s flat leaf indices into groups by the LAST
        bucket that covers each leaf — the bucket whose pull completes
        the leaf. Consumers that apply per group (chunked optimizer
        apply) see group k's leaves become ready together around bucket
        k's pull, so group-granular work pipelines with later buckets
        still in flight. Groups are returned in bucket order with empty
        groups dropped; together they cover every leaf exactly once."""
        _, _, keyed = self._plan(tree, name)
        nleaves = len(jax.tree_util.tree_leaves(tree))
        last: Dict[int, int] = {}
        for bi, (_, b) in enumerate(keyed):
            for s in b.segments:
                last[s.leaf_index] = bi       # ascending bi: max wins
        groups: List[List[int]] = [[] for _ in keyed]
        for li in sorted(last):
            groups[last[li]].append(li)
        extras = [li for li in range(nleaves) if li not in last]
        if extras:                  # zero-size leaves: no covering
            if not groups:          # bucket, ready immediately — group 0
                groups = [[]]
            groups[0].extend(sorted(extras))
        return [g for g in groups if g]

    def _record(self, name: str, stage: str, key: int, t0: float,
                step: Optional[int] = None,
                round: Optional[int] = None) -> float:
        """Timeline + stage-histogram helper; returns a fresh t0. The
        histogram observation is ALWAYS on (the latency distributions
        are the production signal); the timeline event only inside a
        trace window. ``round`` tags wire spans (PS_PUSH/PS_PULL) with
        their PS round so the merged trace / critical-path analyzer
        joins them against the server's (key, round) span records."""
        import time
        now = time.time()
        observe_stage(stage, now - t0)
        if self.timeline is not None:
            self.timeline.record(name, stage, t0, now - t0, key,
                                 step=step, round=round)
        return now

    def _next_round(self, pskey: int) -> int:
        """This push's round for ``pskey``, PER KEY. First use of a key
        seeds from the SERVER's completed round — elastic rejoin of a
        live job (the reference's is_recovery skip-barrier analog,
        global.cc:283-297): a predecessor may have died BETWEEN bucket
        pushes, leaving keys at different rounds, so a single per-decl
        seed would misalign the lagging keys forever. Fresh jobs see 0
        everywhere (one extra RPC per key, amortized across the
        pipeline workers). The per-key admission gate serializes two
        live rounds' tasks on one key, but the increment is still
        atomic under the lock — "one task per key per EXCHANGE" is no
        longer "one task per key in flight"."""
        with self._key_rounds_lock:
            cur = self._key_rounds.get(pskey)
        if cur is None:
            # the server RPC stays outside the lock; losing the seed
            # race is fine (both see the same server round)
            cur = (int(self.backend.round(pskey))
                   if hasattr(self.backend, "round") else 0)
        with self._key_rounds_lock:
            nxt = self._key_rounds.get(pskey, cur) + 1
            self._key_rounds[pskey] = nxt
        return nxt

    def _next_round_seq(self) -> int:
        return self.plane.pulls.next_round_seq()

    # ------------------------------------------------ pull scheduling
    #
    # Pushes keep backward-completion order (bucket 0 = output-side
    # layers, available first), but pulls drain by NEXT-STEP FIRST-USE
    # priority — the plane's PullQueue (see admission.PullQueue for the
    # why of that ordering).

    def _enqueue_pull(self, rnd: "_Round", idx: int, buf) -> None:
        self.plane.pulls.put(rnd.round_seq, rnd.pull_prio[idx],
                             (rnd, idx, buf))
        self._pull_ex.submit(self._pull_next)

    def _pull_next(self) -> None:
        """One pull slot: drain the highest-priority landed bucket
        (not necessarily the one whose push scheduled this slot)."""
        rnd, idx, buf = self.plane.pulls.pop()
        pskey, _ = rnd.keyed[idx]
        exc: Optional[BaseException] = None
        try:
            rnd.pull_one(idx, buf)
        except BaseException as e:   # noqa: BLE001 — relayed below
            exc = e
            rnd.bucket_state[idx] = "failed"
            # tail-failure postmortem: the error surfaces to the
            # caller at the next sync point, possibly seconds from
            # now — dump what HAPPENED on this key's path while the
            # flight ring still holds it
            from ..common.logging import get_logger
            flight.dump(get_logger(), keys=[pskey],
                        reason=f"pull failure key={pskey} "
                               f"round={rnd.rounds[idx]}: "
                               f"{type(e).__name__}: {e}")
        finally:
            self.plane.gate.release(pskey)
            rnd._pull_finished(exc)

    def _routed(self, rnd, op) -> None:
        """Run ``op(epoch)`` under the round's placement-epoch tag.
        WrongEpoch (the key migrated after the round resolved its
        routes) is an explicit reroute signal: refresh the view and
        retry ONCE with the fresh epoch — the plane's routing table is
        authoritative, so the second attempt lands on the new owner."""
        if rnd is None or rnd.route_epoch is None:
            return op(None)
        from .plane.placement import WrongEpoch
        try:
            return op(rnd.route_epoch)
        except WrongEpoch:
            rnd.route_epoch = self.backend.placement_epoch()
            return op(rnd.route_epoch)

    def _lag_routes(self, pskey: int) -> bool:
        """Does ``pskey`` ride the bounded-staleness path? Only with
        K>1, and only dense keys (see the _plan declaration note)."""
        return (self.plane.max_lag > 1
                and pskey not in self._chains
                and (self._cplane is None
                     or not self._cplane.active(pskey)))

    def _lag_verdict(self, pskey: int, rnd_num: int, flags: int) -> None:
        """Worker-side note of the server's serve verdict (the server
        records the DECISION; this names what this worker observed)."""
        if flags and flight.get_recorder().enabled:
            verdict = ("barrier" if flags & LAG_BARRIER
                       else "stale")
            flight.record("lag_admit",
                          detail=f"verdict={verdict} key={pskey} "
                                 f"round={rnd_num} (served)")

    def _round_level(self, rnd, idx: int) -> int:
        """The codec level this round's decision trace pinned for
        bucket ``idx`` (0 = none/dense)."""
        if (rnd is None or idx is None
                or getattr(rnd, "clevels", None) is None):
            return 0
        return rnd.clevels[idx]

    def _device_encode_on(self) -> bool:
        """Resolve (once) whether PS_COMPRESS runs on device —
        BPS_COMPRESS_DEVICE plus the bitwise probe-or-fallback
        (compress/device.py)."""
        if self._dev_enc is None:
            if self._cplane is None:
                self._dev_enc = False
            else:
                try:
                    from ..compress.device import device_encode_enabled
                    self._dev_enc = device_encode_enabled()
                except Exception:   # noqa: BLE001 — probe-or-fallback
                    self._dev_enc = False
        return self._dev_enc

    def _d2h_account(self, pskey: int, nbytes: int) -> None:
        self._m_d2h_bytes.inc(nbytes)
        m = self._d2h_layer.get(pskey)
        if m is not None:
            m.inc(nbytes)

    def _push_bucket_device(self, rnd, idx: int):
        """Device-side PS_COMPRESS: gather + EF fold + quantize ON
        DEVICE, D2H only the encoded payload, push it fused. Returns
        the pull staging buffer on success, None to signal the host
        fallback (a host-fed leaf, or a kernel failure — logged once).
        The encode runs BEFORE any state mutation commits, so a
        fallback never leaves a half-staged EF pending."""
        import time

        import jax
        pskey, b = rnd.keyed[idx]
        level = rnd.clevels[idx]
        parts = []
        for s in b.segments:
            src = rnd.sources[s.leaf_index]
            if not isinstance(src, jax.Array):
                return None
            parts.append((src, s.leaf_offset, s.length))
        t0 = time.time()
        try:
            payload, d2h = self._cplane.encode_on_device(
                pskey, parts, level, rnd.rounds[idx])
        except Exception as e:   # noqa: BLE001 — probe-or-fallback
            if not getattr(self, "_dev_warned", False):
                self._dev_warned = True
                from ..common.logging import get_logger
                get_logger().warning(
                    "device encode failed for key %d (%s: %s) — "
                    "falling back to the host codec", pskey,
                    type(e).__name__, e)
            return None
        self._record(rnd.decl_name, "PS_COMPRESS_DEV", pskey, t0,
                     step=rnd.step_tag)
        # honest D2H accounting: a leaf SHARED with a host bucket
        # crosses PCIe dense anyway (it is in host_leaves), so this
        # bucket's segments on such leaves saved nothing — count their
        # dense bytes on top of the payload, or the bench's d2h ratio
        # would report a saving that never physically happened
        if rnd.host_leaves:
            item = np.dtype(b.dtype).itemsize
            d2h += sum(s.length * item for s in b.segments
                       if s.leaf_index in rnd.host_leaves)
        self._d2h_account(pskey, d2h)
        self._m_push_bytes.inc(len(payload))
        try:
            self._routed(rnd, lambda epoch:
                         self.backend.push_fused(pskey, payload,
                                                 epoch=epoch)
                         if epoch is not None
                         else self.backend.push_fused(pskey, payload))
        except Exception as e:
            # mirror push_one's host-path handler: the round counter
            # advanced but the push never landed — drop the entry so a
            # retried exchange() re-seeds from the server's round
            # instead of pulling a round that will never complete
            flight.record("push", key=pskey, round=rnd.rounds[idx],
                          nbytes=len(payload), stage="PS_COMPRESS_DEV",
                          outcome=f"error:{type(e).__name__}")
            with self._key_rounds_lock:
                self._key_rounds.pop(pskey, None)
            raise
        flight.record("push", key=pskey, round=rnd.rounds[idx],
                      nbytes=len(payload), stage="PS_COMPRESS_DEV")
        # pull staging buffer (the fused pull path decodes into its own
        # array; np.empty is malloc-only)
        return np.empty(b.size, dtype=b.dtype)

    def _push_bucket(self, pskey, b, buf, rnd=None, idx=None) -> None:
        # flight-recorder envelope: one event per wire push with its
        # outcome — the postmortem's raw material (obs/flight.py)
        rnd_num = (rnd.rounds[idx]
                   if rnd is not None and idx is not None else None)
        try:
            self._push_bucket_impl(pskey, b, buf, rnd=rnd, idx=idx)
        except BaseException as e:   # noqa: BLE001 — re-raised
            flight.record("push", key=pskey, round=rnd_num,
                          nbytes=buf.nbytes,
                          outcome=f"error:{type(e).__name__}")
            raise
        flight.record("push", key=pskey, round=rnd_num,
                      nbytes=buf.nbytes)

    def _push_bucket_impl(self, pskey, b, buf, rnd=None, idx=None) -> None:
        chain = self._chains.get(pskey)
        if chain is not None:
            # legacy COMPRESS stage right before PUSH (reference:
            # core_loops.cc:498-536): wire bytes are compressed; the
            # server decompresses, dense-sums, recompresses the merge
            payload = chain.compress(buf)
            self._m_push_bytes.inc(len(payload))
            self.backend.push_bytes(pskey, payload)
            return
        plane = self._cplane
        if plane is not None and plane.active(pskey):
            import time
            round_tag = (rnd.rounds[idx]
                         if rnd is not None and idx is not None else 0)
            level = self._round_level(rnd, idx)
            if level:
                # fused PS_COMPRESS stage, on the pack worker the
                # moment the bucket's last leaf landed — EF residual
                # folded in, new residual staged for commit-on-pull.
                # (level > 0 implies a live rnd: levels come from the
                # round's pinned trace, so _record is always valid.)
                t0 = time.time()
                payload = plane.encode(pskey, buf, level, round_tag)
                self._record(rnd.decl_name, "PS_COMPRESS", pskey,
                             t0, step=rnd.step_tag)
                self._m_push_bytes.inc(len(payload))
                self._routed(rnd, lambda epoch:
                             self.backend.push_fused(pskey, payload,
                                                     epoch=epoch)
                             if epoch is not None
                             else self.backend.push_fused(pskey,
                                                          payload))
                return
            # dense round of a plane-managed key: per-layer byte
            # accounting keeps the controller's wire-load signal live
            # at level none (which is when up-ratchets consult it),
            # and any accumulated EF residual from a decayed level is
            # flushed into this dense round once
            plane.note_dense_push(pskey, buf.nbytes)
            buf = plane.fold_residual(pskey, buf, round_tag)
        self._m_push_bytes.inc(buf.nbytes)
        if (rnd is not None and idx is not None
                and self._lag_routes(pskey)):
            # versioned-round push: the server folds it into round
            # rounds[idx] (or the open round, if that one already
            # sealed without us — the late-fold contract)
            self.backend.push_lag(pskey, self.plane.worker_id,
                                  rnd.rounds[idx], buf)
            return
        self._routed(rnd, lambda epoch:
                     self.backend.push(pskey, buf, epoch=epoch)
                     if epoch is not None
                     else self.backend.push(pskey, buf))

    def _pull_layer_inc(self, pskey: int, n: int) -> None:
        m = self._pull_layer.get(pskey)
        if m is not None:
            m.inc(n)

    def _pull_bucket(self, pskey, b, buf, rnd_num, rnd=None, idx=None):
        try:
            out = self._pull_bucket_impl(pskey, b, buf, rnd_num,
                                         rnd=rnd, idx=idx)
        except BaseException as e:   # noqa: BLE001 — re-raised
            flight.record("pull", key=pskey, round=rnd_num,
                          outcome=f"error:{type(e).__name__}")
            from ..compress.wire import CodecError
            if isinstance(e, CodecError):
                # a refused decode is a peer/config divergence, not a
                # stall: dump the key's recent codec decisions and
                # rounds alongside the loud refusal
                from ..common.logging import get_logger
                flight.dump(get_logger(), keys=[pskey],
                            reason=f"CodecError on pull key={pskey} "
                                   f"round={rnd_num}: {e}")
            raise
        flight.record("pull", key=pskey, round=rnd_num,
                      nbytes=buf.nbytes)
        return out

    def _pull_bucket_impl(self, pskey, b, buf, rnd_num, rnd=None,
                          idx=None):
        chain = self._chains.get(pskey)
        if chain is not None:
            payload = self.backend.pull_bytes(pskey, round=rnd_num)
            self._m_pull_bytes.inc(len(payload))
            self._pull_layer_inc(pskey, len(payload))
            return chain.decompress(payload).astype(b.dtype)
        plane = self._cplane
        if plane is not None and plane.active(pskey):
            level = self._round_level(rnd, idx)
            if level:
                import time
                nbytes = b.size * np.dtype(b.dtype).itemsize
                div = plane.topk_div
                payload = self._routed(rnd, lambda epoch:
                                       self.backend.pull_fused(
                                           pskey, nbytes, str(b.dtype),
                                           level, round=rnd_num,
                                           epoch=epoch, div=div)
                                       if epoch is not None
                                       else self.backend.pull_fused(
                                           pskey, nbytes, str(b.dtype),
                                           level, round=rnd_num,
                                           div=div))
                self._m_pull_bytes.inc(len(payload))
                self._pull_layer_inc(pskey, len(payload))
                # PS_DECOMPRESS on the pull → H2D path feeding the
                # chunked apply; commits the round's EF residual.
                # (level > 0 implies a live rnd, as in _push_bucket.)
                t0 = time.time()
                merged = plane.decode(pskey, payload, rnd_num)
                self._record(rnd.decl_name, "PS_DECOMPRESS", pskey,
                             t0, step=rnd.step_tag)
                return merged
        if rnd_num and self._lag_routes(pskey):
            flags = self.backend.pull_lag(pskey, self.plane.worker_id,
                                          rnd_num, buf)
            self._lag_verdict(pskey, rnd_num, flags)
            self._m_pull_bytes.inc(buf.nbytes)
            self._pull_layer_inc(pskey, buf.nbytes)
            return buf
        self._routed(rnd, lambda epoch:
                     self.backend.pull(pskey, buf, round=rnd_num,
                                       epoch=epoch)
                     if epoch is not None
                     else self.backend.pull(pskey, buf, round=rnd_num))
        self._m_pull_bytes.inc(buf.nbytes)
        self._pull_layer_inc(pskey, buf.nbytes)
        if plane is not None:
            # dense round of a plane-managed key: still commit (a
            # residual flush pinned to this round clears on its pull)
            plane.commit(pskey, rnd_num)
        return buf

    def exchange(self, tree, name: Optional[str] = None):
        """One sync round (PER-KEY round counters, server-seeded on
        first use — see _next_round): every bucket is packed, pushed,
        and pulled, pipelined per bucket in priority order (see class
        docstring). Returns the summed tree."""
        return self._exchange_impl(tree, name, detach=False)

    def completed_rounds(self) -> int:
        """Rounds this exchange has COMPLETED — the max per-key round
        counter (0 before any exchange). After a rejoin the counters
        were seeded from the server, so a restarted worker reads how
        far the JOB is, not how far this process got: the fleet
        supervisor's restart path derives "steps remaining" from this
        (docs/launcher.md)."""
        with self._key_rounds_lock:
            return max(self._key_rounds.values(), default=0)

    def exchange_async(self, tree, name: Optional[str] = None):
        """Like ``exchange`` but returns as soon as every bucket's PUSH
        is submitted to the pipeline executors; call ``.result()`` on
        the returned handle to drain the pulls and get the summed tree.

        The contract callers rely on (torch _Dispatcher): this worker's
        pushes reach the wire without waiting for any pull, so a peer's
        round can always complete — a caller holding a scheduling slot
        through a blocking pull cannot deadlock the exchange the way a
        monolithic push+pull call can (two workers' slot pools wedged
        on disjoint key sets; the reference avoids the same geometry
        with free-running separate push/pull loops,
        core_loops.cc:538-618)."""
        return self._exchange_impl(tree, name, detach=True)

    def exchange_stream(self, tree, name: Optional[str] = None,
                        sharded=None):
        """Streaming sync round: returns a ``_StreamingExchange`` whose
        ``ready()`` iterator yields each leaf the moment its last
        covering bucket's pull unpacks. This makes leaf completion
        first-class: the trainer overlaps H2D upload and the chunked
        optimizer apply with still-in-flight pulls of later buckets —
        the step-tail analogue of the reference's free-running pull loop
        feeding the framework as partitions land (operations.cc:140-180).

        ``sharded``: a ``sharded_update`` round view — push every
        bucket, pull only the owned ones, stream only owned leaves."""
        return self._exchange_impl(tree, name, detach=True, stream=True,
                                   sharded=sharded)

    def exchange_ingest(self, template, name: Optional[str] = None,
                        step: Optional[int] = None, sharded=None):
        """Incremental-ingest sync round — the step-HEAD mirror of
        ``exchange_stream``. ``template`` is any tree with the grads'
        structure/shapes/dtypes (typically the param tree; no values
        are read from it). Returns an ``_IngestExchange``: the caller
        ``feed``s leaves group-by-group as the staged backward
        materializes them, each bucket's ``copy_to_host_async`` → pack
        → push fires the moment its last covering leaf arrives (instead
        of requiring the full tree up front), and pulls chase pushes so
        ``ready()``/``result()`` stream exactly like
        ``exchange_stream``. With PR 1's streamed tail this closes the
        full pipeline: bwd(group k+1) ∥ D2H/push(group k) ∥ server-sum
        ∥ pull/H2D/apply."""
        self._ensure_executors()
        self._ensure_watchdog()
        return _IngestExchange(_Round(self, template, name,
                                      stream=True, ingest=True,
                                      step=step, sharded=sharded))

    def _ensure_executors(self) -> None:
        # Creation is locked: the multi-channel torch dispatcher reaches
        # here concurrently, and a double-created pair would orphan
        # threads close() never shuts down
        with self._ex_lock:
            if self._push_ex is None:
                width = max(2, self.pipeline_depth)
                self._push_ex = ThreadPoolExecutor(
                    width, thread_name_prefix="bps-ps-push")
                self._pull_ex = ThreadPoolExecutor(
                    width, thread_name_prefix="bps-ps-pull")

    def _exchange_impl(self, tree, name: Optional[str], detach: bool,
                       stream: bool = False, sharded=None):
        self._ensure_watchdog()
        rnd = _Round(self, tree, name, stream=stream, sharded=sharded)
        for li, l in enumerate(rnd.sources):   # start ALL D2H copies first so
            if hasattr(l, "copy_to_host_async") and (   # transfers overlap
                    rnd.host_leaves is None or li in rnd.host_leaves):
                l.copy_to_host_async()   # device-encoded-only leaves skip —
                #                          their payload IS the D2H

        if not detach and not stream and (self.pipeline_depth <= 1
                                          or len(rnd.keyed) == 1):
            # serial: push everything (the server sums as they land),
            # then drain pulls in the same order
            bufs = [rnd.push_one(i) for i in range(len(rnd.keyed))]
            for i, buf in enumerate(bufs):
                rnd.pull_one(i, buf)
            return rnd.assemble()
        # pipelined (always, for the detached form: its no-deadlock
        # contract needs pushes on executor threads, not the caller's)
        self._ensure_executors()
        for i in range(len(rnd.keyed)):
            rnd.submit_bucket(i)
        if stream:
            return _StreamingExchange(rnd)
        if not detach:
            return rnd.drain()
        return _PendingExchange(rnd.drain)


class AsyncPSWorker:
    """Async-PS training worker: local step + weight-delta push + fresh
    weight pull, no inter-worker barrier.

    ``BPS_ASYNC_WIRE_DTYPE`` (e.g. ``bfloat16``) narrows the DELTA wire
    format: pushes cross the wire at half the bytes and the transport
    (or HostPSBackend) upcasts into the full-precision store. Deltas
    tolerate the rounding (one step's worth of error, folded into a
    fp32 accumulator); the weight PULL stays at store precision by
    default — set ``BPS_ASYNC_PULL_DTYPE`` too only if the model
    tolerates lossy weights."""

    def __init__(self, backend: HostPSBackend, params, name: str = "model",
                 init_store: bool = True,
                 registry: Optional[NameRegistry] = None) -> None:
        import os as _os
        self.backend = backend
        self.wire_dtype = _os.environ.get("BPS_ASYNC_WIRE_DTYPE") or None
        self.pull_dtype = _os.environ.get("BPS_ASYNC_PULL_DTYPE") or None
        if self.wire_dtype is not None:
            np.dtype(self.wire_dtype)     # fail fast on a typo
        leaves, self.treedef = jax.tree_util.tree_flatten(params)
        self.shapes = [l.shape for l in leaves]
        self.dtypes = [str(np.dtype(l.dtype)) for l in leaves]
        self.sizes = [int(np.prod(l.shape)) for l in leaves]
        if registry is not None:
            # registry-assigned key space (declared_key<<16 | i) so several
            # async workers / other declared tensors never collide on PS
            # keys; the legacy bare range stays for single-model scripts
            decl = registry.declare(name)    # idempotent per name
            self.keys = [decl.key_for_partition(i)
                         for i in range(len(leaves))]
        else:
            self.keys = list(range(len(leaves)))
        if init_store:
            for k, l in zip(self.keys, leaves):
                arr = np.ascontiguousarray(np.asarray(l).reshape(-1))
                self.backend.init_key(k, arr.nbytes, str(arr.dtype), init=arr)

    def pull_weights(self):
        outs = []
        for k, n, dt, shp in zip(self.keys, self.sizes, self.dtypes, self.shapes):
            buf = np.empty(n, dtype=self.pull_dtype or dt)
            self.backend.pull(k, buf)
            outs.append(buf.astype(dt).reshape(shp)
                        if self.pull_dtype else buf.reshape(shp))
        return jax.tree_util.tree_unflatten(self.treedef, outs)

    def _wire(self, arr: np.ndarray) -> np.ndarray:
        if self.wire_dtype and str(arr.dtype) != self.wire_dtype:
            arr = arr.astype(self.wire_dtype)
        return np.ascontiguousarray(arr)

    def push_delta(self, new_params, old_params):
        """Push w_new - w_old; the server accumulates deltas into the
        global weights (reference: async push of ``w - prev_w``)."""
        new_l = jax.tree_util.tree_leaves(new_params)
        old_l = jax.tree_util.tree_leaves(old_params)
        for k, nw, od in zip(self.keys, new_l, old_l):
            delta = np.asarray(nw).reshape(-1) - np.asarray(od).reshape(-1)
            self.backend.push(k, self._wire(delta))

    def push_delta_tree(self, delta):
        """Push pre-computed deltas (e.g. produced on-device inside the
        jitted step, so the subtraction — and the wire-dtype cast, see
        DistributedTrainer._delta_fn — fuses and only ONE narrow tree
        crosses D2H instead of two wide ones)."""
        for k, d in zip(self.keys, jax.tree_util.tree_leaves(delta)):
            if hasattr(d, "copy_to_host_async"):
                d.copy_to_host_async()
        for k, d in zip(self.keys, jax.tree_util.tree_leaves(delta)):
            self.backend.push(
                k, self._wire(np.asarray(d).reshape(-1)))


class RowSparseExchange:
    """Sync row-sparse exchange: push touched (idx, rows), pull the dense
    merged table (reference: reserved kRowSparsePushPull,
    common.h:267-271 — no handler existed there; here it is the PS
    path's native sparse mode, implemented for embedding-style grads)."""

    def __init__(self, backend: HostPSBackend,
                 registry: Optional[NameRegistry] = None) -> None:
        self.backend = backend
        self.registry = registry or NameRegistry()
        self._inited: Dict[int, tuple] = {}     # key -> (num_rows, cols)
        self._rounds: Dict[int, int] = {}

    def exchange(self, idx, rows, num_rows: int, name: str) -> np.ndarray:
        """One sync round; returns the dense [num_rows, cols] sum across
        workers. Distinct tables need distinct names (one PS key each)."""
        idx = np.asarray(idx, np.int32).reshape(-1)
        rows = np.asarray(rows)
        if rows.ndim != 2:
            raise ValueError(f"rows must be [n, cols]; got {rows.shape}")
        cols, dtype = rows.shape[1], str(rows.dtype)
        key = self.registry.declare(name).key_for_partition(0)
        dense_nbytes = num_rows * cols * rows.dtype.itemsize
        prev = self._inited.get(key)
        if prev is None:
            self.backend.init_key(key, dense_nbytes, dtype)
            self._inited[key] = (num_rows, cols)
        elif prev != (num_rows, cols):
            raise ValueError(f"table {name!r} was {prev}, now "
                             f"{(num_rows, cols)} — shape must be stable")
        rnd = self._rounds.get(key)
        if rnd is None:
            # server-seeded like the dense exchange: an elastically
            # rejoined worker resumes at the live job's round, not 1
            # (pulling round 1 would return a stale table immediately).
            # Read BEFORE pushing — our own push may complete the round.
            rnd = (int(self.backend.round(key))
                   if hasattr(self.backend, "round") else 0)
        rnd += 1
        self._rounds[key] = rnd
        self.backend.push_rowsparse(key, idx, rows, dense_nbytes, dtype)
        out = np.empty(num_rows * cols, rows.dtype)
        self.backend.pull(key, out, round=rnd)
        return out.reshape(num_rows, cols)

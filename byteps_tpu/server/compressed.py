"""Server-side compressed-key handling for the host reduction service.

The reference server registers a compressor per key from the kwargs the
worker serializes at init (server.cc:222-252), decompresses every push
before handing it to the summation engine, and re-compresses the merged
buffer once per round so pulls ship compressed bytes back
(server.cc:86-113). ``CompressedKeyStore`` is that logic here, wrapped
around any dense backend (the native engine shards in-process, or the
backend behind the TCP transport server).
"""

from __future__ import annotations

import threading
from typing import Dict, Optional, Tuple

import numpy as np

from ..ops.compression.host import (HostCodec, HostErrorFeedback,
                                    create_server_chain)

# recompressed rounds kept per key: all workers pull round r before r+2
# can complete (they must push r+1 first), so 4 is comfortably safe for
# the stochastic codecs where a recompute would yield different bytes
_CACHE_ROUNDS = 4


class CompressedKeyStore:
    """Per-key codecs + once-per-round recompression cache."""

    def __init__(self) -> None:
        self._codecs: Dict[int, HostCodec] = {}
        self._kwargs: Dict[int, Tuple] = {}
        self._lock = threading.Lock()
        # key -> {round: payload bytes}, insertion-ordered for eviction
        self._cache: Dict[int, Dict[int, bytes]] = {}

    def register(self, key: int, kwargs: Dict[str, str], size: int,
                 dtype: str) -> Optional[HostCodec]:
        """Idempotent per key (reference init-push arrives once per
        worker). A re-registration with DIFFERENT kwargs is a
        misconfigured worker whose payloads would be silently misparsed —
        raise instead."""
        ident = (tuple(sorted(kwargs.items())), int(size), str(dtype))
        with self._lock:
            codec = self._codecs.get(key)
            if codec is not None:
                if self._kwargs[key] != ident:
                    raise ValueError(
                        f"key {key} already registered with "
                        f"{self._kwargs[key]}, re-register with {ident} "
                        f"— workers disagree on compression config")
                return codec
            # server chain = ef → compressor (the reference's server
            # registry skips only momentum, compressor_registry.cc:40-56,
            # so recompression error is EF-compensated when configured)
            codec = create_server_chain(kwargs, size, dtype)
            if codec is not None:
                self._codecs[key] = codec
                self._kwargs[key] = ident
                self._cache[key] = {}
            return codec

    def cached(self, key: int, rnd: int) -> Optional[bytes]:
        """Recompressed payload for a completed round, if still cached."""
        if rnd == 0:
            return None
        with self._lock:
            return self._cache.get(key, {}).get(rnd)

    def has(self, key: int) -> bool:
        return key in self._codecs

    def codec(self, key: int) -> HostCodec:
        return self._codecs[key]

    def payload_nbytes(self, key: int) -> int:
        return self._codecs[key].payload_nbytes()

    def decompress(self, key: int, payload) -> np.ndarray:
        return self._codecs[key].decompress(payload)

    def recompress(self, key: int, dense: np.ndarray, rnd: int) -> bytes:
        """Compress the merged buffer for ``rnd``; cached so every worker
        pulling the same round gets byte-identical payloads even for
        stochastic codecs. ``rnd`` 0 (async mode: latest) is never cached
        — the store mutates between pulls — and bypasses error-feedback
        state (compressing every pull would advance the EF accumulator
        many times per merge; EF's round-over-round compensation only
        makes sense for the once-per-round sync path)."""
        if rnd == 0:
            codec = self._codecs[key]
            if isinstance(codec, HostErrorFeedback):
                codec = codec.inner
            return codec.compress(dense)
        with self._lock:
            rounds = self._cache[key]
            buf = rounds.get(rnd)
            if buf is None:
                buf = self._codecs[key].compress(dense)
                rounds[rnd] = buf
                while len(rounds) > _CACHE_ROUNDS:
                    rounds.pop(next(iter(rounds)))
            return buf

    def put_cached(self, key: int, rnd: int, buf: bytes) -> None:
        """Insert an externally-produced (native) recompression for a
        completed round; same eviction as recompress()."""
        if rnd == 0:
            return
        with self._lock:
            rounds = self._cache[key]
            rounds.setdefault(rnd, buf)
            while len(rounds) > _CACHE_ROUNDS:
                rounds.pop(next(iter(rounds)))

    def reset(self) -> None:
        with self._lock:
            self._codecs.clear()
            self._cache.clear()


def _native_codec(store: CompressedKeyStore, backend, key: int):
    """(kind, codec) when the key's chain can run FULLY FUSED in C++
    (zero-Python decompress→enqueue / pull→recompress; reference:
    server.cc:86-113 does codec work inside the engine, not in
    per-connection interpreter threads): bare onebit or topk on fp32
    both ways; bare randomk pushes fused (same wire/scatter as topk).
    Everything else — EF chains, dithering, randomk's recompress,
    non-fp32 keys — routes through the Python chain whose heavy legs
    are themselves native primitives (host.py ``_native``: C++ loops,
    GIL released, chain state stays in Python), so "not fused" no
    longer means "interpreted"."""
    import os
    if os.environ.get("BPS_NATIVE_CODEC", "1") in ("0", "false"):
        return None, None      # A/B knob: force the Python codec path
    from ..ops.compression.host import HostOnebit, HostRandomk, HostTopk
    codec = store._codecs.get(key)
    if codec is None or codec.dtype != np.float32:
        return None, None
    if isinstance(codec, HostOnebit) and hasattr(backend, "push_onebit"):
        return "onebit", codec
    if type(codec) is HostTopk and hasattr(backend, "push_topk"):
        return "topk", codec
    if type(codec) is HostRandomk and hasattr(backend, "push_topk"):
        # randomk's (idx|vals) wire layout and last-wins scatter are
        # identical to topk, so the PUSH side decompress+sum runs
        # native; the RECOMPRESS keeps the Python chain (its
        # worker-synchronized XorShift state lives there) — half the
        # codec work still leaves the GIL
        return "randomk_push", codec
    return None, None


def _native_onebit(store: CompressedKeyStore, backend, key: int):
    """Back-compat shim for the onebit-only check (tests use it)."""
    kind, codec = _native_codec(store, backend, key)
    return codec if kind == "onebit" else None


def compressed_push(store: CompressedKeyStore, backend, key: int,
                    payload) -> None:
    """Decompress → dense push into the summation engine (reference:
    BytePSServerEngineThread decompress before SUM_RECV, server.cc:86-113)."""
    kind, codec = _native_codec(store, backend, key)
    if kind is not None and len(payload) != codec.payload_nbytes():
        # same strictness as the Python decompress (which raises on a
        # mis-sized buffer): a truncated frame must not be silently
        # mis-split into garbage indices/values by the native scatter
        raise ValueError(
            f"key {key}: compressed payload is {len(payload)} "
            f"bytes, codec expects {codec.payload_nbytes()}")
    if kind == "onebit":
        backend.push_onebit(key, payload)
        return
    if kind in ("topk", "randomk_push"):
        backend.push_topk(key, payload)
        return
    backend.push(key, store.decompress(key, payload))


def compressed_pull(store: CompressedKeyStore, backend, key: int,
                    rnd: int, timeout_ms: int = 30000) -> bytes:
    """Dense pull of the merged round → recompress (cached per round).
    A cache hit means the round already completed and was compressed —
    later pullers skip the dense copy out of the engine entirely."""
    buf = store.cached(key, rnd)
    if buf is not None:
        return buf
    kind, codec = _native_codec(store, backend, key)
    if kind == "randomk_push":
        kind = None                   # pull side: Python chain + cache
    if kind is not None:
        if kind == "onebit":
            buf = backend.pull_onebit(key, codec.payload_nbytes(),
                                      round=rnd, timeout_ms=timeout_ms,
                                      use_scale=codec.use_scale)
        else:
            buf = backend.pull_topk(key, codec.payload_nbytes(),
                                    round=rnd, timeout_ms=timeout_ms)
        # deterministic codecs, so caching is for THROUGHPUT, not
        # byte-identity: later pullers of the round skip the dense
        # copy out of the engine and the recompress entirely (without
        # this, native measured SLOWER than Python at 4 workers —
        # every puller paid the full pull+compress the cache elides)
        store.put_cached(key, rnd, buf)
        return buf
    codec = store.codec(key)
    dense = np.empty(codec.size, codec.dtype)
    backend.pull(key, dense, round=rnd, timeout_ms=timeout_ms)
    return store.recompress(key, dense, rnd)

"""Hierarchical intra-host aggregation: the local tier of a two-tier
PS plane (reference: BytePS's intra-node reduce before the NIC —
PAPER.md's ~2x bottleneck-utilization claim rests on never shipping a
byte across hosts that a host-local sum could have absorbed).

``LocalAggBackend`` sits behind an ordinary ``PSTransportServer`` on
each host: the ``local_size`` colocated workers push/pull against it
over loopback/UDS/shm exactly as they would against a remote shard
(same frames, same dedup, same reconnect machinery — the local hop is
a full PS endpoint, not a side channel), it folds each key's
``local_size`` gradients into ONE host sum, and only that sum rides
the cross-host wire to the remote plane shards via a single upstream
``RemotePSBackend`` client. After the remote round completes, ONE
upstream pull feeds every local worker's pull (fan-out staging, the
OP_PULL_PART pattern) — so cross-host bytes are dense/``local_size``
in BOTH directions.

Accounting sees through the tier by construction:

- remote shards run with ``num_workers = hosts`` (one logical
  contribution per host-seal), so engine round gates, ``StaleStore``
  round counts, and span per-worker arrivals all stay exact —
  a host's seal IS ``local_size`` worker contributions;
- the K-lag contract (docs/admission.md) is spoken at host
  granularity: the agg folds per (key, round) and pushes/pulls
  upstream as worker id ``host_id``, so staleness bounds, grace
  seals, and late-folds count hosts;
- fused/compressed keys ride the PR-11 decode-free path locally too:
  codec-homogeneous payloads merge in a host-local ``FusedSumStore``
  and the re-encoded host sum is pushed upstream still compressed —
  the lossless local_size reduction composes multiplicatively with
  the lossy codec one.

Observability: ``ps/local_agg_bytes`` (bytes arriving over the local
hop) vs ``ps/remote_push_bytes`` (bytes this host actually put on the
cross-host wire) make the tier's reduction auditable per process, and
every seal decision is flight-recorded KEY-LESS so any postmortem can
distinguish a slow local hop from a slow remote one.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Dict, Optional, Tuple

import numpy as np

from ..obs import flight
from ..obs.metrics import get_registry


def hier_enabled(local_size: int) -> bool:
    """The BPS_HIER_AGG knob: ``on`` forces the tier (invalid below 2
    workers/host — there is nothing to fold), ``off`` disables it even
    when the topology has one, ``auto`` (default) enables it exactly
    when a host groups more than one worker."""
    mode = os.environ.get("BPS_HIER_AGG", "auto").strip().lower()
    if mode in ("off", "0", "false", "no"):
        return False
    if mode in ("on", "1", "true", "yes"):
        if local_size < 2:
            raise ValueError(
                f"BPS_HIER_AGG=on with local_size={local_size}: the "
                "local tier needs >=2 workers per host to fold")
        return True
    return local_size > 1


_STAGE_TTL_SECS = 120.0


class _KeyState:
    """Per-key local fold: the host's partial sum for the round in
    flight. Count-based like the engine (local_size arrivals = one
    seal) — the same round semantics the flat path has, shifted one
    tier down."""

    __slots__ = ("nbytes", "dtype", "acc", "arrived", "sealed", "lock")

    def __init__(self, nbytes: int, dtype: str) -> None:
        self.nbytes = int(nbytes)
        self.dtype = dtype
        self.acc: Optional[np.ndarray] = None
        self.arrived = 0
        self.sealed = 0          # local rounds sealed (pushed upstream)
        self.lock = threading.Lock()


class LocalAggBackend:
    """The per-host local aggregator backend (see module docstring).

    Satisfies the full backend surface ``PSTransportServer`` consumes —
    dense (push/pull/round), fused (push_fused/pull_fused), and K-lag
    (declare_lag/push_lag/pull_lag) — so the front transport needs no
    hierarchical special-casing at all.

    The ONE surface it refuses is the sharded embedding store
    (OP_EMBED_*): rowsparse pushes compose — the transport expands
    them to dense and this backend folds the dense sum like any other
    (tests/test_hier.py pins the parity) — but embed tables must NOT
    ride the agg: there is no row store here, and passing through
    would re-shard one table's rows across the agg's upstream plane.
    ``is_local_agg`` lets the transport's ``embed_store`` refuse
    loudly at first use (docs/embedding.md failure matrix)."""

    is_local_agg = True

    def __init__(self, upstream, local_size: int, host_id: int = 0) -> None:
        self.upstream = upstream
        self.num_workers = int(local_size)   # the transport's gate size
        self.host_id = int(host_id)
        self._keys: Dict[int, _KeyState] = {}
        self._keys_lock = threading.Lock()
        self._inited: set = set()
        # fan-out staging: ONE upstream fetch per (key, round[, codec])
        # feeds every local puller — the OP_PULL_PART stage pattern.
        # TTL-swept so a worker dying mid-pull can't strand stages.
        self._stages: Dict[Tuple, Dict] = {}
        self._stage_lock = threading.Lock()
        self._stage_sweep_at = 0.0
        # K-lag local folds: (key, round) -> [acc, arrived]; several
        # rounds coexist (that is what the lag bound buys)
        self._lag_acc: Dict[Tuple[int, int], list] = {}
        self._lag_declared: Dict[int, int] = {}
        # local fused store: codec-homogeneous host merge, decode-free
        from .homog import FusedSumStore
        self._fstore = FusedSumStore(self.num_workers)
        reg = get_registry()
        self.m_local_bytes = reg.counter("ps/local_agg_bytes")
        self.m_remote_bytes = reg.counter("ps/remote_push_bytes")

    # ------------------------------------------------------------ dense

    def init_key(self, key: int, nbytes: int, dtype: str = "float32",
                 init: Optional[np.ndarray] = None,
                 fused: bool = False) -> None:
        key = int(key)
        with self._keys_lock:
            st = self._keys.get(key)
            if st is None or (st.nbytes, st.dtype) != (int(nbytes), dtype):
                self._keys[key] = _KeyState(nbytes, dtype)
            first = key not in self._inited
            self._inited.add(key)
        if fused:
            from .homog import homog_enabled
            if homog_enabled():
                self._fstore.init_key(key, nbytes, dtype, init)
        # every local worker INITs; forward once — the upstream client
        # keeps an init replay log per key and the remote store is
        # first-wins anyway, so duplicate fan-up is pure wire noise
        if first:
            self.upstream.init_key(key, nbytes, dtype, init=init,
                                   fused=fused)

    def _state(self, key: int) -> _KeyState:
        st = self._keys.get(int(key))
        if st is None:
            raise KeyError(f"push/pull({key}) before init")
        return st

    def push(self, key: int, data: np.ndarray) -> None:
        """Local fold; the ``local_size``-th arrival SEALS the host
        round and pushes the one host sum upstream (the only dense
        bytes that ever cross hosts)."""
        st = self._state(key)
        self.m_local_bytes.inc(int(data.nbytes))
        with st.lock:
            if st.acc is None:
                st.acc = np.array(data, dtype=st.dtype, copy=True)
                st.arrived = 1
            else:
                st.acc += data.astype(st.dtype, copy=False)
                st.arrived += 1
            if st.arrived < self.num_workers:
                return
            host_sum, st.acc, st.arrived = st.acc, None, 0
            st.sealed += 1
            rnd = st.sealed
        t0 = time.time()
        self.upstream.push(key, host_sum)
        self.m_remote_bytes.inc(int(host_sum.nbytes))
        # key-less by design: seal events are context for EVERY key's
        # postmortem (slow local hop vs slow remote hop)
        flight.record("hier_seal", round=rnd, nbytes=int(host_sum.nbytes),
                      detail=f"dense fanin={self.num_workers} "
                             f"up_ms={(time.time() - t0) * 1e3:.1f}")

    # ------------------------------------------------ fan-out staging

    def _sweep_stages(self, now: float) -> None:
        if now < self._stage_sweep_at:
            return
        self._stage_sweep_at = now + 30.0
        cutoff = now - _STAGE_TTL_SECS
        for k in [k for k, st in self._stages.items()
                  if st["t"] < cutoff and st["ev"].is_set()]:
            del self._stages[k]

    def _staged_fetch(self, stage_key: Tuple, fetch, timeout_ms: int):
        """ONE upstream fetch per stage key, fanned out to every local
        caller. The first caller runs ``fetch`` in its own connection
        thread; the other ``local_size - 1`` wait on the event. An
        errored fetch is served to current waiters and the stage popped
        immediately so the next retry slice re-fetches; a successful
        stage lives until ``local_size`` callers were served (or TTL)."""
        now = time.time()
        with self._stage_lock:
            self._sweep_stages(now)
            st = self._stages.get(stage_key)
            if st is None:
                st = {"ev": threading.Event(), "data": None, "err": None,
                      "served": 0, "t": now}
                self._stages[stage_key] = st
                first = True
            else:
                st["t"] = now
                first = False
        if first:
            try:
                st["data"] = fetch()
            except Exception as e:  # noqa: BLE001 — relayed to callers
                st["err"] = e
            finally:
                st["ev"].set()
        if not st["ev"].wait(timeout=(int(timeout_ms) or 30000) / 1e3 + 5):
            # fetch still in flight: retryable, and NOT served — a
            # premature served count could pop the stage under it
            raise TimeoutError(
                f"hier fetch {stage_key} did not resolve in time")
        with self._stage_lock:
            if st["err"] is not None:
                self._stages.pop(stage_key, None)
            else:
                st["served"] += 1
                if st["served"] >= self.num_workers:
                    self._stages.pop(stage_key, None)
        if st["err"] is not None:
            raise st["err"]
        return st["data"]

    def pull(self, key: int, out: np.ndarray, round: int = 0,
             timeout_ms: int = 30000) -> None:
        key = int(key)
        if not round:
            # async/snapshot pull of "latest": no round to stage on —
            # forward per caller (rare control-plane path)
            self.upstream.pull(key, out, round=0, timeout_ms=timeout_ms)
            return

        def fetch():
            buf = np.empty_like(out)
            self.upstream.pull(key, buf, round=int(round),
                               timeout_ms=int(timeout_ms) or 30000)
            return buf

        data = self._staged_fetch((key, int(round)), fetch, timeout_ms)
        np.copyto(out, data)

    def round(self, key: int) -> int:
        """GLOBAL rounds (host seals advance them 1:1 with worker
        rounds), so elastic rejoin reseeds from the same counter the
        flat path would."""
        return int(self.upstream.round(int(key)))

    # ------------------------------------------------------------ fused

    def push_fused(self, key: int, payload) -> None:
        key = int(key)
        self.m_local_bytes.inc(len(payload))
        if self._fstore.managed(key):
            from ..compress import wire
            cid = wire.peek(payload)[0]
            before = self._fstore.round(key)
            self._fstore.ingest(key, payload)
            after = self._fstore.round(key)
            # the local_size-th homogeneous payload sealed round(s):
            # re-encode the host merge at the SAME codec and push it
            # upstream still compressed (lossless x lossy composition)
            for r in range(before + 1, after + 1):
                merged = self._fstore.pull_payload(
                    key, cid, r, timeout_ms=5000, div=wire.TOPK_DIV)
                self.upstream.push_fused(key, merged)
                self.m_remote_bytes.inc(len(merged))
                flight.record("hier_seal", round=r, nbytes=len(merged),
                              detail=f"fused cid={cid} "
                                     f"fanin={self.num_workers}")
            return
        # unmanaged fused push: decode once locally, ride the dense fold
        from ..compress import wire
        st = self._state(key)
        dense = wire.decode_for_store(payload, (st.nbytes, st.dtype))
        self.push(key, dense)

    def pull_fused(self, key: int, nbytes: int, dtype: str, codec: int,
                   round: int = 0, timeout_ms: int = 30000,
                   div: Optional[int] = None) -> bytes:
        key = int(key)
        fetch = lambda: self.upstream.pull_fused(  # noqa: E731
            key, int(nbytes), dtype, int(codec), round=int(round),
            timeout_ms=int(timeout_ms) or 30000, div=div)
        if not round:
            return fetch()
        return self._staged_fetch((key, int(round), int(codec), div),
                                  fetch, timeout_ms)

    def drop_cached(self, key: int) -> None:
        """New tenancy of the key (migration re-init): cached fused
        stages for recurring round numbers must not alias."""
        with self._stage_lock:
            for k in [k for k in self._stages if k[0] == int(key)]:
                del self._stages[k]

    # ----------------------------------------------------------- K-lag

    def declare_lag(self, key: int, max_lag: int) -> None:
        self._lag_declared[int(key)] = int(max_lag)
        self.upstream.declare_lag(int(key), int(max_lag))

    def push_lag(self, key: int, worker: int, rnd: int,
                 data: np.ndarray) -> None:
        """Per-(key, round) local fold — several rounds coexist, that
        is the lag bound. The host's round seal goes upstream as ONE
        contribution from worker id ``host_id`` (staleness at host
        granularity: a local straggler delays its host's seal, and the
        REMOTE StaleStore's grace/late-fold machinery absorbs the
        missing HOST, exactly-once, contribution gap counted in
        hosts)."""
        key, rnd = int(key), int(rnd)
        st = self._state(key)
        self.m_local_bytes.inc(int(data.nbytes))
        with st.lock:
            ent = self._lag_acc.get((key, rnd))
            if ent is None:
                ent = self._lag_acc[(key, rnd)] = [
                    np.array(data, dtype=st.dtype, copy=True), 1]
            else:
                ent[0] += data.astype(st.dtype, copy=False)
                ent[1] += 1
            if ent[1] < self.num_workers:
                return
            self._lag_acc.pop((key, rnd))
            host_sum = ent[0]
        self.upstream.push_lag(key, self.host_id, rnd, host_sum)
        self.m_remote_bytes.inc(int(host_sum.nbytes))
        flight.record("hier_seal", round=rnd, nbytes=int(host_sum.nbytes),
                      detail=f"lag fanin={self.num_workers} "
                             f"host={self.host_id}")

    def pull_lag(self, key: int, worker: int, rnd: int, out: np.ndarray,
                 timeout_ms: int = 30000) -> int:
        key, rnd = int(key), int(rnd)

        def fetch():
            buf = np.empty_like(out)
            flags = self.upstream.pull_lag(key, self.host_id, rnd, buf,
                                           timeout_ms=int(timeout_ms)
                                           or 30000)
            return int(flags), buf

        flags, data = self._staged_fetch((key, rnd, "lag"), fetch,
                                         timeout_ms)
        np.copyto(out, data)
        return int(flags)

    # ------------------------------------------------------------ misc

    def close(self) -> None:
        try:
            self.upstream.close()
        except Exception:
            pass

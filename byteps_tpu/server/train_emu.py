"""END-TO-END training A/B under emulated NICs: ring vs PS vs
PS+compression vs PS+CrossBarrier.

Round 3 proved the PS pattern's bandwidth win at the EXCHANGE level
(allreduce_emu.py: one G-byte round through throttled sockets). The
reference's claim is stronger — "double the *training speed*"
(reference: README.md:9,46; docs/performance.md whole-model img/s
tables) — so this module trains a real torch model end to end with
N REAL worker processes, all gradient traffic charged to per-endpoint
``throttle.Nic`` token buckets, and compares:

  - ``ring``   — bucketed ring allreduce between the worker processes
    (reduce-scatter + all-gather over throttled TCP), with backward
    OVERLAP: grads enter a comm thread's queue the moment autograd
    produces them (hook order is identical across workers, so the
    collectives match). This is the Horovod-style baseline, given the
    same courtesy overlap the PS arm gets from its dispatcher.
  - ``ps``     — the torch plugin path: ``DistributedOptimizer`` over
    ``s = n`` standalone throttled PS servers (the reference's win
    condition: spare server NICs).
  - ``ps_onebit`` — same, with the onebit codec registered on every
    Gradient.* key ≥ BPS_MIN_COMPRESS_BYTES: 32× fewer wire bytes,
    decompress→sum→recompress on the (native) server engine.
  - ``cb``     — ``ps`` + ``CrossBarrier`` per-parameter scheduling.

Every worker feeds the SAME global batch, so ring / ps / cb loss
trajectories must track serial single-process training to float
tolerance (rtol=1e-5, CI-asserted in tests/test_train_emu.py — the
ring's left-to-right partial-sum order is not bit-identical to the
serial sum for every n); onebit is lossy and is asserted on
convergence instead. samples/sec is measured per mode.

Run ``examples/ps_training_ab.py`` for the sweep table in
docs/performance.md.
"""

from __future__ import annotations

import json
import os
import socket
import subprocess
import sys
import threading
import time
from typing import Dict, List, Optional

import numpy as np

from .throttle import Nic, ThrottledSocket

__all__ = ["RingPeer", "run_training", "serial_reference"]


# --------------------------------------------------------------------------
# process-based ring
# --------------------------------------------------------------------------

class RingPeer:
    """One worker process's membership in a ring over throttled TCP.

    Worker i accepts from worker i-1 and dials worker i+1 (mod n); both
    directions are charged to THIS endpoint's ``Nic``. ``allreduce``
    runs the bandwidth-optimal reduce-scatter + all-gather (2(n-1)
    steps, each moving ceil(len/n) elements), the same schedule as
    ``allreduce_emu.ring_allreduce`` but persistent across calls so a
    training loop can reuse the wiring every step."""

    def __init__(self, index: int, n: int, ports: List[int],
                 rate: float, latency: float = 0.0,
                 connect_timeout: float = 60.0) -> None:
        self.i, self.n = index, n
        nic = Nic(rate, latency) if rate > 0 else None

        # bind with retry: the parent probed these ports as free, but
        # each worker spends seconds importing torch before binding —
        # a stranger can grab the port in that window (TOCTOU). Retry
        # absorbs TIME_WAIT and transient squatters; a persistent owner
        # still surfaces as EADDRINUSE at the deadline.
        deadline0 = time.time() + connect_timeout / 2
        while True:
            ls = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
            ls.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
            try:
                ls.bind(("127.0.0.1", ports[index]))
                break
            except OSError:
                ls.close()
                if time.time() >= deadline0:
                    raise
                time.sleep(0.1)
        ls.listen(1)
        self._listener = ls

        # dial the next peer with retry (it may not be listening yet),
        # accepting from the previous peer concurrently — a sequential
        # connect-then-accept deadlocks the ring at n=2
        nxt = ("127.0.0.1", ports[(index + 1) % n])
        out_sock: List[Optional[socket.socket]] = [None]
        err: List[BaseException] = []

        def dial() -> None:
            deadline = time.time() + connect_timeout
            while True:
                try:
                    s = socket.create_connection(nxt, timeout=2.0)
                    s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
                    s.settimeout(None)
                    out_sock[0] = s
                    return
                except OSError as e:
                    if time.time() >= deadline:
                        err.append(e)
                        return
                    time.sleep(0.05)

        t = threading.Thread(target=dial, daemon=True)
        t.start()
        ls.settimeout(connect_timeout)
        conn, _ = ls.accept()
        conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        t.join()
        if err:
            raise err[0]
        self._tx_raw, self._rx_raw = out_sock[0], conn
        self.nic = nic             # byte counters read by the curve rig
        if nic is not None:
            self._tx = ThrottledSocket(out_sock[0], nic)
            self._rx = ThrottledSocket(conn, nic)
        else:
            self._tx, self._rx = out_sock[0], conn

    def allreduce(self, x: np.ndarray) -> np.ndarray:
        """In-place-ish sum-allreduce of a flat fp32 array; returns the
        summed array (padded schedule, result trimmed)."""
        from .allreduce_emu import ring_rounds
        n = self.n
        if n == 1:
            return x
        elems = x.size
        chunk = -(-elems // n)
        buf = np.zeros(chunk * n, np.float32)
        buf[:elems] = x
        ring_rounds(self._tx, self._rx, buf.reshape(n, chunk), n, self.i)
        return buf[:elems]

    def close(self) -> None:
        for s in (self._tx_raw, self._rx_raw, self._listener):
            try:
                s.close()
            except Exception:
                pass


# --------------------------------------------------------------------------
# worker process body (one per mode; dispatched by __main__ below)
# --------------------------------------------------------------------------

def _build_model(width: int, depth: int):
    import torch
    torch.manual_seed(0)
    layers = []
    for _ in range(depth):
        layers += [torch.nn.Linear(width, width), torch.nn.Tanh()]
    return torch.nn.Sequential(*layers)


def _global_batch(width: int, batch: int):
    import torch
    rs = np.random.RandomState(1)
    x = torch.tensor(rs.randn(batch, width), dtype=torch.float32)
    y = torch.tensor(rs.randn(batch, width), dtype=torch.float32)
    return x, y


def serial_reference(steps: int, width: int = 256, depth: int = 8,
                     batch: int = 64, lr: float = 0.05) -> List[float]:
    """Single-process torch training on the same global batch — the
    trajectory every lossless distributed mode must reproduce."""
    import torch
    model = _build_model(width, depth)
    opt = torch.optim.SGD(model.parameters(), lr=lr)
    x, y = _global_batch(width, batch)
    losses = []
    for _ in range(steps):
        opt.zero_grad()
        loss = torch.nn.functional.mse_loss(model(x), y)
        loss.backward()
        opt.step()
        losses.append(float(loss))
    return losses


def _worker_ring() -> Dict:
    """Ring-allreduce worker with backward OVERLAP: a post-accumulate
    hook enqueues each param's grad; a comm thread ring-allreduces them
    in registration order (identical on every worker, so the n
    collectives pair correctly) while later grads are still being
    computed; step() drains."""
    import queue as _q

    import torch

    i = int(os.environ["TRAIN_EMU_RANK"])
    n = int(os.environ["TRAIN_EMU_WORLD"])
    ports = json.loads(os.environ["TRAIN_EMU_RING_PORTS"])
    rate = float(os.environ["TRAIN_EMU_RATE"])
    latency = float(os.environ.get("TRAIN_EMU_LATENCY", "0"))
    steps = int(os.environ["TRAIN_EMU_STEPS"])
    width = int(os.environ["TRAIN_EMU_WIDTH"])
    depth = int(os.environ["TRAIN_EMU_DEPTH"])
    batch = int(os.environ["TRAIN_EMU_BATCH"])
    lr = float(os.environ["TRAIN_EMU_LR"])

    ring = RingPeer(i, n, ports, rate, latency)
    model = _build_model(width, depth)
    opt = torch.optim.SGD(model.parameters(), lr=lr)
    x, y = _global_batch(width, batch)

    # comm thread: ring collectives must run in the SAME order on every
    # worker; autograd hook order (reverse layer) is deterministic for
    # this model, so hook-order draining is safe — the same contract the
    # PS arm's declaration-order keys rely on
    q: "_q.Queue" = _q.Queue()
    pending: List = []
    errs: List[BaseException] = []

    def comm() -> None:
        while True:
            item = q.get()
            if item is None:
                return
            p, done = item
            try:
                flat = p.grad.detach().numpy().ravel().astype(
                    np.float32, copy=True)
                summed = ring.allreduce(flat) / n
                with torch.no_grad():
                    p.grad.copy_(torch.from_numpy(
                        summed.reshape(p.grad.shape)))
            except BaseException as e:   # noqa: BLE001 — joined in step
                errs.append(e)
            finally:
                done.set()

    ct = threading.Thread(target=comm, daemon=True)
    ct.start()

    def make_hook():
        def hook(p):
            done = threading.Event()
            pending.append(done)
            q.put((p, done))
        return hook

    for p in model.parameters():
        p.register_post_accumulate_grad_hook(make_hook())

    losses = []
    t0 = None
    warm = 1
    tx0 = rx0 = 0
    for step in range(steps + warm):
        if step == warm:
            t0 = time.perf_counter()
            if ring.nic is not None:       # wire accounting: timed steps
                tx0, rx0 = ring.nic.tx_bytes, ring.nic.rx_bytes
        opt.zero_grad()
        loss = torch.nn.functional.mse_loss(model(x), y)
        loss.backward()
        for done in pending:              # drain this step's collectives
            if not done.wait(120):
                raise TimeoutError(
                    "ring allreduce did not complete within 120s — "
                    "hung peer or a NIC rate too slow for this model")
        pending.clear()
        if errs:
            raise errs[0]
        opt.step()
        losses.append(float(loss))
    dt = time.perf_counter() - t0
    q.put(None)
    ct.join(10)
    out = {"sps": batch * steps / dt, "losses": losses}
    if ring.nic is not None:
        out["tx_per_step"] = (ring.nic.tx_bytes - tx0) / steps
        out["rx_per_step"] = (ring.nic.rx_bytes - rx0) / steps
    ring.close()
    return out


def _worker_ps() -> Dict:
    """PS-mode worker: the real torch plugin over throttled transport.
    mode ps_onebit registers the onebit codec on every Gradient.* key
    before the optimizer declares them (first-declare-wins kwargs);
    mode cb wraps with CrossBarrier."""
    import torch

    import byteps_tpu.torch as bps

    mode = os.environ["TRAIN_EMU_MODE"]
    steps = int(os.environ["TRAIN_EMU_STEPS"])
    width = int(os.environ["TRAIN_EMU_WIDTH"])
    depth = int(os.environ["TRAIN_EMU_DEPTH"])
    batch = int(os.environ["TRAIN_EMU_BATCH"])
    lr = float(os.environ["TRAIN_EMU_LR"])

    model = _build_model(width, depth)
    bps.init()
    if mode == "ps_onebit":
        for name, _ in model.named_parameters():
            bps.declare("Gradient." + name, compressor_type="onebit",
                        compressor_onebit_scaling="true")
    opt = torch.optim.SGD(model.parameters(), lr=lr)
    opt = bps.DistributedOptimizer(
        opt, named_parameters=model.named_parameters())
    if mode == "cb":
        opt = bps.CrossBarrier(model, opt, num_steps=10 ** 6)
    bps.broadcast_parameters(model.state_dict(), root_rank=0)
    x, y = _global_batch(width, batch)

    from ..common.global_state import GlobalState
    gs = GlobalState._instance
    nic = getattr(gs.ps_backend, "_nic", None) if gs is not None else None

    losses = []
    t0 = None
    warm = 1
    tx0 = rx0 = 0
    if mode == "cb":
        opt.step()                        # step 0 (init)
    for step in range(steps + warm):
        if step == warm:
            if mode == "cb":
                opt.flush()               # timing starts clean
            t0 = time.perf_counter()
            if nic is not None:           # wire accounting: timed steps
                tx0, rx0 = nic.tx_bytes, nic.rx_bytes
        opt.zero_grad()
        loss = torch.nn.functional.mse_loss(model(x), y)
        loss.backward()
        opt.step()
        losses.append(float(loss))
    if mode == "cb":
        opt.flush()
    dt = time.perf_counter() - t0
    out = {"sps": batch * steps / dt, "losses": losses}
    if nic is not None:
        out["tx_per_step"] = (nic.tx_bytes - tx0) / steps
        out["rx_per_step"] = (nic.rx_bytes - rx0) / steps
    if mode == "cb":
        opt.close()
    bps.shutdown()
    return out


def _worker_main() -> None:
    mode = os.environ["TRAIN_EMU_MODE"]
    out = _worker_ring() if mode == "ring" else _worker_ps()
    print("TRAIN_EMU_RESULT " + json.dumps(out), flush=True)


# --------------------------------------------------------------------------
# parent-side orchestration
# --------------------------------------------------------------------------

def _free_ports(k: int) -> List[int]:
    socks, ports = [], []
    for _ in range(k):
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        socks.append(s)
        ports.append(s.getsockname()[1])
    for s in socks:
        s.close()
    return ports


def run_training(mode: str, n_workers: int, rate: float,
                 latency: float = 0.0, steps: int = 8, width: int = 256,
                 depth: int = 8, batch: int = 64, lr: float = 0.05,
                 timeout: float = 600.0,
                 partition_bytes: Optional[int] = None) -> Dict:
    """Launch ``n_workers`` worker processes in ``mode`` and return
    {"sps": min-over-workers samples/sec, "losses": worker-0 trajectory}.
    ``losses`` covers EVERY step including the 1 untimed warmup, so it
    compares 1:1 against ``serial_reference(steps + 1)``; ``sps`` times
    only the post-warmup window (the first step pays connection dials
    and key-init RPCs).

    PS modes start ``n_workers`` standalone throttled servers in THIS
    process (each with its own Nic — the reference's extra-server-NICs
    win condition); the ring needs no servers."""
    assert mode in ("ring", "ps", "ps_onebit", "cb"), mode
    env = dict(
        os.environ,
        TRAIN_EMU_MODE=mode, TRAIN_EMU_WORLD=str(n_workers),
        TRAIN_EMU_RATE=str(rate), TRAIN_EMU_LATENCY=str(latency),
        TRAIN_EMU_STEPS=str(steps), TRAIN_EMU_WIDTH=str(width),
        TRAIN_EMU_DEPTH=str(depth), TRAIN_EMU_BATCH=str(batch),
        TRAIN_EMU_LR=str(lr),
    )
    # the shm/IPC data planes bypass the throttled sockets — pin off
    for k in ("BPS_ENABLE_SHM", "BPS_ENABLE_IPC", "BYTEPS_ENABLE_IPC"):
        env.pop(k, None)
    # a peer's FIRST push can sit behind its interpreter/torch startup
    # for tens of seconds on a contended CI box, and the 30 s pull
    # default then fails a correctness rig on liveness grounds (seen
    # as a rare [cb] suite flake) — widen it; inherited values win
    env.setdefault("BPS_PULL_TIMEOUT_MS", "120000")
    # ~32 KB buckets: the torch path's per-PARAM exchanges otherwise
    # ride 256 KB buckets whose coarse frames pace poorly under
    # contended token buckets AND delay each round's completion —
    # measured 1516 -> 590 ms/step at 5 MB/s x 4 workers (the exchange
    # rig independently landed on ~the same bucket size). NOT for the
    # compressed mode: 33 KB buckets sit under the 64 KB compression
    # floor, silently disabling the codec — and its wire frames are
    # 32x smaller anyway, so coarse per-param buckets pace fine.
    # Forced (not setdefault): an inherited BPS_PARTITION_BYTES from
    # the calling process (e.g. conftest.py) must not leak in —
    # callers choose via the partition_bytes parameter.
    if partition_bytes is not None:
        env["BPS_PARTITION_BYTES"] = str(partition_bytes)
    elif mode != "ps_onebit":
        env["BPS_PARTITION_BYTES"] = "33000"
    else:
        env.pop("BPS_PARTITION_BYTES", None)

    servers, backends = [], []
    procs: List[subprocess.Popen] = []
    try:
        if mode == "ring":
            env["TRAIN_EMU_RING_PORTS"] = json.dumps(_free_ports(n_workers))
        else:
            from .engine import PSServer
            from .transport import PSTransportServer
            for _ in range(n_workers):        # s = n (non-colocated)
                be = PSServer(num_workers=n_workers, engine_threads=1)
                srv = PSTransportServer(
                    be, host="127.0.0.1", port=0,
                    nic=Nic(rate, latency) if rate > 0 else None)
                backends.append(be)
                servers.append(srv)
            env.update(
                BPS_ENABLE_PS="1",
                BPS_NUM_WORKER=str(n_workers),
                BPS_SERVER_ADDRS=",".join(
                    f"127.0.0.1:{s.port}" for s in servers),
                # round-robin bucket placement across the server shards
                # (allreduce_emu.py measured djb2 hotspotting +25%)
                BPS_KEY_HASH_FN="naive",
                BPS_EMU_NIC_RATE=str(rate),
                BPS_EMU_NIC_LATENCY=str(latency),
            )
        for wid in range(n_workers):
            wenv = dict(env, TRAIN_EMU_RANK=str(wid),
                        BPS_WORKER_ID=str(wid))
            procs.append(subprocess.Popen(
                [sys.executable, "-m", "byteps_tpu.server.train_emu"],
                env=wenv, stdout=subprocess.PIPE,
                stderr=subprocess.STDOUT, text=True,
                cwd=os.path.dirname(os.path.dirname(
                    os.path.dirname(os.path.abspath(__file__))))))
        outs = []
        for p in procs:
            try:
                out, _ = p.communicate(timeout=timeout)
            except subprocess.TimeoutExpired:
                for q in procs:
                    q.kill()
                out, _ = p.communicate()
            outs.append(out)
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
        for s in servers:
            s.close()
        for be in backends:
            be.close()
    results = []
    # report EVERY failed worker: a pull-timeout in worker 0 is usually
    # the SYMPTOM of worker 1 dying/stalling before its push — raising
    # on the first rank alone hides the root cause's traceback
    failed = [(wid, out) for wid, (p, out) in enumerate(zip(procs, outs))
              if p.returncode != 0]
    if failed:
        raise RuntimeError("\n\n".join(
            f"{mode} worker {wid} failed:\n{out[-3000:]}"
            for wid, out in failed))
    for wid, (p, out) in enumerate(zip(procs, outs)):
        line = [ln for ln in out.splitlines()
                if ln.startswith("TRAIN_EMU_RESULT ")]
        if not line:
            raise RuntimeError(f"{mode} worker {wid}: no result\n"
                               f"{out[-2000:]}")
        results.append(json.loads(line[-1].split(" ", 1)[1]))
    # the straggler sets training speed; trajectories must agree anyway
    out = {"sps": min(r["sps"] for r in results),
           "losses": results[0]["losses"],
           "all_losses": [r["losses"] for r in results]}
    if results and "tx_per_step" in results[0]:
        out["tx_per_step"] = (sum(r["tx_per_step"] for r in results)
                              / len(results))
        out["rx_per_step"] = (sum(r["rx_per_step"] for r in results)
                              / len(results))
    return out


if __name__ == "__main__":
    _worker_main()

"""Emulated NIC: token-bucket bandwidth + per-frame latency on sockets.

The reference's identity claim is that the PS communication pattern
uses *bottleneck bandwidth* better than allreduce — "up to 2×" on slow
networks (reference: README.md:9,46; docs/rationale.md "The PS
communication pattern is better, theoretically"). This box has one
chip and a loopback network, so the claim can't be measured natively;
what can be measured is the wire pattern itself under an emulated
bandwidth constraint. ``Nic`` models one machine's full-duplex network
interface: independent tx/rx token buckets (bytes/sec) plus a
per-frame latency charge. ``ThrottledSocket`` wraps a real socket so
every byte the transport actually moves pays for NIC tokens — the
throttle sits under the REAL framing/threading/dedup stack, not a
simulator, so protocol overheads (headers, acks, connection pools)
are charged at their true size.

Used by ``allreduce_emu.py`` / ``examples/ps_vs_allreduce_bench.py``
to run the PS data plane and a ring-allreduce emulation over the SAME
throttled sockets and compare (docs/performance.md "PS vs allreduce").
"""

from __future__ import annotations

import threading
import time
from typing import Optional

__all__ = ["TokenBucket", "Nic", "ThrottledSocket"]


class TokenBucket:
    """Classic token bucket: ``consume(n)`` sleeps until n byte-tokens
    are available at ``rate`` bytes/sec (burst-capped). Thread-safe —
    concurrent connections of one endpoint share the bucket, which is
    the point: they share the NIC."""

    def __init__(self, rate: float, burst: Optional[int] = None) -> None:
        self.rate = float(rate)
        self.burst = float(burst if burst is not None
                           else max(64 << 10, rate / 50))
        self._tokens = self.burst
        self._t = time.monotonic()
        self._lock = threading.Lock()
        # wake before the bucket fills: sleeping past the burst-fill
        # time truncates accrual at the cap and silently paces BELOW
        # rate (measured 7× slow with a 64 KB burst and 50 ms sleeps)
        self._quantum = min(0.05, max(0.002, self.burst / self.rate / 2))
        # cached metric handles: consume() is the pacing hot path
        from ..obs.metrics import get_registry
        self._m_stalls = get_registry().counter("nic/stalls")
        self._m_stall_s = get_registry().histogram("nic/stall_s")

    def _refill(self) -> None:
        """Accrue tokens up to the burst cap (caller holds _lock)."""
        now = time.monotonic()
        self._tokens = min(
            self.burst, self._tokens + (now - self._t) * self.rate)
        self._t = now

    def consume(self, n: int) -> None:
        left = float(n)
        t_stall = None          # set on first sleep: stall accounting
        while left > 0:         # costs nothing on the no-wait fast path
            with self._lock:
                self._refill()
                take = min(left, self._tokens)
                self._tokens -= take
                left -= take
                wait = left / self.rate if left > 0 else 0.0
            if wait > 0:
                if t_stall is None:
                    t_stall = time.monotonic()
                time.sleep(min(wait, self._quantum))
        if t_stall is not None:
            self._m_stalls.inc()
            self._m_stall_s.observe(time.monotonic() - t_stall)

    def try_consume(self, n: int) -> bool:
        """Deduct n tokens iff they are ALL available right now (no
        sleep, no partial take). The fast-path gate: a frame the bucket
        can cover whole needs no pacing interleave — skipping the
        chunk loop is what lifts high-rate links from ~0.4 GB/s of
        Python chunk overhead to wire speed."""
        with self._lock:
            self._refill()
            if self._tokens >= n:
                self._tokens -= n
                return True
            return False


class Nic:
    """One emulated machine NIC: full-duplex (independent tx/rx buckets
    at ``rate`` bytes/sec each, like a real Ethernet port) plus
    ``latency`` seconds charged once per send call (frame)."""

    # control-frame exemption: request headers and ST_OK acks (tens of
    # bytes) ride free. A real link interleaves at packet granularity —
    # an ack waits at most ~1 MTU behind bulk traffic — but a
    # frame-granular token bucket queues it behind every paced payload
    # byte, and the starved push-acks measurably cascade (stalled push
    # pipelines → late round completion → idle NICs, +80% on the PS
    # path at 5 MB/s). Exempt bytes are <0.1% of traffic.
    SMALL_FRAME = 64

    def __init__(self, rate: float, latency: float = 0.0,
                 burst: Optional[int] = None,
                 rx_rate: Optional[float] = None) -> None:
        """``rx_rate``: optional asymmetric ingress rate (defaults to
        ``rate``). Models contended directions independently — e.g. a
        PS server whose egress is the k-worker incast bottleneck while
        its ingress keeps line rate (bench.ps_cross_breakdown)."""
        self.rate = float(rate)
        self.latency = float(latency)
        self.tx = TokenBucket(rate, burst)
        self.rx = TokenBucket(rate if rx_rate is None else rx_rate, burst)
        # wire accounting (every byte, incl. exempt control frames):
        # the scaling-curve rig asserts these against the analytic
        # per-endpoint byte model — noise-free evidence the stack's
        # wire pattern matches the scaling story, where wall clock on
        # a shared-core box cannot be (examples/scaling_curve_emu.py).
        # Locked: one Nic is shared by concurrent connections/threads
        # (that sharing is the whole point, see TokenBucket), and an
        # unlocked += loses updates under interleaving
        self.tx_bytes = 0
        self.rx_bytes = 0
        self._count_lock = threading.Lock()

    def book_tx(self, n: int) -> None:
        """Record ``n`` tx bytes as SENT. ThrottledSocket.sendall books
        per successful chunk write, AFTER the write: booking the whole
        frame up front meant a mid-frame send failure plus reconnect
        counted the frame twice (the aborted attempt's unsent remainder
        plus the full resend) — the curve rig's analytic byte model
        only tolerates bytes that actually went to the kernel."""
        with self._count_lock:
            self.tx_bytes += n

    def frame_latency(self) -> None:
        """The per-frame latency charge — exactly once per send call,
        never per chunk (a chunked 8 MB frame is still ONE frame)."""
        if self.latency:
            time.sleep(self.latency)

    def count_tx(self, n: int) -> None:
        """Frame-level tx accounting + latency in one call — the form
        control-frame senders (on_send) use, where the write either
        happens whole or not at all."""
        self.book_tx(n)
        self.frame_latency()

    def on_send(self, n: int) -> None:
        self.count_tx(n)
        if n > self.SMALL_FRAME:
            self.tx.consume(n)

    def on_recv(self, n: int) -> None:
        with self._count_lock:
            self.rx_bytes += n
        if n > self.SMALL_FRAME:
            self.rx.consume(n)

    def chunk_size(self) -> int:
        """Pacing granularity for frames the bucket can't cover whole:
        ~2 ms of link time, clamped to [64 KB, 4 MB]. Tiny fixed chunks
        at multi-GB/s rates put a Python iteration every 64 KB on the
        hot path (measured: the whole stack capped at ~0.4 GB/s)."""
        return int(min(4 << 20, max(64 << 10, self.rate * 0.002)))


class ThrottledSocket:
    """Delegating socket wrapper that charges a ``Nic`` for every byte.

    Only the calls the transport stack uses are metered (``sendall``,
    ``recv``, ``recv_into``); everything else proxies through. Wrapping
    is idempotent-safe: accessors like ``fileno``/``settimeout`` hit
    the real socket."""

    __slots__ = ("_sock", "_nic")

    def __init__(self, sock, nic: Nic) -> None:
        self._sock = sock
        self._nic = nic

    # pacing granularity: when the bucket can't cover a frame whole,
    # tokens are charged per CHUNK interleaved with the writes — a
    # frame charged up front and bulk-written serializes sender pacing
    # with receiver pacing whenever the payload exceeds the kernel
    # socket buffer (measured: ring steps cost 2× the link time at
    # 2 MB chunks on slow links). When the bucket CAN cover it, one
    # charge + one sendall: the chunk loop itself was the bottleneck
    # at 10 Gbps-class rates (~0.4 GB/s of Python-iteration overhead).
    def sendall(self, data) -> None:
        view = memoryview(data)
        n = len(view)
        nic = self._nic
        nic.frame_latency()              # once per FRAME, never per chunk
        if n <= nic.SMALL_FRAME or nic.tx.try_consume(n):
            self._sock.sendall(view)
            nic.book_tx(n)
            return
        chunk = nic.chunk_size()
        for off in range(0, n, chunk):
            part = view[off:off + chunk]
            nic.tx.consume(len(part))
            self._sock.sendall(part)
            # booked per successful chunk write: a send failure mid-
            # frame leaves only the chunks that reached the kernel
            # counted, so the reconnect's resend can't double-count
            # the frame
            nic.book_tx(len(part))

    def sendmsg(self, buffers, *rest) -> int:
        """Vectored send, metered. Without this override ``__getattr__``
        would hand the transport the RAW socket's ``sendmsg`` and every
        vectored byte would bypass the Nic — unthrottled AND uncounted,
        silently blinding the scaling-curve byte model. One sendmsg call
        is ONE frame (one latency charge); like ``sendall`` it returns
        only once everything is written, so the caller's partial-send
        resume loop never re-enters (which would recharge the frame)."""
        views = [memoryview(b) for b in buffers]
        n = sum(len(v) for v in views)
        nic = self._nic
        nic.frame_latency()
        if n <= nic.SMALL_FRAME or nic.tx.try_consume(n):
            sent = self._sock.sendmsg(views)
            nic.book_tx(sent)
            if sent < n:
                # finish the short write's remainder without a second
                # latency/bucket charge — still the same frame
                skip = sent
                for v in views:
                    if skip >= len(v):
                        skip -= len(v)
                        continue
                    part = v[skip:] if skip else v
                    skip = 0
                    self._sock.sendall(part)
                    nic.book_tx(len(part))
            return n
        chunk = nic.chunk_size()
        for v in views:
            for off in range(0, len(v), chunk):
                part = v[off:off + chunk]
                nic.tx.consume(len(part))
                self._sock.sendall(part)
                nic.book_tx(len(part))
        return n

    def recv(self, n: int, *flags):
        data = self._sock.recv(n, *flags)
        self._nic.on_recv(len(data))
        return data

    def recv_into(self, buf, nbytes: int = 0, *flags) -> int:
        r = self._sock.recv_into(buf, nbytes, *flags)
        self._nic.on_recv(r)
        return r

    def __getattr__(self, name):
        return getattr(self._sock, name)

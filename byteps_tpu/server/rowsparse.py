"""Row-sparse push_pull through the PS path.

The reference RESERVED this (``RequestType::kRowSparsePushPull``,
common.h:267-271) but never implemented a handler — embedding-style
gradients, where only a few rows of a [num_rows, cols] table are
nonzero per step, had to ride the dense path. Here it's implemented:
workers push only the touched (row-index, row) pairs; the server
scatters them into a dense accumulator and the summation engine merges
across workers exactly like a dense push (duplicate indices within one
push are summed, matching scatter-add semantics); pulls return the
dense merged table. Wire cost per push is ~touched_rows·cols instead of
num_rows·cols.

Wire format (little-endian): ``n:u32 | idx:i32[n] | rows:dtype[n·cols]``.
The transport frame's ``nbytes`` field carries the DENSE table byte size
so the server can derive num_rows without per-key metadata.
"""

from __future__ import annotations

import struct

import numpy as np


def pack_rows(idx, rows) -> bytes:
    """(int row indices [n], row values [n, cols]) → wire bytes."""
    idx = np.ascontiguousarray(np.asarray(idx, dtype=np.int32).reshape(-1))
    rows = np.ascontiguousarray(np.asarray(rows))
    if rows.ndim != 2 or rows.shape[0] != idx.shape[0]:
        raise ValueError(f"rows must be [n, cols] with n == len(idx); got "
                         f"idx {idx.shape}, rows {rows.shape}")
    return struct.pack("<I", idx.shape[0]) + idx.tobytes() + rows.tobytes()


def unpack_rows(buf, dtype: str):
    """wire bytes → (idx [n], rows [n, cols]) — cols derived from size.
    ``buf`` may be any buffer (memoryview included); no copy is made."""
    (n,) = struct.unpack_from("<I", buf, 0)
    idx = np.frombuffer(buf, np.int32, count=n, offset=4)
    rows = np.frombuffer(buf, np.dtype(dtype), offset=4 + 4 * n)
    if n:
        if rows.size % n:
            raise ValueError("row payload not divisible by index count")
        rows = rows.reshape(n, rows.size // n)
    else:
        rows = rows.reshape(0, 0)
    return idx, rows


def scatter_dense(idx, rows, num_rows: int, dtype: str) -> np.ndarray:
    """Scatter-ADD rows into a dense [num_rows, cols] table (duplicate
    indices sum, the scatter-add contract)."""
    cols = rows.shape[1] if rows.size else 0
    dense = np.zeros((num_rows, cols), np.dtype(dtype))
    if rows.size:
        np.add.at(dense, idx, rows)
    return dense


def rowsparse_push(backend, key: int, idx, rows, dense_nbytes: int,
                   dtype=None, meta=None) -> None:
    """Expand a sparse (idx, rows) push to dense and hand it to the
    summation engine (same expand-then-dense-sum shape as the compressed
    path, server.cc:86-113). An EMPTY push contributes a zero table —
    it must still join the sync round or peers block on the merge.

    ``meta`` (dict) pins cols per key on first push: a later push whose
    cols differ — a mis-built worker — is rejected instead of silently
    scattering rows at wrong offsets."""
    idx = np.asarray(idx, np.int32).reshape(-1)
    rows = np.asarray(rows)
    dtype = str(rows.dtype) if dtype is None else str(np.dtype(dtype))
    itemsize = np.dtype(dtype).itemsize
    if dense_nbytes % itemsize:
        raise ValueError("table size not a multiple of the element size")
    total = dense_nbytes // itemsize
    if idx.size == 0 or rows.size == 0:
        backend.push(key, np.zeros(total, dtype))
        return
    if rows.ndim != 2 or rows.shape[0] != idx.size:
        raise ValueError(f"rows must be [n, cols] with n == len(idx); got "
                         f"idx {idx.shape}, rows {rows.shape}")
    cols = rows.shape[1]
    if meta is not None:
        prev = meta.setdefault(key, cols)
        if prev != cols:
            raise ValueError(f"key {key}: cols {cols} != established "
                             f"{prev} — workers disagree on the table")
    if total % cols:
        raise ValueError(f"cols={cols} incompatible with a "
                         f"{dense_nbytes}-byte table")
    num_rows = total // cols
    if idx.min() < 0 or idx.max() >= num_rows:
        raise ValueError(f"row index out of range [0, {num_rows})")
    backend.push(key, scatter_dense(idx, rows, num_rows, dtype)
                 .astype(dtype, copy=False).reshape(-1))

"""PS vs ring-allreduce under an emulated bandwidth constraint.

The reference's raison d'être is that the PS pattern uses bottleneck
bandwidth better than allreduce — "up to 2×" on slow networks
(reference: README.md:9,46; docs/rationale.md). The arithmetic behind
the claim, for G gradient bytes, n workers, s parameter servers, every
machine behind a B bytes/sec full-duplex NIC:

- **ring allreduce**: every worker sends AND receives
  ``2(n-1)/n × G`` → time ``2(n-1)/n × G/B``.
- **PS, s EXTRA server machines**: each worker pushes G up and pulls
  G down (overlapped on a full-duplex NIC) → ``G/B``; each server
  moves ``n×G/s`` each way → ``nG/(sB)``. At ``s = n`` the worker NIC
  is the bottleneck and PS wins by ``2(n-1)/n`` — →2× at large n.
- **PS colocated** (servers share worker NICs): each machine moves
  ``2G`` each way → ``2G/B``, WORSE than ring — which is why the
  reference's win condition is spare CPU machines
  (docs/rationale.md), and why this repo's in-jit path uses XLA
  collectives, not PS, inside a slice.

This module measures all three over the SAME stack: the real
`PSTransportServer`/`RemotePSBackend` data plane (framing, dedup,
connection pools, pipelined exchange) and a ring allreduce written on
the same throttled sockets, with every endpoint's bytes charged to a
`throttle.Nic`. Run ``examples/ps_vs_allreduce_bench.py`` for the
sweep table in docs/performance.md; `tests/test_ps_vs_allreduce.py`
asserts the crossover in CI.
"""

from __future__ import annotations

import socket
import threading
import time
from typing import Dict, List, Optional, Tuple

import numpy as np

from .throttle import Nic, ThrottledSocket

__all__ = ["ring_allreduce", "ps_exchange", "predicted_times"]


def predicted_times(n_workers: int, n_servers: int, nbytes: int,
                    rate: float, colocated: bool = False,
                    parts: int = 32) -> Dict[str, float]:
    """The analytic times the emulation should reproduce.

    The PS term includes the last-bucket tail: after the final gradient
    byte lands, the server holding the last bucket must fan the merged
    ``G/parts`` bytes out to all n workers through its one NIC —
    ``n×G/(parts×B)``. Smaller buckets shrink the tail, more RPCs raise
    the constant; parts=32 measured best on this stack (the emulation
    matches this model within a few % once placement is balanced)."""
    g, b, n = float(nbytes), float(rate), n_workers
    ring = 2 * (n - 1) / n * g / b
    tail = n * (g / parts) / b
    if colocated:
        ps = 2 * g / b + tail
    else:
        ps = max(g / b, n * g / (max(n_servers, 1) * b)) + tail
    return {"ring_s": ring, "ps_s": ps}


# --------------------------------------------------------------------------
# ring allreduce over throttled loopback TCP
# --------------------------------------------------------------------------

from .transport import _recv_exact


def ring_rounds(tx, rx, view: np.ndarray, n: int, i: int) -> None:
    """The bandwidth-optimal ring schedule on an open (tx, rx) pair:
    n-1 reduce-scatter rounds then n-1 all-gather rounds over
    ``view`` ([n, chunk] fp32, modified in place). Each round sends on
    a helper thread while receiving — full-duplex, like NCCL's ring.
    Shared by the one-shot bench (``ring_allreduce``) and the
    persistent training peer (``train_emu.RingPeer``)."""
    chunk = view.shape[1]
    for step in range(n - 1):              # reduce-scatter
        s_idx = (i - step) % n
        r_idx = (i - step - 1) % n
        snd = threading.Thread(target=tx.sendall,
                               args=(view[s_idx].tobytes(),))
        snd.start()
        got = np.frombuffer(_recv_exact(rx, chunk * 4), np.float32)
        snd.join()
        view[r_idx] += got
    for step in range(n - 1):              # all-gather
        s_idx = (i + 1 - step) % n
        r_idx = (i - step) % n
        snd = threading.Thread(target=tx.sendall,
                               args=(view[s_idx].tobytes(),))
        snd.start()
        got = np.frombuffer(_recv_exact(rx, chunk * 4), np.float32)
        snd.join()
        view[r_idx] = got


def ring_allreduce(n_workers: int, nbytes: int, rate: float,
                   latency: float = 0.0, iters: int = 1,
                   verify: bool = True) -> float:
    """Bandwidth-optimal ring allreduce (reduce-scatter + all-gather,
    2(n-1) steps) between n worker threads over loopback TCP, each
    endpoint charged to its own ``Nic(rate, latency)``. Returns
    measured seconds per iteration."""
    n = n_workers
    elems = nbytes // 4
    chunk = -(-elems // n)                  # ceil
    padded = chunk * n
    nics = [Nic(rate, latency) for _ in range(n)]

    # ring wiring: worker i accepts from i-1, connects to i+1
    listeners = []
    for _ in range(n):
        ls = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        ls.bind(("127.0.0.1", 0))
        ls.listen(1)
        listeners.append(ls)
    out_socks: List[Optional[socket.socket]] = [None] * n
    in_socks: List[Optional[socket.socket]] = [None] * n

    def connect(i):
        s = socket.create_connection(
            ("127.0.0.1", listeners[(i + 1) % n].getsockname()[1]))
        s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        out_socks[i] = s

    cts = [threading.Thread(target=connect, args=(i,)) for i in range(n)]
    [t.start() for t in cts]
    for i in range(n):
        conn, _ = listeners[i].accept()
        conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        in_socks[i] = conn
    [t.join() for t in cts]
    for ls in listeners:
        ls.close()

    datas = [np.random.RandomState(i).randn(padded).astype(np.float32)
             for i in range(n)]
    want = np.sum(datas, axis=0) if verify else None
    results: List[Optional[np.ndarray]] = [None] * n
    errors: List[BaseException] = []
    barrier = threading.Barrier(n + 1)

    def worker(i: int) -> None:
        tx = ThrottledSocket(out_socks[i], nics[i])
        rx = ThrottledSocket(in_socks[i], nics[i])
        try:
            for _ in range(iters):
                barrier.wait()
                x = datas[i].copy()
                ring_rounds(tx, rx, x.reshape(n, chunk), n, i)
                results[i] = x
                barrier.wait()
        except BaseException as e:   # noqa: BLE001 — surfaced below
            errors.append(e)
            try:
                barrier.abort()
            except Exception:
                pass

    ts = [threading.Thread(target=worker, args=(i,)) for i in range(n)]
    [t.start() for t in ts]
    total = 0.0
    try:
        for _ in range(iters):
            barrier.wait()
            t0 = time.perf_counter()
            barrier.wait()
            total += time.perf_counter() - t0
    except threading.BrokenBarrierError:
        pass                      # a worker aborted; its error re-raised below
    finally:
        [t.join() for t in ts]
        for s in out_socks + in_socks:
            try:
                s.close()
            except Exception:
                pass
    if errors:
        raise errors[0]
    if verify:
        for r in results:
            np.testing.assert_allclose(r, want, rtol=1e-4, atol=1e-4)
    return total / iters


# --------------------------------------------------------------------------
# PS exchange over the real transport, throttled
# --------------------------------------------------------------------------

def ps_exchange(n_workers: int, n_servers: int, nbytes: int, rate: float,
                latency: float = 0.0, iters: int = 1,
                partition_bytes: Optional[int] = None,
                colocated: bool = False, verify: bool = True,
                compression: Optional[Dict[str, str]] = None,
                server_rate: Optional[float] = None,
                server_rx_rate: Optional[float] = None) -> float:
    """One PS sync round (push G, pull merged G) per iteration through
    the REAL transport stack, every endpoint throttled.

    ``colocated=True`` models servers running ON the worker machines:
    server j shares worker j's Nic (j mod n_workers), so its traffic
    competes for the same emulated port — the deployment where the
    reference itself says PS stops winning.

    ``compression`` (reference-format kwargs, e.g. onebit) rides the
    real compressed wire: workers push codec payloads, the (native)
    server codec decompresses/sums/recompresses — LOSSY, so verify is
    skipped; the point is wire time where bandwidth is the bottleneck.

    ``server_rate``/``server_rx_rate`` throttle the server tier
    asymmetrically (egress vs ingress) — the server-egress-bound incast
    regime ``bench.py ps_plane`` measures shard scaling under."""
    import os
    from ..common.naming import NameRegistry
    from .engine import PSServer
    from .ps_mode import PSGradientExchange
    from .transport import PSTransportServer, RemotePSBackend

    # the shm/IPC data planes carry payloads OUTSIDE the throttled
    # sockets (only a segment name crosses the wire) — with either
    # enabled the comparison is meaningless, so pin both off here
    saved = {k: os.environ.pop(k, None)
             for k in ("BPS_ENABLE_SHM", "BPS_ENABLE_IPC",
                       "BYTEPS_ENABLE_IPC")}

    def _restore_env() -> None:
        for k, v in saved.items():
            if v is not None:
                os.environ[k] = v

    if partition_bytes is None:
        # 32 buckets: in the bandwidth-bound regime PS time ≈
        # G/B × (1 + n/parts) — early buckets' rounds complete while
        # later buckets still push, and the last bucket's merged
        # result fans out to n workers through one server NIC (the
        # tail predicted_times models). 4 coarse buckets measurably
        # serialize into push-all-then-pull-all (193 ms vs 108 ms at
        # 50 MB/s); past ~64 buckets per-RPC overhead wins instead
        partition_bytes = max(32 << 10, nbytes // 32)
    worker_nics = [Nic(rate, latency) for _ in range(n_workers)]
    if colocated:
        server_nics = [worker_nics[j % n_workers] for j in range(n_servers)]
    else:
        server_nics = [Nic(server_rate if server_rate is not None
                           else rate, latency, rx_rate=server_rx_rate)
                       for _ in range(n_servers)]

    try:
        backends = [PSServer(num_workers=n_workers, engine_threads=1)
                    for _ in range(n_servers)]
        servers = [PSTransportServer(be, host="127.0.0.1", nic=nic)
                   for be, nic in zip(backends, server_nics)]
    except BaseException:
        _restore_env()
        raise
    addrs = [f"127.0.0.1:{s.port}" for s in servers]

    elems = nbytes // 4
    datas = [np.random.RandomState(100 + i).randn(elems).astype(np.float32)
             for i in range(n_workers)]
    verify = verify and not compression   # lossy codec: timing only
    want = np.sum(datas, axis=0) if verify else None

    reg = NameRegistry()
    # ring placement: the server plane's byte-weighted virtual-node
    # assignment is balanced BY CONSTRUCTION (max−min assigned bytes
    # bounded by one bucket), so no hash needs hand-tuning per workload.
    # History: djb2 put 5/16 buckets on one server and built_in 20/64 —
    # every round then gated on the hottest server's NIC (+25%
    # measured) — and a "naive == round-robin" special case papered
    # over it here until the ring fixed it at the source
    # (tests/test_server_plane.py asserts the balance bound).
    try:
        if compression:
            reg.declare("lb", **compression)
        remotes = [RemotePSBackend(addrs, nic=worker_nics[i],
                                   hash_fn="ring")
                   for i in range(n_workers)]
        exs = [PSGradientExchange(remotes[i],
                                  partition_bytes=partition_bytes,
                                  registry=reg, min_compress_bytes=0)
               for i in range(n_workers)]
        # SEQUENTIAL pre-planning: every worker builds its own plan (and
        # its own compressor chains — per-worker state) before the
        # threads start, so concurrent first-use init_key never races;
        # server-side init is idempotent
        for ex in exs:
            ex._plan({"g": datas[0]}, "lb" if compression else None)
    except BaseException:
        for s in servers:
            s.close()
        for be in backends:
            be.close()
        _restore_env()
        raise

    results: List[Optional[np.ndarray]] = [None] * n_workers
    errors: List[BaseException] = []
    barrier = threading.Barrier(n_workers + 1)

    def worker(i: int) -> None:
        try:
            for _ in range(iters):
                barrier.wait()
                results[i] = exs[i].exchange(
                    {"g": datas[i]},
                    name="lb" if compression else None)["g"]
                barrier.wait()
        except BaseException as e:   # noqa: BLE001
            errors.append(e)
            try:
                barrier.abort()
            except Exception:
                pass

    ts = [threading.Thread(target=worker, args=(i,))
          for i in range(n_workers)]
    [t.start() for t in ts]
    total = 0.0
    try:
        for _ in range(iters):
            barrier.wait()
            t0 = time.perf_counter()
            barrier.wait()
            total += time.perf_counter() - t0
    except threading.BrokenBarrierError:
        pass                      # a worker aborted; its error re-raised below
    finally:
        [t.join() for t in ts]
        for r in remotes:
            try:
                r.close()
            except Exception:
                pass
        for s in servers:
            s.close()
        for be in backends:
            be.close()
        _restore_env()                # restore the caller's data-plane env
    if errors:
        raise errors[0]
    if verify:
        for r in results:
            np.testing.assert_allclose(np.asarray(r), want,
                                       rtol=1e-4, atol=1e-4)
    return total / iters

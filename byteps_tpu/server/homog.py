"""Codec-homogeneous server summation for the fused compression plane.

The PR-7 server path decodes EVERY fused push to a dense f32 buffer,
feeds it through the native engine's store (copy + mutex + sum), and
re-extracts + re-encodes the merge on the pull side — so only the wire
shrank, while the server's merge path still moved dense bytes per
worker. This store takes over the ROUND for fused-managed keys:

  - pushes (fused payloads AND dense rounds of managed keys) are
    buffered per key; the ``num_workers``-th arrival completes the
    round, exactly the engine's publication rule — cross-step's
    per-key admission gate guarantees in-flight arrivals all belong to
    one round, the same property the engine relies on;
  - a round whose arrivals all carry the SAME lossy codec
    (int8/fp8/fp16 — scalar-widenable) is merged in ONE fused
    widen->add pass per payload straight into the f32 accumulator:
    no engine store write/read, no per-worker dense staging, and the
    pull side serves the merged payload bytes from here — the
    decode+re-encode round-trip through the dense engine is GONE on
    the merge path (``server/fused_rounds_homog`` vs
    ``server/fused_dense_decodes``, counter-asserted in tests);
  - heterogeneous arrivals (divergent per-worker decision traces,
    topk's non-widenable sparsity, mixed dense/fused rounds) fall back
    to the dense sum — LOUDLY counted (``server/fused_rounds_fallback``
    + one WARNING per key) but bit-identical to the engine path;
  - BITWISE PARITY: the accumulator applies the exact float ops the
    dense path applies (per-payload ``widen * scale`` then
    arrival-order adds; first arrival copies, like the engine), and
    the merged payload is produced by ``wire.encode`` under the same
    ``sr_seed(key, round)`` the dense pull re-encode uses — so a
    homog-merged round and a dense-path round serve byte-identical
    pulls, and forward-log replay / failover across divergent paths
    stays bit-exact.

Round numbering is shard-local starting at 0, matching the engine
(``init_key`` on an existing key = a new tenancy = reset, the same
rule the fused pull cache follows). ``BPS_FUSED_HOMOG=0`` disables the
takeover (every fused push then decodes into the engine as before).
"""

from __future__ import annotations

import os
import threading
import time
from typing import Dict, Optional

import numpy as np

from ..common.logging import get_logger
from ..compress import wire
from ..obs.metrics import get_registry

log = get_logger()

#: codecs whose payloads widen into the f32 accumulator in one pass
#: (scalar scale or none): topk stays on the dense fallback — a sparse
#: union-sum is not a widen, and re-selection needs the dense merge
DIRECT_CODECS = (wire.CODEC_FP16, wire.CODEC_INT8, wire.CODEC_FP8_E4M3,
                 wire.CODEC_FP8_E5M2)


def homog_enabled() -> bool:
    return (os.environ.get("BPS_FUSED_HOMOG", "1") or "1") \
        .strip().lower() not in ("0", "off", "false", "no")


class _Merged:
    __slots__ = ("dense", "payloads")

    def __init__(self, dense: np.ndarray) -> None:
        self.dense = dense          # merged f32/store-dtype round
        self.payloads: Dict[tuple, bytes] = {}   # (cid, div) -> encoded
        #   lazily wire.encode'd on first pull at that codec — the
        #   merged SUM always needs a renormalizing re-encode, so there
        #   is no stored-arrival payload to serve directly


class _KeyState:
    __slots__ = ("nbytes", "dtype", "elems", "init", "completed",
                 "arrivals", "rounds", "cv", "warned")

    def __init__(self, nbytes: int, dtype: str,
                 init: Optional[np.ndarray]) -> None:
        self.nbytes = int(nbytes)
        self.dtype = np.dtype(dtype)
        self.elems = self.nbytes // self.dtype.itemsize
        self.init = None if init is None else \
            np.array(init, dtype=self.dtype).reshape(-1)
        self.completed = 0
        self.arrivals: list = []    # ("p", bytes) | ("d", ndarray)
        self.rounds: Dict[int, _Merged] = {}
        self.cv = threading.Condition()
        self.warned = False


class FusedSumStore:
    """Per-server round store for fused-managed keys (see module doc).
    One instance per summation endpoint — embedded by
    ``HostPSBackend`` (in-process) and by the transport server's
    ``FusedFront`` (raw-engine deployments)."""

    def __init__(self, num_workers: int, retain: int = 4) -> None:
        self.num_workers = max(1, int(num_workers))
        self.retain = max(2, int(retain))
        self._lock = threading.Lock()
        self._keys: Dict[int, _KeyState] = {}
        reg = get_registry()
        self.m_homog = reg.counter("server/fused_rounds_homog")
        self.m_fallback = reg.counter("server/fused_rounds_fallback")
        self.m_decodes = reg.counter("server/fused_dense_decodes")
        self.m_merge_cpu = reg.counter("server/fused_merge_cpu_s")
        self.m_pull_hits = reg.counter("server/fused_pull_hits")
        self.m_pull_encodes = reg.counter("server/fused_pull_encodes")

    # ------------------------------------------------------- lifecycle

    def init_key(self, key: int, nbytes: int, dtype: str = "float32",
                 init: Optional[np.ndarray] = None) -> None:
        """Register (or RESET — a re-init is a new tenancy of the key,
        the migration-replay rule) a managed key."""
        with self._lock:
            self._keys[int(key)] = _KeyState(nbytes, dtype, init)

    def managed(self, key: int) -> bool:
        return int(key) in self._keys

    def drop(self, key: int) -> None:
        with self._lock:
            self._keys.pop(int(key), None)

    def _st(self, key: int) -> _KeyState:
        st = self._keys.get(int(key))
        if st is None:
            raise KeyError(f"key {key} is not fused-managed")
        return st

    # ------------------------------------------------------ push side

    def ingest(self, key: int, payload) -> None:
        """One worker's fused payload for the key's pending round.
        STRUCTURALLY validated (``wire.validate`` — header, element
        count, body length, topk index bounds) BEFORE it can count as
        an arrival: a torn payload that refused only inside the merge
        would discard the other workers' buffered arrivals and poison
        the round; validated here, the merge cannot raise for payload
        reasons and the torn pusher's retry completes the round."""
        st = self._st(key)
        try:
            wire.validate(payload, st.elems)
        except wire.CodecError as e:
            raise wire.CodecError(f"key {key}: {e}") from None
        self._arrive(key, st, ("p", bytes(payload)))

    def ingest_dense(self, key: int, arr: np.ndarray) -> None:
        """A dense push of a managed key (a level-``none`` round, or a
        divergent worker's dense arrival). Copies — the caller reuses
        its buffer."""
        st = self._st(key)
        a = np.asarray(arr).reshape(-1)
        if a.nbytes != st.nbytes:
            # wire transcode mirror: narrow pushes land in store dtype
            a = a.astype(st.dtype)
            if a.nbytes != st.nbytes:
                raise ValueError(
                    f"dense push of {arr.nbytes}B for key {key}, store "
                    f"holds {st.nbytes}B")
        if a.dtype != st.dtype:
            a = a.astype(st.dtype)
        self._arrive(key, st, ("d", np.array(a, copy=True)))

    def _arrive(self, key: int, st: _KeyState, item: tuple) -> None:
        with st.cv:
            st.arrivals.append(item)
            if len(st.arrivals) < self.num_workers:
                return
            arrivals, st.arrivals = st.arrivals, []
            t0 = time.thread_time()
            merged = self._merge(key, st, arrivals)
            self.m_merge_cpu.inc(time.thread_time() - t0)
            st.completed += 1
            st.rounds[st.completed] = merged
            old = st.completed - self.retain
            if old in st.rounds:
                del st.rounds[old]
            st.cv.notify_all()

    def _widen_into(self, acc: Optional[np.ndarray],
                    payload: bytes, st: _KeyState) -> np.ndarray:
        """One fused widen->scale(->add) pass — float-op-identical to
        ``wire.decode`` followed by the engine's arrival-order sum
        (first arrival copies, the rest add in place)."""
        dec = wire.decode(payload, st.elems, st.dtype)
        if acc is None:
            return dec
        np.add(acc, dec, out=acc)
        return acc

    def _merge(self, key: int, st: _KeyState, arrivals: list) -> _Merged:
        cids = [wire.peek(p)[0] if k == "p" else None
                for k, p in arrivals]
        homog = (cids[0] in DIRECT_CODECS
                 and all(c == cids[0] for c in cids))
        acc: Optional[np.ndarray] = None
        if homog:
            for _, p in arrivals:
                acc = self._widen_into(acc, p, st)
            self.m_homog.inc()
            return _Merged(acc)
        # dense / heterogeneous fallback — bit-identical to the engine
        # path (decode each arrival, arrival-order sum). Loud only when
        # a LOSSY payload had to dense-decode: an all-dense round is
        # just a level-none round doing its job.
        lossy = [c for c in cids if c not in (None, wire.CODEC_NONE)]
        for kind, p in arrivals:
            if kind == "d":
                dec = p
            else:
                dec = wire.decode(p, st.elems, st.dtype)
                if wire.lossy(wire.peek(p)[0]):
                    self.m_decodes.inc()
            if acc is None:
                # both kinds are store-private: ingest_dense copied the
                # dense arrival, decode allocated the payload's —
                # accumulate in place, no extra full-bucket memcpy
                acc = dec
            else:
                np.add(acc, dec, out=acc)
        if lossy:
            self.m_fallback.inc()
            if not st.warned:
                st.warned = True
                log.warning(
                    "fused key %d round %d fell back to the dense merge "
                    "(arrival codecs %s) — divergent per-worker decision"
                    " traces or a non-widenable codec; the homogeneous "
                    "decode-free sum needs every worker at one codec",
                    key, st.completed + 1,
                    [wire.codec_name(c) if c is not None else "dense"
                     for c in cids])
        return _Merged(acc)

    # ------------------------------------------------------ pull side

    def _wait_round(self, key: int, st: _KeyState, rnd: int,
                    timeout_ms: int) -> _Merged:
        deadline = time.monotonic() + timeout_ms / 1e3
        with st.cv:
            while st.completed < rnd:
                left = deadline - time.monotonic()
                if left <= 0:
                    raise TimeoutError(
                        f"pull({key}) round={rnd} timed out after "
                        f"{timeout_ms}ms (fused store at round "
                        f"{st.completed})")
                st.cv.wait(min(left, 0.5))
            if rnd not in st.rounds:
                raise ValueError(
                    f"pull({key}) round={rnd}: round evicted from the "
                    f"fused store (retains {self.retain}, completed "
                    f"{st.completed}) — puller fell outside the "
                    f"in-flight window")
            return st.rounds[rnd]

    def pull_dense(self, key: int, out: np.ndarray, round: int = 0,
                   timeout_ms: int = 30000) -> None:
        st = self._st(key)
        if round == 0:
            with st.cv:
                if st.completed == 0:
                    src = st.init if st.init is not None else \
                        np.zeros(st.elems, st.dtype)
                else:
                    src = st.rounds[st.completed].dense
        else:
            src = self._wait_round(key, st, int(round), timeout_ms).dense
        if out.dtype == src.dtype:
            np.copyto(out.reshape(-1), src)
        else:
            np.copyto(out.reshape(-1), src.astype(out.dtype))

    def pull_payload(self, key: int, cid: int, round: int,
                     timeout_ms: int = 30000,
                     div: int = wire.TOPK_DIV) -> bytes:
        """The merged round at the requested codec: the stored merge's
        bytes when already encoded, else ONE ``wire.encode`` under the
        shared ``sr_seed(key, round)`` (byte-identical to the dense
        path's pull re-encode), cached per (codec, div)."""
        st = self._st(key)
        rnd = int(round)
        if rnd == 0:
            with st.cv:
                rnd = st.completed
            if rnd == 0:
                raise ValueError(
                    f"pull_fused({key}) round=0 with no completed round")
        m = self._wait_round(key, st, rnd, timeout_ms)
        with st.cv:
            hit = m.payloads.get((cid, div))
        if hit is not None:
            self.m_pull_hits.inc()
            return hit
        payload = wire.encode(cid, m.dense, div=div,
                              seed=wire.sr_seed(key, rnd))
        self.m_pull_encodes.inc()
        with st.cv:
            m.payloads.setdefault((cid, div), payload)
        return payload

    # -------------------------------------------------- observability

    def round(self, key: int) -> int:
        st = self._st(key)
        with st.cv:
            return st.completed

    def pending(self) -> int:
        """Buffered-but-unmerged arrivals across keys — folded into the
        server backlog gauge the compression controller reads."""
        with self._lock:
            keys = list(self._keys.values())
        return sum(len(st.arrivals) for st in keys)


class FusedFront:
    """Duck-typed fused/dense front for a RAW dense backend (the native
    ``PSServer`` behind a transport server): routes managed keys into a
    ``FusedSumStore`` and everything else straight through — the same
    split ``HostPSBackend`` does internally, packaged for servers whose
    backend has no fused surface of its own."""

    def __init__(self, backend, num_workers: int) -> None:
        self.backend = backend
        self.store = FusedSumStore(num_workers)
        self._cache = wire.FusedPullCache()
        self._meta: Dict[int, tuple] = {}   # key -> (nbytes, dtype)

    def init_key(self, key: int, nbytes: int, dtype: str = "float32",
                 init: Optional[np.ndarray] = None,
                 fused: bool = False) -> None:
        if fused and homog_enabled():
            self.store.init_key(key, nbytes, dtype, init)
        elif self.store.managed(key):
            self.store.drop(key)    # re-declared non-fused: hand back
        self._meta[int(key)] = (int(nbytes), dtype)
        self.backend.init_key(key, nbytes, dtype, init)

    def push(self, key: int, data: np.ndarray) -> None:
        if self.store.managed(key):
            self.store.ingest_dense(key, data)
        else:
            self.backend.push(key, data)

    def pull(self, key: int, out: np.ndarray, round: int = 0,
             timeout_ms: int = 30000) -> None:
        if self.store.managed(key):
            self.store.pull_dense(key, out, round, timeout_ms)
        else:
            self.backend.pull(key, out, round=round,
                              timeout_ms=timeout_ms)

    def push_fused(self, key: int, payload) -> None:
        if self.store.managed(key):
            self.store.ingest(key, payload)
            return
        # unmanaged fused push: the PR-7 decode-into-engine path, with
        # the dense decode now first-class-counted (lossy payloads
        # only — a `none` frame is a frombuffer view, not a decode;
        # same rule the merge fallback applies)
        dense = wire.decode_for_store(payload, self._meta.get(int(key)))
        if wire.lossy(wire.peek(payload)[0]):
            self.store.m_decodes.inc()
        self.backend.push(key, dense)

    def pull_fused(self, key: int, nbytes: int, dtype: str, codec: int,
                   round: int = 0, timeout_ms: int = 30000,
                   div: Optional[int] = None) -> bytes:
        if self.store.managed(key):
            return self.store.pull_payload(key, codec, round, timeout_ms,
                                           div=div or wire.TOPK_DIV)
        return wire.pull_encoded(self.backend, self._cache, key, nbytes,
                                 dtype, codec, round,
                                 timeout_ms=timeout_ms,
                                 div=div or wire.TOPK_DIV)

    def round(self, key: int) -> int:
        if self.store.managed(key):
            return self.store.round(key)
        return int(self.backend.round(key))

    def drop_cached(self, key: int) -> None:
        self._cache.drop(key)

"""Unified admission plane: every "may this byte / this apply proceed"
decision in one place.

Four independently-grown scheduling components used to share this
responsibility (ROADMAP item 1 called collapsing them "the refactor
everything else wants"):

  1. the exchange's per-key push admission gate (two rounds in flight
     under cross-step; ``PSGradientExchange._admit_key``),
  2. the exchange's landed-bucket pull priority heap
     (``_enqueue_pull`` / ``_pull_next``),
  3. the staged-segment launcher's cross-step epoch gate
     (``cross_step``'s ``wait_epoch(e - 1)``),
  4. the two-class wire send scheduler (``server/sched.py``).

They now live here as one plane with one contract. ``KeyGate`` is the
per-key apply-order gate, ``PullQueue`` is the pull scheduler,
``SendScheduler`` is the wire gate (``server/sched.py`` remains as a
compatibility shim re-exporting it), and ``AdmissionPlane`` is the
facade an exchange owns. The external surfaces are unchanged at the
default configuration: same metrics (``ps/admission_*``, ``sched/*``),
same key-less ``send_admit`` flight events, same scheduler trace shape
the critical-path analyzer carves credit waits from.

On top of the unification sits **K-round bounded staleness**
(``StaleStore``): the server versions each key's rounds, workers
declare ``BPS_MAX_LAG=K``, and the plane decides per (key, round)
whether to

  - **serve** a complete sum (every worker contributed — the only
    verdict that exists at K=1, bitwise-identical to the classic path),
  - **stale-serve**: seal the round without the stragglers' gradients
    when every missing worker still has slack under its bound (a worker
    may miss at most K-1 CONSECUTIVE rounds), or
  - **barrier**: some missing worker has exhausted its slack — block
    until its push arrives, draining the in-flight round before any
    further progress.

A gradient is never dropped: a push that arrives for an already-sealed
round folds into the CURRENT open round's accumulator and counts as
that worker's contribution to it (resetting its miss streak), so a
permanently slow worker contributes one gradient per push at its own
pace and costs the fleet *lag, not wall-clock*. Sealed sums are
published as immutable snapshots — every puller of a round sees the
same bytes, so replicated workers stay bit-identical. Every
stale-serve and barrier decision is recorded as a key-less flight
event (like codec and ``send_admit`` decisions) and counted under the
``lag/*`` metric families.
"""

from __future__ import annotations

import heapq
import itertools
import os
import threading
import time
from collections import deque
from typing import Dict, List, Optional

import numpy as np

from ..obs.metrics import get_registry

CLASS_GRAD = 0
CLASS_ACT = 1

# CLASS_ACT priority base: any activation outranks any gradient bucket
# (grad priorities are leaf-count-bounded, far below this)
ACT_PRIO_BASE = 1 << 20

# frames at or below this ride free (request headers, acks, control
# ops) — same reasoning as throttle.Nic.SMALL_FRAME: scheduling tiny
# frames buys nothing and a queued ack would stall the very pipeline
# the scheduler exists to keep busy
MIN_SCHED_BYTES = 4096

# pull_lag verdict flags (bit 0 and 1 of the response status byte)
LAG_COMPLETE = 0       # every worker contributed — the K=1 verdict
LAG_STALE = 1          # sealed under the bound without some workers
LAG_BARRIER = 2        # a bound was exhausted; the pull waited it out


def resolve_max_lag(explicit: Optional[int] = None) -> int:
    """The declared staleness bound K. 1 (the default) is today's sync
    path: a round publishes only when every worker contributed."""
    if explicit is not None:
        return max(1, int(explicit))
    try:
        return max(1, int(os.environ.get("BPS_MAX_LAG", "1") or 1))
    except ValueError:
        return 1


def lag_grace_s() -> float:
    """``BPS_LAG_GRACE_MS``: how long a seal-eligible pull waits for
    natural completion before sealing (0 = seal immediately)."""
    try:
        return max(0.0, float(
            os.environ.get("BPS_LAG_GRACE_MS", "0") or 0)) / 1e3
    except ValueError:
        return 0.0


# ===================================================================
# per-key push admission (component 1)
# ===================================================================


class KeyGate:
    """Per-key push admission: at most ``depth`` rounds of one key may
    be pushed-but-unpulled at once; excess pushes queue FIFO per key so
    rounds stay ordered on the wire. Depth 1 is the classic cross-step
    contract (round k+1's push waits for round k's pull — the server
    publishes one round per key at a time); under bounded staleness the
    depth is K, because the versioned store holds K rounds per key.
    Deferred admissions are counted and their wait timed — the gate is
    where a lost pull turns into a silent wedge, so its depth/latency
    are first-class signals."""

    def __init__(self, depth: int = 1) -> None:
        self.depth = max(1, int(depth))
        self._lock = threading.Lock()
        self._held: Dict[int, int] = {}
        self._waiters: Dict[int, deque] = {}
        reg = get_registry()
        self._m_wait = reg.histogram("ps/admission_wait_s")
        self._m_defer = reg.counter("ps/admission_deferred")

    def admit(self, pskey: int, submit) -> None:
        """Run ``submit`` now if ``pskey`` has an admission slot free,
        else defer it until a slot releases (FIFO per key)."""
        from ..obs import flight
        with self._lock:
            if self._held.get(pskey, 0) >= self.depth:
                self._m_defer.inc()
                t0 = time.time()

                def deferred(submit=submit, t0=t0):
                    wait = time.time() - t0
                    self._m_wait.observe(wait)
                    flight.record("admit", key=pskey,
                                  detail=f"deferred {wait:.3f}s")
                    submit()

                self._waiters.setdefault(pskey, deque()).append(deferred)
                return
            self._held[pskey] = self._held.get(pskey, 0) + 1
        flight.record("admit", key=pskey)
        submit()

    def release(self, pskey: int) -> None:
        with self._lock:
            waiters = self._waiters.get(pskey)
            if waiters:
                submit = waiters.popleft()
                if not waiters:
                    del self._waiters[pskey]
            else:
                n = self._held.get(pskey, 0) - 1
                if n <= 0:
                    self._held.pop(pskey, None)
                else:
                    self._held[pskey] = n
                return
        submit()                     # slot passes to the successor

    def state(self) -> dict:
        """Holders and queued waiters — the watchdog's dump shape."""
        with self._lock:
            return {"busy": sorted(self._held),
                    "waiters": {k: len(v)
                                for k, v in self._waiters.items()}}


# ===================================================================
# landed-bucket pull scheduling (component 2)
# ===================================================================


class PullQueue:
    """Pull scheduler for landed buckets: a min-heap ordered by (round
    age, next-step first-use priority, FIFO). Pushes keep
    backward-completion order, but pulls drain input-side-first because
    those params gate fwd(k+1)'s first gated segment — without this the
    reverse-packed plan applies the input layers LAST and the
    cross-step overlap window collapses to zero. Also owns the
    monotonically increasing round sequence the age ordering keys on."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._heap: List[tuple] = []
        self._seq = 0
        self._round_seq = 0

    def next_round_seq(self) -> int:
        with self._lock:
            self._round_seq += 1
            return self._round_seq

    def put(self, round_seq: int, prio: int, payload) -> None:
        with self._lock:
            heapq.heappush(self._heap,
                           (round_seq, prio, self._seq, payload))
            self._seq += 1

    def pop(self):
        """The highest-priority landed bucket (oldest round first, then
        first-use priority, then FIFO)."""
        with self._lock:
            return heapq.heappop(self._heap)[3]

    def __len__(self) -> int:
        with self._lock:
            return len(self._heap)


# ===================================================================
# two-class wire send scheduling (component 4 — was server/sched.py)
# ===================================================================


class _Ticket:
    __slots__ = ("klass", "prio", "key", "nbytes", "seq", "t_enq")

    def __init__(self, klass: int, prio: int, key: int, nbytes: int,
                 seq: int) -> None:
        self.klass = klass
        self.prio = prio
        self.key = key
        self.nbytes = int(nbytes)
        self.seq = seq
        self.t_enq = time.monotonic()

    def order(self):
        """Heap key: priority DESC, key ASC, then FIFO — the BytePS
        ``scheduled_queue`` comparator."""
        eff = self.prio + (ACT_PRIO_BASE if self.klass == CLASS_ACT else 0)
        return (-eff, self.key, self.seq)


class SendScheduler:
    """Wire-admission gate (BytePS ``scheduled_queue.cc:82-146`` +
    ``BYTEPS_SCHEDULING_CREDIT``): ``acquire`` blocks until the frame
    is the highest-priority queued entry AND byte credit is available;
    ``release`` returns the credit once the bytes left this host.
    ``CLASS_ACT`` frames (activations — latency-critical, a stage
    blocks on them) carry a large priority base so they always outrank
    ``CLASS_GRAD``; within grads the exchange assigns reverse-FIRST-USE
    priorities, the same order the pull queue drains, so the send and
    pull sides agree on who is urgent. One frame is always admitted
    even if larger than the whole credit, so a giant bucket cannot
    deadlock. With the credit at 0 (default) the gate is inert.

    Every admission is recorded in a bounded trace (class, key,
    priority, enqueue/admit sequence numbers, wait) — the "scheduler
    trace" the tests, ``bench.py pp``, and the critical-path analyzer's
    credit carve consume — plus registry metrics (``sched/*``)."""

    def __init__(self, credit_bytes: int, trace_cap: int = 4096) -> None:
        self.credit = int(credit_bytes)
        self._cv = threading.Condition()
        self._heap: List[tuple] = []          # (order, ticket)
        self._seq = itertools.count(1)
        self._inflight = 0
        self._trace: deque = deque(maxlen=trace_cap)
        self._admit_seq = 0
        reg = get_registry()
        self._m_act = reg.counter("sched/admitted_act")
        self._m_grad = reg.counter("sched/admitted_grad")
        self._m_overtakes = reg.counter("sched/overtakes")
        self._m_wait = reg.histogram("sched/credit_wait_s")
        self._g_inflight = reg.gauge("sched/inflight_bytes")

    # ------------------------------------------------------------ gate

    def acquire(self, klass: int, prio: int, key: int,
                nbytes: int) -> Optional[_Ticket]:
        """Block until this frame may be written. Returns the ticket to
        pass to ``release`` (None for frames below the scheduling
        floor — nothing to release)."""
        if nbytes <= MIN_SCHED_BYTES:
            return None
        t = _Ticket(klass, prio, key, nbytes, next(self._seq))
        entry = (t.order(), t)
        with self._cv:
            heapq.heappush(self._heap, entry)
            while not (self._heap[0] is entry
                       and (self._inflight == 0
                            or self._inflight + t.nbytes <= self.credit)):
                self._cv.wait(1.0)
            heapq.heappop(self._heap)
            self._inflight += t.nbytes
            self._g_inflight.set(self._inflight)
            self._admit_seq += 1
            # an overtake: some entry enqueued BEFORE us is still
            # queued — we jumped the line on priority
            overtook = any(e[1].seq < t.seq for e in self._heap)
            waited = time.monotonic() - t.t_enq
            self._trace.append({
                "class": "act" if klass == CLASS_ACT else "grad",
                "key": key, "prio": prio, "nbytes": t.nbytes,
                "enq_seq": t.seq, "admit_seq": self._admit_seq,
                "wait_s": waited, "overtook": overtook,
                # wall-clock ADMIT stamp: the credit wait occupied
                # [t - wait_s, t] — the interval the critical-path
                # analyzer subtracts out of PS_PUSH spans as "credit"
                "t": time.time(),
            })
        (self._m_act if klass == CLASS_ACT else self._m_grad).inc()
        if overtook:
            self._m_overtakes.inc()
        self._m_wait.observe(waited)
        # flight-recorder send-admission event, KEY-LESS like the codec
        # decisions (obs/flight.py): the admission ordering is context
        # for EVERY key's postmortem — a frame that waited did so
        # because of some OTHER key's burst, so filtering it out of
        # that key's dump would hide exactly the why. The enabled check
        # comes FIRST: with the recorder off the per-frame cost must
        # stay one attribute read, not an f-string build.
        from ..obs import flight
        if flight.get_recorder().enabled:
            flight.record(
                "send_admit", nbytes=t.nbytes,
                detail=f"class={'act' if klass == CLASS_ACT else 'grad'} "
                       f"key={key} prio={prio} wait_ms={waited * 1e3:.1f} "
                       f"overtook={overtook}")
        return t

    def release(self, ticket: Optional[_Ticket]) -> None:
        if ticket is None:
            return
        with self._cv:
            self._inflight -= ticket.nbytes
            self._g_inflight.set(self._inflight)
            self._cv.notify_all()

    # ------------------------------------------------------------ views

    def trace(self) -> List[dict]:
        """Admission records, oldest first (bounded window)."""
        with self._cv:
            return list(self._trace)

    def queued(self) -> int:
        with self._cv:
            return len(self._heap)

    def inflight(self) -> int:
        return self._inflight


_send_lock = threading.Lock()
_send_current: Optional[SendScheduler] = None
_send_configured = False


def configure_send(
        credit_bytes: Optional[int] = None) -> Optional[SendScheduler]:
    """(Re)build the process-global wire scheduler. ``None`` re-reads
    ``BPS_SCHEDULING_CREDIT`` (``BYTEPS_SCHEDULING_CREDIT`` accepted);
    credit <= 0 disables. Called by ``bps.init`` so the env contract
    matches every other knob; tests call it directly between arms."""
    global _send_current, _send_configured
    if credit_bytes is None:
        credit_bytes = int(
            os.environ.get("BPS_SCHEDULING_CREDIT",
                           os.environ.get("BYTEPS_SCHEDULING_CREDIT", "0"))
            or 0)
    with _send_lock:
        _send_current = (SendScheduler(credit_bytes)
                         if credit_bytes > 0 else None)
        _send_configured = True
        return _send_current


def send_scheduler() -> Optional[SendScheduler]:
    """The process-global wire scheduler, or None when disabled. First
    call resolves from the env so directly-constructed transports
    (tests, scripts without ``bps.init``) honor the credit knob."""
    if not _send_configured:
        configure_send()
    return _send_current


# ===================================================================
# K-round bounded staleness (server side)
# ===================================================================


class _LagKey:
    __slots__ = ("size", "dtype", "max_lag", "cv", "acc", "contrib",
                 "published", "published_upto", "streak", "late_folds")

    def __init__(self, size: int, dtype: str, max_lag: int,
                 num_workers: int) -> None:
        self.size = int(size)
        self.dtype = np.dtype(dtype)
        self.max_lag = int(max_lag)
        self.cv = threading.Condition()
        self.acc: Dict[int, np.ndarray] = {}       # open rounds' sums
        self.contrib: Dict[int, set] = {}          # round -> worker ids
        self.published: Dict[int, tuple] = {}      # round -> (sum, flags)
        self.published_upto = 0
        # consecutive published rounds each worker missed; the bound is
        # streak <= max_lag - 1, enforced at seal time
        self.streak = [0] * num_workers
        self.late_folds = 0


class StaleStore:
    """Server-side versioned round store for lag-managed keys.

    The decision table, evaluated by the pull of the oldest unpublished
    round (earlier pulls are served from published snapshots):

      every worker contributed          -> publish COMPLETE (flags 0)
      missing workers all have slack
        (streak + 1 <= K - 1)           -> wait ``BPS_LAG_GRACE_MS``,
                                           then SEAL (stale-serve)
      some missing worker is at bound   -> BARRIER: block until its
                                           push arrives (draining the
                                           in-flight round), then
                                           publish

    K=1 makes the seal condition unsatisfiable (a miss would need
    streak <= -1), so the store degenerates to complete-round-only —
    the classic sync semantics. A push for an already-published round
    folds into the current open round and counts as that worker's
    contribution to it (see module docstring): sums are conserved,
    every gradient is applied exactly once, and a permanently slow
    worker alternates miss/contribute instead of drifting to a
    permanent barrier.

    A fresh store that sees its first push at round r > 1 adopts
    r - 1 as its published head — the elastic rejoin / server-failover
    resync (the exchange seeds per-key rounds from the server, so a
    replacement server must meet workers at the fleet's live round,
    not at 1)."""

    def __init__(self, num_workers: int, spans=None) -> None:
        self.num_workers = max(1, int(num_workers))
        self.spans = spans
        self._lock = threading.Lock()
        self._keys: Dict[int, _LagKey] = {}
        reg = get_registry()
        self._m_stale = reg.counter("lag/stale_serves")
        self._m_barrier = reg.counter("lag/barrier_falls")
        self._m_late = reg.counter("lag/late_folds")
        self._m_evicted = reg.counter("lag/evicted_serves")
        self._g_streak = reg.gauge("lag/max_streak")

    # ------------------------------------------------------- contract

    def declare(self, key: int, size: int, dtype: str,
                max_lag: int) -> None:
        """Route ``key``'s rounds through this store with bound
        ``max_lag``. Idempotent; a conflicting re-declaration (workers
        disagreeing on K) is a loud config error."""
        key, max_lag = int(key), int(max_lag)
        with self._lock:
            st = self._keys.get(key)
            if st is not None:
                if st.max_lag != max_lag:
                    raise ValueError(
                        f"key {key} lag bound re-declared {max_lag} != "
                        f"{st.max_lag} — workers disagree on BPS_MAX_LAG")
                return
            self._keys[key] = _LagKey(size, dtype, max_lag,
                                      self.num_workers)

    def managed(self, key: int) -> bool:
        with self._lock:
            return int(key) in self._keys

    def declared(self, key: int) -> Optional[int]:
        with self._lock:
            st = self._keys.get(int(key))
            return None if st is None else st.max_lag

    def streaks(self, key: int) -> List[int]:
        st = self._st(key)
        with st.cv:
            return list(st.streak)

    def round(self, key: int) -> int:
        """Last published round — what a rejoining worker seeds from."""
        st = self._st(key)
        with st.cv:
            return st.published_upto

    def _st(self, key: int) -> _LagKey:
        with self._lock:
            st = self._keys.get(int(key))
        if st is None:
            raise KeyError(f"key {key} is not lag-managed "
                           f"(declare_lag never reached this server)")
        return st

    # ------------------------------------------------------ data path

    def push(self, key: int, worker: int, rnd: int,
             data: np.ndarray) -> int:
        """Fold one worker's gradient. Returns the round it landed in:
        ``rnd`` itself, or the current open round when ``rnd`` was
        already sealed (late fold)."""
        st = self._st(key)
        worker, rnd = int(worker), int(rnd)
        data = np.asarray(data).reshape(-1)
        with st.cv:
            if st.published_upto == 0 and not st.acc and rnd > 1:
                st.published_upto = rnd - 1      # failover/rejoin adopt
            if rnd <= st.published_upto:
                tgt = st.published_upto + 1      # late fold (see class)
                st.late_folds += 1
                self._m_late.inc()
            else:
                tgt = rnd
            acc = st.acc.get(tgt)
            if acc is None:
                acc = st.acc[tgt] = np.zeros(st.size, st.dtype)
                st.contrib[tgt] = set()
            if data.dtype != st.dtype:
                data = data.astype(st.dtype)
            acc += data
            st.contrib[tgt].add(worker)
            st.cv.notify_all()
        return tgt

    def pull(self, key: int, worker: int, rnd: int, out: np.ndarray,
             timeout_ms: int = 30000) -> int:
        """Block until every round <= ``rnd`` is published (publishing
        them per the decision table), then copy round ``rnd``'s
        snapshot into ``out``. Returns the verdict flags
        (LAG_COMPLETE / LAG_STALE, plus LAG_BARRIER when this pull had
        to wait out an exhausted bound)."""
        st = self._st(key)
        rnd = int(rnd)
        grace = lag_grace_s()
        deadline = time.monotonic() + int(timeout_ms) / 1e3
        flags = 0
        barrier_logged: set = set()
        with st.cv:
            t_wait0 = time.monotonic()
            while st.published_upto < rnd:
                nxt = st.published_upto + 1
                contrib = st.contrib.get(nxt, ())
                missing = [w for w in range(self.num_workers)
                           if w not in contrib]
                if not missing:
                    self._publish(st, key, nxt, sealed=False)
                    continue
                can_seal = all(st.streak[m] + 1 <= st.max_lag - 1
                               for m in missing)
                now = time.monotonic()
                if can_seal and now - t_wait0 >= grace:
                    self._publish(st, key, nxt, sealed=True,
                                  missing=missing)
                    continue
                if not can_seal and nxt not in barrier_logged:
                    barrier_logged.add(nxt)
                    flags |= LAG_BARRIER
                    self._m_barrier.inc()
                    self._decision("barrier", key, nxt, missing, st)
                if now >= deadline:
                    raise TimeoutError(
                        f"pull_lag key={key} round={rnd} blocked "
                        f"{int(timeout_ms)}ms at round {nxt} "
                        f"(missing workers {missing}, "
                        f"streaks {list(st.streak)})")
                # seal-eligible: sleep only to the end of the grace
                # window (tiny floor against spin — NOT 10ms+, or any
                # grace shorter than the floor would silently stretch
                # to it and lose the seal race to the late push)
                st.cv.wait(min(
                    deadline - now,
                    max(grace - (now - t_wait0), 0.0005)
                    if can_seal else 0.25))
            ent = st.published.get(rnd)
            if ent is None:
                # the worker fell beyond the retention window: its own
                # round's snapshot is gone. Serve the newest published
                # sum instead — under bounded staleness a hopelessly
                # behind worker reads the freshest state (its pushes
                # late-fold, so its gradients still land exactly once);
                # erroring here would wedge the one worker the lag
                # contract exists to keep off the critical path.
                ent = st.published[st.published_upto]
                flags |= LAG_STALE
                self._m_evicted.inc()
                self._decision("evicted", key, rnd, (), st)
            arr, f = ent
            flags |= f
            view = out.reshape(-1)
            if view.dtype == arr.dtype:
                np.copyto(view, arr)
            else:
                view[:] = arr.astype(view.dtype)
        return flags

    # ------------------------------------------------------- internals

    def _publish(self, st: _LagKey, key: int, rnd: int, sealed: bool,
                 missing=()) -> None:
        """Publish round ``rnd``'s accumulator as an immutable snapshot
        and advance the streak bookkeeping. Caller holds ``st.cv``."""
        acc = st.acc.pop(rnd, None)
        contrib = st.contrib.pop(rnd, set())
        if acc is None:             # nobody pushed (drained rejoin gap)
            acc = np.zeros(st.size, st.dtype)
        st.published[rnd] = (acc, LAG_STALE if sealed else LAG_COMPLETE)
        st.published_upto = rnd
        for w in range(self.num_workers):
            st.streak[w] = 0 if w in contrib else st.streak[w] + 1
        cut = rnd - (2 * st.max_lag + 4)
        for old in [r for r in st.published if r <= cut]:
            del st.published[old]
        if sealed:
            self._m_stale.inc()
            self._g_streak.set(max(st.streak))
            get_registry().gauge(f"lag/streak/{key}").set(max(st.streak))
            self._decision("stale", key, rnd, missing, st)
            if self.spans is not None:
                self.spans.note_seal(key, rnd, missing)
        st.cv.notify_all()

    def _decision(self, verdict: str, key: int, rnd: int, missing,
                  st: _LagKey) -> None:
        # KEY-LESS like send_admit: a sealed round is context for every
        # key's postmortem (the enabled check first — see SendScheduler)
        from ..obs import flight
        if flight.get_recorder().enabled:
            flight.record(
                "lag_admit",
                detail=f"verdict={verdict} key={key} round={rnd} "
                       f"missing={sorted(missing)} "
                       f"streaks={list(st.streak)} K={st.max_lag}")


# ===================================================================
# the facade an exchange owns
# ===================================================================


class AdmissionPlane:
    """One object owning every admission decision for an exchange: the
    per-key push gate (depth = K), the landed-bucket pull queue, the
    cross-step epoch bound, and (via the process-global) the wire send
    scheduler. The server-side ``StaleStore`` is its peer on the other
    end of the wire — ``HostPSBackend`` instantiates one lazily when
    the first ``declare_lag`` arrives."""

    def __init__(self, max_lag: Optional[int] = None,
                 worker_id: Optional[int] = None) -> None:
        self.max_lag = resolve_max_lag(max_lag)
        self.worker_id = (int(os.environ.get("BPS_WORKER_ID", "0") or 0)
                          if worker_id is None else int(worker_id))
        self.gate = KeyGate(depth=self.max_lag)
        self.pulls = PullQueue()

    def send(self) -> Optional[SendScheduler]:
        """The wire send gate (process-global; None when inert)."""
        return send_scheduler()

    def gate_round(self, e: int) -> int:
        """The newest epoch whose params must be APPLIED before step
        ``e`` may launch — the cross-step driver's wait target. K=1 is
        the classic two-rounds-in-flight window (wait on e-1)."""
        return e - self.max_lag

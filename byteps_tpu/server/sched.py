"""Compatibility shim: the two-class wire send scheduler moved into
the unified admission plane (``server/admission.py``), which owns
every "may this byte proceed" decision — the per-key push gate, the
pull priority queue, the wire credit gate, and the bounded-staleness
round store. Importers of ``server.sched`` keep working; the class,
trace shape, metrics, and ``send_admit`` flight events are unchanged.

``configure()`` / ``current()`` delegate to the plane's process-global
instance, so mixing old and new import paths still yields ONE
scheduler per process.
"""

from __future__ import annotations

from typing import Optional

from .admission import (     # noqa: F401 — re-exported surface
    ACT_PRIO_BASE,
    CLASS_ACT,
    CLASS_GRAD,
    MIN_SCHED_BYTES,
    SendScheduler,
    _Ticket,
    configure_send,
    send_scheduler,
)


def configure(credit_bytes: Optional[int] = None) -> Optional[SendScheduler]:
    return configure_send(credit_bytes)


def current() -> Optional[SendScheduler]:
    return send_scheduler()

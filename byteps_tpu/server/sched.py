"""Two-class priority scheduler for the transport's send path.

BytePS's core loops never write a tensor to the wire unscheduled:
every partition enters a priority queue (``scheduled_queue.cc:82-146``,
priority = reverse declaration order so the NEXT forward's first layers
jump the line) and a byte CREDIT caps how much may be in flight at
once (``BYTEPS_SCHEDULING_CREDIT``, scheduled_queue.cc:35-45) — that
is what lets a small, late, latency-critical frame overtake a
bandwidth burst already queued. We reproduced the 12-stage pipeline
but, with one traffic class (gradients), never needed the scheduler.

Pipeline parallelism adds the second class: activations /
activation-grads (``OP_ACT_PUSH``) are LATENCY-sensitive — a stage
blocks until they arrive — while gradient pushes are BANDWIDTH-heavy
and deadline-free until the next step's first use. ``SendScheduler``
is the wire-admission gate both classes pass through before their
bytes touch a socket:

- entries are ordered ``(priority desc, key asc, fifo)`` — the
  reference's ``scheduled_queue`` comparator;
- ``CLASS_ACT`` frames carry a large priority base so they always
  outrank ``CLASS_GRAD``; within grads, the exchange assigns
  reverse-FIRST-USE priorities (input-side buckets first — the same
  order its cross-step pull heap drains, so the send and pull sides
  agree on who is urgent);
- ``BPS_SCHEDULING_CREDIT`` bytes may be in flight at once (one frame
  is always admitted even if larger than the whole credit, so a giant
  bucket cannot deadlock). While a burst holds the credit, later
  frames QUEUE — and queued order is priority order, which is exactly
  when an activation overtakes.

The queue is per egress endpoint in spirit; in this process model all
of a worker's connections share one host NIC, so the scheduler is
process-global (``current()``) and every client (gradient backends,
activation exchanges) routes sends through the same instance — the
reference's per-connection queues collapse to one when the bottleneck
is the shared NIC. With the credit at 0 (default) the scheduler is
inert: sends are admitted immediately and nothing queues.

Every admission is recorded in a bounded trace (class, key, priority,
enqueue/admit sequence numbers, wait) — the "scheduler trace" the
tests and ``bench.py pp`` assert overtakes from — plus registry
metrics (``sched/admitted_act``, ``sched/admitted_grad``,
``sched/overtakes``, ``sched/credit_wait_s``).
"""

from __future__ import annotations

import heapq
import itertools
import os
import threading
import time
from collections import deque
from typing import List, Optional

from ..obs.metrics import get_registry

CLASS_GRAD = 0
CLASS_ACT = 1

# CLASS_ACT priority base: any activation outranks any gradient bucket
# (grad priorities are leaf-count-bounded, far below this)
ACT_PRIO_BASE = 1 << 20

# frames at or below this ride free (request headers, acks, control
# ops) — same reasoning as throttle.Nic.SMALL_FRAME: scheduling tiny
# frames buys nothing and a queued ack would stall the very pipeline
# the scheduler exists to keep busy
MIN_SCHED_BYTES = 4096


class _Ticket:
    __slots__ = ("klass", "prio", "key", "nbytes", "seq", "t_enq")

    def __init__(self, klass: int, prio: int, key: int, nbytes: int,
                 seq: int) -> None:
        self.klass = klass
        self.prio = prio
        self.key = key
        self.nbytes = int(nbytes)
        self.seq = seq
        self.t_enq = time.monotonic()

    def order(self):
        """Heap key: priority DESC, key ASC, then FIFO — the BytePS
        ``scheduled_queue`` comparator."""
        eff = self.prio + (ACT_PRIO_BASE if self.klass == CLASS_ACT else 0)
        return (-eff, self.key, self.seq)


class SendScheduler:
    """Wire-admission gate: ``acquire`` blocks until the frame is the
    highest-priority queued entry AND byte credit is available;
    ``release`` returns the credit once the bytes left this host
    (the transport releases after the frame's roundtrip send — with a
    paced/throttled socket that spans the frame's true wire time, the
    closest host-side analogue of the reference's ack-released
    credits)."""

    def __init__(self, credit_bytes: int, trace_cap: int = 4096) -> None:
        self.credit = int(credit_bytes)
        self._cv = threading.Condition()
        self._heap: List[tuple] = []          # (order, ticket)
        self._seq = itertools.count(1)
        self._inflight = 0
        self._trace: deque = deque(maxlen=trace_cap)
        self._admit_seq = 0
        reg = get_registry()
        self._m_act = reg.counter("sched/admitted_act")
        self._m_grad = reg.counter("sched/admitted_grad")
        self._m_overtakes = reg.counter("sched/overtakes")
        self._m_wait = reg.histogram("sched/credit_wait_s")
        self._g_inflight = reg.gauge("sched/inflight_bytes")

    # ------------------------------------------------------------ gate

    def acquire(self, klass: int, prio: int, key: int,
                nbytes: int) -> Optional[_Ticket]:
        """Block until this frame may be written. Returns the ticket to
        pass to ``release`` (None for frames below the scheduling
        floor — nothing to release)."""
        if nbytes <= MIN_SCHED_BYTES:
            return None
        t = _Ticket(klass, prio, key, nbytes, next(self._seq))
        entry = (t.order(), t)
        with self._cv:
            heapq.heappush(self._heap, entry)
            while not (self._heap[0] is entry
                       and (self._inflight == 0
                            or self._inflight + t.nbytes <= self.credit)):
                self._cv.wait(1.0)
            heapq.heappop(self._heap)
            self._inflight += t.nbytes
            self._g_inflight.set(self._inflight)
            self._admit_seq += 1
            # an overtake: some entry enqueued BEFORE us is still
            # queued — we jumped the line on priority
            overtook = any(e[1].seq < t.seq for e in self._heap)
            waited = time.monotonic() - t.t_enq
            self._trace.append({
                "class": "act" if klass == CLASS_ACT else "grad",
                "key": key, "prio": prio, "nbytes": t.nbytes,
                "enq_seq": t.seq, "admit_seq": self._admit_seq,
                "wait_s": waited, "overtook": overtook,
                # wall-clock ADMIT stamp: the credit wait occupied
                # [t - wait_s, t] — the interval the critical-path
                # analyzer subtracts out of PS_PUSH spans as "credit"
                "t": time.time(),
            })
        (self._m_act if klass == CLASS_ACT else self._m_grad).inc()
        if overtook:
            self._m_overtakes.inc()
        self._m_wait.observe(waited)
        # flight-recorder send-admission event, KEY-LESS like the codec
        # decisions (obs/flight.py): the admission ordering is context
        # for EVERY key's postmortem — a frame that waited did so
        # because of some OTHER key's burst, so filtering it out of
        # that key's dump would hide exactly the why. The enabled check
        # comes FIRST: with the recorder off the per-frame cost must
        # stay one attribute read, not an f-string build.
        from ..obs import flight
        if flight.get_recorder().enabled:
            flight.record(
                "send_admit", nbytes=t.nbytes,
                detail=f"class={'act' if klass == CLASS_ACT else 'grad'} "
                       f"key={key} prio={prio} wait_ms={waited * 1e3:.1f} "
                       f"overtook={overtook}")
        return t

    def release(self, ticket: Optional[_Ticket]) -> None:
        if ticket is None:
            return
        with self._cv:
            self._inflight -= ticket.nbytes
            self._g_inflight.set(self._inflight)
            self._cv.notify_all()

    # ------------------------------------------------------------ views

    def trace(self) -> List[dict]:
        """Admission records, oldest first (bounded window)."""
        with self._cv:
            return list(self._trace)

    def queued(self) -> int:
        with self._cv:
            return len(self._heap)

    def inflight(self) -> int:
        return self._inflight


# ---------------------------------------------------------------- global

_lock = threading.Lock()
_current: Optional[SendScheduler] = None
_configured = False


def configure(credit_bytes: Optional[int] = None) -> Optional[SendScheduler]:
    """(Re)build the process-global scheduler. ``None`` re-reads
    ``BPS_SCHEDULING_CREDIT`` (``BYTEPS_SCHEDULING_CREDIT`` accepted);
    credit <= 0 disables. Called by ``bps.init`` so the env contract
    matches every other knob; tests call it directly between arms."""
    global _current, _configured
    if credit_bytes is None:
        credit_bytes = int(
            os.environ.get("BPS_SCHEDULING_CREDIT",
                           os.environ.get("BYTEPS_SCHEDULING_CREDIT", "0"))
            or 0)
    with _lock:
        _current = SendScheduler(credit_bytes) if credit_bytes > 0 else None
        _configured = True
        return _current


def current() -> Optional[SendScheduler]:
    """The process-global scheduler, or None when disabled. First call
    resolves from the env so directly-constructed transports (tests,
    scripts without ``bps.init``) honor ``BPS_SCHEDULING_CREDIT``."""
    if not _configured:
        configure()
    return _current

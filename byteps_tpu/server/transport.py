"""TCP transport for the host reduction service — the reference's
ps-lite "van" equivalent (reference: ps-lite ZMQ/TCP van, SURVEY §2.6;
worker call sites ZPush/ZPull core_loops.cc:567-613).

Wire protocol: one persistent connection per worker, length-prefixed
binary frames:

    request  := op:u8 | key:u64 | round:u64 | nbytes:u64 | timeout_ms:u64
                | plen:u64 | dtype:u8[8] | payload[plen]
    response := status:u8 | nbytes:u64 | payload[nbytes]

ops: 1=INIT (``nbytes`` = store size, payload = optional initial value),
2=PUSH (payload = data; ``round`` carries a dedup token
``worker_incarnation<<32 | per-key seq`` so a push retried after a
dropped ACK is applied exactly once — see ``RemotePSBackend``),
3=PULL (``nbytes`` = expected size, no payload;
response carries the merged buffer), 4=CLOSE, 5=INIT_C (``nbytes`` =
DENSE store size, payload = serialized compression kwargs — the server
registers a codec for the key, reference server.cc:222-252), 6=PUSH_C
(payload = compressed bytes; server decompresses then dense-sums),
7=PULL_C (``nbytes`` unused/0 — the payload size is fixed by the key's
codec; server recompresses the merged round once and serves identical
bytes to every worker, reference server.cc:86-113), 8=PUSH_RS
(row-sparse push: ``nbytes`` = DENSE table byte size, payload =
``n|idx|rows`` per server/rowsparse.py; server scatters to dense then
engine-sums — the reference's reserved-but-unimplemented
kRowSparsePushPull). status: 0=OK, 1=error
(backend rejected the request; the error response carries a UTF-8
message as payload and the connection stays usable), 2=timeout.

``PSTransportServer`` fronts a ``PSServer``/``HostPSBackend`` (the
native C++ summation engine) with a threaded socket server: one thread
per worker connection; the engine's sticky key→thread queues do the
summation exactly as in-process. ``RemotePSBackend`` is the worker-side
client with the same interface as ``HostPSBackend`` (including
``push_pull``'s per-key round counter), so ``PSGradientExchange`` and
``AsyncPSWorker`` work unchanged across process/host boundaries.
"""

from __future__ import annotations

import os
import socket
import struct
import threading
import time
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

try:                       # registers "bfloat16" with numpy for the
    import ml_dtypes       # noqa: F401 — bf16 wire transcode path
except ImportError:        # pragma: no cover — jax ships ml_dtypes
    pass

from ..common.naming import place_key

_HDR = struct.Struct("!BQQQQQ8s")   # op, key, round, nbytes, timeout, plen, dtype
_RSP = struct.Struct("!BQ")

OP_INIT, OP_PUSH, OP_PULL, OP_CLOSE = 1, 2, 3, 4
OP_INIT_C, OP_PUSH_C, OP_PULL_C = 5, 6, 7
OP_PUSH_RS = 8   # row-sparse push: nbytes = DENSE table size, payload =
                 # n|idx|rows (server/rowsparse.py wire format)
OP_ROUND = 9     # query the key's latest completed round (response
                 # payload = u64) — a restarted worker of a LIVE job
                 # resyncs its round counters from this instead of
                 # stalling on round 1 (elastic rejoin)
# Shared-memory data plane (reference: ps-lite's zero-copy ZPush/ZPull
# on shm for colocated worker↔server, core_loops.cc:567-613 /
# BYTEPS_ENABLE_IPC): the frame carries only the segment name and
# length; the payload lives at offset 0 of a worker-owned POSIX shm
# segment (one per connection channel) the server attaches to —
# gradient bytes never cross a socket. Field semantics are unchanged
# from the socket ops: ``round`` = dedup token (push) / sync round
# (pull), ``timeout`` = pull timeout ms.
OP_PUSH_SHM = 10   # payload = segment name, ``nbytes`` = data length
OP_PULL_SHM = 11   # same; the server PULLs INTO the segment
# Connection STRIPING for large tensors (VERDICT r4 #4 — the role of
# ps-lite's multi-lane RDMA/UCX vans): one logical push/pull split
# over several pooled connections in flight at once.
#   OP_PUSH_PART: nbytes = TOTAL length, rnd = dedup token shared by
#     all parts; payload = _PART prefix + the part's bytes. The server
#     stages parts per (key, token) and applies ONCE when complete.
#     The prefix's nonce is 0 (the token already identifies the op and
#     MUST be stable across retries for the staging dedup).
#   OP_PULL_PART: rnd = round; payload = _PART prefix (no data). The
#     server round-blocks once per (key, round, nonce), caches the
#     merged bytes while the op's parts drain, and each part response
#     carries its [offset, offset+len) slice — the client receives
#     straight into the caller's buffer (zero-copy scatter). The nonce
#     is fresh per LOGICAL pull attempt: without it, concurrent
#     striped pullers of the same async key share a (key, round=0)
#     stage, and the second fetch after the first op's parts drain it
#     can serve a NEWER store value to the first op's stragglers — a
#     torn tensor assembled from two different rounds (ADVICE.md).
OP_PUSH_PART = 12
OP_PULL_PART = 13
# Replica-log ops for the server plane's primary-backup replication
# (byteps_tpu.server.plane): the forward-log of a key's summed rounds
# lives in a ReplicaStore hosted by the BACKUP shard's transport
# server, so after the primary dies the promoted shard replays pulls
# from its local log bit-exact (docs/server-plane.md).
#   OP_REPL_PUT: ``round`` = plane round, payload = merged bytes
#     (idempotent last-wins; every worker logs the identical merge).
#   OP_REPL_GET: ``round`` = plane round; response payload = one
#     presence byte (0/1) + the logged bytes — a zero-length logged
#     round stays distinguishable from "never logged".
#   OP_REPL_BASE: response payload = u64 highest logged round.
OP_REPL_PUT, OP_REPL_GET, OP_REPL_BASE = 14, 15, 16
# Fused compression plane (byteps_tpu.compress): unlike INIT_C/PUSH_C/
# PULL_C (one immutable codec registered per key), the payload is
# SELF-DESCRIBING — a codec header rides every frame, so the adaptive
# controller can re-decide a layer's codec at any round boundary and
# the server decodes whatever arrives (or refuses LOUDLY on a codec-
# version mismatch / torn header, compress.wire.CodecError).
#   OP_PUSH_F: ``round`` = dedup token (like OP_PUSH); payload =
#     header + codec body. Server decodes → dense-sums in the engine.
#   OP_PULL_F: ``round`` = sync round, ``nbytes`` = DENSE size, dtype =
#     dense dtype; payload = codec:u8 | topk-div:u16le (the level the
#     worker's decision trace pinned for this round + its configured
#     keep fraction). Server pulls the merged round dense, encodes it
#     at that codec (cached per (key, round, codec, div) —
#     deterministic codecs, so the cache is throughput-only), responds
#     with the payload.
OP_PUSH_F, OP_PULL_F = 17, 18
# Point-to-point activation plane (byteps_tpu.pipeline, MPMD pipeline
# parallelism): activations / activation-grads hop stage→stage through
# the RECEIVER's mailbox, never through the server sum.
#   OP_ACT_PUSH: key = activation channel (pipeline.exchange.act_key),
#     ``round`` = absolute microbatch seq; payload = the boundary's
#     concatenated var bytes. Last-wins per (key, seq), so the
#     transport's resend path is idempotent for free.
#   OP_ACT_PULL: remote take — blocks server-side (sliced, like
#     OP_PULL) until the (key, seq) frame arrives; response = payload.
# ACT frames are the transport's LATENCY class: the client tags them
# ``sched.CLASS_ACT`` so they overtake queued gradient bursts in the
# send scheduler (BPS_SCHEDULING_CREDIT).
OP_ACT_PUSH, OP_ACT_PULL = 19, 20
# Sharded weight update (byteps_tpu.sharded_update): the group OWNER
# publishes post-apply parameter bytes, non-owners fetch them instead
# of gradients. A versioned last-wins mailbox like the act store, but
# NON-destructive reads (dp-1 replicas read each frame) with bounded
# retention (the two-round cross-step window + slack).
#   OP_PARAM_PUT: key = param-class key (bit 41 | decl<<16 | group),
#     ``round`` = the sharded step seq; payload = the group's
#     concatenated leaf bytes. Idempotent last-wins per (key, seq).
#     PUT frames ride the wire scheduler's LATENCY class with
#     next-step first-use priority — they gate the next forward like
#     activations do.
#   OP_PARAM_GET: ``round`` = seq; blocks server-side (sliced, like
#     OP_PULL) until the frame arrives; response = payload. A timeout
#     is the owner-death diagnostic's trigger, never a silent hang.
OP_PARAM_PUT, OP_PARAM_GET = 21, 22
# Fleet telemetry plane (byteps_tpu.obs.fleet): serve this SERVER
# process's registry snapshot + heartbeat (monotonic uptime, op
# counters) as one JSON response. Request carries no payload and the
# response is an ordinary reply, so the op is reuse-safe by
# construction and NEVER credit-gated — the send scheduler only gates
# payload-bearing frames, and the client scrapes on a DEDICATED
# channel outside the data-plane pools: telemetry must flow when the
# data plane is wedged (that is precisely when it is needed).
OP_STATS = 23
# Elastic rejoin (docs/elasticity.md): the newest retained seq in a
# key's param mailbox, so a rejoining sharded-update owner resumes its
# param-frame sequence from the server's retained frames instead of
# re-publishing from seq 0 (which would strand every non-owner blocked
# on the real next seq). Response payload = u64 seq (0 = empty).
OP_PARAM_SEQ = 24
# Causal trace plane (byteps_tpu.obs.spans): serve this server's
# per-(key, round) span ring — first arrival, per-worker arrival
# ts+bytes, merge-wait, per-pull serve spans — plus the server's wall
# clock ``now`` (the NTP-style clock-alignment sample). Same contract
# as OP_STATS: no payload, reuse-safe, NEVER credit-gated, scraped on
# the dedicated stats channel so a wedged data plane cannot starve it.
OP_TRACE = 25
# Bounded staleness (server/admission.StaleStore, docs/admission.md):
# OP_LAG_DECL declares a key's K bound (rnd = K); it is replayed on
# reconnect like inits — the failover contract — so a replacement
# server relearns every key's bound before the first versioned frame.
# OP_PUSH_LAG / OP_PULL_LAG carry ``rnd = worker_id << 48 | round``
# (48 bits of round, 16 of worker). The pull response prefixes one
# verdict byte (admission.LAG_* flags) to the dense payload.
OP_LAG_DECL, OP_PUSH_LAG, OP_PULL_LAG = 26, 27, 28
# Sharded embedding store (server/embed.py, docs/embedding.md): rows
# of a table hash-placed across shards, addressed by id in the PAYLOAD
# (one key per table — bit 43 of the key space), pulled conditionally
# against cached per-row versions and pushed as dedup'd row-sparse
# sums. Transport-owned like the act/param mailboxes so raw-PSServer
# fleet server roles speak it; REFUSED on a hierarchical-agg front
# (embed_store below — an aggregator has no row store to serve from).
#   OP_EMBED_INIT: payload = JSON table meta; idempotent first-wins.
#   OP_EMBED_PULL: payload = n:u32|ids:u64[n]|cached_vers:u64[n]
#     [|table_epoch:u64]; response = table_epoch:u64|flags:u8[n]|
#     vers:u64[n]|full rows for flag==1 only. A request epoch behind
#     the table's forces every row full (failover/restore coherence).
#   OP_EMBED_PUSH: payload = n:u32|ids:u64[n]|deltas:dtype[n·cols];
#     ``rnd`` = push dedup token — a reconnect retry applies once.
OP_EMBED_INIT, OP_EMBED_PULL, OP_EMBED_PUSH = 29, 30, 31
# Embed durability (ISSUE 20, server↔server + admin ops):
#   OP_EMBED_REPL: chain forward of applied rows — key = slice key
#     (table | origin shard), ``rnd`` = the originating push's dedup
#     token, payload = n:u32|ids:u64[n]|vers:u64[n]|rows (ABSOLUTE
#     post-apply state; last-wins by version on the replica).
#   OP_EMBED_FAILOVER: promote this server for a dead slice — key =
#     slice key, payload = JSON {"dead": [shards]}; response = JSON
#     stats {table, slice, rows, errors, epoch, already}. Idempotent.
#   OP_EMBED_SNAP / OP_EMBED_RESTORE: payload = JSON {"path"}; the
#     server dumps/loads its whole row store as one npz (atomic
#     tmp+rename on SNAP); response = JSON stats.
OP_EMBED_REPL, OP_EMBED_FAILOVER = 32, 33
OP_EMBED_SNAP, OP_EMBED_RESTORE = 34, 35
_PART = struct.Struct("!IIHHQ")  # offset, part_len, part_idx, nparts, nonce
_LAG_ROUND_MASK = (1 << 48) - 1
ST_OK, ST_ERR, ST_TIMEOUT, ST_GONE = 0, 1, 2, 3


class _ServerTimeout(TimeoutError):
    """An ST_TIMEOUT reply — an APPLICATION answer on a healthy
    connection. Distinct from the OS's TimeoutError (ETIMEDOUT, which
    also subclasses OSError and SHOULD take the reconnect path)."""

# applied seqs kept as an exact set above a contiguous floor — bounds
# memory while letting out-of-order same-key pushes through
_DEDUP_WINDOW = 256


class _PosixShm:
    """Minimal POSIX shared-memory segment (shm_open + mmap), used
    instead of multiprocessing.shared_memory to keep the resource
    tracker out of the picture: this Python's tracker mis-handles the
    create-in-one-process/attach-in-another lifecycle (spurious
    KeyErrors and exit warnings), and ownership here is explicit —
    workers create and unlink their segments, the server only attaches.
    A SIGKILLed worker can strand its current /dev/shm/bps-shm-*
    files (0600, one or two per connection channel) until reboot or a
    manual ``rm`` — the documented cost of skipping the tracker."""

    __slots__ = ("name", "size", "_mmap", "buf")

    def __init__(self, name: Optional[str] = None, create: bool = False,
                 size: int = 0) -> None:
        import mmap as _mmap
        import os as _os
        import secrets as _secrets
        from multiprocessing import shared_memory as _sm
        posixshmem = _sm._posixshmem
        if create:
            while True:
                name = f"/bps-shm-{_secrets.token_hex(6)}"
                try:
                    fd = posixshmem.shm_open(
                        name, _os.O_CREAT | _os.O_EXCL | _os.O_RDWR,
                        mode=0o600)
                    break
                except FileExistsError:
                    continue
            _os.ftruncate(fd, size)
        else:
            fd = posixshmem.shm_open(name, _os.O_RDWR, mode=0o600)
            size = _os.fstat(fd).st_size
        try:
            self._mmap = _mmap.mmap(fd, size)
        finally:
            _os.close(fd)
        self.name = name
        self.size = size
        self.buf = memoryview(self._mmap)

    def close(self) -> None:
        try:
            self.buf.release()
            self._mmap.close()
        except (BufferError, ValueError):
            pass

    def unlink(self) -> None:
        from multiprocessing import shared_memory as _sm
        try:
            _sm._posixshmem.shm_unlink(self.name)
        except OSError:
            pass


class _ShmCache:
    """Server-side LRU of attached worker shm segments, bounded by
    count AND bytes (a worker's segment growth abandons old names —
    already unlinked, but mapped here until evicted; the byte bound
    keeps dead generations from pinning multi-GB of shm). Slices are
    taken under the lock so a concurrent eviction can't release a
    buffer between lookup and use; an evicted-while-exported buffer
    stays alive because _PosixShm.close backs off on BufferError."""

    def __init__(self, cap: int = 64, cap_bytes: int = 1 << 30) -> None:
        self._segs: Dict[str, _PosixShm] = {}   # insertion order = LRU
        self._lock = threading.Lock()
        self._cap = cap
        self._cap_bytes = cap_bytes

    def view(self, name: str, nbytes: int) -> memoryview:
        with self._lock:
            seg = self._segs.pop(name, None)
            if seg is None:
                seg = _PosixShm(name=name)
            self._segs[name] = seg              # (re)insert most-recent
            while len(self._segs) > self._cap or (
                    len(self._segs) > 1 and
                    sum(s.size for s in self._segs.values())
                    > self._cap_bytes):
                old = next(iter(self._segs))
                if old == name:
                    break
                try:
                    self._segs.pop(old).close()
                except Exception:
                    pass
            if nbytes > seg.size:
                raise ValueError(f"shm window {nbytes}B exceeds segment "
                                 f"{name} ({seg.size}B)")
            return seg.buf[:nbytes]

    def close(self) -> None:
        with self._lock:
            for seg in self._segs.values():
                try:
                    seg.close()
                except Exception:
                    pass
            self._segs.clear()


class _DedupState:
    """Per-(key, worker-incarnation) push-dedup record."""

    __slots__ = ("floor", "applied", "claims", "ts")

    def __init__(self) -> None:
        self.floor = 0          # every seq <= floor is applied
        self.applied: set = set()   # applied seqs above floor
        self.claims: set = set()    # seqs whose apply is in flight
        self.ts = 0.0

    def is_applied(self, seq: int) -> bool:
        return seq <= self.floor or seq in self.applied

    def record(self, seq: int) -> None:
        self.applied.add(seq)
        # advance the contiguous floor, then cap the exact window
        while (self.floor + 1) in self.applied:
            self.floor += 1
            self.applied.discard(self.floor)
        while len(self.applied) > _DEDUP_WINDOW:
            low = min(self.applied)
            self.applied.discard(low)
            self.floor = max(self.floor, low)


def _as_bytes(arr) -> memoryview:
    """Byte view of any numpy array — dtypes outside the buffer protocol
    (bfloat16) go through a uint8 reinterpret."""
    a = np.ascontiguousarray(arr)
    try:
        return memoryview(a).cast("B")
    except (ValueError, TypeError):
        return memoryview(a.view(np.uint8))


def _recv_exact(sock: socket.socket, n: int) -> memoryview:
    buf = bytearray(n)
    _recv_exact_into(sock, memoryview(buf))
    return memoryview(buf)


def _recv_exact_into(sock: socket.socket, view: memoryview) -> None:
    """Fill ``view`` from the socket — the zero-copy receive: dense
    pulls land straight in the caller's preallocated array instead of
    paying an allocate + copy per pull (VERDICT r4 #4)."""
    n = len(view)
    got = 0
    while got < n:
        r = sock.recv_into(view[got:], n - got)
        if r == 0:
            raise ConnectionError("peer closed")
        got += r


def _byteview(p) -> memoryview:
    """Flat byte view of any buffer — multi-byte-item views (a numpy
    float array passed raw) are recast so vector lengths are BYTE
    lengths, the unit sendmsg's return value and the partial-send
    bookkeeping below are denominated in."""
    v = p if isinstance(p, memoryview) else memoryview(p)
    if v.itemsize != 1 or v.ndim != 1:
        v = v.cast("B")
    return v


def _send_frame(sock, hdr, parts) -> None:
    """Vectored zero-copy frame send: header + payload parts ride ONE
    ``sendmsg`` scatter-gather array of memoryviews, so no frame size
    pays a join/copy (the old path materialized ``hdr + b"".join(...)``
    for every frame up to 16 KB) and no part count pays per-part
    syscalls. A short vectored write resumes from the first unsent
    byte — fully-sent vectors are dropped, the split one is resliced
    (slicing a memoryview is a view, not a copy).

    Sockets without a vectored primitive (test doubles) degrade to
    sequential ``sendall`` per part — still no join, single-part
    frames still one write for the payload. ThrottledSocket implements
    its OWN metered ``sendmsg`` (throttle.py): its ``__getattr__``
    would otherwise proxy this call to the raw socket and every
    vectored byte would silently bypass the emulated NIC's pacing AND
    the wire-byte accounting the scaling-curve rig asserts against."""
    sendmsg = getattr(sock, "sendmsg", None)
    if sendmsg is None:
        sock.sendall(hdr)
        for p in parts:
            sock.sendall(p)
        return
    bufs = [_byteview(hdr)]
    for p in parts:
        bufs.append(_byteview(p))
    while bufs:
        # cap the iovec count: sendmsg raises EMSGSIZE past IOV_MAX
        # (1024 on Linux) and a large row-gather can exceed it; the
        # resume loop below already handles the unsent tail
        n = sendmsg(bufs[:1024])
        while bufs and n >= len(bufs[0]):
            n -= len(bufs[0])
            bufs.pop(0)
        if n:
            bufs[0] = bufs[0][n:]


def _send_req(sock: socket.socket, op: int, key: int, rnd: int, nbytes: int,
              timeout_ms: int, dtype: str, payload) -> None:
    """``payload``: None, one buffer, or a SEQUENCE of buffers sent
    back to back as one wire payload (scatter-gather — striped parts
    prepend their _PART prefix without copying the data slice)."""
    parts = ([] if payload is None
             else list(payload) if isinstance(payload, (tuple, list))
             else [payload])
    # normalize to byte views up front: plen must be a BYTE count even
    # if a caller hands a multi-byte-item buffer (len() of a float32
    # memoryview counts elements)
    parts = [_byteview(p) for p in parts]
    plen = sum(len(p) for p in parts)
    hdr = _HDR.pack(op, key, rnd, nbytes, timeout_ms, plen,
                    dtype.encode()[:8].ljust(8, b"\0"))
    if not parts:
        sock.sendall(hdr)
        return
    _send_frame(sock, hdr, parts)


# The reused-recv-buffer invariant: an op's handler must CONSUME its
# payload before the connection reads the next frame, because the next
# frame overwrites the shared buffer. This allowlist names the ops whose
# handlers are known to copy synchronously (the engine/stage copies the
# bytes before the handler returns); any op NOT listed gets a fresh
# buffer — a new op that stashes a payload view past its handler return
# degrades to an allocation instead of silently corrupting frames.
_REUSE_SAFE_OPS = frozenset(
    {OP_INIT, OP_PUSH, OP_PUSH_C, OP_PUSH_RS, OP_PUSH_PART,
     OP_REPL_PUT,    # ReplicaStore.put copies via bytes() synchronously
     OP_PUSH_F,      # wire.decode materializes (or the engine copies
                     # the dense view) before the handler returns
     OP_ACT_PUSH,    # ActStore.put copies via bytes() synchronously
     OP_PARAM_PUT,   # ParamStore.put copies via bytes() synchronously
     OP_PUSH_LAG,    # StaleStore.push folds (+=) before returning
     OP_EMBED_PUSH,  # EmbedRowStore.apply folds row-wise (new arrays)
                     # before returning
     OP_EMBED_PULL,  # ids/vers views are consumed inside .pull()
                     # (the row buffer is a fresh concatenation)
     OP_EMBED_REPL})  # handler materializes via bytes() before
#                       repl_apply stores per-row copies


def _recv_req(sock: socket.socket, rholder: Optional[list] = None):
    op, key, rnd, nbytes, timeout, plen, dt = _HDR.unpack(
        _recv_exact(sock, _HDR.size))
    if not plen:
        payload = None
    elif (rholder is not None and plen > (64 << 10)
            and op in _REUSE_SAFE_OPS):
        # large payloads land in the connection's REUSED buffer: a fresh
        # bytearray(n) zero-fills n bytes before the recv overwrites
        # them — at 8 MB pushes that zeroing alone was a measurable
        # slice of the wire path. Safe because the allowlisted handlers
        # consume their payload synchronously (the engine copies before
        # returning). Grown by REPLACEMENT, never resize: the caller's
        # loop still holds the previous frame's memoryview, and resizing
        # an exported bytearray raises BufferError and kills the
        # connection
        if len(rholder[0]) < plen:
            rholder[0] = bytearray(plen)
        payload = memoryview(rholder[0])[:plen]
        _recv_exact_into(sock, payload)
    else:
        payload = _recv_exact(sock, plen)
    return op, key, rnd, nbytes, timeout, dt.rstrip(b"\0").decode(), payload


# ------------------------------------------------------------------ server

def _ipc_path(port: int) -> str:
    """Deterministic UDS path for a server's IPC listener — colocated
    workers derive it from the TCP port they were given, so no extra
    address plumbing is needed (reference: BYTEPS_ENABLE_IPC switches
    colocated worker↔server traffic off the network stack,
    docs/best-practice.md). Sockets live in a 0700 per-uid directory —
    a world-writable shared path would let another local user squat the
    name (denying startup) or bind an impostor listener that workers
    auto-upgrade their gradients to."""
    import os as _os
    import stat as _stat
    import tempfile as _tempfile
    base = _os.environ.get("BPS_IPC_DIR")
    if not base:
        base = _os.path.join(_tempfile.gettempdir(),
                             f"bps-ipc-{_os.getuid()}")
    _os.makedirs(base, mode=0o700, exist_ok=True)
    st = _os.stat(base)
    if st.st_uid != _os.getuid() or (st.st_mode & 0o077):
        raise RuntimeError(
            f"IPC dir {base} must be owned by uid {_os.getuid()} with "
            f"mode 0700 (found uid {st.st_uid}, mode "
            f"{_stat.S_IMODE(st.st_mode):o}) — refusing to exchange "
            f"gradients over a tamperable socket path")
    return _os.path.join(base, f"bps-ipc-{port}.sock")


def _bump_bufs(s: socket.socket, nbytes: int = 4 << 20) -> None:
    """Grow a UDS's kernel buffers: the AF_UNIX default (~208KB) makes
    multi-MB gradient frames ping-pong between the peers with a context
    switch per buffer-full, which measured SLOWER than loopback TCP
    (whose autotuned windows absorb bulk writes)."""
    for opt in (socket.SO_SNDBUF, socket.SO_RCVBUF):
        try:
            s.setsockopt(socket.SOL_SOCKET, opt, nbytes)
        except OSError:
            pass


def _ipc_enabled() -> bool:
    import os as _os
    return _os.environ.get(
        "BPS_ENABLE_IPC", _os.environ.get("BYTEPS_ENABLE_IPC", "0")) \
        not in ("0", "", "false")


class PSTransportServer:
    """Threaded TCP front for a local summation backend.

    With BPS_ENABLE_IPC=1 the server ALSO listens on a Unix-domain
    socket (path derived from the TCP port) and colocated workers
    auto-upgrade their connections to it — loopback TCP's
    checksum/segmentation overhead gone, same frames, same handler
    (the reference's colocated-IPC deployment knob)."""

    def __init__(self, backend, host: str = "0.0.0.0", port: int = 0,
                 key_meta=None, nic=None):
        self.backend = backend
        # fused/homogeneous front (server/homog.py): backends with a
        # fused surface of their own (HostPSBackend) handle managed
        # keys internally; a RAW engine (PSServer) gets wrapped so the
        # homogeneous decode-free sum exists on every deployment. Ops
        # that can touch managed keys route through ``_fb``.
        if hasattr(backend, "push_fused"):
            self._fb = backend
        else:
            from .homog import FusedFront
            self._fb = FusedFront(backend,
                                  getattr(backend, "num_workers", 1))
        # optional emulated-NIC throttle (throttle.Nic): every accepted
        # connection's bytes are charged to this server endpoint's
        # bandwidth — see throttle.py / the PS-vs-allreduce bench
        self._nic = nic
        from .compressed import CompressedKeyStore
        self.compressed = CompressedKeyStore()
        # per-key traffic log (reference: PS_KEY_LOG on the server,
        # server.cc:408-409)
        import os as _os
        self._key_log = _os.environ.get(
            "BPS_KEY_LOG", _os.environ.get("PS_KEY_LOG", "")) in ("1", "true")
        self._rs_cols: Dict[int, int] = {}   # row-sparse: pinned cols/key
        # key -> (nbytes, dtype), recorded at INIT/INIT_C so the store can
        # be snapshotted (the reference has NO PS-state checkpoint —
        # docs/rationale.md leaves server recovery as future work);
        # seeded with restore_snapshot's meta when recovering
        self._key_meta: Dict[int, Tuple[int, str]] = dict(key_meta or {})
        # (key, worker_incarnation) -> _DedupState. A push retried after
        # a lost ACK carries the same token and is acknowledged without
        # re-applying — without this, a sync-mode reconnect could
        # double-count one worker's gradient in the round's sum (the
        # per-round push counter would fill early with another worker
        # missing). Applied seqs are EXACT-membership (recent set +
        # contiguous floor), not a high-water mark, so concurrent
        # same-key pushes whose frames land out of order are both
        # applied. ``claims`` marks seqs whose apply is IN FLIGHT, so a
        # retry racing the original apply (conn reset mid-sum, instant
        # redial) blocks on its outcome instead of re-applying
        # concurrently. Applied seqs are recorded only after a
        # successful apply: a dedup hit always means the payload reached
        # the store. Entries for dead incarnations are swept after
        # ``BPS_PUSH_DEDUP_TTL_SECS`` (default 600 — far beyond any
        # retry window) of inactivity so elastic worker churn can't grow
        # the table without bound.
        self._push_seen: Dict[Tuple[int, int], _DedupState] = {}
        # replica log hosted FOR other shards' keys (server plane
        # primary-backup replication, OP_REPL_*) — created on first use
        # so plain deployments never pay the import
        self._replica = None
        self._replica_lock = threading.Lock()
        # activation mailbox (pipeline stage→stage plane, OP_ACT_*) —
        # likewise lazy; plain PS deployments never allocate it
        self._acts = None
        self._acts_lock = threading.Lock()
        # param mailbox (sharded weight update, OP_PARAM_*) — lazy too
        self._params = None
        # sharded embedding row store (server/embed.py, OP_EMBED_*) —
        # lazy; deployments without tables never allocate it
        self._embed = None
        self._embed_lock = threading.Lock()
        self._shm = _ShmCache()
        # fused-pull caching lives behind self._fb (the backend's own
        # FusedPullCache, or FusedFront's, or the homog store's merged
        # payload dict) — the transport layer holds no codec state
        # striping reassembly/scatter state (OP_PUSH_PART/OP_PULL_PART):
        # parts of one logical op arrive on DIFFERENT connection
        # threads. Stages carry a last-activity stamp and are swept
        # after _STRIPE_TTL_SECS — a client dying mid-striped-op (or a
        # retry racing a completed stage) must not strand full-tensor
        # staging buffers for the server's lifetime
        self._stripe_lock = threading.Lock()
        self._push_stage: Dict[Tuple[int, int], Dict] = {}
        self._pull_stage: Dict[Tuple[int, int], Dict] = {}
        self._stripe_sweep_at = 0.0
        self._push_lock = threading.Lock()
        self._push_cv = threading.Condition(self._push_lock)
        # bounded-staleness store for RAW backends (see the lag-op
        # helpers below) — lazy, K=1 deployments never allocate it
        self._stale = None
        self._stale_lock = threading.Lock()
        self._dedup_ttl = float(_os.environ.get(
            "BPS_PUSH_DEDUP_TTL_SECS", "600"))
        self._dedup_sweep_at = 0.0
        # cached metric handles — _handle runs per request; a registry
        # name lookup there is avoidable data-plane overhead
        from ..obs.metrics import get_registry
        self._m_requests = get_registry().counter("transport/requests")
        self._m_merge_wait = get_registry().histogram(
            "server/merge_wait_s")
        # heartbeat state for OP_STATS (obs/fleet.py): MONOTONIC birth
        # time (a scraper seeing uptime go backwards has watched this
        # process restart — wall clocks can step, this cannot) and a
        # plain per-server request count (the registry counter above is
        # process-wide and shared by colocated servers)
        self._t0_mono = time.monotonic()
        self._t0_wall = time.time()
        self._n_requests = 0
        # causal span ring (obs/spans.py, OP_TRACE): per-(key, round)
        # arrival/serve records. A backend with its OWN ring
        # (HostPSBackend) records internally — this layer then only
        # serves it, never double-notes the same push into two rings.
        from ..obs.spans import ServerSpanRing
        ring = getattr(backend, "spans", None)
        self._own_spans = ring is None
        self.spans = ring if ring is not None else ServerSpanRing(
            num_workers=getattr(backend, "num_workers", 1))
        # the clock-alignment sample source — an attribute so skew
        # tests (and one day a chaos rig) can inject a stepped clock
        self._trace_now = time.time
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind((host, port))
        self.port = self._sock.getsockname()[1]
        self._sock.listen(64)
        self._stop = threading.Event()
        self._accept_thread = threading.Thread(
            target=self._accept_loop, args=(self._sock, True),
            daemon=True, name="bps-ps-accept")
        self._accept_thread.start()
        self._ipc_sock = None
        self.ipc_path = None
        if _ipc_enabled():
            import os as _os
            path = _ipc_path(self.port)
            try:
                _os.unlink(path)
            except OSError:
                pass
            self._ipc_sock = socket.socket(socket.AF_UNIX,
                                           socket.SOCK_STREAM)
            _bump_bufs(self._ipc_sock)
            self._ipc_sock.bind(path)
            self._ipc_sock.listen(64)
            self.ipc_path = path
            threading.Thread(target=self._accept_loop,
                             args=(self._ipc_sock, False),
                             daemon=True, name="bps-ps-ipc-accept").start()

    def _accept_loop(self, sock: socket.socket, is_tcp: bool) -> None:
        while not self._stop.is_set():
            try:
                conn, _ = sock.accept()
            except OSError:
                return
            if is_tcp:
                conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            if self._nic is not None:
                from .throttle import ThrottledSocket
                conn = ThrottledSocket(conn, self._nic)
            threading.Thread(target=self._serve_conn, args=(conn,),
                             daemon=True, name="bps-ps-conn").start()

    def _handle(self, conn, op, key, rnd, nbytes, timeout, dtype, payload):
        """One request; backend errors become ST_ERR/ST_TIMEOUT responses
        (the connection survives — one bad request must not take down the
        worker's whole data plane)."""
        self._m_requests.inc()
        self._n_requests += 1    # heartbeat op counter; GIL-atomic int
        #                          add is plenty for a liveness signal
        try:
            if self._key_log and op in (OP_PUSH, OP_PULL, OP_PUSH_C,
                                        OP_PUSH_RS):
                # OP_PULL_C logs in its branch — its size is the codec
                # payload, known only after the pull
                from ..common.logging import get_logger
                get_logger().info("PS_KEY_LOG op=%d key=%d bytes=%d rnd=%d",
                                  op, key,
                                  len(payload) if payload else nbytes, rnd)
            if op == OP_INIT:
                init = (np.frombuffer(payload, dtype=dtype)
                        if payload is not None else None)
                # rnd bit 0 = the worker's plan-time fused-managed
                # declaration (compression-plane keys): hands the key's
                # rounds to the homogeneous fused store
                self._fb.init_key(key, nbytes, dtype, init=init,
                                  fused=bool(int(rnd) & 1))
                self._key_meta[key] = (int(nbytes), dtype)
                # a (re-)init marks a new tenancy of the key on this
                # shard (migration replay): shard-local rounds restart,
                # so cached fused pulls from a previous tenancy would
                # alias the recurring round numbers. HostPSBackend
                # drops its own cache inside init_key; FusedFront
                # exposes the drop explicitly.
                if hasattr(self._fb, "drop_cached"):
                    self._fb.drop_cached(key)
                conn.sendall(_RSP.pack(ST_OK, 0))
            elif op == OP_PUSH:
                # wire transcode: a frame dtype narrower than the store
                # (bf16 async deltas, BPS_ASYNC_WIRE_DTYPE) halves wire
                # bytes; the store keeps full precision (the reference's
                # inter-node fp16 compression, applied the TPU way)
                arr = np.frombuffer(payload, dtype=dtype)
                meta = self._key_meta.get(key)
                if meta is not None and meta[1] != dtype:
                    arr = arr.astype(meta[1])
                self._note_push(self._apply_push_once(
                    key, rnd, lambda: self._fb.push(key, arr)),
                    key, rnd, len(payload))
                conn.sendall(_RSP.pack(ST_OK, 0))
            elif op == OP_PULL:
                out = self._pull_dense(key, rnd, nbytes, dtype, timeout)
                # vectored: status + dense sum in one gather write
                _send_frame(conn, _RSP.pack(ST_OK, out.nbytes),
                            [_as_bytes(out)])
            elif op == OP_INIT_C:
                from ..ops.compression.host import deserialize_kwargs
                kwargs = deserialize_kwargs(bytes(payload or b""))
                size = nbytes // np.dtype(dtype).itemsize
                self.compressed.register(key, kwargs, size, dtype)
                self.backend.init_key(key, nbytes, dtype)
                self._key_meta[key] = (int(nbytes), dtype)
                conn.sendall(_RSP.pack(ST_OK, 0))
            elif op == OP_PUSH_C:
                from .compressed import compressed_push
                plen_c = len(payload)
                self._note_push(self._apply_push_once(
                    key, rnd,
                    lambda: compressed_push(self.compressed, self.backend,
                                            key, payload)),
                    key, rnd, plen_c)
                conn.sendall(_RSP.pack(ST_OK, 0))
            elif op == OP_PUSH_F:
                # payload stays ENCODED through the front: managed keys
                # buffer it for the homogeneous merge (no dense decode
                # on this path), unmanaged keys decode into the engine
                pay = bytes(payload)
                self._note_push(self._apply_push_once(
                    key, rnd, lambda: self._fb.push_fused(key, pay)),
                    key, rnd, len(pay))
                conn.sendall(_RSP.pack(ST_OK, 0))
            elif op == OP_PULL_F:
                from ..compress import wire as cwire
                pb = bytes(payload or b"\0")
                cid = int(pb[0])
                div = (struct.unpack("<H", pb[1:3])[0]
                       if len(pb) >= 3 else cwire.TOPK_DIV)
                t0 = time.time()
                buf = self._fb.pull_fused(
                    key, int(nbytes), dtype, cid, round=int(rnd),
                    timeout_ms=int(timeout) or 30000,
                    div=div or cwire.TOPK_DIV)
                # same bottleneck signal OP_PULL feeds (_pull_dense):
                # merge wait + the slowest worker's push lag; cache
                # hits observe ~0 and don't skew the histogram
                self._m_merge_wait.observe(time.time() - t0)
                if self._own_spans:
                    self.spans.note_serve(key, int(rnd), t0,
                                          time.time() - t0)
                if self._key_log:
                    from ..common.logging import get_logger
                    get_logger().info(
                        "PS_KEY_LOG op=%d key=%d bytes=%d rnd=%d",
                        op, key, len(buf), rnd)
                conn.sendall(_RSP.pack(ST_OK, len(buf)))
                conn.sendall(buf)
            elif op == OP_PUSH_RS:
                from .rowsparse import rowsparse_push, unpack_rows
                idx, rows = unpack_rows(payload, dtype)
                plen_rs = len(payload)
                self._note_push(self._apply_push_once(
                    key, rnd,
                    lambda: rowsparse_push(self.backend, key, idx, rows,
                                           int(nbytes), dtype,
                                           meta=self._rs_cols)),
                    key, rnd, plen_rs)
                conn.sendall(_RSP.pack(ST_OK, 0))
            elif op == OP_ROUND:
                # a transport-owned StaleStore (raw-engine fallback)
                # versions the key's rounds itself — the elastic-rejoin
                # resync must see ITS counter, not the engine's zeros
                if self._stale is not None and self._stale.managed(key):
                    rv = struct.pack("!Q", int(self._stale.round(key)))
                else:
                    rv = struct.pack("!Q", int(self._fb.round(key)))
                conn.sendall(_RSP.pack(ST_OK, len(rv)) + rv)
            elif op == OP_PUSH_SHM:
                view = self._shm.view(bytes(payload).decode(), int(nbytes))
                data = np.frombuffer(view, dtype=dtype)
                self._note_push(self._apply_push_once(
                    key, rnd, lambda: self._fb.push(key, data)),
                    key, rnd, int(nbytes))
                del data, view   # release the buffer before reuse/unlink
                conn.sendall(_RSP.pack(ST_OK, 0))
            elif op == OP_PULL_SHM:
                view = self._shm.view(bytes(payload).decode(), int(nbytes))
                out = np.frombuffer(view, dtype=dtype)
                t0 = time.time()
                try:
                    self._fb.pull(key, out, round=int(rnd),
                                  timeout_ms=int(timeout) or 30000)
                finally:
                    del out, view
                if self._own_spans:
                    self.spans.note_serve(key, int(rnd), t0,
                                          time.time() - t0)
                conn.sendall(_RSP.pack(ST_OK, 0))
            elif op == OP_PUSH_PART:
                off, plen_, idx, nparts, _ = _PART.unpack(
                    payload[:_PART.size])
                stage_key = (key, int(rnd))
                now = time.time()
                with self._stripe_lock:
                    self._sweep_stages(now)
                    st = self._push_stage.get(stage_key)
                    if st is None:
                        st = {"buf": bytearray(int(nbytes)), "got": 0,
                              "seen": set(), "t": now}
                        self._push_stage[stage_key] = st
                    st["t"] = now
                # the multi-MB copy runs OUTSIDE the lock — part ranges
                # are disjoint, and copying under a server-wide lock
                # would serialize exactly the parallel staging striping
                # exists for. A retried part overwrites its own range
                # (idempotent) but only counts once toward completion
                memoryview(st["buf"])[off:off + plen_] = \
                    payload[_PART.size:_PART.size + plen_]
                with self._stripe_lock:
                    if idx not in st["seen"]:
                        st["seen"].add(idx)
                        st["got"] += plen_
                    complete = st["got"] >= int(nbytes)
                    if complete:
                        self._push_stage.pop(stage_key, None)
                if complete:
                    arr = np.frombuffer(st["buf"], dtype=dtype)
                    meta = self._key_meta.get(key)
                    if meta is not None and meta[1] != dtype:
                        arr = arr.astype(meta[1])
                    self._note_push(self._apply_push_once(
                        key, rnd, lambda: self.backend.push(key, arr)),
                        key, rnd, int(nbytes))
                conn.sendall(_RSP.pack(ST_OK, 0))
            elif op == OP_PULL_PART:
                off, plen_, idx, nparts, nonce = _PART.unpack(
                    payload[:_PART.size])
                # nonce in the stage key: concurrent striped pulls of
                # one async key (round=0) must each get their OWN
                # fetch, or a late part can be served a newer value
                stage_key = (key, int(rnd), int(nonce))
                now = time.time()
                with self._stripe_lock:
                    self._sweep_stages(now)
                    st = self._pull_stage.get(stage_key)
                    if st is None:
                        st = {"ev": threading.Event(), "data": None,
                              "err": None, "served": 0,
                              "nparts": int(nparts), "t": now}
                        self._pull_stage[stage_key] = st
                        fetch = True
                    else:
                        st["t"] = now
                        fetch = False
                if fetch:
                    # ONE round-blocked engine pull feeds every part
                    try:
                        st["data"] = _as_bytes(
                            self._pull_dense(key, rnd, nbytes, dtype,
                                             timeout))
                    except Exception as e:  # noqa: BLE001 — relayed below
                        st["err"] = e
                    finally:
                        st["ev"].set()
                if not st["ev"].wait(
                        timeout=(int(timeout) or 30000) / 1e3 + 5):
                    # fetch still in flight: surface a retryable timeout
                    # WITHOUT counting ourselves served — a premature
                    # served count could pop the stage under the fetch
                    raise TimeoutError(
                        f"pull({key}) round={rnd}: striped fetch did "
                        f"not resolve in time")
                with self._stripe_lock:
                    st["served"] += 1
                    if st["served"] >= st["nparts"]:
                        self._pull_stage.pop(stage_key, None)
                if st["err"] is not None:
                    raise st["err"]
                part = st["data"][off:off + plen_]
                _send_frame(conn, _RSP.pack(ST_OK, len(part)), [part])
            elif op == OP_PARAM_PUT:
                self.param_store().put(key, int(rnd),
                                       bytes(payload or b""))
                conn.sendall(_RSP.pack(ST_OK, 0))
            elif op == OP_PARAM_GET:
                data = self.param_store().get(
                    key, int(rnd), timeout_ms=int(timeout) or 30000)
                conn.sendall(_RSP.pack(ST_OK, len(data)))
                if data:
                    conn.sendall(data)
            elif op == OP_PARAM_SEQ:
                rv = struct.pack("!Q",
                                 int(self.param_store().latest(key)))
                conn.sendall(_RSP.pack(ST_OK, len(rv)) + rv)
            elif op == OP_ACT_PUSH:
                self.act_store().put(key, int(rnd),
                                     bytes(payload or b""))
                conn.sendall(_RSP.pack(ST_OK, 0))
            elif op == OP_ACT_PULL:
                data = self.act_store().take(
                    key, int(rnd), timeout_ms=int(timeout) or 30000)
                conn.sendall(_RSP.pack(ST_OK, len(data)))
                if data:
                    conn.sendall(data)
            elif op == OP_REPL_PUT:
                self._replica_store().put(key, int(rnd),
                                          bytes(payload or b""))
                conn.sendall(_RSP.pack(ST_OK, 0))
            elif op == OP_REPL_GET:
                data = self._replica_store().get(key, int(rnd))
                if data is None:
                    conn.sendall(_RSP.pack(ST_OK, 1) + b"\x00")
                else:
                    conn.sendall(_RSP.pack(ST_OK, 1 + len(data)) + b"\x01")
                    conn.sendall(data)
            elif op == OP_REPL_BASE:
                rv = struct.pack("!Q",
                                 int(self._replica_store().base(key)))
                conn.sendall(_RSP.pack(ST_OK, len(rv)) + rv)
            elif op == OP_STATS:
                import json as _json
                body = _json.dumps(self.stats_payload()).encode()
                conn.sendall(_RSP.pack(ST_OK, len(body)))
                conn.sendall(body)
            elif op == OP_TRACE:
                import json as _json
                body = _json.dumps(self.trace_payload()).encode()
                conn.sendall(_RSP.pack(ST_OK, len(body)))
                conn.sendall(body)
            elif op == OP_EMBED_INIT:
                import json as _json
                self.embed_store().init_table(
                    key, _json.loads(bytes(payload or b"{}")))
                conn.sendall(_RSP.pack(ST_OK, 0))
            elif op == OP_EMBED_PULL:
                ep, flags, vers, rowbuf = self.embed_store().pull(
                    key, payload)
                # vectored: status + epoch + flags + versions + the row
                # gather in ONE sendmsg — the zero-copy path the sparse
                # pull rides (rows are copied once under the table
                # lock, never joined again)
                _send_frame(conn,
                            _RSP.pack(ST_OK, len(ep) + len(flags)
                                      + len(vers) + len(rowbuf)),
                            [ep, flags, vers, rowbuf])
            elif op == OP_EMBED_PUSH:
                pay = payload   # consumed synchronously by apply()
                plen_e = len(pay)
                tok = int(rnd)
                self._note_push(self._apply_push_once(
                    key, rnd,
                    lambda: self.embed_store().apply(key, pay,
                                                     token=tok)),
                    key, rnd, plen_e)
                conn.sendall(_RSP.pack(ST_OK, 0))
            elif op == OP_EMBED_REPL:
                self.embed_store().repl_apply(key, int(rnd),
                                              bytes(payload or b""))
                conn.sendall(_RSP.pack(ST_OK, 0))
            elif op == OP_EMBED_FAILOVER:
                import json as _json
                req = _json.loads(bytes(payload or b"{}"))
                st = self.embed_store().failover(
                    key, req.get("dead") or (),
                    observe=bool(req.get("observe")))
                body = _json.dumps(st).encode()
                conn.sendall(_RSP.pack(ST_OK, len(body)))
                conn.sendall(body)
            elif op == OP_EMBED_SNAP:
                import json as _json
                req = _json.loads(bytes(payload or b"{}"))
                st = self.embed_store().save_shard(str(req["path"]))
                body = _json.dumps(st).encode()
                conn.sendall(_RSP.pack(ST_OK, len(body)))
                conn.sendall(body)
            elif op == OP_EMBED_RESTORE:
                import json as _json
                req = _json.loads(bytes(payload or b"{}"))
                st = self.embed_store().restore_shard(str(req["path"]))
                body = _json.dumps(st).encode()
                conn.sendall(_RSP.pack(ST_OK, len(body)))
                conn.sendall(body)
            elif op == OP_LAG_DECL:
                self._lag_declare(key, int(rnd))
                conn.sendall(_RSP.pack(ST_OK, 0))
            elif op == OP_PUSH_LAG:
                w, r = int(rnd) >> 48, int(rnd) & _LAG_ROUND_MASK
                arr = np.frombuffer(payload, dtype=dtype)
                meta = self._key_meta.get(key)
                if meta is not None and meta[1] != dtype:
                    arr = arr.astype(meta[1])
                # the packed rnd doubles as the dedup token: ident
                # becomes (key, worker<<16), seq the round — exactly
                # one fold per (worker, round) across reconnect retries
                self._apply_push_once(
                    key, rnd, lambda: self._lag_push(key, w, r, arr,
                                                     len(payload)))
                conn.sendall(_RSP.pack(ST_OK, 0))
            elif op == OP_PULL_LAG:
                w, r = int(rnd) >> 48, int(rnd) & _LAG_ROUND_MASK
                out = np.empty(int(nbytes) // np.dtype(dtype).itemsize,
                               dtype=dtype)
                flags = self._lag_pull(key, w, r, out,
                                       int(timeout) or 30000)
                _send_frame(conn,
                            _RSP.pack(ST_OK, 1 + out.nbytes)
                            + bytes([flags & 0xFF]),
                            [_as_bytes(out)])
            elif op == OP_PULL_C:
                from .compressed import compressed_pull
                buf = compressed_pull(self.compressed, self.backend, key,
                                      int(rnd), int(timeout) or 30000)
                if self._key_log:
                    from ..common.logging import get_logger
                    get_logger().info(
                        "PS_KEY_LOG op=%d key=%d bytes=%d rnd=%d",
                        op, key, len(buf), rnd)
                conn.sendall(_RSP.pack(ST_OK, len(buf)))
                conn.sendall(buf)
            else:
                conn.sendall(_RSP.pack(ST_ERR, 0))
        except TimeoutError as e:
            msg = str(e).encode()
            conn.sendall(_RSP.pack(ST_TIMEOUT, len(msg)) + msg)
        except Exception as e:
            from .engine import ServerClosed
            if isinstance(e, ServerClosed):
                # shutting down: tell the worker to reconnect (a
                # supervisor restart + snapshot restore is transparent)
                msg = str(e).encode()
                conn.sendall(_RSP.pack(ST_GONE, len(msg)) + msg)
            else:   # backend rejections (bad length, key, …)
                msg = f"{type(e).__name__}: {e}".encode()[:4096]
                conn.sendall(_RSP.pack(ST_ERR, len(msg)) + msg)

    def _note_push(self, applied: bool, key: int, rnd: int,
                   nbytes: int) -> None:
        """One data-plane push reached the store: record the arrival in
        the span ring (dedup duplicates — ``applied=False`` — are NOT
        arrivals; counting them would shear the count-derived round
        attribution). The worker id is the push dedup token's
        incarnation (``rnd >> 32``; 0 for tokenless/legacy frames).
        Skipped when the backend runs its own ring (it noted already)."""
        if applied and self._own_spans:
            self.spans.note_arrival(key, rnd >> 32, nbytes)

    def trace_payload(self) -> dict:
        """The OP_TRACE response body: the span ring + this server's
        wall clock (``now`` — the clock-alignment sample the client
        midpoints against its own send/recv stamps). Reads only
        already-published state, like ``stats_payload``."""
        return self.spans.payload(now=self._trace_now())

    # ------------------------------------------ bounded staleness ops
    #
    # A backend with its own lag surface (HostPSBackend) serves the
    # versioned rounds itself; a RAW engine (PSServer) gets a
    # transport-owned StaleStore — the FusedFront pattern, applied to
    # the K-lag contract so every deployment speaks it.

    def _lag_local(self):
        if self._stale is None:
            with self._stale_lock:
                if self._stale is None:
                    from .admission import StaleStore
                    self._stale = StaleStore(
                        getattr(self.backend, "num_workers", 1),
                        spans=self.spans)
        return self._stale

    def _lag_declare(self, key: int, max_lag: int) -> None:
        if hasattr(self.backend, "declare_lag"):
            self.backend.declare_lag(key, max_lag)
            return
        meta = self._key_meta.get(key)
        if meta is None:
            raise KeyError(f"declare_lag({key}) before init")
        nbytes, dtype = meta
        self._lag_local().declare(
            key, nbytes // np.dtype(dtype).itemsize, dtype, max_lag)

    def _lag_push(self, key: int, worker: int, rnd: int,
                  arr: np.ndarray, wire_bytes: int) -> None:
        if hasattr(self.backend, "push_lag"):
            self.backend.push_lag(key, worker, rnd, arr)
            return
        tgt = self._lag_local().push(key, worker, rnd, arr)
        if self._own_spans:
            self.spans.note_arrival(key, worker, wire_bytes, rnd=tgt)

    def _lag_pull(self, key: int, worker: int, rnd: int,
                  out: np.ndarray, timeout_ms: int) -> int:
        import time
        if hasattr(self.backend, "pull_lag"):
            return int(self.backend.pull_lag(key, worker, rnd, out,
                                             timeout_ms))
        t0 = time.time()
        flags = self._lag_local().pull(key, worker, rnd, out, timeout_ms)
        self._m_merge_wait.observe(time.time() - t0)
        if self._own_spans:
            self.spans.note_serve(key, rnd, t0, time.time() - t0)
        return int(flags)

    def _replica_store(self):
        if self._replica is None:
            with self._replica_lock:
                if self._replica is None:
                    from .plane.replica import ReplicaStore
                    self._replica = ReplicaStore()
        return self._replica

    def act_store(self):
        """This server's activation mailbox (pipeline plane) — also the
        LOCAL take endpoint for a colocated stage driver, so a received
        activation never makes a second hop."""
        if self._acts is None:
            with self._acts_lock:
                if self._acts is None:
                    from ..pipeline.exchange import ActStore
                    self._acts = ActStore()
        return self._acts

    def stats_payload(self) -> dict:
        """The OP_STATS response body: this process's registry snapshot
        plus this server's heartbeat (the shared ServerStats/v1 shape,
        obs/fleet.py). Every field is a read of already-published state
        — no round-blocking, no engine waits — so the scrape answers
        even while the data plane is wedged on a lost pull (the whole
        point of a liveness signal)."""
        from ..obs.fleet import server_stats_payload
        return server_stats_payload(
            time.monotonic() - self._t0_mono, len(self._key_meta),
            requests=self._n_requests,
            queue_depth_fn=(self.backend.queue_depth
                            if hasattr(self.backend, "queue_depth")
                            else None),
            start_ts=self._t0_wall)

    def embed_store(self):
        """This server's sharded embedding row store (OP_EMBED_*,
        server/embed.py) — lazy like the act/param mailboxes. REFUSED
        on a hierarchical-aggregation front (server/hier.py): an
        aggregator's local fold has no row store, and silently passing
        embed ops through would split one table's rows across the
        agg's own upstream sharding — serving rows from the WRONG
        shard's lazy-init values. Point EmbedClient at the plane
        shards directly (docs/embedding.md failure matrix)."""
        if self._embed is None:
            with self._embed_lock:
                if self._embed is None:
                    if getattr(self.backend, "is_local_agg", False):
                        raise RuntimeError(
                            "embed tables cannot ride a hierarchical "
                            "aggregator front (BPS_HIER_AGG): the agg "
                            "tier folds dense gradients and has no row "
                            "store — connect EmbedClient to the plane "
                            "shards (BPS_SERVER_ADDRS), not the agg")
                    from .embed import EmbedRowStore
                    # the dedup-seed hook lets a failover promotion
                    # install the replicated log's push tokens into
                    # THIS server's dedup table — a worker retrying an
                    # acked-at-the-dead-primary push lands here and is
                    # acknowledged without re-applying (exactly-once
                    # across failover, ISSUE 20)
                    self._embed = EmbedRowStore(
                        dedup_seed=self._seed_push_token)
        return self._embed

    def _seed_push_token(self, key: int, token: int) -> None:
        """Mark a push-dedup token as already applied for ``key`` —
        the failover-replay half of ``_apply_push_once``'s contract
        (tokens arrive via the replicated embed log, not the wire)."""
        tok = int(token)
        if not tok:
            return
        ident = (int(key), tok >> 32)
        seq = tok & 0xFFFFFFFF
        with self._push_lock:
            st = self._push_seen.get(ident)
            if st is None:
                st = self._push_seen[ident] = _DedupState()
            if not st.is_applied(seq):
                st.record(seq)
            st.ts = time.time()

    def param_store(self):
        """This server's param mailbox (sharded weight update,
        OP_PARAM_*) — lazy like the act store, so plain deployments
        never allocate it."""
        if self._params is None:
            with self._acts_lock:
                if self._params is None:
                    from ..sharded_update import ParamStore
                    self._params = ParamStore()
        return self._params

    def _pull_dense(self, key, rnd, nbytes, dtype, timeout) -> np.ndarray:
        """Round-blocked engine pull in WIRE dtype — the one transcode
        rule shared by OP_PULL and the striped fetch: a frame dtype
        narrower than the store downcasts on the way out."""
        import time
        t0 = time.time()
        elems = int(nbytes) // np.dtype(dtype).itemsize
        meta = self._key_meta.get(key)
        if meta is not None and meta[1] != dtype:
            store = np.empty(elems, dtype=meta[1])
            self._fb.pull(key, store, round=int(rnd),
                          timeout_ms=int(timeout) or 30000)
            out = store.astype(dtype)
        else:
            out = np.empty(elems, dtype=dtype)
            self._fb.pull(key, out, round=int(rnd),
                          timeout_ms=int(timeout) or 30000)
        # server-side merge wait: sum time + the lag of the slowest
        # worker's push — the transport server's bottleneck signal
        self._m_merge_wait.observe(time.time() - t0)
        if self._own_spans:
            self.spans.note_serve(key, int(rnd), t0, time.time() - t0)
        return out

    _STRIPE_TTL_SECS = 120.0

    def _sweep_stages(self, now: float) -> None:
        """Drop abandoned striping stages (caller holds _stripe_lock).
        A pull stage is only swept once its fetch resolved — sweeping a
        stage whose engine pull is in flight would strand late parts
        waiting on an event nobody will set."""
        if now < self._stripe_sweep_at:
            return
        self._stripe_sweep_at = now + 30.0
        cutoff = now - self._STRIPE_TTL_SECS
        for d in (self._push_stage, self._pull_stage):
            for k in [k for k, st in d.items()
                      if st["t"] < cutoff
                      and ("ev" not in st or st["ev"].is_set())]:
                del d[k]

    def _apply_push_once(self, key: int, rnd: int, apply_fn) -> bool:
        """Run ``apply_fn`` exactly once per dedup token; returns True
        when THIS call applied the payload (False = dedup hit — the
        span ring must not count a retried frame as a second arrival).
        Tokenless pushes (rnd=0: legacy frames, raw clients) apply
        unconditionally. A
        duplicate of an APPLIED seq is acknowledged without re-applying; a
        duplicate racing the original's in-flight apply (conn reset
        mid-sum + instant redial) WAITS for that apply's outcome — ack if
        it succeeded, apply itself if it failed. Applied seqs are exact
        membership (not a high-water mark), so two threads pushing the
        same key through one backend both count even when their frames
        land out of order. The applied mark is recorded only after the
        backend accepted the payload, so a dedup hit can never mask a
        push lost mid-apply (that stalls the round loudly instead)."""
        if not rnd:
            apply_fn()
            return True
        ident = (key, rnd >> 32)
        seq = rnd & 0xFFFFFFFF
        now = time.time()
        with self._push_lock:
            if now >= self._dedup_sweep_at:
                self._dedup_sweep_at = now + self._dedup_ttl / 4
                dead = [k for k, st in self._push_seen.items()
                        if now - st.ts > self._dedup_ttl and not st.claims]
                for k in dead:
                    del self._push_seen[k]
            st = self._push_seen.get(ident)
            if st is None:
                st = self._push_seen[ident] = _DedupState()
            while True:
                if st.is_applied(seq):
                    st.ts = now
                    return False                  # duplicate, already applied
                if seq not in st.claims:
                    st.claims.add(seq)            # we own the apply
                    break
                self._push_cv.wait(1.0)   # original in flight: await outcome
        try:
            apply_fn()
        except BaseException:
            with self._push_lock:
                # retract the claim so the waiting retry (or a later
                # resend) applies it instead
                st.claims.discard(seq)
                self._push_cv.notify_all()
            raise
        with self._push_lock:
            st.record(seq)
            st.ts = time.time()
            st.claims.discard(seq)
            self._push_cv.notify_all()
        return True

    def _serve_conn(self, conn: socket.socket) -> None:
        rholder = [bytearray()]  # reused across this connection's frames
        try:
            while True:
                op, key, rnd, nbytes, timeout, dtype, payload = \
                    _recv_req(conn, rholder)
                if op == OP_CLOSE:
                    conn.sendall(_RSP.pack(ST_OK, 0))
                    return
                self._handle(conn, op, key, rnd, nbytes, timeout, dtype,
                             payload)
        except (ConnectionError, OSError):
            pass
        finally:
            conn.close()

    def snapshot(self, path: str, timeout_ms: int = 250) -> int:
        """Best-effort dump of every known key's latest merged value to an
        .npz (the reference has no PS-state checkpoint — server death
        loses the async-mode weights; this closes that gap). Returns the
        number of keys saved. Keys whose pull fails or times out (e.g. a
        sync-mode key with no completed round yet — async pulls return
        immediately) are skipped with a warning; the short per-key
        timeout bounds the stall a sync-mode snapshot can cause.

        Embed tables ride the same file: live rows + versions + metas
        as ``e<key>|…`` entries next to the dense ``k<key>|<dtype>``
        ones (only when the embed store was ever touched — plain
        deployments pay nothing)."""
        embed = (self._embed.snapshot_state()
                 if self._embed is not None else None)
        return snapshot_store(self.backend, list(self._key_meta.items()),
                              path, timeout_ms, embed=embed)

    def restore(self, path: str) -> int:
        """Re-seed the store from a snapshot. NOTE: this server accepts
        connections from construction — to guarantee a reconnecting
        worker's INIT can't land first and pin its own values, restore
        the BACKEND before constructing the transport
        (``restore_snapshot`` + the ``key_meta`` ctor arg, as
        bpslaunch-tpu --server does). Embed ``e<key>|…`` entries (if
        present) repopulate the row store and bump each table's epoch
        past the saved one."""
        meta = restore_snapshot(self.backend, path)
        self._key_meta.update(meta)
        data = np.load(path)
        embed = {n: data[n] for n in data.files if n.startswith("e")}
        if embed:
            self.embed_store().restore_state(embed)
        return len(meta)

    def close(self) -> None:
        self._stop.set()
        if self._embed is not None:
            try:
                self._embed.close()
            except Exception:
                pass
        self._shm.close()
        try:
            self._sock.close()
        except OSError:
            pass
        if self._ipc_sock is not None:
            import os as _os
            try:
                self._ipc_sock.close()
            except OSError:
                pass
            try:
                _os.unlink(self.ipc_path)
            except OSError:
                pass


# ------------------------------------------------------- state snapshots

def snapshot_store(backend, key_meta, path: str,
                   timeout_ms: int = 250, embed=None) -> int:
    """Dump ``key_meta`` (iterable of (key, (nbytes, dtype))) from
    ``backend`` to ``path`` atomically. Entries are named
    ``k<key>|<dtype>`` with raw-byte payloads, so dtypes numpy can't
    round-trip through npz (bfloat16) survive. ``embed`` (optional) is
    an already-rendered ``EmbedRowStore.snapshot_state()`` dict whose
    ``e<key>|…`` entries ride the same npz."""
    import os as _os

    from ..common.logging import get_logger
    arrays = {}
    for key, (nbytes, dtype) in sorted(key_meta):
        buf = np.empty(nbytes // np.dtype(dtype).itemsize, dtype)
        try:
            # round 0 = latest published value
            backend.pull(key, buf, round=0, timeout_ms=timeout_ms)
        except Exception as e:
            get_logger().warning("snapshot: skipping key %d: %s", key, e)
            continue
        arrays[f"k{key}|{dtype}"] = buf.view(np.uint8)
    if embed:
        arrays.update(embed)
    tmp = f"{path}.tmp.npz"
    np.savez(tmp, **arrays)
    _os.replace(tmp, path)         # atomic: readers never see a torn file
    get_logger().info("snapshot: %d keys -> %s", len(arrays), path)
    return len(arrays)


def restore_snapshot(backend, path: str):
    """Re-seed ``backend`` from a snapshot; returns the key→(nbytes,
    dtype) meta restored. Run this BEFORE the transport server starts
    accepting, or a fast-reconnecting worker's INIT can allocate the key
    first and the restored value is silently dropped (server-side init
    is first-wins). Non-dense entries (embed ``e<key>|…``) are left to
    ``PSTransportServer.restore``."""
    from ..common.logging import get_logger
    data = np.load(path)
    meta = {}
    for name in data.files:
        if not name.startswith("k"):
            continue               # embed entries, handled by the caller
        keypart, dtype = name[1:].split("|", 1)
        key = int(keypart)
        arr = np.frombuffer(data[name].tobytes(), np.dtype(dtype))
        backend.init_key(key, arr.nbytes, dtype, init=arr)
        meta[key] = (arr.nbytes, dtype)
    get_logger().info("restore: %d keys <- %s", len(meta), path)
    return meta


# ------------------------------------------------------------------ client

class _Channel:
    """One pooled connection; ``sock`` is None until first use. ``shm``
    is the channel's worker-owned segment for the shared-memory data
    plane (created on demand, grown by replacement)."""

    __slots__ = ("sock", "shm")

    def __init__(self, sock: Optional[socket.socket]) -> None:
        self.sock = sock
        self.shm = None

    @staticmethod
    def _unlink(seg) -> None:
        try:
            seg.unlink()   # name gone; the server's attachment survives
            seg.close()
        except Exception:
            pass

    def ensure_shm(self, nbytes: int):
        if self.shm is None or self.shm.size < nbytes:
            if self.shm is not None:
                self._unlink(self.shm)
            self.shm = _PosixShm(create=True, size=max(nbytes, 1 << 20))
        return self.shm

    def drop_shm(self) -> None:
        if self.shm is not None:
            self._unlink(self.shm)
            self.shm = None


class RemotePSBackend:
    """Worker-side client; same interface as HostPSBackend, keys sharded
    over N transport servers with the same placement hash (reference:
    key→server placement global.cc:628-677).

    Fault tolerance (ours — ps-lite aborts on van failure): a dropped
    connection triggers reconnect-with-backoff for up to
    ``reconnect_secs`` (BPS_RECONNECT_SECS, default 30; 0 disables).
    Recorded ``init_key`` calls are REPLAYED on the fresh connection so a
    restarted server re-learns the key table (values come from its
    snapshot, see BPS_SERVER_SNAPSHOT — without one, async training
    restarts from the replayed init values). Clean recovery is an
    async-PS property: sync rounds reset with the server while the
    worker's round counters don't, so a sync-mode reconnect can stall
    on pulls (documented limitation). Retried pushes carry a
    ``worker_incarnation<<32 | per-key seq`` dedup token: a push whose
    ACK was lost is re-sent but applied exactly once by a surviving
    server, so a sync-mode connection blip cannot double-count this
    worker's gradient in the round. The incarnation id is fresh per
    RemotePSBackend instance, so a RESTARTED worker's pushes are never
    mistaken for its predecessor's. Only a server that itself restarted
    (losing the dedup table) can re-apply a retried push — and that
    path already resets rounds, which async mode absorbs as one
    duplicated delta and sync mode surfaces as the documented stall."""

    def __init__(self, addrs: Sequence[str], hash_fn: str = "djb2",
                 async_mode: bool = False,
                 reconnect_secs: Optional[float] = None,
                 conns_per_shard: Optional[int] = None,
                 nic=None, lazy_dial: bool = False):
        import os as _os
        import queue as _queue
        self._addrs = [a.rsplit(":", 1) for a in addrs]
        # optional emulated-NIC throttle (throttle.Nic) charged for this
        # worker endpoint's traffic across ALL its channels
        self._nic = nic
        self.hash_fn = hash_fn
        from ..common.naming import check_mixed_mode_enabled, placement_from_env
        check_mixed_mode_enabled(hash_fn)
        self._placement = placement_from_env()
        # hash_fn="ring": byte-weighted consistent-hash placement from
        # the server plane (balanced by construction under the
        # exchange's declaration-order contract) instead of the env
        # hash — see HostPSBackend for the full rationale
        self._ring = None
        if hash_fn == "ring" and len(addrs) > 1:
            from .plane.placement import DEFAULT_VNODES, PlacementService
            self._ring = PlacementService(
                len(addrs),
                vnodes=int(self._placement.get("vnodes") or 0)
                or DEFAULT_VNODES)
        self.async_mode = async_mode
        self._dead = False      # set by close(); aborts redial loops
        self.reconnect_secs = (
            float(_os.environ.get("BPS_RECONNECT_SECS", "30"))
            if reconnect_secs is None else reconnect_secs)
        # connection POOL per shard: the transport server handles one
        # request per connection at a time, so a round-blocked PULL would
        # stall every later request on its socket — extra channels let
        # the pipelined exchange push bucket k+1 while bucket k's pull
        # waits on the server's merge (the reference's free-running
        # push/pull loops, core_loops.cc:538-618)
        self._nconns = (int(_os.environ.get("BPS_PS_CONNS", "4"))
                        if conns_per_shard is None else conns_per_shard)
        self._nconns = max(1, self._nconns)
        # connection striping threshold: a logical push/pull at least
        # this large is split over the pool's connections in flight at
        # once (0 = off, the default). Striping targets multi-core
        # hosts where parallel streams buy parallel recv+apply; on a
        # single-core box it measured NEGATIVE (0.99 -> 0.66 GB/s push
        # at 10 Gbps — thread switching with no extra cycles to win),
        # so it is opt-in: BPS_STRIPE_MIN=4194304 is a sane setting for
        # real deployments (docs/performance.md "transport wire speed")
        self._stripe_min = int(_os.environ.get("BPS_STRIPE_MIN", "0"))
        self._stripe_exec = None
        self._stripe_exec_lock = threading.Lock()
        # placement-aware striping (ring mode): one large bucket's
        # stripes live as independent sub-keys on DISTINCT ring
        # successors (PlacementService.place_stripes), so a hot key's
        # traffic spreads across servers instead of saturating its
        # primary's NIC. key -> [(byte off, byte len, subkey)];
        # subkey -> shard index (consulted by _shard before any hash)
        self._stripe_plans: Dict[int, list] = {}
        self._stripe_shards: Dict[int, int] = {}
        # per-key send priority for the two-class wire scheduler
        # (sched.SendScheduler): the exchange assigns reverse-first-use
        # priorities at plan time via set_send_priority
        self._send_prio: Dict[int, int] = {}
        self._rounds: Dict[int, int] = {}
        # push dedup: fresh nonzero 32-bit incarnation id + per-key seq
        # (seq lives in the frame's ``round`` field, unused by pushes)
        self._wid = int.from_bytes(_os.urandom(4), "big") or 1
        self._push_seq: Dict[int, int] = {}
        self._push_seq_lock = threading.Lock()
        self._shard_bytes: Dict[int, int] = {}
        self._placed: set = set()
        # init_key replay log per shard index: key -> args
        self._inits: List[Dict[int, tuple]] = [dict() for _ in addrs]
        # bounded-staleness contract replay log (docs/admission.md):
        # key -> K per shard. A restarted server has an empty StaleStore
        # — without the re-declaration its first post-reconnect push
        # would be rejected and the worker's lag budget silently lost
        self._lag_decls: List[Dict[int, int]] = [dict() for _ in addrs]
        # embed-table declaration replay log (OP_EMBED_INIT is
        # idempotent first-wins, so replaying into a restarted server
        # re-declares the table; its ROWS come from lazy re-init +
        # whatever pushes land after — the same async-recovery
        # semantics as the dense store without a snapshot)
        self._embed_inits: List[Dict[int, bytes]] = [dict() for _ in addrs]
        # DEDICATED telemetry channel per shard (OP_STATS, obs/fleet):
        # scrapes must not draw from the data-plane pools — when every
        # pooled channel is parked on a round-blocked pull (the wedged
        # state the fleet plane exists to observe), a pool-queued
        # scrape would block behind exactly the stall it should report
        self._stats_chans: List[Optional[_Channel]] = [None] * len(addrs)
        self._stats_locks = [threading.Lock() for _ in addrs]
        self._pools: List[_queue.Queue] = []
        for i in range(len(addrs)):
            pool = _queue.Queue()
            if lazy_dial:
                # plane-managed shard clients (docs/elasticity.md): an
                # elastic REPLACEMENT joins a fleet that may already
                # have a dead shard — construction must succeed and the
                # first op's connection error drive the plane's
                # failover, not a constructor crash. Plain deployments
                # keep the eager dial (a typo'd addr fails at startup).
                pool.put(_Channel(None))
            else:
                pool.put(_Channel(self._dial(i)))  # eager: validate addr
            for _ in range(self._nconns - 1):
                pool.put(_Channel(None))        # dialed on first use
            self._pools.append(pool)
        # shared-memory data plane: colocated shards only (the reference
        # gates its shm path the same way — BYTEPS_ENABLE_IPC colocated
        # deployments)
        shm_on = _os.environ.get("BPS_ENABLE_SHM", "0") not in ("0", "",
                                                                "false")
        self._shm_shards = [
            shm_on and host in ("unix", "127.0.0.1", "localhost")
            for host, _ in self._addrs]

    def _dial(self, i: int) -> socket.socket:
        s = self._dial_raw(i)
        if self._nic is not None:
            from .throttle import ThrottledSocket
            s = ThrottledSocket(s, self._nic)
        return s

    def _dial_raw(self, i: int) -> socket.socket:
        host, port = self._addrs[i]
        if host == "unix":                 # explicit "unix:/path.sock"
            s = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
            _bump_bufs(s)
            s.connect(port)
            return s
        if _ipc_enabled() and host in ("127.0.0.1", "localhost"):
            # colocated server: auto-upgrade to its Unix-domain listener
            # (path derived from the TCP port; fall back to TCP when the
            # server predates the knob or runs elsewhere)
            import os as _os
            path = _ipc_path(int(port))
            if _os.path.exists(path):
                s = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
                _bump_bufs(s)
                try:
                    s.connect(path)
                    return s
                except OSError:
                    s.close()
        s = socket.create_connection((host, int(port)))
        s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        return s

    def _shard(self, key: int) -> int:
        s = self._stripe_shards.get(key)
        if s is not None:            # striping sub-key: pinned at init
            return s
        if self._ring is not None:
            try:
                return self._ring.shard_of(key)
            except KeyError:
                # pre-init op: ring-primary routing only — recording a
                # zero-weight assignment here would poison the byte-
                # weighted balance and diverge placement across workers
                # (see HostPSBackend._shard_index)
                return self._ring.ring.lookup(key)
        return place_key(key, len(self._pools), self.hash_fn,
                         **self._placement)

    def _reconnect(self, i: int, ch: "_Channel", deadline: float) -> None:
        """Redial ``ch`` on shard ``i`` with backoff until ``deadline``,
        then replay the shard's init_key log (a restarted server has an
        empty key table; its values come from the snapshot, which restore
        seeds BEFORE accepting — so replayed inits are no-ops there;
        several channels replaying is harmless for the same reason).
        Raises ConnectionError when the budget runs out."""
        import time as _time

        from ..common.logging import get_logger
        from ..obs.metrics import get_registry
        get_registry().counter("transport/reconnects").inc()
        delay = 0.1
        while True:
            if self._dead:
                raise ConnectionError(
                    f"PS backend closed while reconnecting to "
                    f"{':'.join(self._addrs[i])}")
            try:
                old_sock = ch.sock
                ch.sock = self._dial(i)
                if old_sock is not None:    # don't leak one fd per retry
                    try:
                        old_sock.close()
                    except OSError:
                        pass
                break
            except OSError as e:
                if _time.time() + delay > deadline:
                    raise ConnectionError(
                        f"PS server {':'.join(self._addrs[i])} unreachable "
                        f"for {self.reconnect_secs:.0f}s: {e}") from e
                _time.sleep(delay)
                delay = min(delay * 2, 2.0)
        get_logger().warning("reconnected to PS server %s; replaying %d "
                             "key inits", ":".join(self._addrs[i]),
                             len(self._inits[i]))
        for args in self._inits[i].values():
            self._send_init(ch.sock, *args)
        # replay the K-lag contract after the inits (declare_lag needs
        # the key's meta present server-side)
        for k, lag in self._lag_decls[i].items():
            self._roundtrip(ch.sock, OP_LAG_DECL, k, int(lag), 0, 0,
                            "uint8", None)
        # replay embed-table declarations (idempotent first-wins)
        for k, body in self._embed_inits[i].items():
            self._roundtrip(ch.sock, OP_EMBED_INIT, k, 0, 0, 0,
                            "uint8", memoryview(body))

    def _send_init(self, sock, key, nbytes, dtype, init, compression,
                   fused=False):
        if compression:
            from ..ops.compression.host import serialize_kwargs
            self._roundtrip(sock, OP_INIT_C, key, 0, nbytes, 0, dtype,
                            memoryview(serialize_kwargs(compression)))
        else:
            payload = None if init is None else _as_bytes(init)
            self._roundtrip(sock, OP_INIT, key, 1 if fused else 0,
                            nbytes, 0, dtype, payload)

    @staticmethod
    def _roundtrip(sock, op, key, rnd, nbytes, timeout_ms, dtype, payload,
                   recv_into=None):
        _send_req(sock, op, key, rnd, nbytes, timeout_ms, dtype, payload)
        status, rbytes = _RSP.unpack(_recv_exact(sock, _RSP.size))
        if (recv_into is not None and status == ST_OK
                and rbytes == len(recv_into)):
            # zero-copy dense pull: the payload lands straight in the
            # caller's preallocated buffer
            _recv_exact_into(sock, recv_into)
            return memoryview(b"")
        data = _recv_exact(sock, rbytes) if rbytes else memoryview(b"")
        if status == ST_TIMEOUT:
            raise _ServerTimeout(bytes(data).decode() or
                                 f"pull({key}) timed out")
        if status == ST_GONE:
            # server announced shutdown mid-request — treat like a dropped
            # connection so _rpc's reconnect path takes over
            raise ConnectionError(bytes(data).decode() or "server gone")
        if status != ST_OK:
            raise RuntimeError(f"PS server rejected key={key} op={op}: "
                               f"{bytes(data).decode()!r}")
        return data

    def _roundtrip_with_retry(self, i: int, ch: "_Channel", op, key, rnd,
                              nbytes, timeout_ms, dtype, payload,
                              recv_into=None):
        """One roundtrip on ``ch``, with the reconnect policy: redials
        draw on ONE shared budget because the retry itself can land on
        a still-dying server (GONE frames)."""
        import time as _time
        try:
            if ch.sock is None:          # lazily-dialed pool channel
                ch.sock = self._dial(i)
            return self._roundtrip(ch.sock, op, key, rnd, nbytes,
                                   timeout_ms, dtype, payload,
                                   recv_into=recv_into)
        except _ServerTimeout:
            # an APPLICATION reply on a healthy connection — and
            # TimeoutError subclasses OSError, so without this explicit
            # re-raise the reconnect path below would swallow every
            # server-side pull timeout into a redial-and-resend loop for
            # the whole reconnect budget. The OS's ETIMEDOUT (a real
            # link failure) deliberately still takes the reconnect path.
            raise
        except (ConnectionError, OSError):
            if self.reconnect_secs <= 0:
                raise
            from ..obs.metrics import get_registry
            deadline = _time.time() + self.reconnect_secs
            while True:
                try:
                    self._reconnect(i, ch, deadline)
                    # the request is re-sent whole on the fresh channel
                    # (push dedup keeps it exactly-once server-side)
                    get_registry().counter("transport/resends").inc()
                    return self._roundtrip(ch.sock, op, key, rnd, nbytes,
                                           timeout_ms, dtype, payload,
                                           recv_into=recv_into)
                except _ServerTimeout:
                    raise
                except (ConnectionError, OSError):
                    if _time.time() >= deadline:
                        raise
                    _time.sleep(0.2)

    # payload-bearing ops the wire scheduler gates (the bandwidth
    # class; OP_ACT_PUSH is the latency class — see server/sched.py).
    # OP_REPL_PUT is included: a replication forward-log upload is a
    # merged-round-sized payload — unscheduled it would saturate the
    # NIC outside the credit and nothing could overtake it
    _SCHED_GRAD_OPS = frozenset({OP_PUSH, OP_PUSH_C, OP_PUSH_RS,
                                 OP_PUSH_PART, OP_PUSH_F, OP_REPL_PUT,
                                 OP_PUSH_LAG, OP_EMBED_PUSH,
                                 OP_EMBED_REPL})

    def _rpc(self, op: int, key: int, rnd: int, nbytes: int,
             timeout_ms: int, dtype: str, payload: Optional[memoryview],
             pull_into: Optional[np.ndarray] = None) -> bytes:
        # two-class wire admission (BPS_SCHEDULING_CREDIT): payload
        # frames queue in (priority desc, key asc) order behind the
        # byte credit, so a small CLASS_ACT frame overtakes a queued
        # gradient burst. Credit is held across the frame's roundtrip
        # (send + ack) — the host-side analogue of the reference's
        # ack-released scheduling credit. Disabled (credit 0) this is
        # two dict lookups.
        ticket = scheduler = None
        if payload is not None:
            from . import sched as _sched
            scheduler = _sched.current()
            if scheduler is not None:
                plen = (sum(len(p) for p in payload)
                        if isinstance(payload, (tuple, list))
                        else len(payload))
                if op == OP_ACT_PUSH:
                    ticket = scheduler.acquire(_sched.CLASS_ACT, 0, key,
                                               plen)
                elif op == OP_PARAM_PUT:
                    # sharded-update param frames are the latency class
                    # too — they gate the next step's forward — with
                    # next-step first-use priority among themselves
                    # (set_send_priority at sharded-plan time)
                    ticket = scheduler.acquire(
                        _sched.CLASS_ACT, self._send_prio.get(key, 0),
                        key, plen)
                elif op in self._SCHED_GRAD_OPS:
                    ticket = scheduler.acquire(
                        _sched.CLASS_GRAD, self._send_prio.get(key, 0),
                        key, plen)
        try:
            return self._rpc_unscheduled(op, key, rnd, nbytes,
                                         timeout_ms, dtype, payload,
                                         pull_into=pull_into)
        finally:
            if ticket is not None:
                scheduler.release(ticket)

    def _rpc_unscheduled(self, op, key, rnd, nbytes, timeout_ms, dtype,
                         payload, pull_into=None) -> bytes:
        i = self._shard(key)
        ch = self._pools[i].get()        # blocks while all channels busy
        try:
            recv_into = None
            if (pull_into is not None
                    and pull_into.flags["C_CONTIGUOUS"]):
                try:                     # writable byte view of the
                    recv_into = memoryview(pull_into).cast("B")
                except (ValueError, TypeError):   # bfloat16 etc.
                    recv_into = memoryview(pull_into.view(np.uint8))
            data = self._roundtrip_with_retry(i, ch, op, key, rnd, nbytes,
                                              timeout_ms, dtype, payload,
                                              recv_into=recv_into)
            if pull_into is not None:
                if len(data):            # non-zero-copy fallback path
                    np.copyto(pull_into,
                              np.frombuffer(data, dtype=pull_into.dtype)
                              .reshape(pull_into.shape))
                return b""          # dense pulls land in pull_into; don't
                                    # re-copy megabytes for a discarded value
            return bytes(data)
        finally:
            self._pools[i].put(ch)   # even with a dead sock: keep the pool
                                     # size invariant; next user redials

    def init_key(self, key: int, nbytes: int, dtype: str = "float32",
                 init: Optional[np.ndarray] = None,
                 compression: Optional[Dict[str, str]] = None,
                 fused: bool = False) -> None:
        if self._ring is not None:
            self._ring.place(key, nbytes)    # byte-weighted, idempotent
        if compression:
            from ..ops.compression.host import serialize_kwargs
            self._rpc(OP_INIT_C, key, 0, nbytes, 0, dtype,
                      memoryview(serialize_kwargs(compression)))
        else:
            payload = None if init is None else _as_bytes(init)
            # OP_INIT rnd bit 0 = fused-managed declaration (the
            # compression plane's plan-time eligibility): the server
            # hands the key's rounds to its homogeneous fused store
            self._rpc(OP_INIT, key, 1 if fused else 0, nbytes, 0, dtype,
                      payload)
        # record for replay after a reconnect (restarted server has an
        # empty key table) — only once ACCEPTED, or a rejected conflicting
        # re-declaration would poison the replay log; keep a copy of init
        # (the caller may mutate it). The fused flag replays too — a
        # restarted server must re-manage the key, not silently fall
        # back to dense decodes.
        i = self._shard(key)
        self._inits[i][key] = (key, nbytes, dtype,
                               None if init is None else np.array(init),
                               dict(compression) if compression else None,
                               bool(fused))
        # count only after the server accepted, once per key (re-inits are
        # no-ops server-side — don't skew the load stats)
        if key not in self._placed:
            self._placed.add(key)
            from ..common.naming import log_key_placement
            log_key_placement(key, nbytes, i, self._shard_bytes,
                              self.hash_fn)
        self._plan_stripes(key, nbytes, dtype, init, compression)

    # striping sub-keys ride bits 48+ of the u64 wire key — disjoint
    # from gradient keys (decl<<16|bucket) and the activation channel
    # space (bit 40)
    @staticmethod
    def _stripe_subkey(key: int, part: int) -> int:
        return key | ((part + 1) << 48)

    def _plan_stripes(self, key: int, nbytes: int, dtype: str,
                      init, compression) -> None:
        """Placement-aware striping (ring mode): init each stripe of a
        large key as its own sub-key on a DISTINCT ring successor
        (``PlacementService.place_stripes``), so later push/pull of the
        key fans its bytes over several servers' NICs instead of one
        shard's connection pool. Dense ops of the key (round queries,
        fused/compressed frames — whose payloads are not
        range-separable) keep routing to the primary, so the plan only
        engages for plain dense keys."""
        if (self._ring is None or compression or key in self._stripe_plans
                or key >= (1 << 40)):    # never re-stripe sub/act keys
            return
        # the fused compression plane is level-per-ROUND: level-0 rounds
        # take the plain push/pull path, level>0 rounds push_fused to
        # the key's primary — striping only the dense rounds would
        # split one key's round counters across two stores and wedge
        # the next pull. A compress-managed deployment keeps
        # single-shard routing (codec payloads are not range-separable).
        import os as _os

        from ..common.global_state import GlobalState
        comp = (GlobalState.get().config.compress
                if GlobalState.initialized()
                else (_os.environ.get("BPS_COMPRESS", "none")
                      or "none").lower())
        if comp not in ("", "none"):
            return
        ranges = self._stripe_ranges(int(nbytes))
        if not ranges:
            return
        shards = self._ring.place_stripes(key, len(ranges))
        item = np.dtype(dtype).itemsize
        flat = (None if init is None
                else np.ascontiguousarray(init).reshape(-1))
        plan = []
        for j, (off, ln) in enumerate(ranges):
            skey = self._stripe_subkey(key, j)
            self._stripe_shards[skey] = shards[j]
            part_init = (None if flat is None
                         else flat[off // item:(off + ln) // item])
            payload = None if part_init is None else _as_bytes(part_init)
            self._rpc(OP_INIT, skey, 0, ln, 0, dtype, payload)
            self._inits[shards[j]][skey] = (
                skey, ln, dtype,
                None if part_init is None else np.array(part_init), None)
            plan.append((off, ln, skey))
        self._stripe_plans[key] = plan

    def set_send_priority(self, key: int, prio: int) -> None:
        """Send-scheduler priority for ``key``'s frames (higher = sent
        earlier under BPS_SCHEDULING_CREDIT). The exchange assigns
        reverse-first-use bucket priorities here at plan time; stripes
        of the key inherit it."""
        self._send_prio[key] = int(prio)
        for _, _, skey in self._stripe_plans.get(key, ()):
            self._send_prio[skey] = int(prio)

    @property
    def incarnation(self) -> int:
        """This client's push-dedup incarnation id — the worker id the
        server's span ring records per arrival, and therefore the id a
        watchtower incident blames. Surfaced so a driver (the ps_watch
        bench) can map a blamed id back to a fleet role."""
        return self._wid

    def _push_token(self, key: int) -> int:
        with self._push_seq_lock:
            seq = self._push_seq.get(key, 0) + 1
            if seq > 0xFFFFFFFF:
                # seq field exhausted: roll to a fresh incarnation (the
                # server tracks (incarnation, seq) pairs, so this resets
                # dedup cleanly instead of wrapping into "already seen"
                # territory where every push would be dropped as a retry)
                import os as _os
                self._wid = int.from_bytes(_os.urandom(4), "big") or 1
                self._push_seq.clear()
                seq = 1
            self._push_seq[key] = seq
        return (self._wid << 32) | seq

    def _shm_rpc(self, op: int, key: int, rnd: int,
                 arr: Optional[np.ndarray] = None,
                 out: Optional[np.ndarray] = None,
                 timeout_ms: int = 30000) -> None:
        """Data-plane op through the channel's shared segment: only the
        (name, length) addressing crosses the socket. Reconnect uses
        the same single budget as ``_rpc``; the segment survives
        redials (it is addressed by name per frame)."""
        i = self._shard(key)
        ch = self._pools[i].get()
        try:
            nbytes = arr.nbytes if arr is not None else out.nbytes
            try:
                seg = ch.ensure_shm(nbytes)
                if arr is not None:
                    seg.buf[:nbytes] = _as_bytes(arr)
            except OSError as e:
                # client-side shm_open/ftruncate failure (small or full
                # /dev/shm): same degradation as a server-side attach
                # rejection, not a hard op failure
                raise RuntimeError(f"client-side shm unavailable: {e}") from e
            dtype = str(arr.dtype if arr is not None else out.dtype)
            self._roundtrip_with_retry(i, ch, op, key, rnd, nbytes,
                                       timeout_ms, dtype,
                                       memoryview(seg.name.encode()))
            if out is not None:
                flat = np.frombuffer(seg.buf[:nbytes], dtype=out.dtype)
                np.copyto(out, flat.reshape(out.shape))
        finally:
            self._pools[i].put(ch)

    def _shm_disable(self, i: int, err: Exception) -> None:
        """No shared /dev/shm with the server (SSH-tunneled loopback,
        separate containers): degrade this shard to the socket path
        like the UDS auto-upgrade does, instead of hard-failing every
        op on a mis-set env var."""
        from ..common.logging import get_logger
        self._shm_shards[i] = False
        get_logger().warning(
            "BPS_ENABLE_SHM: server %s cannot attach this worker's shm "
            "segment (%s) — no shared /dev/shm? falling back to the "
            "socket data plane for this shard",
            ":".join(self._addrs[i]), err)

    def _stripe_ranges(self, nbytes: int):
        """[(offset, length)] for a striped op, or None when striping is
        off / not worth it. Parts are element-aligned 16-byte multiples
        so a part boundary can never split a wire element."""
        if self._stripe_min <= 0 or self._nconns < 2:
            return None
        if nbytes < max(self._stripe_min, 2 * (256 << 10)):
            return None
        if nbytes >= (1 << 32):
            return None     # _PART offsets are u32; huge ops go dense
        nparts = min(self._nconns, (nbytes + self._stripe_min - 1)
                     // self._stripe_min)
        if nparts < 2:
            return None
        step = ((nbytes + nparts - 1) // nparts + 15) & ~15
        return [(off, min(step, nbytes - off))
                for off in range(0, nbytes, step)]

    def _stripe_pool_get(self):
        with self._stripe_exec_lock:     # two racing creators would
            if self._stripe_exec is None:  # leak the loser's threads
                from concurrent.futures import ThreadPoolExecutor
                self._stripe_exec = ThreadPoolExecutor(
                    max_workers=self._nconns,
                    thread_name_prefix="bps-stripe")
            return self._stripe_exec

    def _stripe_run(self, fn, items) -> None:
        """Run one striped op's parts concurrently and wait for ALL of
        them before surfacing the first error — an early raise would
        let a retry attempt race its own stragglers on the server's
        shared (key, round) stage."""
        futs = [self._stripe_pool_get().submit(fn, it) for it in items]
        first = None
        for f in futs:
            try:
                f.result()
            except Exception as e:  # noqa: BLE001 — re-raised below
                if first is None:
                    first = e
        if first is not None:
            raise first

    def push(self, key: int, data: np.ndarray) -> None:
        plan = self._stripe_plans.get(key)
        if plan is not None:
            # placement-aware stripes: each part is an ordinary dense
            # push of its own sub-key on its own shard — full framing,
            # dedup, and reconnect per part, flying concurrently
            view = _as_bytes(data)
            dtype = str(data.dtype)

            def push_part(args):
                off, ln, skey = args
                self._rpc(OP_PUSH, skey, self._push_token(skey), 0, 0,
                          dtype, view[off:off + ln])

            self._stripe_run(push_part, plan)
            return
        tok = self._push_token(key)
        i = self._shard(key)
        if self._shm_shards[i]:
            try:
                self._shm_rpc(OP_PUSH_SHM, key, tok, arr=data)
                return
            except RuntimeError as e:     # server rejected: can't attach
                self._shm_disable(i, e)   # same token: exactly-once holds
        view = _as_bytes(data)
        ranges = self._stripe_ranges(len(view))
        if ranges is None:
            self._rpc(OP_PUSH, key, tok, 0, 0, str(data.dtype), view)
            return
        # striped push: the parts fly on separate pooled connections
        # concurrently; the server reassembles per (key, token) and
        # applies exactly once (dedup rides the shared token)
        dtype = str(data.dtype)
        nparts = len(ranges)

        def send_part(args):
            pi, (off, ln) = args
            self._rpc(OP_PUSH_PART, key, tok, len(view), 0, dtype,
                      (_PART.pack(off, ln, pi, nparts, 0),
                       view[off:off + ln]))

        self._stripe_run(send_part, list(enumerate(ranges)))

    # Round-blocked pulls wait on the server in SHORT slices and the
    # client loops to its own deadline: a severed connection then costs
    # at most one slice instead of silently re-arming the full wait on
    # every reconnect — without this, steady connection churn could
    # extend a "30 s" pull indefinitely (observed as livelock under
    # fault injection, tests/test_fault_injection.py).
    _PULL_SLICE_MS = 2000

    def _sliced_pull(self, attempt, timeout_ms: int, descr: str):
        """Run ``attempt(slice_ms)`` until it succeeds or ONE global
        deadline expires; server-side waits are per-slice."""
        import time as _time
        deadline = _time.time() + timeout_ms / 1e3
        while True:
            left_ms = max(1, int((deadline - _time.time()) * 1e3))
            try:
                return attempt(min(self._PULL_SLICE_MS, left_ms))
            except TimeoutError:
                if _time.time() >= deadline:
                    raise TimeoutError(
                        f"{descr} timed out after {timeout_ms}ms "
                        f"(sliced waits)") from None

    def pull(self, key: int, out: np.ndarray, round: int = 0,
             timeout_ms: Optional[int] = None) -> None:
        if timeout_ms is None:
            # the default is a liveness diagnostic, not a correctness
            # bound — BPS_PULL_TIMEOUT_MS lets contended CI boxes (where
            # a peer's first round can sit behind interpreter startup
            # for tens of seconds) widen it without touching prod
            timeout_ms = int(os.environ.get(
                "BPS_PULL_TIMEOUT_MS", "30000") or 30000)
        plan = self._stripe_plans.get(key)
        if plan is not None and not out.flags["C_CONTIGUOUS"]:
            # a striped key's data lives ONLY in the sub-keys — falling
            # through to the dense base key (which never sees a push)
            # would round-block forever. Stage through a contiguous
            # buffer instead; the extra copy is the price of a strided
            # caller, not a wrong answer.
            staged = np.empty(out.shape, out.dtype)
            self.pull(key, staged, round=round, timeout_ms=timeout_ms)
            np.copyto(out, staged)
            return
        if plan is not None:
            # placement-aware stripes: one dense pull per sub-key on
            # its own shard, each landing straight in out's byte range
            # (zero-copy scatter). Every worker pushes every stripe
            # every round, so the sub-keys' server rounds advance in
            # lockstep with the logical key's round
            flat = out.view(np.uint8).reshape(-1)
            dtype = str(out.dtype)

            def pull_part(args):
                def one(slice_ms):
                    off, ln, skey = args
                    self._rpc(OP_PULL, skey, round, ln, slice_ms, dtype,
                              None, pull_into=flat[off:off + ln])
                self._sliced_pull(one, timeout_ms,
                                  f"pull({key}) stripe round={round}")

            self._stripe_run(pull_part, plan)
            return

        def attempt(slice_ms: int) -> None:
            i = self._shard(key)
            if self._shm_shards[i]:
                try:
                    self._shm_rpc(OP_PULL_SHM, key, round, out=out,
                                  timeout_ms=slice_ms)
                    return
                except RuntimeError as e:   # server cannot attach our shm
                    self._shm_disable(i, e)
            ranges = (self._stripe_ranges(out.nbytes)
                      if out.flags["C_CONTIGUOUS"] else None)
            if ranges is None:
                self._rpc(OP_PULL, key, round, out.nbytes, slice_ms,
                          str(out.dtype), None, pull_into=out)
                return
            # striped pull: each part round-blocks on the SAME (key,
            # round, nonce) server stage (one engine pull feeds all of
            # THIS op's parts) and its slice lands straight in `out`
            # (zero-copy scatter). The nonce is fresh per attempt so a
            # retry can never race its own (or a concurrent puller's)
            # stragglers on a shared stage — the abandoned stage is
            # TTL-swept server-side
            flat = out.view(np.uint8).reshape(-1)
            nparts = len(ranges)
            dtype = str(out.dtype)
            import os as _os
            nonce = int.from_bytes(_os.urandom(8), "big")

            def pull_part(args):
                pi, (off, ln) = args
                self._rpc(OP_PULL_PART, key, round, out.nbytes, slice_ms,
                          dtype, (_PART.pack(off, ln, pi, nparts, nonce),),
                          pull_into=flat[off:off + ln])

            self._stripe_run(pull_part, list(enumerate(ranges)))

        self._sliced_pull(attempt, timeout_ms,
                          f"pull({key}) round={round}")

    # Fleet telemetry client (byteps_tpu.obs.fleet): scrape EVERY
    # shard's registry snapshot + heartbeat over OP_STATS — placement-
    # independent (the scrape is about the servers, not any key),
    # never credit-gated (no payload = nothing for the send scheduler
    # to gate), and on a dedicated per-shard channel so a wedged data
    # plane cannot starve telemetry.

    def _stats_rpc(self, i: int, op: int,
                   timeout_ms: int) -> Tuple[dict, float, float]:
        """One telemetry roundtrip (OP_STATS/OP_TRACE) on shard ``i``'s
        dedicated channel; returns (payload, t_send, t_recv) — the
        send/recv wall stamps bracket the roundtrip for the NTP-style
        clock-offset midpoint (obs.spans.ClockEstimator)."""
        import json as _json

        # client-side SOCKET timeout, not just the frame field: a
        # black-holed host (power loss, partition without an RST) —
        # exactly the silent death the fleet plane detects — would
        # otherwise block this recv forever and starve every shard's
        # scrape behind it. socket.timeout is an OSError: it takes the
        # same one-redial-then-fail path as a severed connection.
        sock_to = timeout_ms / 1e3 + 1.0
        with self._stats_locks[i]:
            ch = self._stats_chans[i]
            if ch is None:
                ch = self._stats_chans[i] = _Channel(None)
            try:
                if ch.sock is None:
                    ch.sock = self._dial(i)
                ch.sock.settimeout(sock_to)
                t_send = time.time()
                data = self._roundtrip(ch.sock, op, 0, 0, 0,
                                       timeout_ms, "uint8", None)
                t_recv = time.time()
            except (ConnectionError, OSError):
                # ONE redial, then fail loudly: a scrape is cheap and
                # periodic — burning the full reconnect budget here
                # would hold the scrape thread through exactly the
                # outage it should be reporting as staleness
                old, ch.sock = ch.sock, None
                if old is not None:
                    try:
                        old.close()
                    except OSError:
                        pass
                ch.sock = self._dial(i)
                ch.sock.settimeout(sock_to)
                t_send = time.time()
                data = self._roundtrip(ch.sock, op, 0, 0, 0,
                                       timeout_ms, "uint8", None)
                t_recv = time.time()
            return _json.loads(bytes(data).decode()), t_send, t_recv

    def stats_shard(self, i: int, timeout_ms: int = 5000) -> dict:
        """One shard's OP_STATS scrape; raises on an unreachable shard
        (the aggregate ``stats()`` folds that into an error entry —
        the scraper's staleness machinery owns the retry cadence)."""
        return self._stats_rpc(i, OP_STATS, timeout_ms)[0]

    def stats(self, timeout_ms: int = 5000) -> Dict[str, dict]:
        """{shard label: OP_STATS payload} for EVERY shard. Unreachable
        shards become ``{"error": …}`` entries instead of raising — the
        fleet scraper turns those into stale scrape-age + ``up=0``,
        never an exception on its control thread."""
        out: Dict[str, dict] = {}
        for i in range(len(self._addrs)):
            try:
                out[f"s{i}"] = self.stats_shard(i, timeout_ms)
            except Exception as e:   # noqa: BLE001 — per-shard isolation
                out[f"s{i}"] = {"error": f"{type(e).__name__}: {e}"}
        return out

    def trace_shard(self, i: int,
                    timeout_ms: int = 5000) -> Tuple[dict, float, float]:
        """One shard's OP_TRACE scrape on the dedicated stats channel:
        (ServerSpans payload, t_send, t_recv). The wall stamps bracket
        the roundtrip — the clock-offset probe's raw material."""
        return self._stats_rpc(i, OP_TRACE, timeout_ms)

    def trace(self, timeout_ms: int = 5000) -> Dict[str, dict]:
        """{shard label: {"payload", "t_send", "t_recv"}} for every
        shard (``{"error": …}`` for unreachable ones) — the causal
        span + clock-alignment scrape the fleet scraper drives."""
        out: Dict[str, dict] = {}
        for i in range(len(self._addrs)):
            try:
                p, t0, t1 = self.trace_shard(i, timeout_ms)
                out[f"s{i}"] = {"payload": p, "t_send": t0, "t_recv": t1}
            except Exception as e:   # noqa: BLE001 — per-shard isolation
                out[f"s{i}"] = {"error": f"{type(e).__name__}: {e}"}
        return out

    def round(self, key: int) -> int:
        """The server's latest completed round for ``key`` (see
        HostPSBackend.round — the elastic-rejoin resync point). A
        striped key reports the slowest stripe's round — the only
        round every stripe is guaranteed to have completed."""
        plan = self._stripe_plans.get(key)
        if plan is not None:
            return min(
                struct.unpack("!Q", self._rpc(OP_ROUND, skey, 0, 0, 0,
                                              "uint8", None))[0]
                for _, _, skey in plan)
        data = self._rpc(OP_ROUND, key, 0, 0, 0, "uint8", None)
        return struct.unpack("!Q", data)[0]

    # Bounded-staleness client (server/admission.py StaleStore,
    # docs/admission.md): the K-lag contract is declared once per key,
    # pushes/pulls carry (worker, round) packed in the frame's round
    # field, and a pull's reply leads with one verdict byte
    # (LAG_COMPLETE / LAG_STALE / LAG_BARRIER) before the dense sum.

    def declare_lag(self, key: int, max_lag: int) -> None:
        """Declare ``key``'s staleness bound K; recorded for replay so
        a restarted server relearns the contract on reconnect."""
        self._rpc(OP_LAG_DECL, key, int(max_lag), 0, 0, "uint8", None)
        self._lag_decls[self._shard(key)][key] = int(max_lag)

    def push_lag(self, key: int, worker: int, rnd: int,
                 data: np.ndarray) -> None:
        """Versioned push into ``key``'s round ``rnd``. The packed
        round field doubles as the server's dedup token — ident
        (key, worker<<16), seq rnd — so a reconnect retry of the same
        (worker, round) folds exactly once."""
        packed = (int(worker) << 48) | (int(rnd) & _LAG_ROUND_MASK)
        self._rpc(OP_PUSH_LAG, key, packed, 0, 0, str(data.dtype),
                  _as_bytes(data))

    def pull_lag(self, key: int, worker: int, rnd: int, out: np.ndarray,
                 timeout_ms: int = 30000) -> int:
        """Pull round ``rnd``'s published sum; returns the verdict
        flags. Barrier waits are sliced like dense pulls so connection
        churn cannot silently re-arm the full server-side wait."""
        packed = (int(worker) << 48) | (int(rnd) & _LAG_ROUND_MASK)

        def attempt(slice_ms: int) -> int:
            data = self._rpc(OP_PULL_LAG, key, packed, out.nbytes,
                             slice_ms, str(out.dtype), None)
            np.copyto(out, np.frombuffer(data[1:], dtype=out.dtype)
                      .reshape(out.shape))
            return data[0]

        return self._sliced_pull(attempt, timeout_ms,
                                 f"pull_lag({key}) round={rnd}")

    # Replica-log client (server plane primary-backup replication,
    # docs/server-plane.md): the plane backend wraps SINGLE-address
    # RemotePSBackend clients as shard handles, so these ops always
    # target this client's one server — the shard the plane chose as
    # the key's backup.

    def repl_put(self, key: int, round: int, payload) -> None:
        """Forward-log a completed round's merged bytes (idempotent
        last-wins: every worker logs the identical published merge)."""
        self._rpc(OP_REPL_PUT, key, int(round), 0, 0, "uint8",
                  memoryview(bytes(payload)))

    def repl_get(self, key: int, round: int) -> Optional[bytes]:
        """The logged bytes for ``round``, or None when never logged /
        aged out of the retention window."""
        data = self._rpc(OP_REPL_GET, key, int(round), 0, 0, "uint8",
                         None)
        if not data or data[:1] == b"\x00":
            return None
        return data[1:]

    def repl_base(self, key: int) -> int:
        """Highest logged round (0 = nothing logged) — the round base a
        promoted shard re-counts from after failover."""
        data = self._rpc(OP_REPL_BASE, key, 0, 0, 0, "uint8", None)
        return struct.unpack("!Q", data)[0]

    def push_bytes(self, key: int, payload) -> None:
        """Compressed push: ship the codec payload as-is; the server
        decompresses and dense-sums (wire bytes stay compressed — the
        bandwidth win the reference's inter-node compression is for)."""
        self._rpc(OP_PUSH_C, key, self._push_token(key), 0, 0, "uint8",
                  memoryview(payload))

    def push_fused(self, key: int, payload) -> None:
        """Fused-plane push (byteps_tpu.compress): self-describing codec
        payload, decoded on arrival by the server; dedup-tokenized like
        any push so a retried frame is applied exactly once."""
        self._rpc(OP_PUSH_F, key, self._push_token(key), 0, 0, "uint8",
                  memoryview(payload))

    def pull_fused(self, key: int, nbytes: int, dtype: str, codec: int,
                   round: int = 0, timeout_ms: int = 30000,
                   div: Optional[int] = None) -> bytes:
        """Fused-plane pull: the merged round encoded server-side at
        ``codec`` (the level this worker's decision trace pinned for
        the round) — wire bytes stay compressed in BOTH directions.
        The frame's payload carries (codec:u8 | topk div:u16le) so the
        server's re-encode honors this worker's keep fraction."""
        from ..compress.wire import TOPK_DIV
        payload = bytes((int(codec),)) + struct.pack(
            "<H", int(div) if div else TOPK_DIV)
        return self._sliced_pull(
            lambda slice_ms: self._rpc(
                OP_PULL_F, key, round, int(nbytes), slice_ms, dtype,
                payload),
            timeout_ms, f"pull_fused({key}) round={round}")

    # Activation-plane client (byteps_tpu.pipeline): point-to-point
    # stage→stage frames into the PEER's mailbox. CLASS_ACT in the send
    # scheduler — the latency class that overtakes gradient bursts.

    def act_push(self, key: int, seq: int, payload) -> None:
        """Deliver one boundary frame (activations or activation-grads)
        into the receiving stage's mailbox; last-wins per (key, seq) so
        the transport's resend path is idempotent."""
        self._rpc(OP_ACT_PUSH, key, int(seq), 0, 0, "uint8",
                  _as_bytes(np.asarray(payload).view(np.uint8)))

    def act_pull(self, key: int, seq: int,
                 timeout_ms: int = 30000) -> bytes:
        """Remote take: block until the (key, seq) frame arrives in the
        peer's mailbox, then fetch it — the pull-model form (the local
        take via ``PSTransportServer.act_store`` is the fast path)."""
        return self._sliced_pull(
            lambda slice_ms: self._rpc(OP_ACT_PULL, key, int(seq), 0,
                                       slice_ms, "uint8", None),
            timeout_ms, f"act_pull({key:#x}) seq={seq}")

    # Sharded-update param plane (byteps_tpu.sharded_update): the group
    # owner's post-apply param bytes into the server's param mailbox;
    # non-owners block-fetch them instead of pulling gradients.

    def param_put(self, key: int, seq: int, payload) -> None:
        """Publish one param frame; idempotent last-wins per (key, seq)
        so the transport's resend path re-stores identical bytes."""
        self._rpc(OP_PARAM_PUT, key, int(seq), 0, 0, "uint8",
                  memoryview(bytes(payload)))

    def param_get(self, key: int, seq: int,
                  timeout_ms: int = 30000) -> bytes:
        """Blocking NON-destructive fetch of the (key, seq) param frame
        (dp-1 replicas read each frame). A timeout here is the
        owner-death signal the sharded tail turns into its loud per-key
        diagnostic."""
        return self._sliced_pull(
            lambda slice_ms: self._rpc(OP_PARAM_GET, key, int(seq), 0,
                                       slice_ms, "uint8", None),
            timeout_ms, f"param_get({key:#x}) seq={seq}")

    def param_latest(self, key: int) -> int:
        """Newest retained seq in the server's param mailbox for
        ``key`` (0 = empty) — the elastic-rejoin seq seed
        (OP_PARAM_SEQ; docs/elasticity.md)."""
        data = self._rpc(OP_PARAM_SEQ, key, 0, 0, 0, "uint8", None)
        return struct.unpack("!Q", data)[0]

    def push_rowsparse(self, key: int, idx, rows, dense_nbytes: int,
                      dtype=None) -> None:
        """Row-sparse push: only the touched rows cross the wire. dtype
        defaults to the rows array's own dtype (mis-declaring it would
        reinterpret the bytes server-side)."""
        from .rowsparse import pack_rows
        if dtype is None:
            dtype = str(np.asarray(rows).dtype)
        self._rpc(OP_PUSH_RS, key, self._push_token(key), dense_nbytes, 0,
                  dtype, memoryview(pack_rows(idx, rows)))

    # Sharded-embedding client (server/embed.py EmbedClient rides
    # these; docs/embedding.md): one key per TABLE, rows addressed by
    # id inside the payload — EmbedClient wraps single-address
    # backends per shard (the plane-backend idiom), so these ops
    # always target this client's one server.

    def embed_init(self, key: int, meta: dict) -> None:
        """Declare a table (idempotent first-wins server-side;
        conflicting shape/dtype/seed refused loudly). Recorded for
        replay so a restarted server relearns the declaration."""
        import json as _json
        body = _json.dumps(meta).encode()
        self._rpc(OP_EMBED_INIT, key, 0, 0, 0, "uint8",
                  memoryview(body))
        self._embed_inits[self._shard(key)][key] = body

    def embed_pull(self, key: int, payload,
                   timeout_ms: int = 30000) -> bytes:
        """Conditional sparse row pull: ship ids + cached versions,
        receive flags + versions + only the rows whose version moved.
        Never round-blocked — embedding rows live under the async
        weight-delta contract, not the sync round gate."""
        return self._rpc(OP_EMBED_PULL, key, 0, 0, timeout_ms,
                         "uint8", memoryview(payload))

    def embed_push(self, key: int, payload,
                   token: Optional[int] = None) -> None:
        """Row-sparse delta push (ids + folded rows); dedup-tokenized
        like any push so a reconnect retry applies exactly once, and
        CLASS_GRAD in the wire scheduler like any gradient burst.
        ``token`` lets the caller pin the dedup token across a
        FAILOVER retry (EmbedClient allocates one per slice batch and
        resends it verbatim to the promoted replica — the replicated
        log already carries it iff the dead primary applied)."""
        self._rpc(OP_EMBED_PUSH, key,
                  self._push_token(key) if token is None else int(token),
                  0, 0, "uint8", memoryview(payload))

    def embed_repl(self, key: int, token: int, payload,
                   timeout_ms: int = 30000) -> None:
        """Chain forward of applied rows to a slice successor (server→
        server): absolute post-apply state + versions, dedup token in
        ``rnd`` so the replica can seed exactly-once across failover."""
        self._rpc(OP_EMBED_REPL, key, int(token), 0, timeout_ms,
                  "uint8", memoryview(payload))

    def embed_failover(self, key: int, payload,
                       timeout_ms: int = 30000) -> bytes:
        """Promote this client's server for a dead slice (``key`` = the
        slice key); returns the server's JSON stats body."""
        return self._rpc(OP_EMBED_FAILOVER, key, 0, 0, timeout_ms,
                         "uint8", memoryview(payload))

    def embed_snap(self, key: int, payload,
                   timeout_ms: int = 60000) -> bytes:
        """Ask this client's server to dump its embed row store to the
        JSON-named path (atomic tmp+rename); returns JSON stats."""
        return self._rpc(OP_EMBED_SNAP, key, 0, 0, timeout_ms,
                         "uint8", memoryview(payload))

    def embed_restore(self, key: int, payload,
                      timeout_ms: int = 60000) -> bytes:
        """Ask this client's server to load its embed row store from
        the JSON-named path; returns JSON stats."""
        return self._rpc(OP_EMBED_RESTORE, key, 0, 0, timeout_ms,
                         "uint8", memoryview(payload))

    def pull_bytes(self, key: int, round: int = 0,
                   timeout_ms: int = 30000) -> bytes:
        return self._sliced_pull(
            lambda slice_ms: self._rpc(OP_PULL_C, key, round, 0,
                                       slice_ms, "uint8", None),
            timeout_ms, f"pull_bytes({key}) round={round}")

    def push_pull(self, key: int, data: np.ndarray,
                  timeout_ms: int = 30000) -> np.ndarray:
        """One sync round from this worker's perspective: push, then pull
        the round this push completes (per-key local round counter —
        mirrors HostPSBackend.push_pull; round 0 would be a stale read)."""
        self.push(key, data)
        rnd = self._rounds.get(key, 0) + 1
        self._rounds[key] = rnd
        out = np.empty_like(data)
        self.pull(key, out, rnd if not self.async_mode else 0, timeout_ms)
        return out

    def close(self) -> None:
        import queue as _queue
        # flag FIRST: an op thread sitting in _reconnect's redial loop
        # holds its channel outside the pool, so the drain below never
        # reaches it — without the flag it would keep dialing the dead
        # address for up to reconnect_secs AFTER close. A zombie dialer
        # is not just waste: the kernel recycles the dead server's port
        # (sequential ephemeral allocation), and a successful redial
        # sprays init-replay frames at whatever now owns it — observed
        # aborting an unrelated process's gloo listener mid-handshake.
        self._dead = True
        if self._stripe_exec is not None:
            self._stripe_exec.shutdown(wait=True)
            self._stripe_exec = None
        for i, ch in enumerate(self._stats_chans):
            if ch is not None and ch.sock is not None:
                with self._stats_locks[i]:
                    try:
                        ch.sock.close()
                    except OSError:
                        pass
                    ch.sock = None
        for pool in self._pools:
            while True:
                try:
                    ch = pool.get_nowait()
                except _queue.Empty:
                    break
                ch.drop_shm()
                if ch.sock is None:
                    continue
                try:
                    _send_req(ch.sock, OP_CLOSE, 0, 0, 0, 0, "", None)
                    _recv_exact(ch.sock, _RSP.size)
                except (ConnectionError, OSError):
                    pass
                ch.sock.close()

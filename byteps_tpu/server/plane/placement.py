"""Consistent-hash placement with byte-weighted assignment and epochs.

Two layers:

- ``HashRing``: a classic virtual-node consistent-hash ring. It fixes
  the DETERMINISTIC ORDER in which shards are considered for a key
  (the successor walk from the key's ring position) — the property
  failover and striping need: when a shard dies its keys move to their
  ring successors and nobody else's placement changes, and the stripes
  of one large bucket land on consecutive DISTINCT shards.

- ``PlacementService``: the authoritative key→shard table. Assignment
  is BYTE-WEIGHTED: a new key goes to the lightest (by assigned bytes)
  of its ring-preferred candidates, so ``place`` is balanced by
  construction (max/min shard bytes stays within one key of even) —
  this is the at-the-source fix for the hash hot-spots the djb2/
  built_in placements measured (server/allreduce_emu.py: 5/16 buckets
  on one shard, +25% round time). Deterministic given the same
  ``place`` call order, which the exchange's declaration-order
  contract already guarantees across workers (naming.py).

Every assignment change (migration, failover) publishes a new
PLACEMENT EPOCH. Ops tagged with a stale epoch are refused with
``WrongEpoch`` — an explicit reroute signal — instead of landing on a
shard that no longer owns the key and tearing the round's assembly.
"""

from __future__ import annotations

import bisect
import threading
from typing import Dict, List, Optional

from ...obs.metrics import get_registry

DEFAULT_VNODES = 64


def _h64(s: str) -> int:
    """FNV-1a over the string form — process-independent (placement
    must agree across worker processes; Python's salted hash() cannot,
    same reasoning as naming._raw_built_in)."""
    h = 0xCBF29CE484222325
    for ch in s:
        h = ((h ^ ord(ch)) * 0x100000001B3) & 0xFFFFFFFFFFFFFFFF
    return h


def _mix64(x: int) -> int:
    """splitmix64 finalizer for a KEY's ring position. Keys are
    sequential integers (decl<<16 | bucket); FNV over their decimal
    string leaves adjacent keys ~one multiply apart on the ring (they
    differ only in the last digit), which clustered whole key ranges
    onto one shard and made every key share one successor walk. A full
    bit-avalanche mix spreads them uniformly; process-independent."""
    x = (x + 0x9E3779B97F4A7C15) & 0xFFFFFFFFFFFFFFFF
    x = ((x ^ (x >> 30)) * 0xBF58476D1CE4E5B9) & 0xFFFFFFFFFFFFFFFF
    x = ((x ^ (x >> 27)) * 0x94D049BB133111EB) & 0xFFFFFFFFFFFFFFFF
    return x ^ (x >> 31)


class WrongEpoch(RuntimeError):
    """An op carried a placement epoch older than the key's current
    assignment: the key migrated since the caller resolved its route.
    The op was REFUSED before touching any store — the caller must
    refresh its placement view and reroute (ps_mode retries once with
    the fresh epoch)."""

    def __init__(self, key: int, current_epoch: int, owner: int) -> None:
        super().__init__(
            f"stale placement epoch for key {key}: key moved at epoch "
            f"{current_epoch}, now owned by shard {owner} — refresh and "
            f"reroute")
        self.key = key
        self.current_epoch = current_epoch
        self.owner = owner


class HashRing:
    """Virtual-node consistent-hash ring over ``num_shards`` shards.

    ``weights`` (relative byte capacity per shard, default equal)
    scale each shard's vnode count, so a bigger server owns a
    proportionally larger arc — the "byte-weighted virtual nodes" of
    the placement story applied at the capacity level; the per-key
    byte weighting lives in ``PlacementService.place``."""

    def __init__(self, num_shards: int, vnodes: int = DEFAULT_VNODES,
                 weights: Optional[List[float]] = None) -> None:
        if num_shards <= 0:
            raise ValueError("need at least one shard")
        if weights is not None and len(weights) != num_shards:
            raise ValueError(f"{len(weights)} weights for {num_shards} "
                             f"shards")
        self.num_shards = num_shards
        self.vnodes = max(1, int(vnodes))
        w = weights or [1.0] * num_shards
        wmax = max(w)
        pts: List[tuple] = []
        for s in range(num_shards):
            n = max(1, round(self.vnodes * w[s] / wmax))
            for v in range(n):
                # _mix64 on top of the label FNV: similar labels
                # ("shard0#v1"/"shard0#v2") hash one multiply apart,
                # which clustered each shard's vnodes into a few arcs —
                # the avalanche spreads them over the whole ring
                pts.append((_mix64(_h64(f"shard{s}#v{v}")), s))
        pts.sort()
        self._points = [p for p, _ in pts]
        self._owners = [o for _, o in pts]

    def lookup(self, key: int) -> int:
        """The key's primary shard: first vnode clockwise of its hash."""
        i = bisect.bisect_right(self._points, _mix64(key))
        return self._owners[i % len(self._owners)]

    def successors(self, key: int, k: int,
                   skip: Optional[set] = None) -> List[int]:
        """First ``k`` DISTINCT shards on the clockwise walk from the
        key's position, excluding ``skip`` (dead shards). Fewer than
        ``k`` live shards → all of them, in walk order."""
        skip = skip or set()
        i = bisect.bisect_right(self._points, _mix64(key))
        out: List[int] = []
        n = len(self._owners)
        for j in range(n):
            s = self._owners[(i + j) % n]
            if s in skip or s in out:
                continue
            out.append(s)
            if len(out) >= k:
                break
        return out


def publish_shard_bytes(shard_bytes: Dict[int, int],
                        keys_per_shard: Optional[Dict[int, int]] = None
                        ) -> None:
    """Publish per-shard byte (and optionally key-count) loads as
    ``plane/shard_bytes/s<i>`` / ``plane/keys_per_shard/s<i>`` gauges —
    ONE publisher shared by the PlacementService and the classic
    ``HostPSBackend`` accounting, so the rebalancer and the watchdog
    read the same numbers whichever backend is in play."""
    reg = get_registry()
    for s, b in shard_bytes.items():
        reg.gauge(f"plane/shard_bytes/s{s}").set(b)
    if keys_per_shard is not None:
        for s, n in keys_per_shard.items():
            reg.gauge(f"plane/keys_per_shard/s{s}").set(n)


class PlacementService:
    """Authoritative, epoch-versioned key→shard assignment.

    ``fanout`` bounds the candidate set for a new key to its first
    ``fanout`` ring successors (locality-preserving bounded-load mode);
    ``fanout=0`` (default) considers every live shard with the ring
    walk as the deterministic tie-break — true byte-greedy, balanced
    by construction (max−min assigned bytes ≤ the largest single key).
    """

    def __init__(self, num_shards: int, vnodes: int = DEFAULT_VNODES,
                 fanout: int = 0,
                 weights: Optional[List[float]] = None) -> None:
        self.ring = HashRing(num_shards, vnodes=vnodes, weights=weights)
        self.num_shards = num_shards
        self.fanout = int(fanout)
        self.epoch = 1
        self._lock = threading.Lock()
        self._assign: Dict[int, int] = {}
        self._key_bytes: Dict[int, int] = {}
        self._key_epoch: Dict[int, int] = {}
        self._shard_bytes: Dict[int, int] = {s: 0
                                             for s in range(num_shards)}
        self._dead: set = set()
        reg = get_registry()
        self._g_epoch = reg.gauge("plane/epoch")
        self._g_epoch.set(self.epoch)

    # ------------------------------------------------------- assignment

    def _candidates(self, key: int) -> List[int]:
        width = self.fanout if self.fanout > 0 else self.num_shards
        return self.ring.successors(key, width, skip=self._dead)

    def place(self, key: int, nbytes: int) -> int:
        """Assign (or return the assignment of) ``key``. New keys go to
        the lightest candidate by assigned bytes; ties break in ring
        walk order. Idempotent per key — re-placing an assigned key
        returns its current shard regardless of ``nbytes``."""
        with self._lock:
            s = self._assign.get(key)
            if s is not None:
                return s
            cands = self._candidates(key)
            if not cands:
                raise RuntimeError("no live shards left in the plane")
            # min() is first-wins on ties, and cands is already in ring
            # walk order — the deterministic tie-break comes for free
            s = min(cands, key=lambda c: self._shard_bytes[c])
            self._assign[key] = s
            self._key_bytes[key] = int(nbytes)
            self._key_epoch[key] = self.epoch
            self._shard_bytes[s] += int(nbytes)
            self._publish_locked()
            return s

    def shard_of(self, key: int) -> int:
        with self._lock:
            try:
                return self._assign[key]
            except KeyError:
                raise KeyError(f"key {key} has no placement — place() "
                               f"runs at init_key") from None

    def key_epoch(self, key: int) -> int:
        """Epoch at which the key's CURRENT assignment became valid —
        an op resolved before this epoch is stale (WrongEpoch)."""
        with self._lock:
            return self._key_epoch.get(key, 1)

    def check_epoch(self, key: int, epoch: Optional[int]) -> None:
        """Refuse an op whose placement view predates the key's current
        assignment (see WrongEpoch). ``epoch=None`` = trust-the-table
        (single-process callers that share this very service)."""
        if epoch is None:
            return
        with self._lock:
            cur = self._key_epoch.get(key, 1)
            owner = self._assign.get(key, -1)
        if epoch < cur:
            get_registry().counter("plane/wrong_epoch").inc()
            raise WrongEpoch(key, cur, owner)

    def place_stripes(self, key: int, nstripes: int) -> List[int]:
        """Placement-aware striping: the stripes of one large bucket
        land on DISTINCT shards (the key's ring successors), so a hot
        key's traffic spreads instead of saturating its primary. Fewer
        live shards than stripes → shards repeat round-robin in walk
        order (every stripe still has an owner)."""
        with self._lock:
            order = self.ring.successors(key, self.num_shards,
                                         skip=self._dead)
        if not order:
            raise RuntimeError("no live shards left in the plane")
        return [order[i % len(order)] for i in range(nstripes)]

    # ------------------------------------------------- migration / death

    def migrate(self, key: int, dst: int) -> int:
        """Move ``key`` to shard ``dst`` and publish a new placement
        epoch. Returns the new epoch. The DATA move (state replay,
        round-base bookkeeping) is the backend's job — this is the
        routing-table half."""
        with self._lock:
            if dst in self._dead or not 0 <= dst < self.num_shards:
                raise ValueError(f"cannot migrate key {key} to shard "
                                 f"{dst} (dead or out of range)")
            src = self._assign.get(key)
            if src is None:
                raise KeyError(f"key {key} has no placement")
            if src == dst:
                return self.epoch
            nb = self._key_bytes.get(key, 0)
            self._shard_bytes[src] -= nb
            self._shard_bytes[dst] += nb
            self._assign[key] = dst
            self.epoch += 1
            self._key_epoch[key] = self.epoch
            self._g_epoch.set(self.epoch)
            get_registry().counter("plane/migrations").inc()
            self._publish_locked()
            return self.epoch

    def fail_shard(self, shard: int) -> Dict[int, int]:
        """Mark ``shard`` dead and reassign every key it owned to its
        next LIVE ring successor — the key's backup, where its forward
        log already lives. One epoch bump covers the whole failover.
        Returns {key: new_shard} for the moved keys; idempotent — a
        second report of the same death moves nothing."""
        moved: Dict[int, int] = {}
        with self._lock:
            if shard in self._dead:
                return moved
            self._dead.add(shard)
            if len(self._dead) >= self.num_shards:
                raise RuntimeError("every shard in the plane is dead")
            self.epoch += 1
            for key, s in list(self._assign.items()):
                if s != shard:
                    continue
                cands = [c for c in self._candidates(key) if c != shard]
                # promote the FIRST live ring successor, not the
                # lightest candidate: that is the key's backup
                # (backup_of), so the forward log is already local to
                # the new primary — the locality invariant replica.py
                # and the failure matrix promise. Balance is the
                # rebalancer's job, after the fire is out.
                dst = cands[0]
                nb = self._key_bytes.get(key, 0)
                self._shard_bytes[shard] -= nb
                self._shard_bytes[dst] += nb
                self._assign[key] = dst
                self._key_epoch[key] = self.epoch
                moved[key] = dst
            # publish the dead shard's (now zero) load BEFORE dropping
            # it from the table — otherwise its gauge would freeze at
            # the pre-failover value forever
            self._g_epoch.set(self.epoch)
            self._publish_locked()
            self._shard_bytes.pop(shard, None)
        return moved

    def backup_of(self, key: int) -> int:
        """The key's FIRST replication target: its first live ring
        successor AFTER the primary — which is exactly the shard
        ``fail_shard`` walks to first, so after a failover the new
        primary already holds the key's replica log locally."""
        chain = self.backups_of(key, 1)
        return chain[0] if chain else 0

    def backups_of(self, key: int, n: int) -> List[int]:
        """The key's replication CHAIN: its first ``n`` live ring
        successors after the primary, in walk order. ``fail_shard``
        promotes exactly ``chain[0]``, and after that promotion the old
        ``chain[1:]`` become the new primary's successors — so a chain
        of length ``n`` keeps every logged round reachable through
        ``n`` successive shard deaths (the BPS_PLANE_REPLICAS>1
        contract). Degenerate plane (one live shard): that shard, like
        ``backup_of`` always did."""
        if n <= 0:
            return []
        with self._lock:
            s = self._assign.get(key)
            order = self.ring.successors(key, self.num_shards,
                                         skip=self._dead)
        if not order:
            return []
        if s in order:
            i = order.index(s)
            rest = order[i + 1:] + order[:i]
        else:
            rest = order
        return rest[:n] if rest else [order[0]]

    # ------------------------------------------------------------- views

    def live_shards(self) -> List[int]:
        with self._lock:
            return [s for s in range(self.num_shards)
                    if s not in self._dead]

    def shard_bytes(self) -> Dict[int, int]:
        with self._lock:
            return dict(self._shard_bytes)

    def keys_per_shard(self) -> Dict[int, int]:
        with self._lock:
            out = {s: 0 for s in self._shard_bytes}
            for s in self._assign.values():
                out[s] = out.get(s, 0) + 1
            return out

    def key_bytes(self) -> Dict[int, int]:
        with self._lock:
            return dict(self._key_bytes)

    def assignment(self) -> Dict[int, int]:
        with self._lock:
            return dict(self._assign)

    def _publish_locked(self) -> None:
        out = {s: 0 for s in self._shard_bytes}
        for s in self._assign.values():
            out[s] = out.get(s, 0) + 1
        publish_shard_bytes(dict(self._shard_bytes), out)

"""Load-aware key rebalancing — the server plane's control loop.

The controller reads the live signals the PR-4 observability registry
already collects (``server/merge_wait_s``, ``server/engine_queue_depth``)
plus the plane's own per-shard/per-key pushed-byte window, and migrates
the hottest keys from the hottest shard to the coldest at round
boundaries (``PlanePSBackend.migrate_key`` drains the in-flight round,
replays state, publishes epoch N+1).

Grounding: arXiv 2103.00543 — extra communication machinery must be
shown to pay, not assumed. The decision dict records the registry
signals alongside the byte loads so every migration is attributable to
a measured imbalance, and ``bench.py ps_plane`` measures the placement
win under the asymmetric ``throttle.Nic`` instead of asserting it.

Tests drive ``step()`` directly (one deterministic evaluation); the
background thread is the production mode (``BPS_PLANE_REBALANCE_SEC``).
"""

from __future__ import annotations

import threading
from typing import Dict, Optional

from ...common.logging import get_logger
from ...obs.metrics import get_registry


class Rebalancer:
    """Hottest-keys → coldest-shard migration controller."""

    def __init__(self, plane, interval_sec: float = 0.0,
                 imbalance: float = 1.3, max_moves: int = 2,
                 min_key_bytes: int = 0, fleet=None) -> None:
        self.plane = plane
        self.interval_sec = float(interval_sec)
        self.imbalance = float(imbalance)
        self.max_moves = int(max_moves)
        self.min_key_bytes = int(min_key_bytes)
        # fleet telemetry view (obs.fleet.FleetScraper): when present
        # (explicitly, or as the process-current scraper), per-shard
        # SERVER pressure comes from the scraped registries instead of
        # the worker-local proxies, and shards whose scrape went stale
        # are skipped — never migrated onto on old numbers
        self.fleet = fleet
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def _fleet(self):
        if self.fleet is not None:
            return self.fleet
        from ...obs import fleet as fleet_mod
        return fleet_mod.current()

    # ------------------------------------------------------------- policy

    def step(self) -> Dict:
        """One control evaluation. Loads = the live pushed-byte window
        when traffic flowed since the last step, else the static
        assigned-bytes table (cold start / idle plane). Returns the
        decision record (also the no-op reasons, for observability)."""
        reg = get_registry()
        fl = self._fleet()
        if fl is not None:
            # SHARD-ATTRIBUTED server pressure from the scraped fleet
            # view (not the worker-local aggregate): the decision
            # records exactly the signals it read, per shard, with the
            # staleness verdict alongside
            scraped: Dict = {}
            for label, sv in fl.view().items():
                mw = fl.shard_metric(label, "server/merge_wait_s")
                scraped[label] = {
                    "engine_queue_depth": fl.shard_metric(
                        label, "queue_depth"),
                    "merge_wait_p95_ms": (mw or {}).get("p95_ms", 0.0)
                    if isinstance(mw, dict) else 0.0,
                    "age_s": sv["age_s"],
                    "stale": sv["stale"],
                }
            fresh = {k: v for k, v in scraped.items() if not v["stale"]}
            decision = {
                "signal_source": "fleet",
                "scraped": scraped,
                "merge_wait_p95_ms": max(
                    (v["merge_wait_p95_ms"] for v in fresh.values()),
                    default=0.0),
                "queue_depth": max(
                    (v["engine_queue_depth"] or 0
                     for v in fresh.values()), default=0),
                "moved": [],
            }
        else:
            decision = {
                "signal_source": "worker-local",
                "merge_wait_p95_ms": reg.histogram(
                    "server/merge_wait_s").summary().get("p95_ms", 0.0),
                "queue_depth": reg.gauge(
                    "server/engine_queue_depth").value,
                "moved": [],
            }
        live = self.plane.placement.live_shards()
        if fl is not None:
            # a stale shard's load numbers are fiction — skip it as
            # both migration source and target until its scrape
            # freshens (or failover removes it from live_shards)
            stale = [s for s in live if fl.is_stale(s)]
            if stale:
                decision["stale_skipped"] = stale
                live = [s for s in live if s not in stale]
        if len(live) < 2:
            decision["skip"] = ("single live shard" if fl is None
                                or not decision.get("stale_skipped")
                                else "fewer than 2 fresh shards")
            return decision
        win = self.plane.load_window()
        loads = {s: win["shards"].get(s, 0) for s in live}
        key_load = dict(win["keys"])
        if not any(loads.values()):
            loads = {s: b for s, b in self.plane.shard_bytes().items()
                     if s in live}
            key_load = self.plane.placement.key_bytes()
        hot = max(live, key=lambda s: loads.get(s, 0))
        cold = min(live, key=lambda s: loads.get(s, 0))
        hot_b, cold_b = loads.get(hot, 0), loads.get(cold, 0)
        ratio = hot_b / cold_b if cold_b > 0 else float("inf")
        decision.update(hot=hot, cold=cold, hot_bytes=hot_b,
                        cold_bytes=cold_b,
                        ratio=round(ratio, 3) if ratio != float("inf")
                        else "inf")
        if hot_b == 0 or ratio <= self.imbalance:
            decision["skip"] = "balanced"
            return decision
        assign = self.plane.placement.assignment()
        static_bytes = self.plane.placement.key_bytes()
        cands = sorted(
            (k for k, s in assign.items()
             if s == hot and static_bytes.get(k, 0) >= self.min_key_bytes),
            key=lambda k: key_load.get(k, static_bytes.get(k, 0)),
            reverse=True)
        for key in cands[:max(self.max_moves, 0)]:
            kb = key_load.get(key, static_bytes.get(key, 0))
            # never overshoot: a move that would flip the imbalance the
            # other way just oscillates
            if cold_b + kb > hot_b - kb:
                continue
            try:
                epoch = self.plane.migrate_key(key, cold)
            except TimeoutError:
                decision["moved"].append(
                    {"key": key, "skipped": "no round boundary"})
                continue
            hot_b -= kb
            cold_b += kb
            decision["moved"].append({"key": key, "to": cold,
                                      "bytes": kb, "epoch": epoch})
            if cold_b > 0 and hot_b / cold_b <= self.imbalance:
                break
        return decision

    # ------------------------------------------------------------ thread

    def start(self) -> "Rebalancer":
        if self.interval_sec <= 0:
            raise ValueError("start() needs interval_sec > 0 "
                             "(BPS_PLANE_REBALANCE_SEC)")
        if self._thread is not None:
            return self
        self._stop.clear()
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="bps-plane-rebalance")
        self._thread.start()
        return self

    def _loop(self) -> None:
        while not self._stop.wait(self.interval_sec):
            try:
                d = self.step()
                if d.get("moved"):
                    get_logger().info("plane rebalance: %s", d)
            except Exception as e:   # noqa: BLE001 — the control loop
                get_logger().warning(  # must outlive one bad evaluation
                    "plane rebalance step failed: %s", e)

    def stop(self) -> None:
        self._stop.set()
        t, self._thread = self._thread, None
        if t is not None:
            t.join(timeout=5.0)

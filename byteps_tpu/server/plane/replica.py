"""Chain replication: the forward-log of summed rounds.

A ``ReplicaStore`` lives NEXT TO a shard (attached to the in-process
``PSServer`` by the plane backend, or hosted inside a
``PSTransportServer`` and reached over the OP_REPL_* wire ops): it
holds, per key, the BYTES of the last few completed (merged) rounds.
Workers forward-log each round the moment its pull lands — to the
key's whole replication CHAIN, its first ``BPS_PLANE_REPLICAS`` live
ring successors (``PlacementService.backups_of``; 1 = classic
primary-backup, R>1 tolerates R successive deaths on one key's chain,
docs/elasticity.md). The merged bytes are identical on every worker
by construction (the server publishes one merge per round), so
concurrent logs of the same (key, round) are idempotent last-wins
writes.

After a primary dies, the key's ring successor — which is where the
replica log already lives (``PlacementService.backup_of``) — is
promoted: pulls of logged rounds are served from the log bit-exact,
and the one round the admission gate allows in flight is re-pushed by
the workers (reroute + replay instead of a job restart). Retention is
bounded to the cross-step in-flight window plus slack; anything a
straggler could still legally pull is kept.
"""

from __future__ import annotations

import threading
from typing import Dict, Optional

# Rounds retained per key. The per-key admission gate bounds the live
# window to 2 rounds (cross_step.py); 4 leaves slack for a straggler
# pulling round k while k+1 and the log of k+2 race in.
DEFAULT_RETAIN = 4


class ReplicaStore:
    """Bounded per-key round→bytes log with last-wins idempotent puts."""

    def __init__(self, retain: int = DEFAULT_RETAIN) -> None:
        self.retain = max(1, int(retain))
        self._lock = threading.Lock()
        self._rounds: Dict[int, Dict[int, bytes]] = {}
        self._base: Dict[int, int] = {}     # highest logged round per key

    def put(self, key: int, round: int, payload: bytes) -> None:
        """Log round ``round``'s merged bytes for ``key``. Idempotent:
        every worker pulled the same published merge, so a re-log (or a
        concurrent log from another worker) writes identical bytes."""
        if round <= 0:
            raise ValueError(f"replica log rounds are 1-based, got {round}")
        data = bytes(payload)
        with self._lock:
            log = self._rounds.setdefault(key, {})
            log[round] = data
            if round > self._base.get(key, 0):
                self._base[key] = round
            while len(log) > self.retain:
                del log[min(log)]

    def get(self, key: int, round: int) -> Optional[bytes]:
        """The logged merged bytes, or None when that round was never
        logged (or already aged out of the retention window)."""
        with self._lock:
            return self._rounds.get(key, {}).get(round)

    def base(self, key: int) -> int:
        """Highest logged round for ``key`` (0 = nothing logged) — the
        round-translation base a promoted shard starts counting from."""
        with self._lock:
            return self._base.get(key, 0)

    def keys(self):
        with self._lock:
            return list(self._rounds)

    def drop_key(self, key: int) -> None:
        with self._lock:
            self._rounds.pop(key, None)
            self._base.pop(key, None)

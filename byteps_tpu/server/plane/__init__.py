"""Managed server plane: placement, replication, load-aware rebalancing.

Until this package existed the server side was a flat shard list behind
a static hash — a hot key saturated one shard and a server death was a
job restart. The plane gives the server tier its own control loop, the
operational conclusion of the BytePS rationale-doc claim (spare CPU
bandwidth on a scaled-out server tier beats allreduce,
docs/rationale.md):

- ``placement``: consistent-hash ring with byte-weighted virtual-node
  assignment, versioned placement epochs, and placement-aware striping
  (stripes of one large bucket land on DIFFERENT shards);
- ``replica``: forward-log of each key's summed rounds to a backup
  shard, so a killed server becomes reroute + replay instead of a
  restart;
- ``backend``: the worker-facing ``PlanePSBackend`` (same duck
  interface as ``HostPSBackend``/``RemotePSBackend``) that routes
  through the placement service and executes failover + migration;
- ``rebalance``: the load-aware controller that migrates the hottest
  keys to the coldest shards at round boundaries, driven by the live
  obs registry signals (``server/merge_wait_s``,
  ``server/engine_queue_depth``, per-shard push bytes).

See docs/server-plane.md for the protocols and the failure matrix.
"""

from .backend import PlanePSBackend
from .placement import HashRing, PlacementService, WrongEpoch
from .rebalance import Rebalancer
from .replica import ReplicaStore

__all__ = ["HashRing", "PlacementService", "WrongEpoch",
           "PlanePSBackend", "Rebalancer", "ReplicaStore"]

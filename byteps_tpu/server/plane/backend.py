"""``PlanePSBackend`` — the worker-facing driver of the managed plane.

Same duck interface as ``HostPSBackend``/``RemotePSBackend`` (init_key /
push / pull / round / push_bytes / pull_bytes), so
``PSGradientExchange`` runs over it unchanged; underneath, every op is

  1. routed through the ``PlacementService`` (byte-weighted ring
     assignment, versioned epochs — an op tagged with a stale epoch is
     refused with ``WrongEpoch`` before it can tear a round),
  2. replicated (``replicas=R``): the merged bytes of every completed
     round are forward-logged to the key's replication CHAIN — its
     first R live ring successors — the moment this worker's pull
     lands, and the one round the admission gate allows in flight is
     retained worker-side for replay. R=1 is classic primary-backup;
     R>1 tolerates R successive shard deaths on one key's chain
     (docs/elasticity.md),
  3. failed over: a shard-unreachable error triggers reroute — the dead
     shard's keys move to their ring successors (where their replica
     logs already live), inits are replayed from the plane's meta, round
     counters are re-based onto the replica log, and the in-flight round
     is re-pushed. The retried op then completes bit-identically; the
     job never restarts.

Shard clients are either in-process ``PSServer`` instances (their
replica logs live in this plane object) or single-address
``RemotePSBackend`` clients (replica logs live in the remote
``PSTransportServer``, reached via the OP_REPL_* wire ops).
"""

from __future__ import annotations

import threading
import time
from typing import Dict, List, Optional

import numpy as np

from ...common.logging import get_logger
from ...obs.metrics import get_registry
from ..engine import ServerClosed
from .placement import DEFAULT_VNODES, PlacementService
from .replica import ReplicaStore


class _LocalReplica:
    """Replica-log interface over an in-process ``ReplicaStore`` — the
    plane holds the store, so it SURVIVES its shard's death (that is
    the point: the log for a key lives at the key's backup index)."""

    __slots__ = ("_s",)

    def __init__(self, store: ReplicaStore) -> None:
        self._s = store

    def repl_put(self, key: int, round: int, payload) -> None:
        self._s.put(key, round, payload)

    def repl_get(self, key: int, round: int) -> Optional[bytes]:
        return self._s.get(key, round)

    def repl_base(self, key: int) -> int:
        return self._s.base(key)


class PlanePSBackend:
    """Placement-routed, replicated, migratable PS backend."""

    def __init__(self, shards: List, num_workers: int = 1,
                 replicas: int = 0, vnodes: int = DEFAULT_VNODES,
                 fanout: int = 0,
                 placement: Optional[PlacementService] = None,
                 owns_shards: bool = False,
                 worker_id: Optional[int] = None) -> None:
        if not shards:
            raise ValueError("the plane needs at least one shard")
        self._shards = list(shards)
        self.num_workers = int(num_workers)
        self.replicas = max(0, min(int(replicas), len(shards) - 1))
        # replication logs the key's round the moment a pull of it
        # lands; designated logging (worker_id given) has the (key %
        # num_workers)-th worker log each key ONCE instead of every
        # worker uploading the identical merge (W-fold backup ingest on
        # the pull hot path). None = every worker logs — the safe
        # default for hand-built planes that never declared their rank.
        self.worker_id = None if worker_id is None else int(worker_id)
        self.placement = placement or PlacementService(
            len(shards), vnodes=vnodes, fanout=fanout)
        self._owns = owns_shards
        self.async_mode = any(getattr(s, "async_mode", False)
                              for s in shards)
        if self.async_mode and self.replicas > 0:
            # async pulls are round-less: nothing marks a round
            # boundary, so the forward log, the in-flight replay copy,
            # and migration's drain contract all lose their anchor —
            # failover would "succeed" by replaying the original init
            # over accumulated async state. Refuse loudly.
            raise ValueError(
                "BPS_PLANE_REPLICAS>0 does not compose with async mode "
                "(round-less pulls leave nothing to forward-log or "
                "replay) — run the async tier on the flat shard list")
        # replica-log handles: a remote shard client speaks OP_REPL_*
        # itself; an in-process shard gets a plane-held store
        self._repl = [s if hasattr(s, "repl_put")
                      else _LocalReplica(ReplicaStore())
                      for s in shards]
        # param-mailbox handles (sharded weight update, OP_PARAM_*):
        # same split — remote clients speak the wire ops, in-process
        # shards get plane-held stores. Param keys are routed by PURE
        # ring successor order (never placed/migrated): every worker
        # resolves the same shard with no table to diverge, and a shard
        # death moves them to the next successor — where the owner's
        # put RETRY lands too (frames are recomputable, not replicated;
        # docs/sharded-update.md failure matrix).
        self._params = [s if hasattr(s, "param_put") else None
                        for s in shards]
        self._params_local: Dict[int, object] = {}
        self._lock = threading.Lock()
        self._mig_cv = threading.Condition(self._lock)
        # key -> (nbytes, dtype, init copy, compression) for init
        # replay on failover / migration
        self._meta: Dict[int, tuple] = {}
        # plane round r maps to shard-local round r - base (a promoted
        # or migration-target shard starts counting from 0)
        self._round_base: Dict[int, int] = {}
        # this worker's per-key push round (mirrors the exchange's
        # counter; seeds from round() like _next_round does) and the
        # one pushed-but-unpulled round the admission gate allows:
        # key -> (plane round, data copy | None). The copy is what
        # failover re-pushes; kept only when replication is on.
        self._push_round: Dict[int, int] = {}
        self._inflight: Dict[int, tuple] = {}
        # key -> round that fail_shard already re-pushed to the new
        # owner: the push whose failure TRIGGERED the failover is
        # retried by _run, and without this marker that retry would
        # push the same round a second time (double-counted in the
        # new shard's sum)
        self._replayed: Dict[int, int] = {}
        self._logged: Dict[int, int] = {}
        # bounded-staleness contract (key -> K), replayed on failover:
        # the promoted shard's fresh StaleStore relearns the bound and
        # its adopt rule resyncs to the live round on the first push
        # (docs/admission.md failure matrix)
        self._lag_contract: Dict[int, int] = {}
        # keys being migrated right now: push must not slip a new round
        # onto the OLD primary between migrate_key's drain check and
        # the routing switch (that round would be silently lost)
        self._migrating: set = set()
        self._dead: set = set()
        self._fused_ok = False      # _check_fused_shards verdict cache
        self._fused_keys: set = set()   # fused-managed declarations —
        #                                 re-inits (failover/migration
        #                                 replay) carry the flag forward
        # rebalancer inputs: pushed bytes per shard / per key since the
        # last load_window() call
        self._win_shard: Dict[int, int] = {}
        self._win_key: Dict[int, int] = {}
        reg = get_registry()
        self._m_failovers = reg.counter("plane/failovers")
        self._g_lag = reg.gauge("plane/replication_lag")
        # per-key push-vs-logged lag with argmax tracking, so the gauge
        # stays O(1) per op instead of rescanning every key under the
        # plane lock on each push/pull
        self._lag: Dict[int, int] = {}
        self._lag_argmax: Optional[int] = None
        self._t0_mono = time.monotonic()   # stats() heartbeat base for
        #                                    in-process shards
        self._liveness_warned: set = set()   # note_stale replicas=0 warn

    # ------------------------------------------------------------ admin

    def close(self) -> None:
        if self._owns:
            for s in self._shards:
                try:
                    s.close()
                except Exception:   # noqa: BLE001 — best-effort teardown
                    pass

    def placement_epoch(self) -> int:
        """The worker's current placement view — captured by the
        exchange at push time and carried through the round's pull, so
        a migration racing the round is caught as WrongEpoch instead of
        a torn assembly."""
        return self.placement.epoch

    def shard_bytes(self) -> Dict[int, int]:
        return self.placement.shard_bytes()

    def load_window(self) -> Dict[str, Dict[int, int]]:
        """Pushed bytes per shard and per key since the last call
        (reset on read) — the rebalancer's live-load signal."""
        with self._lock:
            out = {"shards": dict(self._win_shard),
                   "keys": dict(self._win_key)}
            self._win_shard.clear()
            self._win_key.clear()
        return out

    def queue_depth(self) -> int:
        n = 0
        for i, s in enumerate(self._shards):
            if i in self._dead or not hasattr(s, "queue_depth"):
                continue
            try:
                n += s.queue_depth()
            except Exception:   # noqa: BLE001 — a dying shard's gauge
                pass            # must not fail the caller
        return n

    def stats(self, timeout_ms: int = 5000) -> Dict[str, dict]:
        """Fleet stats surface over the plane's shard list: remote
        shard clients answer via OP_STATS, in-process shards synthesize
        the same shape, shards already failed over report as errors (a
        scraper reads them as down — which they are). Per-shard
        failures become ``{"error": …}`` entries, never exceptions: the
        scrape thread is the observer of shard death, not a victim."""
        from ...obs.fleet import server_stats_payload
        out: Dict[str, dict] = {}
        for i, s in enumerate(self._shards):
            label = f"s{i}"
            if i in self._dead:
                out[label] = {"error": "failed over (shard marked dead)"}
                continue
            try:
                if hasattr(s, "stats_shard"):
                    # single-address RemotePSBackend shard client
                    out[label] = s.stats_shard(0, timeout_ms)
                elif hasattr(s, "stats"):
                    sub = s.stats(timeout_ms=timeout_ms)
                    out[label] = sub.get("s0") or next(iter(sub.values()))
                else:
                    # raw in-process PSServer shard: the shared shape,
                    # local registry, plane-lifetime heartbeat
                    out[label] = server_stats_payload(
                        time.monotonic() - self._t0_mono,
                        len(self._meta),
                        queue_depth_fn=(s.queue_depth
                                        if hasattr(s, "queue_depth")
                                        else None))
            except Exception as e:   # noqa: BLE001 — per-shard isolation
                out[label] = {"error": f"{type(e).__name__}: {e}"}
        return out

    def trace(self, timeout_ms: int = 5000) -> Dict[str, dict]:
        """Causal trace scrape over the plane's shard list (the
        ``RemotePSBackend.trace()`` shape): remote shard clients answer
        via OP_TRACE with real roundtrip stamps, backends with a local
        ring answer in-process, raw PSServer shards (test rigs) have no
        ring and report an error entry — never an exception."""
        out: Dict[str, dict] = {}
        for i, s in enumerate(self._shards):
            label = f"s{i}"
            if i in self._dead:
                out[label] = {"error": "failed over (shard marked dead)"}
                continue
            try:
                if hasattr(s, "trace_shard"):
                    p, t0, t1 = s.trace_shard(0, timeout_ms)
                    out[label] = {"payload": p, "t_send": t0,
                                  "t_recv": t1}
                elif hasattr(s, "trace"):
                    sub = s.trace(timeout_ms=timeout_ms)
                    out[label] = (sub.get("s0")
                                  or next(iter(sub.values())))
                else:
                    out[label] = {"error": "no trace surface "
                                           "(raw in-process shard)"}
            except Exception as e:   # noqa: BLE001 — per-shard isolation
                out[label] = {"error": f"{type(e).__name__}: {e}"}
        return out

    # ------------------------------------------------- failover plumbing

    def _run(self, key: int, op):
        """Run ``op(shard_client)`` on the key's primary; one
        shard-unreachable error triggers failover and a single retry on
        the new owner. TimeoutError stays an application answer (the
        shard is alive, the round just isn't ready) — it must never
        trigger a failover."""
        for attempt in (0, 1):
            s = self.placement.shard_of(key)
            try:
                return op(self._shards[s], s)
            except TimeoutError:
                raise
            except (ConnectionError, OSError, ServerClosed) as e:
                if attempt:
                    raise
                self.fail_shard(s, cause=e)

    def fail_shard(self, shard: int, cause: Optional[BaseException] = None
                   ) -> Dict[int, int]:
        """Reroute + replay: reassign the dead shard's keys to their
        ring successors, replay their inits there, re-base round
        counters onto the replica log, and re-push the in-flight round.
        Without replication there is nothing to replay — the original
        error propagates (restart-level failure, loud)."""
        with self._lock:
            if shard in self._dead:
                return {}
            if self.replicas <= 0:
                if cause is not None:
                    raise cause
                raise RuntimeError(
                    f"shard {shard} unreachable and BPS_PLANE_REPLICAS=0 "
                    f"— no replica log to fail over onto")
            moved = self.placement.fail_shard(shard)
            self._dead.add(shard)
            self._m_failovers.inc()
            get_logger().warning(
                "plane: shard %d unreachable (%s) — failing over %d "
                "key(s), placement epoch now %d", shard, cause,
                len(moved), self.placement.epoch)
            # membership events are FIRST-CLASS flight events, recorded
            # key-less so every postmortem (any key filter) carries the
            # epoch transition — a post-failover wedge diagnosis names
            # the membership change, not just the stuck keys
            from ...obs import flight
            flight.record(
                "failover", outcome="failover",
                detail=f"shard {shard} dead ({type(cause).__name__}) -> "
                       f"placement epoch {self.placement.epoch}; "
                       f"{len(moved)} key(s) moved")
            # per-key replay errors (the DESTINATION shard dying too —
            # a double death) must not abort the loop: fail_shard is
            # idempotent-by-_dead, so keys left unprocessed here would
            # stay moved-but-never-rebased FOREVER (sheared numbering,
            # silently wrong pulls). Process every key, then re-raise
            # the first transport error — the caller's retry hits the
            # dead destination and fails IT over, which re-bases any
            # key this pass could not (its own fail_shard recomputes
            # from the logs and the new store).
            dst_err: Optional[BaseException] = None
            for key, dst in moved.items():
                try:
                    meta = self._meta.get(key)
                    if meta is not None:
                        nbytes, dtype, init, compression = meta
                        self._init_on(dst, key, nbytes, dtype, init,
                                      compression)
                    lagk = self._lag_contract.get(key)
                    if lagk is not None:
                        self._shards[dst].declare_lag(key, lagk)
                    # the new primary WAS the key's backup (ring
                    # successor), so the forward log is already local to
                    # it; its store counts rounds from 0 → re-base onto
                    # the logged round MINUS the rounds the promoted
                    # store itself already completed: a LATE failover
                    # (an elastic replacement joining after the fleet
                    # promoted, or a worker whose detection staggers a
                    # round behind its peers') sees a log head that
                    # includes rounds the new primary served —
                    # translating by the raw head would shear this
                    # worker's round numbering off the store's. round()
                    # answers 0 for a key the store never saw (the
                    # engine contract — no raise), so there is no silent
                    # fallback here: a transport failure takes the
                    # double-death path below.
                    base = self._repl_base_any(key, prefer=dst)
                    local = int(self._shards[dst].round(key))
                    base = max(0, base - local)
                    self._round_base[key] = base
                    inf = self._inflight.get(key)
                    if (inf is not None and inf[0] > base
                            and inf[1] is not None):
                        # the admission-gate round in flight at death:
                        # only this worker can replace its own
                        # contribution. Mark the round replayed so a
                        # push retry racing this failover (the push that
                        # DETECTED the death) does not apply it a second
                        # time. A fused-plane copy is re-pushed as its
                        # PAYLOAD — the new shard decodes it exactly
                        # like the dead one did (deterministic codecs),
                        # so the replayed sum stays bit-identical.
                        if (isinstance(inf[1], tuple)
                                and inf[1][0] == "fused"):
                            self._shards[dst].push_fused(key, inf[1][1])
                        else:
                            self._shards[dst].push(key, inf[1])
                        self._replayed[key] = inf[0]
                except (ConnectionError, OSError, ServerClosed) as e:
                    if isinstance(e, TimeoutError):
                        raise       # application answer, never a death
                    if dst_err is None:
                        dst_err = e
            try:
                self._shards[shard].close()
            except Exception:   # noqa: BLE001 — it is already dead
                pass
            if dst_err is not None:
                raise dst_err
        return moved

    def note_stale(self, shard: int, age_s: Optional[float] = None,
                   source: str = "fleet") -> bool:
        """Server-side liveness, ACTED ON: the fleet scraper's
        staleness verdict (scrape age past 3 cadences — a BLACK-HOLED
        shard, not just a refused connection) declares the shard dead
        and triggers the same reroute + replay a worker-observed socket
        error would. Returns True when a failover was triggered; False
        when the shard is already dead, out of range, or the plane
        cannot fail over (replicas=0 — observed-only, with one warning
        per shard). Idempotent per shard, like ``fail_shard``."""
        if not 0 <= int(shard) < len(self._shards):
            return False
        shard = int(shard)
        with self._lock:
            if shard in self._dead:
                return False
        if self.replicas <= 0:
            if shard not in self._liveness_warned:
                self._liveness_warned.add(shard)
                get_logger().warning(
                    "plane: shard %d stale per %s (scrape age %.1fs) but "
                    "BPS_PLANE_REPLICAS=0 — liveness verdict stays "
                    "observed-only (no replica log to fail over onto)",
                    shard, source, age_s if age_s is not None else -1.0)
            return False
        from ...obs import flight
        flight.record(
            "member_leave",
            detail=f"shard {shard} declared dead by {source} "
                   f"(scrape age {age_s if age_s is not None else '?'}s)")
        self.fail_shard(shard, cause=TimeoutError(
            f"{source}: scrape age "
            f"{age_s if age_s is not None else '?'}s past the staleness "
            f"line — black-holed shard declared dead server-side"))
        return True

    def _init_on(self, shard: int, key: int, nbytes: int, dtype: str,
                 init, compression) -> None:
        sh = self._shards[shard]
        if compression:
            import inspect
            if "compression" not in inspect.signature(
                    sh.init_key).parameters:
                # in-process PSServer shards take no codec registration
                # (that lives at the transport/backend layer) — a
                # compressed key on such a plane must fail at INIT, not
                # as a TypeError inside a failover replay
                raise ValueError(
                    f"shard {shard} ({type(sh).__name__}) cannot "
                    f"register a compression codec — compressed keys "
                    f"need transport-backed plane shards")
            sh.init_key(key, nbytes, dtype, init=init,
                        compression=compression)
        elif key in self._fused_keys:
            # fused-managed declaration travels with every (re-)init —
            # a failover/migration replay must re-manage the key on the
            # new shard, not silently degrade it to dense decodes. Same
            # signature guard as the compression branch: a raw
            # in-process PSServer shard has no fused surface, and that
            # must fail loudly at init/replay time, never as a
            # TypeError inside a failover replay
            import inspect
            if "fused" not in inspect.signature(
                    sh.init_key).parameters:
                raise ValueError(
                    f"shard {shard} ({type(sh).__name__}) cannot "
                    f"manage fused key {key} — fused declarations "
                    f"need transport-backed plane shards")
            sh.init_key(key, nbytes, dtype, init=init, fused=True)
        else:
            sh.init_key(key, nbytes, dtype, init=init)

    # ----------------------------------------------------- replica log

    def _repl_base_any(self, key: int, prefer: int) -> int:
        """Highest logged round across live shards' stores, preferring
        ``prefer`` (the new primary — normally the only holder)."""
        best = 0
        order = [prefer] + [i for i in range(len(self._shards))
                            if i != prefer and i not in self._dead]
        for i in order:
            try:
                best = max(best, int(self._repl[i].repl_base(key)))
            except Exception:   # noqa: BLE001 — a dead/din store is
                continue        # simply not a log source
        return best

    def _repl_wait(self, key: int, round: int, timeout_ms: int) -> bytes:
        """Fetch a logged round, waiting out the race where ANOTHER
        worker's forward-log of it is still in flight."""
        deadline = time.monotonic() + max(1, timeout_ms) / 1e3
        while True:
            prim = self.placement.shard_of(key)
            order = [prim] + [i for i in range(len(self._shards))
                              if i != prim and i not in self._dead]
            for i in order:
                try:
                    data = self._repl[i].repl_get(key, round)
                except Exception:   # noqa: BLE001 — skip dead stores
                    continue
                if data is not None:
                    return data
            if time.monotonic() >= deadline:
                raise TimeoutError(
                    f"pull({key}) round={round}: not in any replica log "
                    f"(retention window passed, or the logging worker "
                    f"died before its pull)")
            time.sleep(0.01)

    def _logs_key(self, key: int) -> bool:
        """Is this worker the designated forward-logger for ``key``?
        Every worker pulls the identical published merge, so ONE
        logging it suffices (idempotent last-wins makes extras merely
        redundant) — designated logging cuts the backup shard's ingest
        and the pull tail's synchronous upload by the worker count.
        ``worker_id=None`` (hand-built planes): everyone logs."""
        if self.worker_id is None or self.num_workers <= 1:
            return True
        return key % self.num_workers == self.worker_id % self.num_workers

    def _log_round_bytes(self, key: int, round: int, payload) -> None:
        """Forward-log a completed round to the key's replication
        CHAIN — the first ``replicas`` live ring successors
        (``PlacementService.backups_of``), so ``BPS_PLANE_REPLICAS=R``
        keeps every logged round reachable through R successive shard
        deaths, not just one. A chain member dying is a shard death
        like any other: fail it over (idempotent), recompute the chain,
        and keep logging — the pull that carried this merge was healthy
        and must not error. The log stores the exact BYTES the pull
        returned (dense for plain rounds, the encoded payload for fused
        ones), so a replayed pull of the round decodes bit-identically
        to the original."""
        logged: set = set()
        fails = 0
        # ONE chain computation per round on the healthy path (this is
        # the per-pull hot path); recomputed only after a chain
        # member's death actually changed membership
        chain = [b for b in self.placement.backups_of(key, self.replicas)
                 if b not in logged]
        while chain:
            b = chain[0]
            try:
                self._repl[b].repl_put(key, round, payload)
                logged.add(b)
                chain = chain[1:]
            except TimeoutError:
                raise   # repl ops never block server-side: surface it
            except (ConnectionError, OSError, ServerClosed) as e:
                fails += 1
                if fails > len(self._shards):
                    raise
                self.fail_shard(b, cause=e)
                chain = [c for c in self.placement.backups_of(
                    key, self.replicas) if c not in logged]
        with self._lock:
            self._logged[key] = max(self._logged.get(key, 0), round)
            self._update_lag_locked(key)

    def _update_lag_locked(self, key: int) -> None:
        """O(1) gauge refresh for one key's push/log change; a full
        rescan only when the current worst key improves."""
        if not self._logs_key(key):
            return          # never logged by this worker — not lag
        lag = self._push_round.get(key, 0) - self._logged.get(key, 0)
        # read the argmax's PREVIOUS lag before overwriting: when this
        # very key is the argmax and just improved, the stale read
        # would make lag >= cur trivially true and the rescan branch
        # unreachable (gauge stuck low until another key's op)
        old_argmax = self._lag_argmax
        cur = (self._lag.get(old_argmax, -1)
               if old_argmax is not None else -1)
        self._lag[key] = lag
        if lag >= cur:
            self._lag_argmax = key
            self._g_lag.set(lag)
        elif key == old_argmax:
            k2 = max(self._lag, key=self._lag.get)
            self._lag_argmax = k2
            self._g_lag.set(self._lag[k2])

    # ------------------------------------------------------- data plane

    def init_key(self, key: int, nbytes: int, dtype: str = "float32",
                 init: Optional[np.ndarray] = None,
                 compression: Optional[Dict[str, str]] = None,
                 fused: bool = False) -> None:
        self.placement.place(key, nbytes)
        with self._lock:
            if key not in self._meta:
                self._meta[key] = (int(nbytes), dtype,
                                   None if init is None else np.array(init),
                                   dict(compression) if compression
                                   else None)
            if fused:
                self._fused_keys.add(key)
            else:
                # re-declared non-fused: hand the key back (the same
                # rule HostPSBackend and FusedFront apply), or replays
                # would force homog management against the worker's
                # current declaration forever
                self._fused_keys.discard(key)
        self._run(key, lambda sh, i: self._init_on(
            i, key, nbytes, dtype, init, compression))

    def _push_registered(self, key: int, keep, nbytes: int,
                         send) -> None:
        """The ONE push critical section (dense and fused): elastic
        round seeding, wait-and-REGISTER against migration (the dual of
        migrate_key's drain-and-mark: while ``_migrating`` holds the
        key no new round can register — a push slipping onto the OLD
        primary would be silently absent from the replayed state — and
        once ``_inflight`` holds this round the migration drain blocks
        until its pull lands), the failover replay-dedup guard, and the
        rebalancer's load-window booking. ``send(shard_client)`` does
        the actual wire op."""
        with self._lock:
            seed = self._push_round.get(key)
        if seed is None:
            seed = int(self.round(key))  # elastic seed, like _next_round
        with self._mig_cv:
            while key in self._migrating:
                self._mig_cv.wait(timeout=1.0)
            lr = self._push_round.get(key, seed) + 1
            self._push_round[key] = lr
            self._inflight[key] = (lr, keep)
            self._update_lag_locked(key)

        def do(sh, i):
            with self._lock:
                # a failover between the first attempt and this retry
                # already re-pushed this round to the new owner —
                # pushing again would double-count it
                replayed = self._replayed.get(key) == lr
                if replayed:
                    del self._replayed[key]
            if not replayed:
                send(sh)
            with self._lock:
                self._win_shard[i] = self._win_shard.get(i, 0) + nbytes
                self._win_key[key] = self._win_key.get(key, 0) + nbytes

        self._run(key, do)

    def push(self, key: int, data: np.ndarray,
             epoch: Optional[int] = None) -> None:
        self.placement.check_epoch(key, epoch)
        keep = (np.array(data, copy=True) if self.replicas > 0 else None)
        self._push_registered(key, keep, int(getattr(data, "nbytes", 0)),
                              lambda sh: sh.push(key, data))

    def pull(self, key: int, out: np.ndarray, round: int = 0,
             timeout_ms: int = 30000,
             epoch: Optional[int] = None) -> None:
        self.placement.check_epoch(key, epoch)

        def do(sh, i):
            base = self._round_base.get(key, 0)
            if round and round <= base:
                # a round completed before the failover/migration: the
                # live store never saw it — serve the forward log,
                # bit-exact (every worker logged the same merge). The
                # log stores whatever bytes the DESIGNATED worker's
                # pull returned — with BPS_COMPRESS=auto and divergent
                # per-worker decision traces that may be a fused
                # payload while THIS worker's trace pinned dense.
                # Disambiguate by SIZE first (a dense log is exactly
                # out.nbytes; random gradient bytes matching the codec
                # magic must not shunt a healthy dense replay into the
                # decoder), header second; a log entry that is neither
                # refuses loudly inside decode.
                from ...compress import wire as cwire
                data = self._repl_wait(key, round, timeout_ms)
                if len(data) == out.nbytes:
                    flat = np.frombuffer(data, dtype=out.dtype)
                else:
                    flat = cwire.decode(data, expect_elems=out.size,
                                        expect_dtype=out.dtype)
                np.copyto(out.reshape(-1), flat[:out.size])
                return
            sh.pull(key, out, round=(round - base) if round else 0,
                    timeout_ms=timeout_ms)

        self._run(key, do)
        self._finish_pull(key, round, lambda: out.tobytes())

    def _finish_pull(self, key: int, round: int, payload_fn) -> None:
        """The ONE pull tail (dense and fused): forward-log the
        completed round when this worker is its designated logger —
        re-reading the base first, since a failover inside ``_run`` may
        have raised it, and a round at or below base CAME from the log
        (re-uploading it would be a redundant full-payload write on the
        pull tail) — then release the admission-gate in-flight entry
        for migrate_key's drain. ``payload_fn`` supplies the exact
        bytes this pull returned, lazily (non-logging workers never pay
        the copy)."""
        if not round:
            return
        if (self.replicas > 0 and self._logs_key(key)
                and round > self._round_base.get(key, 0)):
            self._log_round_bytes(key, round, payload_fn())
        with self._mig_cv:
            inf = self._inflight.get(key)
            if inf is not None and inf[0] <= round:
                del self._inflight[key]
                self._mig_cv.notify_all()   # migrate_key's drain

    # -------------------------------------------- sharded-update params

    def _param_client(self, key: int):
        """(client, shard index) of ``key``'s param mailbox: its first
        LIVE ring successor (stateless, identical on every worker). The
        shard index is captured WITH the client — a failover must blame
        the shard the op actually ran on, not whatever the ring resolves
        to after a concurrent thread already marked it dead (that next
        successor is healthy)."""
        order = self.placement.ring.successors(key, len(self._shards),
                                               skip=self._dead)
        if not order:
            raise RuntimeError("no live shards left in the plane")
        s = order[0]
        client = self._params[s]
        if client is None:
            client = self._params_local.get(s)
            if client is None:
                from ...sharded_update import ParamStore
                client = self._params_local[s] = ParamStore()
        return client, s

    def param_put(self, key: int, seq: int, payload) -> None:
        for attempt in (0, 1):
            c, s = self._param_client(key)
            try:
                if hasattr(c, "param_put"):
                    return c.param_put(key, seq, payload)
                return c.put(key, seq, payload)
            except (ConnectionError, OSError, ServerClosed) as e:
                if attempt:
                    raise
                self.fail_shard(s, cause=e)   # idempotent per shard

    def param_get(self, key: int, seq: int,
                  timeout_ms: int = 30000) -> bytes:
        for attempt in (0, 1):
            c, s = self._param_client(key)
            try:
                if hasattr(c, "param_get"):
                    return c.param_get(key, seq, timeout_ms=timeout_ms)
                return c.get(key, seq, timeout_ms=timeout_ms)
            except TimeoutError:
                raise          # application answer: owner never put
            except (ConnectionError, OSError, ServerClosed) as e:
                if attempt:
                    raise
                self.fail_shard(s, cause=e)   # idempotent per shard

    def param_latest(self, key: int) -> int:
        """Newest retained seq in ``key``'s param mailbox (0 = empty) —
        the elastic-rejoin seed: a rejoining owner resumes its
        param-frame sequence from the server's retained frames instead
        of re-publishing from seq 0 (which would strand every non-owner
        blocked on the real next seq)."""
        for attempt in (0, 1):
            c, s = self._param_client(key)
            try:
                if hasattr(c, "param_latest"):
                    return int(c.param_latest(key))
                return int(c.latest(key))
            except (ConnectionError, OSError, ServerClosed) as e:
                if attempt:
                    raise
                self.fail_shard(s, cause=e)   # idempotent per shard

    def set_send_priority(self, key: int, prio: int) -> None:
        """Fan the per-key wire-scheduler priority out to every shard
        client that gates sends (grad buckets route by placement, param
        keys by ring successor — the shard owning the key will have it)."""
        for s in self._shards:
            if hasattr(s, "set_send_priority"):
                s.set_send_priority(key, prio)

    def round(self, key: int) -> int:
        base = self._round_base.get(key, 0)
        return base + int(self._run(key, lambda sh, i: sh.round(key)))

    # Bounded-staleness plane surface (server/admission.py StaleStore):
    # lag ops route like any dense op — primary shard, one failover
    # retry. The contract itself is the only replayed state: a promoted
    # shard's fresh store re-learns K (fail_shard) and its adopt rule
    # resyncs to the live round on the first push, so no per-round lag
    # state rides the replica log.

    def declare_lag(self, key: int, max_lag: int) -> None:
        if not all(hasattr(sh, "declare_lag") for sh in self._shards):
            raise ValueError(
                "BPS_MAX_LAG>1 needs lag-capable plane shards "
                "(declare_lag/push_lag/pull_lag) on every shard — a "
                "failover can land the key on any of them")
        self._run(key, lambda sh, i: sh.declare_lag(key, int(max_lag)))
        with self._lock:
            self._lag_contract[key] = int(max_lag)

    def push_lag(self, key: int, worker: int, rnd: int,
                 data: np.ndarray) -> None:
        self._run(key, lambda sh, i: sh.push_lag(key, worker, rnd, data))

    def pull_lag(self, key: int, worker: int, rnd: int, out: np.ndarray,
                 timeout_ms: int = 30000) -> int:
        return int(self._run(key, lambda sh, i: sh.pull_lag(
            key, worker, rnd, out, timeout_ms)))

    def _check_fused_shards(self) -> None:
        """Refuse fused ops EARLY on a plane with any shard that cannot
        speak them (in-process ``PSServer`` shards take raw dense
        buffers only) — the same convention ``_init_on`` sets for
        legacy compressed keys: a capability mismatch must fail at the
        first call (or, via the exchange's construction-time probe,
        before any training), never as an AttributeError inside a
        failover replay that would leave the plane half-migrated.
        EVERY shard is checked — a fused round can land on any of them
        after enough failovers/migrations. The verdict is invariant
        (the shard list never changes), so it is computed once and
        cached off the per-bucket hot path."""
        if self._fused_ok:
            return
        for sh in self._shards:
            if not hasattr(sh, "push_fused"):
                raise ValueError(
                    f"fused compression needs transport-backed plane "
                    f"shards (shard type {type(sh).__name__} has no "
                    f"push_fused/pull_fused) — run the fused plane "
                    f"over RemotePSBackend shards, or set "
                    f"BPS_COMPRESS=none")
        self._fused_ok = True

    def push_fused(self, key: int, payload,
                   epoch: Optional[int] = None) -> None:
        """Fused-plane push: routed, epoch-checked, and REPLICATED like
        a dense push — the in-flight copy kept for failover replay is
        the encoded payload itself, re-pushed through ``push_fused`` so
        the promoted shard's decode (deterministic) reproduces exactly
        what the dead shard summed."""
        self._check_fused_shards()
        self.placement.check_epoch(key, epoch)
        keep = (("fused", bytes(payload)) if self.replicas > 0 else None)
        self._push_registered(key, keep, len(payload),
                              lambda sh: sh.push_fused(key, payload))

    def pull_fused(self, key: int, nbytes: int, dtype: str, codec: int,
                   round: int = 0, timeout_ms: int = 30000,
                   epoch: Optional[int] = None,
                   div: Optional[int] = None) -> bytes:
        """Fused-plane pull. A round at or below the failover/migration
        base is served from the forward log — the log holds the exact
        payload bytes the original pull returned, so the replayed round
        decodes bit-identically (the fused analogue of the dense log
        replay) whenever the workers' decision traces agree (pinned
        codecs / single worker); under ``auto`` with divergent
        per-worker traces the replay is the designated LOGGER's view,
        normalized below so this worker's decode stays well-formed."""
        from ...compress import wire as cwire
        self._check_fused_shards()
        self.placement.check_epoch(key, epoch)

        def do(sh, i):
            base = self._round_base.get(key, 0)
            if round and round <= base:
                data = self._repl_wait(key, round, timeout_ms)
                if len(data) == int(nbytes):
                    # the designated logger's trace pinned DENSE for
                    # this round while ours pinned a codec: wrap the
                    # logged dense bytes in a self-describing `none`
                    # payload so our decode stays well-formed (the
                    # header, not the requested codec, drives decode).
                    # Size disambiguates deterministically — a fused
                    # payload is never exactly the dense length for
                    # any bucket past the compression floor.
                    data = cwire.encode(
                        cwire.CODEC_NONE,
                        np.frombuffer(data, dtype=np.dtype(dtype)))
                return data
            return sh.pull_fused(key, nbytes, dtype, codec,
                                 round=(round - base) if round else 0,
                                 timeout_ms=timeout_ms, div=div)

        data = self._run(key, do)
        self._finish_pull(key, round, lambda: data)
        return data

    def push_bytes(self, key: int, payload) -> None:
        """Compressed push — routed, epoch-checked upstream, but NOT
        replicated (the codec payload is not the merged round; see
        docs/server-plane.md failure matrix)."""
        with self._mig_cv:
            while key in self._migrating:
                self._mig_cv.wait(timeout=1.0)
            lr = self._push_round.get(key, 0) + 1
            self._push_round[key] = lr
            n = len(payload)
            # window accounting only; no replay copy (unreplicated)
            self._inflight[key] = (lr, None)

        def do(sh, i):
            sh.push_bytes(key, payload)
            with self._lock:
                self._win_shard[i] = self._win_shard.get(i, 0) + n
                self._win_key[key] = self._win_key.get(key, 0) + n

        self._run(key, do)

    def pull_bytes(self, key: int, round: int = 0,
                   timeout_ms: int = 30000) -> bytes:
        base = self._round_base.get(key, 0)
        data = self._run(key, lambda sh, i: sh.pull_bytes(
            key, round=(round - base) if round else 0,
            timeout_ms=timeout_ms))
        with self._mig_cv:
            inf = self._inflight.get(key)
            if inf is not None and round and inf[0] <= round:
                del self._inflight[key]
                self._mig_cv.notify_all()   # migrate_key's drain
        return data

    # -------------------------------------------------------- migration

    def migrate_key(self, key: int, dst: int,
                    wait_s: float = 5.0) -> int:
        """Move ``key`` to shard ``dst`` at a round boundary: wait for
        the in-flight round to drain, replay the key's state (latest
        merged round + init meta) to the new owner, re-base the round
        translation, then publish placement epoch N+1. Returns the new
        epoch. Raises TimeoutError if the key never reaches a round
        boundary within ``wait_s`` (the rebalancer skips it and retries
        next cycle)."""
        deadline = time.monotonic() + wait_s
        with self._mig_cv:
            # drain-and-mark is ATOMIC: the instant the in-flight round
            # clears, the key enters _migrating under the same lock, so
            # no push can slip a fresh round onto the old primary
            # between this check and the routing switch below (it would
            # be silently absent from the replayed state)
            while key in self._inflight:
                if time.monotonic() >= deadline:
                    raise TimeoutError(
                        f"key {key}: in-flight round never drained in "
                        f"{wait_s:.1f}s — not at a round boundary")
                self._mig_cv.wait(timeout=0.05)
            self._migrating.add(key)
        try:
            src = self.placement.shard_of(key)
            if src == dst:
                return self.placement.epoch
            meta = self._meta.get(key)
            if meta is None:
                raise KeyError(f"key {key} has no init meta to replay")
            nbytes, dtype, init, compression = meta
            sh = self._shards[src]
            cr = int(sh.round(key))
            state = init
            if cr > 0:
                buf = np.empty(nbytes // np.dtype(dtype).itemsize,
                               dtype=dtype)
                sh.pull(key, buf, round=cr, timeout_ms=5000)
                state = buf
            self._init_on(dst, key, nbytes, dtype, state, compression)
            with self._lock:
                self._round_base[key] = self._round_base.get(key, 0) + cr
            return self.placement.migrate(key, dst)
        finally:
            with self._mig_cv:
                self._migrating.discard(key)
                self._mig_cv.notify_all()

"""Python bindings for the native host reduction service.

The reference loads its server as a ctypes CDLL from ``import
byteps.server`` (reference: server/__init__.py:21-27); we do the same for
``libbps_server.so`` (built from csrc/ via make — no pip/pybind needed).

``PSServer`` is the per-process server shard; ``HostPSBackend`` drives a
set of shards from the worker side, giving push_pull a PS route: device →
host numpy → sharded key stores (placement by the same key hash as the
reference, byteps_tpu.common.naming.place_key) → summation engine → pull →
device. This models the reference's CPU-server bandwidth story and powers
async-PS mode (weight-delta push / fresh-weight pull, no worker barrier;
reference: BYTEPS_ENABLE_ASYNC, torch/__init__.py:186-214).
"""

from __future__ import annotations

import ctypes
import os
import subprocess
from typing import Dict, Optional

import numpy as np

from ..obs.metrics import metrics_enabled

_DTYPES = {"float32": 0, "float64": 1, "int32": 2, "int64": 3,
           "float16": 4, "bfloat16": 5, "uint8": 6}

_LIB: Optional[ctypes.CDLL] = None


def _lib() -> ctypes.CDLL:
    global _LIB
    if _LIB is not None:
        return _LIB
    here = os.path.join(os.path.dirname(__file__), "csrc")
    so = os.path.join(here, "libbps_server.so")
    # run make unconditionally (not just when the .so is missing): the
    # Makefile's source dependency decides whether to rebuild, so a
    # stale .so from before a source change can never be dlopened with
    # missing symbols (every binding below would AttributeError)
    try:
        subprocess.run(["make", "-C", here], check=True,
                       capture_output=True)
    except (subprocess.CalledProcessError, OSError):
        if not os.path.exists(so):
            raise                      # no library at all: surface it
        # toolchain unavailable but a prebuilt .so exists — use it
    lib = ctypes.CDLL(so)
    lib.bps_server_create.restype = ctypes.c_void_p
    lib.bps_server_create.argtypes = [ctypes.c_int] * 4
    lib.bps_server_destroy.argtypes = [ctypes.c_void_p]
    lib.bps_server_begin_shutdown.argtypes = [ctypes.c_void_p]
    lib.bps_server_init_key.restype = ctypes.c_int
    lib.bps_server_init_key.argtypes = [
        ctypes.c_void_p, ctypes.c_uint64, ctypes.c_uint64, ctypes.c_int,
        ctypes.c_void_p]
    lib.bps_server_push.restype = ctypes.c_int
    lib.bps_server_push.argtypes = [
        ctypes.c_void_p, ctypes.c_uint64, ctypes.c_void_p, ctypes.c_uint64]
    lib.bps_server_pull.restype = ctypes.c_int
    lib.bps_server_pull.argtypes = [
        ctypes.c_void_p, ctypes.c_uint64, ctypes.c_void_p, ctypes.c_uint64,
        ctypes.c_uint64, ctypes.c_int]
    lib.bps_server_round.restype = ctypes.c_uint64
    lib.bps_server_round.argtypes = [ctypes.c_void_p, ctypes.c_uint64]
    lib.bps_server_engine_load.restype = ctypes.c_uint64
    lib.bps_server_engine_load.argtypes = [ctypes.c_void_p, ctypes.c_int]
    lib.bps_server_key_thread.restype = ctypes.c_int
    lib.bps_server_key_thread.argtypes = [ctypes.c_void_p, ctypes.c_uint64]
    lib.bps_reduce_sum.argtypes = [
        ctypes.c_void_p, ctypes.c_void_p, ctypes.c_uint64, ctypes.c_int]
    lib.bps_server_push_onebit.restype = ctypes.c_int
    lib.bps_server_push_onebit.argtypes = [
        ctypes.c_void_p, ctypes.c_uint64, ctypes.c_void_p, ctypes.c_uint64]
    lib.bps_server_pull_onebit.restype = ctypes.c_int
    lib.bps_server_pull_onebit.argtypes = [
        ctypes.c_void_p, ctypes.c_uint64, ctypes.c_void_p, ctypes.c_uint64,
        ctypes.c_uint64, ctypes.c_int, ctypes.c_int]
    lib.bps_server_push_topk.restype = ctypes.c_int
    lib.bps_server_push_topk.argtypes = [
        ctypes.c_void_p, ctypes.c_uint64, ctypes.c_void_p, ctypes.c_uint64]
    lib.bps_server_pull_topk.restype = ctypes.c_int
    lib.bps_server_pull_topk.argtypes = [
        ctypes.c_void_p, ctypes.c_uint64, ctypes.c_void_p, ctypes.c_uint64,
        ctypes.c_uint64, ctypes.c_int]
    # standalone codec primitives (round 4): chain state stays in
    # Python, O(n) loops run here — see host.py's _native routing
    lib.bps_codec_onebit_decompress.argtypes = [
        ctypes.c_void_p, ctypes.c_uint64, ctypes.c_void_p]
    lib.bps_codec_topk_select.restype = ctypes.c_int
    lib.bps_codec_topk_select.argtypes = [
        ctypes.c_void_p, ctypes.c_uint64, ctypes.c_uint64,
        ctypes.c_void_p, ctypes.c_void_p]
    lib.bps_codec_scatter_f32.restype = ctypes.c_int
    lib.bps_codec_scatter_f32.argtypes = [
        ctypes.c_void_p, ctypes.c_void_p, ctypes.c_uint64,
        ctypes.c_uint64, ctypes.c_void_p]
    lib.bps_codec_xorshift_indices.argtypes = [
        ctypes.c_uint64, ctypes.c_uint64, ctypes.c_void_p, ctypes.c_void_p]
    lib.bps_codec_dithering_compress.argtypes = [
        ctypes.c_void_p, ctypes.c_uint64, ctypes.c_float, ctypes.c_int,
        ctypes.c_int, ctypes.c_int, ctypes.c_void_p, ctypes.c_void_p]
    lib.bps_pack_segments.argtypes = [
        ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p,
        ctypes.c_uint64, ctypes.c_void_p]
    lib.bps_unpack_segments.argtypes = [
        ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p,
        ctypes.c_void_p, ctypes.c_uint64]
    _LIB = lib
    return lib


def pack_segments(srcs, dst_offs, lens, dst: np.ndarray) -> None:
    """Gather ``len(srcs)`` byte ranges into ``dst`` natively (GIL
    released, OMP across segments). ``srcs``: raw source addresses;
    offsets/lengths in bytes."""
    n = len(srcs)
    _lib().bps_pack_segments(
        (ctypes.c_void_p * n)(*srcs),
        (ctypes.c_uint64 * n)(*dst_offs),
        (ctypes.c_uint64 * n)(*lens),
        n, dst.ctypes.data_as(ctypes.c_void_p))


def unpack_segments(src: np.ndarray, src_offs, dsts, lens) -> None:
    """Scatter byte ranges of ``src`` to raw destination addresses."""
    n = len(dsts)
    _lib().bps_unpack_segments(
        src.ctypes.data_as(ctypes.c_void_p),
        (ctypes.c_uint64 * n)(*src_offs),
        (ctypes.c_void_p * n)(*dsts),
        (ctypes.c_uint64 * n)(*lens), n)


def reduce_sum_inplace(dst: np.ndarray, src: np.ndarray) -> None:
    """dst += src via the native typed reducer (reference: CpuReducer::sum)."""
    assert dst.dtype == src.dtype and dst.nbytes == src.nbytes
    dt = _DTYPES[str(dst.dtype)]
    _lib().bps_reduce_sum(dst.ctypes.data_as(ctypes.c_void_p),
                          src.ctypes.data_as(ctypes.c_void_p),
                          dst.nbytes, dt)


class ServerClosed(RuntimeError):
    """The server is shutting down — transient from a client's view (a
    supervisor may restart it); the transport maps this to a GONE frame
    so workers reconnect instead of failing."""


class PSServer:
    """One native server shard (reference: byteps_server(), server.cc:441-514)."""

    def __init__(self, num_workers: int, engine_threads: int = 4,
                 enable_schedule: bool = False, async_mode: bool = False):
        import threading
        self._lib = _lib()
        self._h = self._lib.bps_server_create(
            num_workers, engine_threads, int(enable_schedule), int(async_mode))
        if not self._h:
            raise RuntimeError("bps_server_create failed")
        self.num_workers = num_workers
        self.engine_threads = engine_threads
        self.async_mode = async_mode
        # close() may race concurrent callers (transport handler threads
        # blocked in pull): a Python-side inflight count plus the native
        # two-phase shutdown (begin_shutdown wakes + refuses, destroy
        # frees only after the drain) makes close() safe under load
        self._cv = threading.Condition()
        self._inflight = 0
        self._closed = False
        self._key_dtypes: dict = {}   # key -> store dtype str (transcode)

    def _enter(self):
        with self._cv:
            if self._closed:
                raise ServerClosed("server closed")
            self._inflight += 1

    def _exit(self):
        with self._cv:
            self._inflight -= 1
            if self._inflight == 0:
                self._cv.notify_all()

    def close(self) -> None:
        with self._cv:
            if self._closed:
                return
            self._closed = True
            h = self._h
        if h:
            # wake blocked pulls (they return rc=-5), then wait for every
            # in-flight ctypes call to leave before freeing the handle
            self._lib.bps_server_begin_shutdown(h)
            with self._cv:
                while self._inflight:
                    self._cv.wait(timeout=1.0)
            self._lib.bps_server_destroy(h)
            self._h = None

    def __del__(self):  # noqa: D105
        try:
            self.close()
        except Exception:
            pass

    def init_key(self, key: int, nbytes: int, dtype: str = "float32",
                 init: Optional[np.ndarray] = None) -> None:
        ptr = init.ctypes.data_as(ctypes.c_void_p) if init is not None else None
        self._enter()
        try:
            rc = self._lib.bps_server_init_key(self._h, key, nbytes,
                                               _DTYPES[dtype], ptr)
        finally:
            self._exit()
        if rc == -5:
            raise ServerClosed(f"init_key({key}): server shutting down")
        if rc != 0:
            raise RuntimeError(f"init_key({key}) failed rc={rc}")
        self._key_dtypes[key] = dtype

    def push(self, key: int, data: np.ndarray) -> None:
        # in-process transcode mirror of the transport server's wire
        # transcode (narrow async-delta pushes land in a full-precision
        # store); no bandwidth at stake here, just uniform semantics
        store = self._key_dtypes.get(key)
        if store is not None and str(data.dtype) != store:
            data = data.astype(store)
        data = np.ascontiguousarray(data)
        self._enter()
        try:
            rc = self._lib.bps_server_push(
                self._h, key, data.ctypes.data_as(ctypes.c_void_p),
                data.nbytes)
        finally:
            self._exit()
        if rc == -5:
            raise ServerClosed(f"push({key}): server shutting down")
        if rc != 0:
            raise RuntimeError(f"push({key}) failed rc={rc} "
                               f"(len mismatch or key not initialised)")

    def pull(self, key: int, out: np.ndarray, round: int = 0,
             timeout_ms: int = 30000) -> None:
        """Pull round ``round`` (1-based; 0 = latest published). Sync-mode
        callers should pass the round their push contributed to."""
        store = self._key_dtypes.get(key)
        if store is not None and str(out.dtype) != store:
            tmp = np.empty(out.size, dtype=store)
            self.pull(key, tmp, round=round, timeout_ms=timeout_ms)
            np.copyto(out, tmp.astype(out.dtype).reshape(out.shape))
            return
        self._enter()
        try:
            rc = self._lib.bps_server_pull(
                self._h, key, out.ctypes.data_as(ctypes.c_void_p),
                out.nbytes, round, timeout_ms)
        finally:
            self._exit()
        if rc == -2:
            raise TimeoutError(f"pull({key}) round={round} timed out "
                               f"after {timeout_ms}ms")
        if rc == -5:
            raise ServerClosed(f"pull({key}): server shutting down")
        if rc != 0:
            raise RuntimeError(f"pull({key}) failed rc={rc}")

    def push_onebit(self, key: int, payload) -> None:
        """Fused native decompress→enqueue of a onebit payload (fp32
        stores; reference: server.cc:86-113 decompress-before-SUM_RECV
        inside the C++ engine). The ctypes call releases the GIL, so
        concurrent workers' payloads decode in parallel."""
        buf = np.frombuffer(bytes(payload), np.uint8)
        self._enter()
        try:
            rc = self._lib.bps_server_push_onebit(
                self._h, key, buf.ctypes.data_as(ctypes.c_void_p),
                buf.nbytes)
        finally:
            self._exit()
        if rc == -5:
            raise ServerClosed(f"push_onebit({key}): server shutting down")
        if rc != 0:
            raise RuntimeError(f"push_onebit({key}) failed rc={rc} "
                               f"(bad payload length or non-fp32 key)")

    def pull_onebit(self, key: int, payload_nbytes: int, round: int = 0,
                    timeout_ms: int = 30000,
                    use_scale: bool = False) -> bytes:
        """Native merged-round pull + onebit recompress in one call."""
        out = np.empty(payload_nbytes, np.uint8)
        self._enter()
        try:
            rc = self._lib.bps_server_pull_onebit(
                self._h, key, out.ctypes.data_as(ctypes.c_void_p),
                out.nbytes, round, timeout_ms, int(use_scale))
        finally:
            self._exit()
        if rc == -2:
            raise TimeoutError(f"pull_onebit({key}) round={round} timed "
                               f"out after {timeout_ms}ms")
        if rc == -5:
            raise ServerClosed(f"pull_onebit({key}): server shutting down")
        if rc != 0:
            raise RuntimeError(f"pull_onebit({key}) failed rc={rc}")
        return out.tobytes()

    def push_topk(self, key: int, payload) -> None:
        """Fused native scatter→enqueue of a topk payload (k int32
        indices + k fp32 values; duplicate indices are LAST-WINS,
        matching the Python scatter ``out[idx] = vals``)."""
        buf = np.frombuffer(bytes(payload), np.uint8)
        self._enter()
        try:
            rc = self._lib.bps_server_push_topk(
                self._h, key, buf.ctypes.data_as(ctypes.c_void_p),
                buf.nbytes)
        finally:
            self._exit()
        if rc == -5:
            raise ServerClosed(f"push_topk({key}): server shutting down")
        if rc != 0:
            raise RuntimeError(f"push_topk({key}) failed rc={rc} "
                               f"(bad payload or non-fp32 key)")

    def pull_topk(self, key: int, payload_nbytes: int, round: int = 0,
                  timeout_ms: int = 30000) -> bytes:
        """Native merged-round pull + top-k reselection (largest |x|,
        ties to the lower index — matches HostTopk)."""
        out = np.empty(payload_nbytes, np.uint8)
        self._enter()
        try:
            rc = self._lib.bps_server_pull_topk(
                self._h, key, out.ctypes.data_as(ctypes.c_void_p),
                out.nbytes, round, timeout_ms)
        finally:
            self._exit()
        if rc == -2:
            raise TimeoutError(f"pull_topk({key}) round={round} timed "
                               f"out after {timeout_ms}ms")
        if rc == -5:
            raise ServerClosed(f"pull_topk({key}): server shutting down")
        if rc != 0:
            raise RuntimeError(f"pull_topk({key}) failed rc={rc}")
        return out.tobytes()

    def round(self, key: int) -> int:
        self._enter()
        try:
            return self._lib.bps_server_round(self._h, key)
        finally:
            self._exit()

    def engine_load(self, tid: int) -> int:
        self._enter()
        try:
            return self._lib.bps_server_engine_load(self._h, tid)
        finally:
            self._exit()

    def queue_depth(self) -> int:
        """Total enqueued-but-unsummed pushes across the engine's sticky
        per-key threads — the server-side backlog gauge."""
        return sum(self.engine_load(t) for t in range(self.engine_threads))

    def key_thread(self, key: int) -> int:
        self._enter()
        try:
            return self._lib.bps_server_key_thread(self._h, key)
        finally:
            self._exit()


class HostPSBackend:
    """Worker-side driver over sharded PSServer instances.

    Keys are placed on shards by hash (reference: global.cc:628-677) via
    ``place_key``. In-process shards model the colocated-server deployment
    (reference: BYTEPS_ENABLE_IPC best-practice); the data path and engine
    are identical for a networked deployment.
    """

    def __init__(self, num_servers: int = 1, num_workers: int = 1,
                 engine_threads: int = 4, enable_schedule: bool = False,
                 async_mode: bool = False, hash_fn: str = "djb2"):
        self.servers = [PSServer(num_workers, engine_threads, enable_schedule,
                                 async_mode)
                        for _ in range(num_servers)]
        self.num_workers = num_workers
        # homogeneous fused summation (server/homog.py): keys declared
        # ``fused=True`` at init have their ROUNDS owned by this store
        # — same-codec arrivals merge in one widen->add pass and pulls
        # are served as payload bytes, no dense decode through the
        # engine. Lazy: plain deployments never allocate it.
        self._homog = None
        # bounded-staleness round store (server/admission.StaleStore):
        # keys declared via declare_lag have their rounds versioned and
        # served under the K-lag contract instead of the native
        # complete-count engine. Lazy like _homog: K=1 deployments
        # never allocate it and stay bit-identical.
        self._stale = None
        self.hash_fn = hash_fn
        from ..common.naming import check_mixed_mode_enabled, placement_from_env
        check_mixed_mode_enabled(hash_fn)
        self._placement = placement_from_env()
        # hash_fn="ring": placement comes from the server plane's
        # byte-weighted consistent-hash service instead of the env hash
        # — balanced by construction (max−min assigned bytes bounded by
        # one key), deterministic across workers under the exchange's
        # declaration-order contract. The env hashes stay for
        # reference-parity deployments.
        self._ring = None
        if hash_fn == "ring" and num_servers > 1:
            from .plane.placement import DEFAULT_VNODES, PlacementService
            self._ring = PlacementService(
                num_servers,
                vnodes=int(self._placement.get("vnodes") or 0)
                or DEFAULT_VNODES)
        self.async_mode = async_mode
        self._rounds: Dict[int, int] = {}
        self._shard_bytes: Dict[int, int] = {}
        # key -> shard override from migrate_key (hash placements have
        # no routing table to rewrite, so moves live here); ring
        # placements rewrite the PlacementService table instead
        self._migrated: Dict[int, int] = {}
        self._key_meta: Dict[int, tuple] = {}    # key -> (nbytes, dtype)
        # plane round = shard-local round + base after a migration (the
        # new shard's store counts from 0)
        self._round_base: Dict[int, int] = {}
        self._placed: set = set()
        self._rs_cols: Dict[int, int] = {}   # row-sparse: pinned cols/key
        from .compressed import CompressedKeyStore
        self.compressed = CompressedKeyStore()
        # fused-plane pull cache (byteps_tpu.compress), created on first
        # fused pull so plain deployments never pay the import
        self._fused_cache = None
        # param mailbox (sharded weight update): one in-process store —
        # worker threads sharing this backend share it, mirroring the
        # transport server's param_store(); lazy, plain deployments
        # never allocate it
        import threading
        self._param_store = None
        self._param_lock = threading.Lock()
        from ..obs.metrics import get_registry
        self._m_pull_wait = get_registry().histogram("server/pull_wait_s")
        self._m_queue_depth = get_registry().gauge(
            "server/engine_queue_depth")
        # unmanaged fused pushes dense-decode per call: cache the
        # counter off the per-bucket hot path (homog.FusedSumStore does
        # the same for its own counters)
        self._m_dense_decodes = get_registry().counter(
            "server/fused_dense_decodes")
        self._qd_next_sample = 0.0
        import time as _time
        self._t0_mono = _time.monotonic()   # heartbeat base for stats()
        # causal span ring (obs/spans.py): per-(key, round) arrival +
        # serve records for the critical-path analyzer. In-process
        # callers carry no dedup token, so the worker id is 0; a
        # fronting PSTransportServer reuses THIS ring (and skips its
        # own recording) so colocated rigs never double-count.
        from ..obs.spans import ServerSpanRing
        self.spans = ServerSpanRing(num_workers=num_workers)

    def close(self) -> None:
        for s in self.servers:
            s.close()

    def _shard_index(self, key: int) -> int:
        s = self._migrated.get(key)
        if s is not None:
            return s
        if self._ring is not None:
            try:
                return self._ring.shard_of(key)
            except KeyError:
                # op before init_key (raw clients' round probes): route
                # to the ring primary WITHOUT recording an assignment —
                # place(key, 0) here would pin the key at weight zero
                # forever (place is idempotent), silently breaking the
                # byte-weighted balance and, worse, diverging this
                # worker's placement sequence from peers that never hit
                # this path. init_key does the real byte-weighted place.
                return self._ring.ring.lookup(key)
        from ..common.naming import place_key
        return place_key(key, len(self.servers), self.hash_fn,
                         **self._placement)

    def _shard(self, key: int) -> PSServer:
        return self.servers[self._shard_index(key)]

    def _homog_store(self):
        if self._homog is None:
            from .homog import FusedSumStore
            self._homog = FusedSumStore(self.num_workers)
        return self._homog

    def _homog_managed(self, key: int) -> bool:
        return self._homog is not None and self._homog.managed(key)

    def init_key(self, key: int, nbytes: int, dtype: str = "float32",
                 init: Optional[np.ndarray] = None,
                 compression: Optional[Dict[str, str]] = None,
                 fused: bool = False) -> None:
        """``compression`` kwargs register a server-side codec for the key
        (reference: server.cc:222-252); the dense store still holds
        ``nbytes`` — pushes arrive compressed, are decompressed into it.
        ``fused=True`` (the exchange's plan-time declaration for
        compression-plane-managed keys) hands the key's rounds to the
        homogeneous fused store — same-codec rounds merge decode-free
        and pulls are served as payload bytes (server/homog.py); a
        re-init resets the store (new tenancy), exactly like the fused
        pull cache."""
        if compression:
            size = nbytes // np.dtype(dtype).itemsize
            self.compressed.register(key, compression, size, dtype)
        from .homog import homog_enabled
        if fused and homog_enabled():
            self._homog_store().init_key(key, nbytes, dtype, init)
        elif self._homog_managed(key):
            self._homog.drop(key)     # re-declared non-fused
        # a (re-)init is a new tenancy: shard-local rounds restart, so
        # cached fused pulls from the previous tenancy would alias the
        # recurring round numbers (the transport server applies the
        # same rule to its own cache)
        if self._fused_cache is not None:
            self._fused_cache.drop(key)
        if self._ring is not None:
            self._ring.place(key, nbytes)    # byte-weighted, idempotent
        self._shard(key).init_key(key, nbytes, dtype, init)
        # init copy kept for migrate_key's round-0 replay (a fresh key
        # moved before any round completes must carry its init, not
        # zero-fill the destination)
        self._key_meta.setdefault(
            key, (int(nbytes), dtype,
                  None if init is None else np.array(init)))
        if key not in self._placed:      # re-inits are no-ops server-side;
            self._placed.add(key)        # don't double-count the load stats
            from ..common.naming import log_key_placement
            log_key_placement(key, nbytes, self._shard_index(key),
                              self._shard_bytes, self.hash_fn)
            # one shared publisher with the plane: the rebalancer and
            # the watchdog read the same plane/shard_bytes gauges
            # whichever backend is in play
            from .plane.placement import publish_shard_bytes
            publish_shard_bytes(dict(self._shard_bytes))

    def push(self, key: int, data: np.ndarray) -> None:
        import time
        if self._homog_managed(key):
            # dense round of a fused-managed key (level none, or a
            # divergent worker's dense arrival): the homog store owns
            # the round either way — splitting one key's rounds across
            # two stores would wedge the next pull
            self._homog.ingest_dense(key, data)
        else:
            self._shard(key).push(key, data)
        self.spans.note_arrival(key, 0, data.nbytes)
        # server-side backlog: how far the summation engine is behind
        # the pushes (the reference's engine_load). RATE-LIMITED — the
        # sample is engine_threads locked ctypes calls per shard, and a
        # per-push cadence measurably taxed small-step pipelines
        if metrics_enabled():
            now = time.time()
            if now >= self._qd_next_sample:
                self._qd_next_sample = now + 0.05
                try:
                    self._m_queue_depth.set(self.queue_depth())
                except Exception:   # noqa: BLE001 — the push LANDED; a
                    pass            # metrics read racing close() must
                    #                 not fail the data plane after it

    def queue_depth(self) -> int:
        """Enqueued-but-unsummed pushes across every shard's engine,
        plus the fused store's buffered arrivals — the backlog signal
        the compression controller reads must keep tracking managed
        keys after their rounds leave the engine."""
        n = sum(s.queue_depth() for s in self.servers)
        if self._homog is not None:
            n += self._homog.pending()
        return n

    def stats(self, timeout_ms: int = 0) -> Dict[str, dict]:
        """In-process form of the fleet stats surface (the shared
        ServerStats/v1 shape, obs/fleet.py — one entry per shard):
        here the "server registry" IS this process's registry, so the
        snapshot is shared across shards and only the per-shard engine
        backlog differs. Keeps FleetScraper / bench / exporter code
        backend-agnostic."""
        import time as _time

        from ..obs.fleet import server_stats_payload
        up = _time.monotonic() - self._t0_mono
        out: Dict[str, dict] = {}
        for i, s in enumerate(self.servers):
            def qd(s=s, i=i):
                n = s.queue_depth()
                if i == 0 and self._homog is not None:
                    n += self._homog.pending()   # fold buffered fused
                return n                         # arrivals once
            out[f"s{i}"] = server_stats_payload(
                up, len(self._key_meta), queue_depth_fn=qd)
        return out

    def trace(self, timeout_ms: int = 0) -> Dict[str, dict]:
        """In-process form of the causal trace scrape (one shared ring
        across shards — see ``spans``): the shape ``RemotePSBackend
        .trace()`` returns, with a zero-width roundtrip (same process,
        same clock — offset estimates to ~0 by construction)."""
        import time as _time
        now = _time.time()
        return {"s0": {"payload": self.spans.payload(now=now),
                       "t_send": now, "t_recv": now}}

    def pull(self, key: int, out: np.ndarray, round: int = 0,
             timeout_ms: int = 30000) -> None:
        import time
        if self._homog_managed(key):
            t0 = time.time()
            self._homog.pull_dense(key, out, round, timeout_ms)
            self._m_pull_wait.observe(time.time() - t0)
            self.spans.note_serve(key, round, t0, time.time() - t0)
            return
        t0 = time.time()
        base = self._round_base.get(key, 0)
        if round and round <= base:
            # the classic backend keeps no forward log (that is the
            # plane's job): a pre-migration round cannot be served —
            # round==base would silently alias to "latest published"
            # (shard round 0) and smaller rounds go negative
            raise ValueError(
                f"pull({key}) round={round}: rounds <= the migration "
                f"base ({base}) left with the old shard — only the "
                f"replicated plane retains them")
        self._shard(key).pull(key, out, (round - base) if round else 0,
                              timeout_ms)
        # how long the merge took to publish from this worker's view —
        # server sum time plus the wait for the other workers' pushes
        self._m_pull_wait.observe(time.time() - t0)
        self.spans.note_serve(key, round, t0, time.time() - t0)

    def round(self, key: int) -> int:
        """Latest COMPLETED sync round for ``key`` (0 = none yet) — lets
        a restarted worker of a live job resynchronize its round
        counters to the server's instead of stalling on round 1
        (the elastic-rejoin analog of the reference's is_recovery
        skip-barrier, global.cc:283-297). Migrated keys report
        ``base + shard round`` (the destination store counts from 0).
        Fused-managed keys answer from the homog store — its counter IS
        the key's round authority (in-process migration never moves it,
        so no base applies)."""
        if self._stale is not None and self._stale.managed(key):
            return self._stale.round(key)
        if self._homog_managed(key):
            return self._homog.round(key)
        return (self._round_base.get(key, 0)
                + int(self._shard(key).round(key)))

    # --------------------------------------- bounded staleness (K>1)

    def declare_lag(self, key: int, max_lag: int) -> None:
        """Hand ``key``'s rounds to the bounded-staleness store with
        bound ``max_lag`` (idempotent; conflicting K is a loud error).
        The key must be init_key'd first — the store snapshots its
        size/dtype from the declaration. The native engine keeps the
        key's dense store (async pulls, raw clients) but versioned
        rounds are served exclusively from the StaleStore."""
        meta = self._key_meta.get(key)
        if meta is None:
            raise KeyError(f"declare_lag({key}) before init_key")
        nbytes, dtype = meta[0], meta[1]
        if self._stale is None:
            from .admission import StaleStore
            self._stale = StaleStore(self.num_workers, spans=self.spans)
        self._stale.declare(key, nbytes // np.dtype(dtype).itemsize,
                            dtype, max_lag)

    def push_lag(self, key: int, worker: int, rnd: int,
                 data: np.ndarray) -> None:
        """Versioned-round push: fold ``worker``'s round-``rnd``
        gradient (or late-fold it into the open round — the arrival is
        recorded against the round it actually landed in, so the span
        ring's (key, round) joins stay truthful under sealing)."""
        tgt = self._stale.push(key, worker, rnd, data)
        self.spans.note_arrival(key, int(worker), data.nbytes, rnd=tgt)

    def pull_lag(self, key: int, worker: int, rnd: int,
                 out: np.ndarray, timeout_ms: int = 30000) -> int:
        """Versioned-round pull; returns the verdict flags
        (admission.LAG_COMPLETE / LAG_STALE / LAG_BARRIER)."""
        import time
        t0 = time.time()
        flags = self._stale.pull(key, worker, rnd, out, timeout_ms)
        dur = time.time() - t0
        self._m_pull_wait.observe(dur)
        self.spans.note_serve(key, rnd, t0, dur)
        return flags

    def migrate_key(self, key: int, dst: int) -> int:
        """Move ``key``'s store to shard ``dst`` at a round boundary:
        replay the latest merged state (or nothing, for a round-0 key)
        to the destination, re-base the round translation, and update
        the ``_shard_bytes`` accounting + ``plane/shard_bytes`` gauges
        so the rebalancer and the watchdog keep seeing truth. Callers
        must be at a round boundary for the key (no pushed-but-unpulled
        round — the plane backend's ``migrate_key`` enforces this; here
        the single-process trainer's step edges are the boundary).
        Returns the destination shard."""
        if not 0 <= dst < len(self.servers):
            raise ValueError(f"shard {dst} out of range "
                             f"0..{len(self.servers) - 1}")
        if self.compressed.has(key) or key in self._rs_cols:
            # the byte-path pulls (pull_bytes/onebit/topk) carry raw
            # plane rounds with no base translation — migrating such a
            # key would leave them waiting on rounds the destination
            # never published. Refuse until the byte paths learn the
            # re-basing the dense path does.
            raise ValueError(
                f"key {key} has a compressed/row-sparse codec — "
                f"migration is dense-path only")
        src = self._shard_index(key)
        if src == dst:
            return dst
        meta = self._key_meta.get(key)
        if meta is None:
            raise KeyError(f"key {key} was never init_key'd — nothing "
                           f"to migrate")
        nbytes, dtype, init = meta
        srv = self.servers[src]
        cr = int(srv.round(key))
        state = init                 # round-0 key: replay its init
        if cr > 0:
            state = np.empty(nbytes // np.dtype(dtype).itemsize,
                             dtype=dtype)
            srv.pull(key, state, round=cr, timeout_ms=5000)
        self.servers[dst].init_key(key, nbytes, dtype, state)
        self._round_base[key] = self._round_base.get(key, 0) + cr
        if self._ring is not None:
            self._ring.migrate(key, dst)     # epoch bump + its counter
        else:
            self._migrated[key] = dst
            from ..obs.metrics import get_registry
            get_registry().counter("plane/migrations").inc()
        self._shard_bytes[src] = self._shard_bytes.get(src, 0) - nbytes
        self._shard_bytes[dst] = self._shard_bytes.get(dst, 0) + nbytes
        from .plane.placement import publish_shard_bytes
        publish_shard_bytes(dict(self._shard_bytes))
        return dst

    def push_onebit(self, key: int, payload) -> None:
        """Native onebit push on the key's shard (see PSServer)."""
        self._shard(key).push_onebit(key, payload)
        # every codec path notes its arrival, or the ring's
        # count-derived rounds shear on keys that mix dense and
        # compressed rounds (the serve of round r would be joined
        # against an earlier round's arrivals)
        self.spans.note_arrival(key, 0, len(payload))

    def pull_onebit(self, key: int, payload_nbytes: int, round: int = 0,
                    timeout_ms: int = 30000,
                    use_scale: bool = False) -> bytes:
        return self._shard(key).pull_onebit(key, payload_nbytes, round,
                                            timeout_ms, use_scale)

    def push_topk(self, key: int, payload) -> None:
        """Native topk push on the key's shard (see PSServer)."""
        self._shard(key).push_topk(key, payload)
        self.spans.note_arrival(key, 0, len(payload))   # see push_onebit

    def pull_topk(self, key: int, payload_nbytes: int, round: int = 0,
                  timeout_ms: int = 30000) -> bytes:
        return self._shard(key).pull_topk(key, payload_nbytes, round,
                                          timeout_ms)

    def push_bytes(self, key: int, payload) -> None:
        """Compressed push: decompress server-side, dense-sum in the
        engine (reference: decompress before SUM_RECV, server.cc:86-113)."""
        from .compressed import compressed_push
        compressed_push(self.compressed, self._shard(key), key, payload)
        self.spans.note_arrival(key, 0, len(payload))   # see push_onebit

    def push_fused(self, key: int, payload) -> None:
        """Fused-plane push (byteps_tpu.compress): the payload is
        SELF-DESCRIBING (codec header). Managed keys buffer it in the
        homogeneous store — same-codec rounds merge in one widen->add
        pass, no dense decode through the engine; unmanaged keys keep
        the PR-7 decode-on-arrival dense sum (now counter-visible). A
        torn/mismatched payload raises CodecError loudly before any
        bytes reach either store."""
        from ..compress import wire
        if self._homog_managed(key):
            self._homog.ingest(key, payload)
            self.spans.note_arrival(key, 0, len(payload))
            return
        dense = wire.decode_for_store(payload, self._key_meta.get(key))
        if wire.lossy(wire.peek(payload)[0]):   # `none` frames are a
            self._m_dense_decodes.inc()         # frombuffer view, not
        self.push(key, dense)                   # a decode

    def pull_fused(self, key: int, nbytes: int, dtype: str, codec: int,
                   round: int = 0, timeout_ms: int = 30000,
                   div: Optional[int] = None) -> bytes:
        """Fused-plane pull: the merged round encoded at the codec the
        caller's decision trace pinned for it (deterministic codecs —
        every puller of (round, codec, div) gets byte-identical
        payloads; caches only skip repeat encodes). Managed keys serve
        straight from the homog store's merged round."""
        from ..compress import wire
        if self._homog_managed(key):
            return self._homog.pull_payload(
                key, codec, round, timeout_ms,
                div=div if div else wire.TOPK_DIV)
        if self._fused_cache is None:
            self._fused_cache = wire.FusedPullCache()
        return wire.pull_encoded(self, self._fused_cache, key, nbytes,
                                 dtype, codec, round,
                                 timeout_ms=timeout_ms,
                                 div=div if div else wire.TOPK_DIV)

    def param_store(self):
        if self._param_store is None:
            with self._param_lock:
                if self._param_store is None:
                    from ..sharded_update import ParamStore
                    self._param_store = ParamStore()
        return self._param_store

    def param_put(self, key: int, seq: int, payload) -> None:
        """Sharded-update param publish (in-process mailbox; last-wins
        per (key, seq) — see sharded_update.ParamStore)."""
        self.param_store().put(key, seq, payload)

    def param_get(self, key: int, seq: int,
                  timeout_ms: int = 30000) -> bytes:
        """Blocking non-destructive fetch of a (key, seq) param frame."""
        return self.param_store().get(key, seq, timeout_ms=timeout_ms)

    def param_latest(self, key: int) -> int:
        """Newest retained param seq for ``key`` (0 = empty) — the
        elastic-rejoin seq seed (sharded_update)."""
        return self.param_store().latest(key)

    def pull_bytes(self, key: int, round: int = 0,
                   timeout_ms: int = 30000) -> bytes:
        """Compressed pull: merged dense round recompressed once, served
        byte-identical to every worker."""
        import time as _time
        from .compressed import compressed_pull
        t0 = _time.time()
        out = compressed_pull(self.compressed, self._shard(key), key,
                              round, timeout_ms)
        self.spans.note_serve(key, round, t0, _time.time() - t0)
        return out

    def push_rowsparse(self, key: int, idx, rows, dense_nbytes: int,
                       dtype=None) -> None:
        """Row-sparse push: only touched rows cross into the store; the
        server scatters to dense before the engine sums (reference:
        reserved kRowSparsePushPull, common.h:267-271 — unimplemented
        there). dtype defaults to the rows array's own dtype."""
        from .rowsparse import rowsparse_push
        rowsparse_push(self._shard(key), key, idx, rows, dense_nbytes,
                       dtype, meta=self._rs_cols)
        self.spans.note_arrival(
            key, 0, int(getattr(rows, "nbytes", 0)))    # see push_onebit

    def push_pull(self, key: int, data: np.ndarray,
                  timeout_ms: int = 30000) -> np.ndarray:
        """One sync round from a single-worker's perspective: push, then
        pull the round this push completes (per-key local round counter)."""
        self.push(key, data)
        rnd = self._rounds.get(key, 0) + 1
        self._rounds[key] = rnd
        out = np.empty_like(data)
        self.pull(key, out, rnd if not self.async_mode else 0, timeout_ms)
        return out
